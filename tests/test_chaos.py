"""Chaos suite: deterministic fault injection drives every recovery path
(RESILIENCE.md; ISSUE 2 acceptance criteria).

Each test arms named injection points (``TS_FAULTS`` syntax via HParams
or ``faultinject.use_plan``) with pinned seeds, so the same call indices
fail on every run, and asserts the recovery *sequence* — skips, rollbacks,
reconnects, fallbacks, restarts — through the ``resilience/*`` obs
counters, not just the final output.

Run explicitly with ``-m chaos`` (scripts/chaos.sh sweeps TS_FAULTS on
top); the whole file is also part of the default suite — every test is
deterministic and CPU-fast.
"""

import json
import socketserver
import threading
import time

import numpy as np
import pytest

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.checkpoint import checkpointer as ckpt_lib
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batcher import Batcher
from textsummarization_on_flink_tpu.data.batching import Batch, SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode import decoder as dec_lib
from textsummarization_on_flink_tpu.obs import Registry
from textsummarization_on_flink_tpu.pipeline import io as io_lib
from textsummarization_on_flink_tpu.resilience import (
    CheckpointCorruptError,
    CircuitBreaker,
    Deadline,
    FaultPlan,
    FaultSpec,
    RetriesExhaustedError,
    StreamIdleError,
    WorkerCrashError,
    faultinject,
)
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _isolated_obs_and_faults():
    """Every chaos test gets a fresh obs registry (counter assertions)
    and leaves no fault plan cached behind."""
    with obs.use_registry(Registry()) as reg:
        yield reg
    faultinject.set_default_plan(None)


# -- trainer: divergence recovery (acceptance criterion 1) -----------------

def hps_tiny(**kw):
    base = dict(batch_size=2, max_enc_steps=6, max_dec_steps=5,
                min_dec_steps=1, hidden_dim=4, emb_dim=3, max_oov_buckets=2,
                vocab_size=0, lr=0.15, adagrad_init_acc=0.1,
                max_grad_norm=2.0)
    base.update(kw)
    return HParams(**base)


class FixedBatcher:
    def __init__(self, batch, n):
        self.batch, self.n = batch, n

    def next_batch(self):
        if self.n <= 0:
            return None
        self.n -= 1
        return self.batch


def make_batch(hps, vocab):
    exs = [SummaryExample.build("a b c d", ["b c ."], vocab, hps),
           SummaryExample.build("c d e f", ["d e ."], vocab, hps)]
    return Batch(exs, hps, vocab)


class TestTrainDivergenceRecovery:
    def test_injected_nan_skips_then_rolls_back_then_completes(
            self, tmp_path, _isolated_obs_and_faults):
        """End-to-end: with ``train.step_nan`` injected 3 times at p=1.0,
        the trainer burns its 2-skip budget, rolls back once with an LR
        cut, and training then resumes to completion without manual
        intervention (the acceptance sequence)."""
        reg = _isolated_obs_and_faults
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t",
                       nan_skip_steps=2, nan_max_rollbacks=1,
                       faults="train.step_nan:1.0:7:3")
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        ck = ckpt_lib.Checkpointer(str(tmp_path / "ckpt"), hps=hps)
        trainer = trainer_lib.Trainer(hps, vocab.size(),
                                      FixedBatcher(batch, 30),
                                      checkpointer=ck,
                                      checkpoint_secs=1e9)
        state = trainer.train(num_steps=6)
        # training COMPLETED despite 3 injected divergences
        assert int(np.asarray(state.step)) == 6
        assert reg.counter("resilience/train/nan_skips_total").value == 2
        assert reg.counter("resilience/train/rollbacks_total").value == 1
        assert reg.counter("train/nan_watchdog_total").value == 3
        # one rollback cut the LR by nan_lr_cut (default 0.5)
        assert reg.gauge("resilience/train/lr_scale").value == 0.5
        assert trainer._faults.stats()["train.step_nan"]["fires"] == 3

    def test_budgets_exhausted_raises_nan_loss_error(self, tmp_path):
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t",
                       nan_skip_steps=1, nan_max_rollbacks=1,
                       faults="train.step_nan:1.0:7")  # unbounded fires
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = trainer_lib.Trainer(hps, vocab.size(),
                                      FixedBatcher(batch, 30))
        with pytest.raises(trainer_lib.NanLossError, match="exhausted"):
            trainer.train(num_steps=6)

    def test_unarmed_injection_keeps_hard_abort(self, tmp_path):
        """Default HParams (both budgets 0): the reference's fail-fast
        watchdog contract survives — an injected divergence aborts."""
        hps = hps_tiny(log_root=str(tmp_path), exp_name="t",
                       faults="train.step_nan:1.0:7:1")
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = trainer_lib.Trainer(hps, vocab.size(),
                                      FixedBatcher(batch, 10))
        with pytest.raises(trainer_lib.NonFiniteLossError, match="injected"):
            trainer.train(num_steps=4)


# -- pipeline source: reconnect with backoff (acceptance criterion 2) ------

def _serve_lines(lines):
    """A TCP server that streams `lines` to every connection, forever
    (each reconnect replays from the start, like a re-polled topic)."""
    class Handler(socketserver.StreamRequestHandler):
        def handle(self):
            try:
                for line in lines:
                    self.wfile.write((line + "\n").encode())
            except (BrokenPipeError, ConnectionResetError):
                pass

    server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
    server.daemon_threads = True
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    return server, server.server_address[1]


class TestSourceReconnect:
    def test_injected_io_read_reconnects_and_delivers_exactly_once(
            self, _isolated_obs_and_faults):
        """Acceptance: with io.read faults injected, the source
        reconnects with backoff and every row reaches the consumer
        exactly once, all visible in resilience/* counters."""
        reg = _isolated_obs_and_faults
        lines = [io_lib.Message(f"u{i}", f"art {i}", "", "ref").to_json()
                 for i in range(5)]
        server, port = _serve_lines(lines)
        try:
            # p=1.0 max=2: the first two read attempts fail, the third
            # connection streams clean — same indices every run
            plan = FaultPlan([FaultSpec("io.read", 1.0, 0, 2)],
                             registry=reg)
            with faultinject.use_plan(plan):
                src = io_lib.ResilientSource(
                    lambda: io_lib.SocketSource("127.0.0.1", port,
                                                max_count=5),
                    max_reconnects=4, seed=0, sleep=lambda d: None)
                got = list(src.rows())
        finally:
            server.shutdown()
            server.server_close()
        assert [r[0] for r in got] == [f"u{i}" for i in range(5)]
        assert plan.stats()["io.read"]["fires"] == 2
        assert reg.counter("resilience/io_reconnects_total").value == 2
        assert reg.counter("resilience/fault/io.read").value == 2

    def test_replayed_rows_are_deduped(self, _isolated_obs_and_faults):
        """A peer that dies mid-stream and replays from the start on
        reconnect must not hand the consumer duplicates."""
        reg = _isolated_obs_and_faults
        rows = [(f"u{i}", f"art {i}", "", "r") for i in range(5)]
        calls = {"n": 0}

        class FlakySource(io_lib.Source):
            schema = io_lib.ARTICLE_INPUT_SCHEMA

            def rows(self):
                calls["n"] += 1
                if calls["n"] == 1:  # first connection dies after 3 rows
                    yield from rows[:3]
                    raise ConnectionResetError("peer died mid-stream")
                yield from rows  # replay from the start

        src = io_lib.ResilientSource(FlakySource, max_reconnects=2, seed=0,
                                     sleep=lambda d: None)
        got = list(src.rows())
        assert got == rows  # exactly once, in order
        assert reg.counter("resilience/io_dup_rows_total").value == 3
        assert reg.counter("resilience/io_reconnects_total").value == 1

    def test_dedup_window_bounds_memory(self, _isolated_obs_and_faults):
        """Dedup memory is a bounded LRU window, not an ever-growing
        set: keys inside the window still dedup, keys evicted from it
        are re-delivered (the documented tradeoff on endless streams),
        and every eviction is counted
        (``pipeline/dedup_evictions_total``, the ISSUE-13 satellite)."""
        reg = _isolated_obs_and_faults
        calls = {"n": 0}

        class FlakySource(io_lib.Source):
            schema = io_lib.ARTICLE_INPUT_SCHEMA

            def rows(self):
                calls["n"] += 1
                if calls["n"] == 1:
                    for i in range(3):
                        yield (f"u{i}", "a", "", "r")
                    raise ConnectionResetError("flap")
                yield ("u2", "a", "", "r")  # within the 2-key window: dup
                yield ("u0", "a", "", "r")  # evicted: re-delivered
                yield ("u3", "a", "", "r")

        src = io_lib.ResilientSource(
            FlakySource, max_reconnects=2, seed=0, dedup_window=2,
            schema=io_lib.ARTICLE_INPUT_SCHEMA, sleep=lambda d: None)
        keys = [r[0] for r in src.rows()]
        assert keys == ["u0", "u1", "u2", "u0", "u3"]
        assert reg.counter("pipeline/dedup_evictions_total").value == 3

    def test_dedup_lru_refresh_protects_replayed_keys(
            self, _isolated_obs_and_faults):
        """The LRU half of the ISSUE-13 satellite: a replayed key
        refreshes its recency, so a peer that replays the same prefix
        on every reconnect cannot age live keys out of the window (the
        FIFO window would have re-delivered u0 here — a duplicate
        leak)."""
        reg = _isolated_obs_and_faults
        calls = {"n": 0}

        class FlakySource(io_lib.Source):
            schema = io_lib.ARTICLE_INPUT_SCHEMA

            def rows(self):
                calls["n"] += 1
                if calls["n"] == 1:
                    yield ("u0", "a", "", "r")
                    yield ("u1", "a", "", "r")
                    raise ConnectionResetError("flap")
                yield ("u0", "a", "", "r")  # replayed: refreshes u0
                yield ("u2", "a", "", "r")  # evicts u1, NOT fresh u0
                yield ("u0", "a", "", "r")  # still inside the window
                yield ("u3", "a", "", "r")

        src = io_lib.ResilientSource(
            FlakySource, max_reconnects=2, seed=0, dedup_window=2,
            schema=io_lib.ARTICLE_INPUT_SCHEMA, sleep=lambda d: None)
        keys = [r[0] for r in src.rows()]
        assert keys == ["u0", "u1", "u2", "u3"]  # u0 never re-delivered
        assert reg.counter("resilience/io_dup_rows_total").value == 2
        assert reg.counter("pipeline/dedup_evictions_total").value == 2

    def test_reconnect_budget_exhausted_raises_typed(
            self, _isolated_obs_and_faults):
        reg = _isolated_obs_and_faults

        class DeadSource(io_lib.Source):
            schema = io_lib.ARTICLE_INPUT_SCHEMA

            def rows(self):
                raise ConnectionRefusedError("nobody home")
                yield  # pragma: no cover

        src = io_lib.ResilientSource(DeadSource, max_reconnects=2, seed=0,
                                     sleep=lambda d: None)
        with pytest.raises(RetriesExhaustedError) as ei:
            list(src.rows())
        assert isinstance(ei.value.__cause__, ConnectionRefusedError)
        assert reg.counter(
            "resilience/io.source/retry_exhausted_total").value == 1

    def test_socket_idle_timeout_raises_stream_idle_error(self):
        """Satellite 1: a silent (but connected) peer surfaces as a typed
        StreamIdleError instead of hanging the source forever."""
        hold = threading.Event()

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                self.wfile.write(
                    (io_lib.Message("u0", "art", "", "r").to_json()
                     + "\n").encode())
                self.wfile.flush()
                hold.wait(5)  # go silent without closing

        server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), Handler)
        server.daemon_threads = True
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            src = io_lib.SocketSource("127.0.0.1", server.server_address[1],
                                      idle_timeout=0.3)
            it = src.rows()
            assert next(it)[0] == "u0"  # live data flows
            t0 = time.monotonic()
            with pytest.raises(StreamIdleError, match="no data"):
                next(it)
            assert time.monotonic() - t0 < 5.0  # bounded, not forever
        finally:
            hold.set()
            server.shutdown()
            server.server_close()


# -- sink: circuit breaker sheds instead of blocking -----------------------

class TestBreakerSink:
    def test_open_breaker_sheds_then_half_open_probe_recovers(
            self, _isolated_obs_and_faults):
        reg = _isolated_obs_and_faults
        clock = [0.0]
        breaker = CircuitBreaker(threshold=3, reset_secs=30.0,
                                 name="io.sink", clock=lambda: clock[0],
                                 registry=reg)
        inner = io_lib.CollectionSink()
        # io.write fails the first 3 protected writes, then heals
        plan = FaultPlan([FaultSpec("io.write", 1.0, 0, 3)], registry=reg)
        with faultinject.use_plan(plan):
            sink = io_lib.BreakerSink(inner, breaker=breaker)
            for i in range(5):
                sink.write((f"u{i}", "a", "s", "r"))
        # 3 failures tripped the breaker; writes 4 and 5 shed immediately
        assert breaker.state == CircuitBreaker.OPEN
        assert inner.rows == []
        assert reg.counter("resilience/sink_errors_total").value == 3
        assert reg.counter("resilience/sink_shed_total").value == 5
        # reset window elapses: the half-open probe write goes through
        # (the fault budget is spent) and the breaker re-closes
        clock[0] = 31.0
        sink.write(("u5", "a", "s", "r"))
        assert breaker.state == CircuitBreaker.CLOSED
        assert inner.rows == [("u5", "a", "s", "r")]
        sink.close()


# -- checkpointer: checksum manifests + corruption fallback ----------------

def tiny_state(hps, seed=0):
    return trainer_lib.init_train_state(
        hps, vsize=12, seed=seed)


class TestCheckpointCorruption:
    def test_manifest_written_and_verified(self, tmp_path):
        hps = hps_tiny()
        ck = ckpt_lib.Checkpointer(str(tmp_path), hps=hps)
        path = ck.save(tiny_state(hps))
        assert ckpt_lib.verify_manifest(path)

    def test_corrupt_latest_falls_back_to_older(
            self, tmp_path, _isolated_obs_and_faults):
        reg = _isolated_obs_and_faults
        hps = hps_tiny()
        ck = ckpt_lib.Checkpointer(str(tmp_path), hps=hps)
        s1 = tiny_state(hps)
        p1 = ck.save(s1)
        s2 = s1._replace(step=s1.step + 5)
        p2 = ck.save(s2)
        assert p1 != p2
        with open(p2, "r+b") as f:  # flip bytes in the newest checkpoint
            f.seek(30)
            f.write(b"\xde\xad\xbe\xef" * 4)
        restored = ck.restore()
        # fell back to the older, intact checkpoint instead of crashing
        assert int(np.asarray(restored.step)) == int(np.asarray(s1.step))
        assert reg.counter("resilience/ckpt_fallbacks_total").value == 1

    def test_explicit_path_surfaces_corruption(self, tmp_path):
        hps = hps_tiny()
        ck = ckpt_lib.Checkpointer(str(tmp_path), hps=hps)
        path = ck.save(tiny_state(hps))
        with open(path, "r+b") as f:
            f.seek(10)
            f.write(b"\x00" * 8)
        # the caller asked for THIS checkpoint: no silent substitution
        with pytest.raises(CheckpointCorruptError, match="checksum"):
            ck.restore(path)

    def test_injected_ckpt_load_fault_falls_back(
            self, tmp_path, _isolated_obs_and_faults):
        reg = _isolated_obs_and_faults
        hps = hps_tiny()
        ck = ckpt_lib.Checkpointer(str(tmp_path), hps=hps)
        s1 = tiny_state(hps)
        ck.save(s1)
        ck.save(s1._replace(step=s1.step + 5))
        # the first load attempt (the newest candidate) fails; the
        # fallback chain serves the older checkpoint
        plan = FaultPlan([FaultSpec("ckpt.load", 1.0, 0, 1)], registry=reg)
        with faultinject.use_plan(plan):
            restored = ck.restore()
        assert restored is not None
        assert int(np.asarray(restored.step)) == int(np.asarray(s1.step))
        assert reg.counter("resilience/ckpt_fallbacks_total").value == 1

    def test_per_job_fault_budget_persists_across_restores(
            self, tmp_path, _isolated_obs_and_faults):
        """HParams(faults="ckpt.load:1.0:0:1") models a dependency that
        fails exactly ONCE then heals: the per-job plan's fire budget
        must survive across restore() calls, not reset per call."""
        hps = hps_tiny(faults="ckpt.load:1.0:0:1")
        ck = ckpt_lib.Checkpointer(str(tmp_path), hps=hps)
        s1 = tiny_state(hps)
        ck.save(s1)
        ck.save(s1._replace(step=s1.step + 5))
        r1 = ck.restore()  # fire 1: newest injected-corrupt -> older
        assert int(np.asarray(r1.step)) == int(np.asarray(s1.step))
        r2 = ck.restore()  # budget spent, fault healed -> newest loads
        assert int(np.asarray(r2.step)) == int(np.asarray(s1.step)) + 5

    def test_load_ckpt_wait_loop_is_observable(
            self, tmp_path, _isolated_obs_and_faults):
        """Satellite 3: a decoder stuck waiting on a trainer is visible
        via ckpt/load_retries_total and ckpt/load_wait_seconds."""
        reg = _isolated_obs_and_faults
        with pytest.raises(FileNotFoundError):
            ckpt_lib.load_ckpt(str(tmp_path), max_retries=2,
                               retry_secs=0.01)
        assert reg.counter("ckpt/load_retries_total").value == 2
        assert reg.gauge("ckpt/load_wait_seconds").value > 0


# -- batcher: etl worker restart budget ------------------------------------

def _vocab():
    return Vocab(words=["the", "cat", "sat", "on", "mat", "."])


class TestEtlWorkerRestarts:
    def test_injected_crashes_restart_within_budget(
            self, _isolated_obs_and_faults):
        reg = _isolated_obs_and_faults
        hps = hps_tiny(batch_size=2, mode="train",
                       faults="etl.worker:1.0:0:2")

        def source():
            return iter([("the cat sat", "<s> the cat . </s>")] * 4)

        b = Batcher("", _vocab(), hps, single_pass=True,
                    example_source=source, max_worker_restarts=3)
        batches = []
        while True:
            batch = b.next_batch()
            if batch is None:
                break
            batches.append(batch)
        # 2 injected crashes consumed 2 restarts; data still flowed
        assert len(batches) == 2
        assert reg.counter(
            "resilience/etl_worker_restarts_total").value == 2

    def test_budget_exhausted_surfaces_worker_crash_error(
            self, _isolated_obs_and_faults):
        hps = hps_tiny(batch_size=2, mode="train",
                       faults="etl.worker:1.0:0")  # crashes forever

        def source():
            return iter([("the cat sat", "<s> the cat . </s>")] * 4)

        b = Batcher("", _vocab(), hps, single_pass=True,
                    example_source=source, max_worker_restarts=2)
        with pytest.raises(WorkerCrashError, match="restart budget spent"):
            for _ in range(100):
                if b.next_batch() is None:
                    break
        assert isinstance(b._fill_error, RuntimeError)

    def test_zero_budget_restores_fail_fast(self, _isolated_obs_and_faults):
        reg = _isolated_obs_and_faults
        hps = hps_tiny(batch_size=2, mode="train",
                       faults="etl.worker:1.0:0:1")

        def source():
            return iter([("the cat sat", "<s> the cat . </s>")] * 4)

        b = Batcher("", _vocab(), hps, single_pass=True,
                    example_source=source, max_worker_restarts=0)
        with pytest.raises(WorkerCrashError):
            for _ in range(100):
                if b.next_batch() is None:
                    break
        assert reg.counter(
            "resilience/etl_worker_restarts_total").value == 0


# -- decoder: deadline degradation -----------------------------------------

DEC_WORDS = ("the a cat dog sat ran mat home big small quick brown fox "
             "jumped over lazy it was day night").split()


class TestDecodeDeadline:
    @pytest.fixture(scope="class")
    def decode_setup(self, tmp_path_factory):
        hps = HParams(batch_size=2, hidden_dim=8, emb_dim=6, vocab_size=24,
                      max_enc_steps=16, max_dec_steps=8, beam_size=2,
                      min_dec_steps=1, max_oov_buckets=4, mode="decode",
                      single_pass=True, decode_deadline_secs=30.0)
        vocab = Vocab(words=DEC_WORDS)
        state = trainer_lib.init_train_state(hps, vocab.size(), seed=0)
        return hps, vocab, state.params

    def _decoder(self, hps, vocab, params, tmp_path, reg):
        def source():
            return iter([
                ("the quick brown fox over the lazy dog .",
                 "<s> the fox . </s>"),
                ("a big cat sat on the small mat .",
                 "<s> the cat sat . </s>")])

        batcher = Batcher("", vocab, hps, single_pass=True,
                          decode_batch_mode="distinct",
                          example_source=source)
        return dec_lib.BeamSearchDecoder(
            hps, vocab, batcher, params=params,
            decode_root=str(tmp_path / "dec"))

    def test_short_deadline_degrades_to_greedy_and_tags(
            self, decode_setup, tmp_path, _isolated_obs_and_faults):
        reg = _isolated_obs_and_faults
        hps, vocab, params = decode_setup
        d = self._decoder(hps, vocab, params, tmp_path, reg)
        batch = d._batcher.next_batch()
        # 1st dispatch: never degraded, and its compile-inclusive wall
        # time is DISCARDED (recording it would lock every later request
        # into greedy); the 2nd full-beam dispatch seeds the estimate
        full = d.decode_batch(batch)
        assert all(not r.degraded for r in full)
        assert d._beam_secs is None
        d.decode_batch(batch)
        assert d._beam_secs is not None
        # 2nd dispatch with a budget far below the estimate -> greedy,
        # tagged degraded, counted
        d._beam_secs = 100.0  # force "budget cannot cover a full beam"
        degraded = d.decode_batch(batch, deadline=Deadline.after(0.5))
        assert all(r.degraded for r in degraded)
        assert len(degraded) == len(full)
        assert reg.counter(
            "resilience/decode_degraded_total").value == len(degraded)
        # a degraded dispatch must not poison the full-beam estimate
        assert d._beam_secs == 100.0

    def test_unbounded_deadline_never_degrades(
            self, decode_setup, tmp_path, _isolated_obs_and_faults):
        reg = _isolated_obs_and_faults
        hps, vocab, params = decode_setup
        d = self._decoder(hps.replace(decode_deadline_secs=0.0), vocab,
                          params, tmp_path, reg)
        batch = d._batcher.next_batch()
        d.decode_batch(batch)
        d._beam_secs = 100.0
        out = d.decode_batch(batch)  # hps deadline 0 = never degrade
        assert all(not r.degraded for r in out)
        assert reg.counter("resilience/decode_degraded_total").value == 0


# -- serve queue under faults (ISSUE 4 chaos satellite) ---------------------

class TestServeChaos:
    """The serve queue under TS_FAULTS=io.read + injected slow batches:
    overload sheds and deadline degradations are COUNTED, and nothing
    hangs — every admitted request resolves within a bound."""

    SERVE_WORDS = ("the a cat dog sat ran mat home big small quick brown "
                   "fox jumped over lazy it was day night").split()

    def test_flaky_source_plus_slow_batches_shed_and_degrade_never_hang(
            self, tmp_path, _isolated_obs_and_faults):
        from textsummarization_on_flink_tpu.serve.errors import (
            ServeOverloadError,
        )
        from textsummarization_on_flink_tpu.serve.server import ServingServer

        reg = _isolated_obs_and_faults
        vocab = Vocab(words=self.SERVE_WORDS)
        hps = HParams(mode="decode", batch_size=2, hidden_dim=8, emb_dim=6,
                      vocab_size=vocab.size(), max_enc_steps=16,
                      max_dec_steps=6, beam_size=2, min_dec_steps=1,
                      max_oov_buckets=4, serve_max_wait_ms=5.0,
                      serve_max_queue=2, decode_deadline_secs=5.0,
                      serve_buckets="16")
        state = trainer_lib.init_train_state(hps, vocab.size(), seed=0)
        inner = dec_lib.BeamSearchDecoder(
            hps, vocab, batcher=None, params=state.params,
            decode_root=str(tmp_path / "serve"))

        class SlowDecoder:
            """Injected slow batches: every dispatch stalls long enough
            for the 2-deep queue to overflow behind it."""

            def decode_batch(self, batch, deadline=None):
                time.sleep(0.1)
                return inner.decode_batch(batch, deadline=deadline)

            def maybe_reload_checkpoint(self, last):
                return last

        # a flapping peer: the first two read attempts die (same indices
        # every run), then the stream replays clean — ResilientSource
        # reconnects with backoff and dedups, exactly like production
        lines = [io_lib.Message(f"u{i}", "the quick brown fox ran", "",
                                "r").to_json() for i in range(12)]
        server_tcp, port = _serve_lines(lines)
        plan = FaultPlan([FaultSpec("io.read", 1.0, 0, 2)], registry=reg)
        serve_server = ServingServer(hps, vocab, decoder=SlowDecoder(),
                                     registry=reg)
        # pre-warm the compile and force the degradation ladder: with a
        # huge full-beam estimate every bounded request degrades to
        # greedy (the decoder's _should_degrade contract)
        inner._beam_warm = True
        inner._beam_secs = 100.0
        admitted, sheds = [], 0
        try:
            with faultinject.use_plan(plan), serve_server:
                src = io_lib.ResilientSource(
                    lambda: io_lib.SocketSource("127.0.0.1", port,
                                                max_count=12),
                    max_reconnects=4, seed=0, sleep=lambda d: None)
                for row in src.rows():
                    try:
                        admitted.append(serve_server.submit(
                            str(row[1]), uuid=str(row[0])))
                    except ServeOverloadError:
                        sheds += 1
                # NEVER hung: every admitted request resolves in bound
                results = [f.result(timeout=120) for f in admitted]
        finally:
            server_tcp.shutdown()
            server_tcp.server_close()
        # the flaky stream reconnected (not silently truncated) ...
        assert reg.counter("resilience/io_reconnects_total").value == 2
        assert plan.stats()["io.read"]["fires"] == 2
        # ... slow batches overflowed the bounded queue into typed sheds
        assert sheds > 0
        assert reg.counter("serve/shed_total").value == sheds
        # ... admitted requests all completed, each degraded to greedy
        # under the enqueue-measured deadline, and all of it is counted
        assert len(results) == len(admitted) == 12 - sheds
        assert all(r.degraded for r in results)
        assert reg.counter("serve/degraded_total").value == len(results)
        assert reg.counter(
            "resilience/decode_degraded_total").value == len(results)
        assert reg.counter("serve/completed_total").value == len(results)

    def test_continuous_mode_chaos_exactly_once_under_faults(
            self, tmp_path, _isolated_obs_and_faults):
        """The ISSUE-6 acceptance chaos run: continuous (slotted) serving
        under io.read faults on the feed, an injected serve.dispatch
        tick failure, slow chunks, and a 2-deep admission queue.  Every
        ADMITTED request must resolve EXACTLY ONCE — with its result or
        the typed injected cause — sheds must be counted, and nothing
        may hang."""
        from textsummarization_on_flink_tpu.serve.errors import (
            ServeOverloadError,
        )
        from textsummarization_on_flink_tpu.serve.server import ServingServer

        reg = _isolated_obs_and_faults
        vocab = Vocab(words=self.SERVE_WORDS)
        hps = HParams(mode="decode", batch_size=2, hidden_dim=8, emb_dim=6,
                      vocab_size=vocab.size(), max_enc_steps=16,
                      max_dec_steps=6, beam_size=2, min_dec_steps=1,
                      max_oov_buckets=4, serve_max_queue=2,
                      serve_mode="continuous", serve_slots=2,
                      serve_refill_chunk=2,
                      faults="serve.dispatch:1.0:11:1")
        state = trainer_lib.init_train_state(hps, vocab.size(), seed=0)
        decoder = dec_lib.BeamSearchDecoder(
            hps, vocab, batcher=None, params=state.params,
            decode_root=str(tmp_path / "cont_chaos"))

        class SlowEngine:
            """Real slot engine with injected slow chunks: each step
            stalls long enough for the 2-deep queue to overflow."""

            def __init__(self, inner):
                self._inner = inner
                self.slots = inner.slots
                self.chunk = inner.chunk

            def pack(self, idx, example):
                self._inner.pack(idx, example)

            def step(self):
                time.sleep(0.1)
                return self._inner.step()

            def unpack(self, idx, example):
                return self._inner.unpack(idx, example)

            def release(self, idx):
                self._inner.release(idx)

        engine = SlowEngine(decoder.slot_engine(slots=2, chunk=2))
        lines = [io_lib.Message(f"u{i}", "the quick brown fox ran", "",
                                "r").to_json() for i in range(12)]
        server_tcp, port = _serve_lines(lines)
        plan = FaultPlan([FaultSpec("io.read", 1.0, 0, 2)], registry=reg)
        serve_server = ServingServer(hps, vocab, decoder=decoder,
                                     engine=engine, registry=reg)
        admitted, sheds = [], 0
        try:
            with faultinject.use_plan(plan), serve_server:
                src = io_lib.ResilientSource(
                    lambda: io_lib.SocketSource("127.0.0.1", port,
                                                max_count=12),
                    max_reconnects=4, seed=0, sleep=lambda d: None)
                for row in src.rows():
                    try:
                        admitted.append(serve_server.submit(
                            str(row[1]), uuid=str(row[0])))
                    except ServeOverloadError:
                        sheds += 1
                # NEVER hung, and EXACTLY ONCE: each admitted future
                # resolves with a result or the typed injected cause
                ok, injected = 0, 0
                for f in admitted:
                    try:
                        f.result(timeout=120)
                        ok += 1
                    except RuntimeError as e:
                        assert "injected serve.dispatch fault" in str(e)
                        injected += 1
                # the loop LIVED ON past the injected tick: a fresh
                # post-fault request must serve normally (how many of
                # the streamed rows beat the fault is a thread race —
                # this one cannot)
                post = serve_server.submit("the quick brown fox ran",
                                           uuid="post")
                assert post.result(timeout=120).uuid == "post"
        finally:
            server_tcp.shutdown()
            server_tcp.server_close()
        assert reg.counter("resilience/io_reconnects_total").value == 2
        assert sheds > 0
        assert reg.counter("serve/shed_total").value == sheds
        assert ok + injected == len(admitted) == 12 - sheds
        # the injected tick failure hit at least one resident request
        assert injected >= 1
        assert reg.counter("serve/errors_total").value == injected
        assert reg.counter("serve/completed_total").value == ok + 1

    def test_injected_dispatch_fault_fails_one_batch_not_the_server(
            self, _isolated_obs_and_faults):
        """serve.dispatch injection: the poisoned batch is rejected
        wholesale with the typed cause; the dispatcher survives and the
        next batch serves."""
        from textsummarization_on_flink_tpu.decode.decoder import (
            DecodedResult,
        )
        from textsummarization_on_flink_tpu.serve.server import ServingServer

        reg = _isolated_obs_and_faults
        vocab = Vocab(words=self.SERVE_WORDS)
        hps = HParams(mode="decode", batch_size=2, max_enc_steps=8,
                      max_dec_steps=4, min_dec_steps=1,
                      serve_max_wait_ms=50.0, serve_max_queue=16,
                      faults="serve.dispatch:1.0:3:1")

        class EchoDecoder:
            def decode_batch(self, batch, deadline=None):
                return [DecodedResult(
                            uuid=batch.uuids[b],
                            article=batch.original_articles[b],
                            decoded_words=["ok"], reference="",
                            abstract_sents=[])
                        for b in range(len(batch.uuids))
                        if batch.real_mask[b]]

            def maybe_reload_checkpoint(self, last):
                return last

        server = ServingServer(hps, vocab, decoder=EchoDecoder(),
                               registry=reg)
        with server:
            doomed = server.submit("the cat sat", uuid="doomed")
            with pytest.raises(RuntimeError, match="injected"):
                doomed.result(timeout=30)
            ok = server.submit("the dog ran", uuid="ok")
            assert ok.result(timeout=30).uuid == "ok"
        assert reg.counter("serve/errors_total").value == 1
        assert reg.counter("serve/completed_total").value == 1
        assert reg.counter("resilience/fault/serve.dispatch").value == 1


# -- flight recorder: dumps under injected faults (ISSUE 9) ----------------

class TestFlightRecorderForensics:
    """Acceptance: under injected ``train.step_nan`` and
    ``serve.dispatch`` faults (the existing TS_FAULTS points), a
    ``flight_<reason>.jsonl`` dump exists holding >= the configured ring
    of frames recorded strictly before the trigger fired."""

    # p=0.35 with seed 5 first fires on the 7th fire() call — verified
    # below against the same RNG the fault plan uses, so the ring (4)
    # is guaranteed full of pre-trigger frames
    FAULT_PROB, FAULT_SEED, FIRST_FIRE = 0.35, 5, 7

    def test_seed_fires_on_seventh_call(self):
        import random

        rng = random.Random(self.FAULT_SEED)
        first = next(i for i in range(1, 100)
                     if rng.random() < self.FAULT_PROB)
        assert first == self.FIRST_FIRE

    def test_injected_train_nan_dumps_preceding_steps(self, tmp_path):
        """Six clean steps flush six frames; the injected NaN at step 6
        dumps the newest 4 of them to the train dir."""
        hps = hps_tiny(
            log_root=str(tmp_path), exp_name="t", metrics_every=1,
            flight_frames=4,
            faults=f"train.step_nan:{self.FAULT_PROB}:{self.FAULT_SEED}:1")
        vocab = Vocab(words=["a", "b", "c", "d", "e", "f", "."])
        batch = make_batch(hps, vocab)
        trainer = trainer_lib.Trainer(hps, vocab.size(),
                                      FixedBatcher(batch, 20))
        with pytest.raises(trainer_lib.NonFiniteLossError, match="injected"):
            trainer.train(num_steps=12)
        dump = tmp_path / "t" / "train" / "flight_train_nan.jsonl"
        assert dump.exists()
        lines = [json.loads(ln) for ln in open(dump, encoding="utf-8")]
        header, frames = lines[0], lines[1:]
        assert header["kind"] == "flight" and header["reason"] == "train_nan"
        assert header["context"] == {"step": 6, "injected": True}
        # >= the configured ring, every frame STRICTLY before the trigger
        assert len(frames) == 4 == header["capacity"]
        assert [f["step"] for f in frames] == [2, 3, 4, 5]
        assert all(f["kind"] == "train_step" and "loss" in f
                   and "global_norm" in f and "step_time" in f
                   and "prefetch_depth" in f for f in frames)

    def test_injected_dispatch_fault_dumps_preceding_ticks(
            self, tmp_path, _isolated_obs_and_faults):
        """Continuous mode: six clean chunk ticks frame the ring; the
        injected serve.dispatch failure on the 7th busy tick dumps them
        (each busy tick frames BEFORE its dispatch, so the failing
        tick's own pre-failure frame is included)."""
        from textsummarization_on_flink_tpu.decode.decoder import (
            DecodedResult,
        )
        from textsummarization_on_flink_tpu.serve.server import ServingServer

        reg = _isolated_obs_and_faults
        vocab = Vocab(words=["the", "cat", "sat", "."])
        hps = HParams(
            mode="decode", batch_size=2, max_enc_steps=8, max_dec_steps=4,
            min_dec_steps=1, serve_max_queue=8, serve_mode="continuous",
            serve_slots=2, serve_refill_chunk=2,
            log_root=str(tmp_path), exp_name="s", flight_frames=4,
            faults=f"serve.dispatch:{self.FAULT_PROB}:{self.FAULT_SEED}:1")

        class NeverFinishEngine:
            """One resident request, resident forever: every tick is a
            busy tick, so fire() call N == busy tick N."""

            slots = 2

            def __init__(self):
                self.packed = {}

            def pack(self, idx, example):
                self.packed[idx] = example

            def step(self):
                return []

            def unpack(self, idx, example):  # pragma: no cover
                return DecodedResult(uuid=example.uuid, article="",
                                     decoded_words=[], reference="",
                                     abstract_sents=[])

            def release(self, idx):
                self.packed.pop(idx, None)

        class StubDec:
            def maybe_reload_checkpoint(self, last):
                return last

        server = ServingServer(hps, vocab, decoder=StubDec(),
                               engine=NeverFinishEngine(), registry=reg)
        with server:
            fut = server.submit("the cat sat", uuid="u0")
            with pytest.raises(RuntimeError, match="injected serve.dispatch"):
                fut.result(timeout=60)
        dump = tmp_path / "s" / "flight_serve_dispatch.jsonl"
        assert dump.exists()
        lines = [json.loads(ln) for ln in open(dump, encoding="utf-8")]
        header, frames = lines[0], lines[1:]
        assert header["reason"] == "serve_dispatch"
        assert header["context"] == {"error": "RuntimeError"}
        # the full configured ring, recorded strictly before the trigger
        assert len(frames) == 4 == header["capacity"]
        assert all(f["kind"] == "serve_tick" for f in frames)
        ticks = [f["tick"] for f in frames]
        assert ticks == sorted(ticks)
        assert ticks == list(range(ticks[0], ticks[0] + 4))  # consecutive
        assert all(f["occupancy"] == 0.5 and f["refills"] in (0, 1)
                   for f in frames)
        assert server._faults.stats()["serve.dispatch"]["fires"] == 1
