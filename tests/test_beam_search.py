"""On-device beam search vs a Python mirror of the reference algorithm.

The mirror re-implements /root/reference beam_search.py's hypothesis
bookkeeping (list-of-Hypothesis, sort by avg log prob, STOP/min_dec_steps
triage, 2*beam expansion, step-0 single-hyp expansion) on the host, calling
the SAME jitted decode_onestep — so any disagreement isolates the
lax.while_loop translation, not the numerics.
"""

import dataclasses

import jax
import numpy as np
import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import START_ID, STOP_ID, UNK_ID
from textsummarization_on_flink_tpu.decode import beam_search
from textsummarization_on_flink_tpu.models import pointer_generator as pg


HPS = HParams(batch_size=2, hidden_dim=8, emb_dim=6, vocab_size=24,
              max_enc_steps=12, max_dec_steps=8, beam_size=3,
              min_dec_steps=2, max_oov_buckets=4, mode="decode")


def make_arrays(hps, seed=0, B=None):
    rng = np.random.RandomState(seed)
    B = B or hps.batch_size
    T = hps.max_enc_steps
    lens = rng.randint(T // 2, T + 1, size=(B,)).astype(np.int32)
    mask = (np.arange(T)[None, :] < lens[:, None]).astype(np.float32)
    enc = (rng.randint(4, hps.vocab_size, size=(B, T)) * mask).astype(np.int32)
    ext = enc.copy()
    oov_pos = (rng.rand(B, T) < 0.15) & (mask > 0)
    ext[oov_pos] = hps.vocab_size + rng.randint(
        0, hps.max_oov_buckets, size=int(oov_pos.sum()))
    return {
        "enc_batch": enc,
        "enc_lens": lens,
        "enc_padding_mask": mask,
        "enc_batch_extend_vocab": ext,
    }


@dataclasses.dataclass
class Hyp:
    tokens: list
    lp: np.float32
    state: tuple  # (c, h) rows
    coverage: np.ndarray

    @property
    def avg(self):
        return self.lp / len(self.tokens)


def python_reference_search(params, hps, arrays, article_idx):
    """beam_search.py:82-168 transliterated, same decode_onestep."""
    one = {k: v[article_idx:article_idx + 1] for k, v in arrays.items()}
    enc = pg.run_encoder(params, hps, one)
    K = hps.beam_size
    T_enc = hps.max_enc_steps
    enc_k = pg.EncoderOutput(
        enc_states=np.broadcast_to(np.asarray(enc.enc_states),
                                   (K,) + enc.enc_states.shape[1:]),
        enc_features=np.broadcast_to(np.asarray(enc.enc_features),
                                     (K,) + enc.enc_features.shape[1:]),
        dec_in_state=None)
    c0 = np.asarray(enc.dec_in_state[0])[0]
    h0 = np.asarray(enc.dec_in_state[1])[0]
    mask_k = np.broadcast_to(one["enc_padding_mask"], (K, T_enc))
    ext_k = np.broadcast_to(one["enc_batch_extend_vocab"], (K, T_enc))
    step_fn = jax.jit(pg.decode_onestep, static_argnames=("hps",))

    hyps = [Hyp([START_ID], np.float32(0.0), (c0, h0),
                np.zeros(T_enc, np.float32)) for _ in range(K)]
    results = []
    steps = 0
    while steps < hps.max_dec_steps and len(results) < K:
        latest = np.array([h.tokens[-1] for h in hyps], np.int32)
        latest = np.where(latest >= hps.vocab_size, UNK_ID, latest)
        state = (np.stack([h.state[0] for h in hyps]),
                 np.stack([h.state[1] for h in hyps]))
        cov = np.stack([h.coverage for h in hyps])
        out = step_fn(params, hps, enc_k, mask_k, ext_k, latest, state, cov)
        topk_ids = np.asarray(out.topk_ids)
        topk_lp = np.asarray(out.topk_log_probs, np.float32)
        new_c = np.asarray(out.state[0])
        new_h = np.asarray(out.state[1])
        new_cov = np.asarray(out.coverage)

        all_hyps = []
        num_orig = 1 if steps == 0 else len(hyps)
        for i in range(num_orig):
            for j in range(2 * K):
                all_hyps.append(Hyp(
                    hyps[i].tokens + [int(topk_ids[i, j])],
                    np.float32(hyps[i].lp + topk_lp[i, j]),
                    (new_c[i], new_h[i]), new_cov[i]))
        hyps = []
        for h in sorted(all_hyps, key=lambda h: h.avg, reverse=True):
            if h.tokens[-1] == STOP_ID:
                if steps >= hps.min_dec_steps:
                    results.append(h)
            else:
                hyps.append(h)
            if len(hyps) == K or len(results) == K:
                break
        steps += 1
    if not results:
        results = hyps
    best = sorted(results, key=lambda h: h.avg, reverse=True)[0]
    return best


@pytest.fixture(scope="module")
def params():
    return pg.init_params(HPS, HPS.vocab_size, jax.random.PRNGKey(42))


@pytest.mark.parametrize("beam_size", [1, None])  # 1 = greedy degenerate
@pytest.mark.parametrize("coverage", [False, True])
@pytest.mark.parametrize("seed", [0, 7])
def test_matches_python_reference(params, coverage, seed, beam_size):
    hps = HPS.replace(coverage=coverage)
    if beam_size is not None:
        hps = hps.replace(beam_size=beam_size)
    arrays = make_arrays(hps, seed=seed)
    out = beam_search.run_beam_search(params, hps, arrays)
    for b in range(hps.batch_size):
        ref = python_reference_search(params, hps, arrays, b)
        n = int(out.length[b])
        got = list(out.tokens[b][:n])
        assert got == ref.tokens, (b, got, ref.tokens)
        np.testing.assert_allclose(out.avg_log_prob[b], ref.avg,
                                   rtol=2e-5, atol=2e-6)


def test_output_invariants(params):
    arrays = make_arrays(HPS, seed=3)
    out = beam_search.run_beam_search(params, HPS, arrays)
    B = HPS.batch_size
    assert out.tokens.shape == (B, HPS.max_dec_steps + 1)
    assert out.attn_dists.shape == (B, HPS.max_dec_steps, HPS.max_enc_steps)
    assert out.p_gens.shape == (B, HPS.max_dec_steps)
    for b in range(B):
        n = int(out.length[b])
        toks = out.tokens[b][:n]
        assert toks[0] == START_ID
        assert 2 <= n <= HPS.max_dec_steps + 1
        # every id inside the static extended vocab
        assert toks.max() < HPS.vocab_size + HPS.max_oov_buckets
        if toks[-1] == STOP_ID:
            # STOP accepted only after min_dec_steps generations
            assert n - 2 >= HPS.min_dec_steps
        assert np.isfinite(out.avg_log_prob[b])
        # attention rows for generated steps are distributions over valid pos
        L = int(arrays["enc_lens"][b])
        for t in range(n - 1):
            row = out.attn_dists[b, t]
            np.testing.assert_allclose(row.sum(), 1.0, atol=1e-4)
            assert row[L:].sum() < 1e-6


@pytest.mark.parametrize("kind", ["scan", "chunked"])
@pytest.mark.parametrize("coverage", [False, True])
def test_loop_kinds_match_while_loop(params, coverage, kind):
    """TS_BEAM_LOOP=scan (fixed trip count, masked updates) and =chunked
    (while over scan chunks — early exit at chunk granularity, ceil(T/C)
    dynamic iterations on RPC-proxied backends) must be token-exact with
    the early-exit while_loop."""
    # chunk=3 does NOT divide max_dec_steps: the masked inner scan must
    # make the overshoot a no-op (chunk is a static jit cache-key arg)
    chunk = 3 if kind == "chunked" else None
    hps = HPS.replace(coverage=coverage)
    arrays = make_arrays(hps, seed=5)
    a = beam_search.run_beam_search_jit(params, hps, arrays, loop="while")
    b = beam_search.run_beam_search_jit(params, hps, arrays, loop=kind,
                                        chunk=chunk)
    np.testing.assert_array_equal(np.asarray(a.tokens), np.asarray(b.tokens))
    np.testing.assert_array_equal(np.asarray(a.length), np.asarray(b.length))
    np.testing.assert_allclose(np.asarray(a.avg_log_prob),
                               np.asarray(b.avg_log_prob), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(a.attn_dists),
                               np.asarray(b.attn_dists), atol=1e-6)


def test_min_dec_steps_blocks_early_stop(params):
    # with min_dec_steps == max-1, any STOP before the horizon is discarded,
    # so results are either long or the live-beam fallback
    hps = HPS.replace(min_dec_steps=HPS.max_dec_steps - 1)
    arrays = make_arrays(hps, seed=1)
    out = beam_search.run_beam_search(params, hps, arrays)
    for b in range(hps.batch_size):
        assert int(out.length[b]) >= hps.max_dec_steps
