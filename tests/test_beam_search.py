"""On-device beam search vs a Python mirror of the reference algorithm.

The mirror re-implements /root/reference beam_search.py's hypothesis
bookkeeping (list-of-Hypothesis, sort by avg log prob, STOP/min_dec_steps
triage, 2*beam expansion, step-0 single-hyp expansion) on the host, calling
the SAME jitted decode_onestep — so any disagreement isolates the
lax.while_loop translation, not the numerics.
"""

import dataclasses

import jax
import numpy as np
import pytest

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import START_ID, STOP_ID, UNK_ID
from textsummarization_on_flink_tpu.decode import beam_search
from textsummarization_on_flink_tpu.models import pointer_generator as pg


HPS = HParams(batch_size=2, hidden_dim=8, emb_dim=6, vocab_size=24,
              max_enc_steps=12, max_dec_steps=8, beam_size=3,
              min_dec_steps=2, max_oov_buckets=4, mode="decode")


def make_arrays(hps, seed=0, B=None):
    rng = np.random.RandomState(seed)
    B = B or hps.batch_size
    T = hps.max_enc_steps
    lens = rng.randint(T // 2, T + 1, size=(B,)).astype(np.int32)
    mask = (np.arange(T)[None, :] < lens[:, None]).astype(np.float32)
    enc = (rng.randint(4, hps.vocab_size, size=(B, T)) * mask).astype(np.int32)
    ext = enc.copy()
    oov_pos = (rng.rand(B, T) < 0.15) & (mask > 0)
    ext[oov_pos] = hps.vocab_size + rng.randint(
        0, hps.max_oov_buckets, size=int(oov_pos.sum()))
    return {
        "enc_batch": enc,
        "enc_lens": lens,
        "enc_padding_mask": mask,
        "enc_batch_extend_vocab": ext,
    }


@dataclasses.dataclass
class Hyp:
    tokens: list
    lp: np.float32
    state: tuple  # (c, h) rows
    coverage: np.ndarray

    @property
    def avg(self):
        return self.lp / len(self.tokens)


def python_reference_search(params, hps, arrays, article_idx):
    """beam_search.py:82-168 transliterated, same decode_onestep."""
    one = {k: v[article_idx:article_idx + 1] for k, v in arrays.items()}
    enc = pg.run_encoder(params, hps, one)
    K = hps.beam_size
    T_enc = hps.max_enc_steps
    enc_k = pg.EncoderOutput(
        enc_states=np.broadcast_to(np.asarray(enc.enc_states),
                                   (K,) + enc.enc_states.shape[1:]),
        enc_features=np.broadcast_to(np.asarray(enc.enc_features),
                                     (K,) + enc.enc_features.shape[1:]),
        dec_in_state=None)
    c0 = np.asarray(enc.dec_in_state[0])[0]
    h0 = np.asarray(enc.dec_in_state[1])[0]
    mask_k = np.broadcast_to(one["enc_padding_mask"], (K, T_enc))
    ext_k = np.broadcast_to(one["enc_batch_extend_vocab"], (K, T_enc))
    step_fn = jax.jit(pg.decode_onestep, static_argnames=("hps",))

    hyps = [Hyp([START_ID], np.float32(0.0), (c0, h0),
                np.zeros(T_enc, np.float32)) for _ in range(K)]
    results = []
    steps = 0
    while steps < hps.max_dec_steps and len(results) < K:
        latest = np.array([h.tokens[-1] for h in hyps], np.int32)
        latest = np.where(latest >= hps.vocab_size, UNK_ID, latest)
        state = (np.stack([h.state[0] for h in hyps]),
                 np.stack([h.state[1] for h in hyps]))
        cov = np.stack([h.coverage for h in hyps])
        out = step_fn(params, hps, enc_k, mask_k, ext_k, latest, state, cov)
        topk_ids = np.asarray(out.topk_ids)
        topk_lp = np.asarray(out.topk_log_probs, np.float32)
        new_c = np.asarray(out.state[0])
        new_h = np.asarray(out.state[1])
        new_cov = np.asarray(out.coverage)

        all_hyps = []
        num_orig = 1 if steps == 0 else len(hyps)
        for i in range(num_orig):
            for j in range(2 * K):
                all_hyps.append(Hyp(
                    hyps[i].tokens + [int(topk_ids[i, j])],
                    np.float32(hyps[i].lp + topk_lp[i, j]),
                    (new_c[i], new_h[i]), new_cov[i]))
        hyps = []
        for h in sorted(all_hyps, key=lambda h: h.avg, reverse=True):
            if h.tokens[-1] == STOP_ID:
                if steps >= hps.min_dec_steps:
                    results.append(h)
            else:
                hyps.append(h)
            if len(hyps) == K or len(results) == K:
                break
        steps += 1
    if not results:
        results = hyps
    best = sorted(results, key=lambda h: h.avg, reverse=True)[0]
    return best


@pytest.fixture(scope="module")
def params():
    return pg.init_params(HPS, HPS.vocab_size, jax.random.PRNGKey(42))


@pytest.mark.parametrize("beam_size", [1, None])  # 1 = greedy degenerate
@pytest.mark.parametrize("coverage", [False, True])
@pytest.mark.parametrize("seed", [0, 7])
def test_matches_python_reference(params, coverage, seed, beam_size):
    hps = HPS.replace(coverage=coverage)
    if beam_size is not None:
        hps = hps.replace(beam_size=beam_size)
    arrays = make_arrays(hps, seed=seed)
    out = beam_search.run_beam_search(params, hps, arrays)
    for b in range(hps.batch_size):
        ref = python_reference_search(params, hps, arrays, b)
        n = int(out.length[b])
        got = list(out.tokens[b][:n])
        assert got == ref.tokens, (b, got, ref.tokens)
        np.testing.assert_allclose(out.avg_log_prob[b], ref.avg,
                                   rtol=2e-5, atol=2e-6)


def test_output_invariants(params):
    arrays = make_arrays(HPS, seed=3)
    out = beam_search.run_beam_search(params, HPS, arrays)
    B = HPS.batch_size
    assert out.tokens.shape == (B, HPS.max_dec_steps + 1)
    assert out.attn_dists.shape == (B, HPS.max_dec_steps, HPS.max_enc_steps)
    assert out.p_gens.shape == (B, HPS.max_dec_steps)
    for b in range(B):
        n = int(out.length[b])
        toks = out.tokens[b][:n]
        assert toks[0] == START_ID
        assert 2 <= n <= HPS.max_dec_steps + 1
        # every id inside the static extended vocab
        assert toks.max() < HPS.vocab_size + HPS.max_oov_buckets
        if toks[-1] == STOP_ID:
            # STOP accepted only after min_dec_steps generations
            assert n - 2 >= HPS.min_dec_steps
        assert np.isfinite(out.avg_log_prob[b])
        # attention rows for generated steps are distributions over valid pos
        L = int(arrays["enc_lens"][b])
        for t in range(n - 1):
            row = out.attn_dists[b, t]
            np.testing.assert_allclose(row.sum(), 1.0, atol=1e-4)
            assert row[L:].sum() < 1e-6


@pytest.mark.parametrize("kind", ["scan", "chunked"])
@pytest.mark.parametrize("coverage", [False, True])
def test_loop_kinds_match_while_loop(params, coverage, kind):
    """TS_BEAM_LOOP=scan (fixed trip count, masked updates) and =chunked
    (while over scan chunks — early exit at chunk granularity, ceil(T/C)
    dynamic iterations on RPC-proxied backends) must be token-exact with
    the early-exit while_loop."""
    # chunk=3 does NOT divide max_dec_steps: the masked inner scan must
    # make the overshoot a no-op (chunk is a static jit cache-key arg)
    chunk = 3 if kind == "chunked" else None
    hps = HPS.replace(coverage=coverage)
    arrays = make_arrays(hps, seed=5)
    a = beam_search.run_beam_search_jit(params, hps, arrays, loop="while")
    b = beam_search.run_beam_search_jit(params, hps, arrays, loop=kind,
                                        chunk=chunk)
    np.testing.assert_array_equal(np.asarray(a.tokens), np.asarray(b.tokens))
    np.testing.assert_array_equal(np.asarray(a.length), np.asarray(b.length))
    np.testing.assert_allclose(np.asarray(a.avg_log_prob),
                               np.asarray(b.avg_log_prob), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(a.attn_dists),
                               np.asarray(b.attn_dists), atol=1e-6)


@pytest.mark.parametrize("chunk", [1, 3, 5, 13])
def test_chunked_early_exit_parity_any_chunk(params, chunk):
    """The chunked loop must stay token-exact with the early-exit while
    loop for EVERY tail-chunk shape (ISSUE 6 satellite): chunk=1
    (degenerate — every step a boundary), 3 and 5 (neither divides
    max_dec_steps=8, so the final chunk overshoots the horizon and the
    masked inner scan must no-op the tail), and 13 (> max_dec_steps —
    one chunk covers the whole search).  The slot loop steps the same
    masked chunk body, so this parity is what continuous serving's
    refill boundaries rest on."""
    arrays = make_arrays(HPS, seed=11)
    a = beam_search.run_beam_search_jit(params, HPS, arrays, loop="while")
    b = beam_search.run_beam_search_jit(params, HPS, arrays, loop="chunked",
                                        chunk=chunk)
    np.testing.assert_array_equal(np.asarray(a.tokens), np.asarray(b.tokens))
    np.testing.assert_array_equal(np.asarray(a.length), np.asarray(b.length))
    np.testing.assert_allclose(np.asarray(a.avg_log_prob),
                               np.asarray(b.avg_log_prob), rtol=1e-6)


def test_chunked_parity_when_no_beam_finishes(params):
    """Tail-chunk parity in the no-early-exit regime: min_dec_steps
    near the horizon forces every article through max_dec_steps, so the
    final (partial) chunk runs right up against the masked boundary."""
    hps = HPS.replace(min_dec_steps=HPS.max_dec_steps - 1)
    arrays = make_arrays(hps, seed=4)
    a = beam_search.run_beam_search_jit(params, hps, arrays, loop="while")
    b = beam_search.run_beam_search_jit(params, hps, arrays, loop="chunked",
                                        chunk=3)
    np.testing.assert_array_equal(np.asarray(a.tokens), np.asarray(b.tokens))
    np.testing.assert_array_equal(np.asarray(a.length), np.asarray(b.length))


class TestSlotSearch:
    """The continuous-batching slot kernels (pack/step/unpack over a
    persistent [slots, beam, ...] state) against the batch search:
    identical per-article trajectories, per-slot activity masking, and
    a jit cache that never grows with slot index or occupancy."""

    def _drive(self, params, hps, state, active, chunk, max_chunks=16):
        """Step until every active slot finishes; returns {slot: output,
        ...} plus the number of chunks run."""
        done = {}
        active = np.array(active)
        for n in range(1, max_chunks + 1):
            state, fin = beam_search.step_slots_jit(params, hps, state,
                                                    active, chunk)
            for s in np.nonzero(np.asarray(fin))[0]:
                done[int(s)] = beam_search.unpack_slot_jit(hps, state, int(s))
                active[s] = False
            if not active.any():
                return state, done, n
        raise AssertionError("slots never finished")

    def test_slot_parity_with_batch_search(self, params):
        """Articles packed into arbitrary slots, stepped with a chunk
        that does NOT divide max_dec_steps, finish token-exact with the
        one-dispatch batch search."""
        arrays = make_arrays(HPS, seed=0)
        ref = beam_search.run_beam_search(params, HPS, arrays)
        slots = 3
        zero = {k: np.zeros((slots,) + v.shape[1:], v.dtype)
                for k, v in arrays.items()}
        state = beam_search.init_slots_jit(params, HPS, zero)
        placement = {2: 0, 0: 1}  # slot -> article
        for slot, art in placement.items():
            one = {k: v[art:art + 1] for k, v in arrays.items()}
            state = beam_search.pack_slot_jit(
                params, HPS, state, slot,
                beam_search.prefill_jit(params, HPS, one))
        _, done, _ = self._drive(params, HPS, state,
                                 [True, False, True], chunk=3)
        assert sorted(done) == sorted(placement)
        for slot, art in placement.items():
            out = done[slot]
            n = int(out.length)
            n_ref = int(ref.length[art])
            assert n == n_ref
            assert list(np.asarray(out.tokens)[:n]) == \
                list(ref.tokens[art][:n_ref])
            np.testing.assert_allclose(np.asarray(out.avg_log_prob),
                                       ref.avg_log_prob[art], rtol=1e-6)
            np.testing.assert_allclose(np.asarray(out.attn_dists),
                                       ref.attn_dists[art], atol=1e-6)

    def test_inactive_slots_never_finish_and_refill_is_exact(self, params):
        """An inactive slot's garbage state never reports finished, and
        packing a NEW article into a just-retired slot reproduces that
        article's batch-search result exactly — the refill contract the
        continuous scheduler depends on."""
        arrays = make_arrays(HPS, seed=9)
        ref = beam_search.run_beam_search(params, HPS, arrays)
        slots = 2
        zero = {k: np.zeros((slots,) + v.shape[1:], v.dtype)
                for k, v in arrays.items()}
        state = beam_search.init_slots_jit(params, HPS, zero)
        one = {k: v[0:1] for k, v in arrays.items()}
        state = beam_search.pack_slot_jit(
            params, HPS, state, 1,
            beam_search.prefill_jit(params, HPS, one))
        state, fin = beam_search.step_slots_jit(
            params, HPS, state, np.array([False, True]), 2)
        assert not bool(np.asarray(fin)[0])  # inactive slot stays silent
        # retire slot 1 whenever it finishes, then REFILL it with
        # article 1 and check the second tenancy end to end
        active = np.array([False, True])
        done = {}
        for _ in range(16):
            for s in np.nonzero(np.asarray(fin))[0]:
                done[int(s)] = beam_search.unpack_slot_jit(HPS, state, int(s))
                active[s] = False
            if done:
                break
            state, fin = beam_search.step_slots_jit(params, HPS, state,
                                                    active, 2)
        assert 1 in done
        two = {k: v[1:2] for k, v in arrays.items()}
        state = beam_search.pack_slot_jit(
            params, HPS, state, 1,
            beam_search.prefill_jit(params, HPS, two))
        _, done2, _ = self._drive(params, HPS, state, [False, True], chunk=2)
        out = done2[1]
        n = int(out.length)
        assert list(np.asarray(out.tokens)[:n]) == \
            list(ref.tokens[1][:int(ref.length[1])])

    def test_slot_kernels_compile_once(self, params):
        """Slot index, occupancy pattern, and article content are all
        traced — after the first pack/step/unpack, serving more articles
        through different slots adds ZERO jit-cache entries (the
        'no per-request recompiles' acceptance claim at kernel level)."""
        arrays = make_arrays(HPS, seed=2)
        slots = 3
        zero = {k: np.zeros((slots,) + v.shape[1:], v.dtype)
                for k, v in arrays.items()}
        state = beam_search.init_slots_jit(params, HPS, zero)
        one = {k: v[0:1] for k, v in arrays.items()}
        state = beam_search.pack_slot_jit(
            params, HPS, state, 0,
            beam_search.prefill_jit(params, HPS, one))
        state, _ = beam_search.step_slots_jit(
            params, HPS, state, np.array([True, False, False]), 3)
        beam_search.unpack_slot_jit(HPS, state, 0)
        sizes = {f: f._cache_size()
                 for f in (beam_search.pack_slot_jit,
                           beam_search.step_slots_jit,
                           beam_search.unpack_slot_jit)}
        for slot, art in ((1, 1), (2, 0), (0, 1)):
            nxt = {k: v[art:art + 1] for k, v in arrays.items()}
            state = beam_search.pack_slot_jit(
                params, HPS, state, slot,
                beam_search.prefill_jit(params, HPS, nxt))
        state, _ = beam_search.step_slots_jit(
            params, HPS, state, np.array([True, True, True]), 3)
        beam_search.unpack_slot_jit(HPS, state, 2)
        for f, before in sizes.items():
            assert f._cache_size() == before, f


def test_min_dec_steps_blocks_early_stop(params):
    # with min_dec_steps == max-1, any STOP before the horizon is discarded,
    # so results are either long or the live-beam fallback
    hps = HPS.replace(min_dec_steps=HPS.max_dec_steps - 1)
    arrays = make_arrays(hps, seed=1)
    out = beam_search.run_beam_search(params, hps, arrays)
    for b in range(hps.batch_size):
        assert int(out.length[b]) >= hps.max_dec_steps
