"""Long-context transformer training example.

The reference truncates articles to max_enc_steps=400
(/root/reference/src/main/python/pointer-generator/batcher.py:52-55) —
it has NO long-context capability.  This example shows the rebuild's
long-context stack (SURVEY.md §5.7) on the transformer family:

  * ``--sp_attention=ring`` + ``--sp``: the encoder sequence axis
    shards over the sp mesh ring; K/V blocks rotate via ppermute with an
    online softmax, so a 16k-token article's [T, T] score matrix never
    exists on any single chip (``--sp_attention=ulysses`` instead
    re-shards sequence->heads via all-to-all; parallel/ring_attention.py);
  * ``--remat``: layer activations recompute in backward, keeping HBM
    flat in depth;
  * ``TS_FLASH=auto``: when a single chip CAN hold a block (head_dim
    lane-aligned), self-attention runs the Pallas TPU flash kernel;
  * bf16 compute for every matmul (f32 accumulation on the vocab
    projection).

Run (single host, 8 chips — 2-way data parallel x 4-way sequence
parallel; sequence length 4096 = 10x the reference's cap):

    python examples/longcontext_train.py \
        --data_path='finished_files/train_*.bin' \
        --vocab_path=finished_files/vocab --log_root=log --exp_name=long \
        --model_family=transformer --hidden_dim=512 --num_heads=8 \
        --max_enc_steps=4096 --batch_size=16 --dp=2 --sp=4 \
        --sp_attention=ring --remat=1 --compute_dtype=bfloat16 \
        --num_steps=1000

Smoke-test on CPU with a virtual mesh:

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/longcontext_train.py --smoke
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from textsummarization_on_flink_tpu import cli  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402


SMOKE = [
    "--model_family=transformer", "--hidden_dim=16", "--emb_dim=16",
    "--num_heads=4", "--enc_layers=2", "--dec_layers=2",
    "--max_enc_steps=64", "--max_dec_steps=8", "--vocab_size=64",
    "--max_oov_buckets=8", "--batch_size=4", "--beam_size=2",
    "--min_dec_steps=1", "--dp=2", "--sp=4", "--sp_attention=ring",
    "--remat=1", "--num_steps=2",
]


def main(argv):
    if "--smoke" in argv:
        import tempfile

        import numpy as np

        from textsummarization_on_flink_tpu.data.batcher import Batcher
        from textsummarization_on_flink_tpu.data.vocab import Vocab
        from textsummarization_on_flink_tpu.train import trainer as trainer_lib

        hps = HParams.from_argv(SMOKE)
        hps.validate()
        words = [f"w{i}" for i in range(60)]
        vocab = Vocab(words=words, max_size=hps.vocab_size)

        def src():
            rng = np.random.RandomState(0)
            while True:
                yield (" ".join(rng.choice(words[:40], 40)),
                       "<s> " + " ".join(rng.choice(words[:40], 4))
                       + " . </s>")

        batcher = Batcher("", vocab, hps, single_pass=False,
                          example_source=src)
        tr = trainer_lib.Trainer(hps, vocab.size(), batcher,
                                 train_dir=tempfile.mkdtemp())
        state = tr.train(num_steps=hps.num_steps)
        print(f"longcontext smoke ok: step={int(state.step)} "
              f"(ring sp={hps.sp}, remat={hps.remat})")
        return
    from textsummarization_on_flink_tpu.data.vocab import Vocab

    hps = HParams.from_argv(argv).replace(mode="train")
    hps.validate()
    vocab = Vocab(hps.vocab_path, hps.vocab_size)
    cli.setup_training(hps, vocab)


if __name__ == "__main__":
    main(sys.argv[1:])
