"""End-to-end streaming pipeline example: fit -> serve in ONE process.

The reference needed two sequential Flink jobs for this (one-TFUtils-call-
per-job blocker, doc/Flink-AI-Extended Integration Report.md:9,260-282;
App.java:202-207 runs startTraining then startInference).  Here the same
flow — train from a stream of (uuid, article, summary, reference) rows,
persist the model as config-only JSON, then serve summaries from a second
stream with per-record flushing — is one script, mirroring
TensorFlowTest.testInferenceAfterTraining (TensorFlowTest.java:68-91) on
the same 8 synthetic rows (TensorFlowTest.java:204-217).

Run on anything (CPU works; tiny model so it finishes in ~a minute):

    python examples/serving_pipeline.py

Swap CollectionSource/CollectionSink for KafkaSource/KafkaSink (topics
flink_train / flink_input / flink_output) to reproduce the reference's
Kafka topology, or SocketSource for testInferenceFromSocket.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import tempfile  # noqa: E402

from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.pipeline import app as app_lib  # noqa: E402
from textsummarization_on_flink_tpu.pipeline.io import (  # noqa: E402
    CollectionSink,
    CollectionSource,
)


def synthetic_rows(n=8):
    """TensorFlowTest.createArticleData(): (uuid, article, summary,
    reference) rows, uuid-i / 'article i.'."""
    return [(f"uuid-{i}", f"article {i} .", "", f"reference {i} .")
            for i in range(n)]


def main():
    log_root = tempfile.mkdtemp(prefix="serving_pipeline_")
    vocab = Vocab(words=["article", "reference", ".", "0", "1", "2", "3",
                         "4", "5", "6", "7"])
    tiny = dict(hidden_dim=16, emb_dim=8, vocab_size=vocab.size(),
                max_enc_steps=16, max_dec_steps=6, beam_size=2,
                min_dec_steps=1, max_oov_buckets=4, batch_size=2,
                log_root=log_root, exp_name="serve")
    # num_steps=0 = train until the bounded stream is exhausted — the 8
    # rows at batch 2 yield exactly 4 steps (the reference's
    # testInferenceAfterTraining trains on the same bounded stream)
    app = app_lib.App(
        train_hps=HParams(mode="train", num_steps=0, **tiny),
        inference_hps=HParams(mode="decode", **tiny),
        vocab=vocab)

    model_json = app.start_training(CollectionSource(synthetic_rows()))
    print(f"model JSON (config-only, weights live in {log_root}):")
    print(f"  {model_json[:120]}...")

    sink = app.start_inference(model_json,
                               source=CollectionSource(synthetic_rows(4)),
                               sink=CollectionSink())
    for uuid, article, summary, reference in sink.rows:
        print(f"  {uuid}: {article!r} -> {summary!r}")
    assert len(sink.rows) == 4

    # same job, concurrent path (SERVING.md): the ServingServer
    # micro-batches the stream through the admission-controlled queue;
    # rows land in completion order, uuid-keyed
    from textsummarization_on_flink_tpu import obs  # noqa: E402

    sink2 = app.start_inference(model_json,
                                source=CollectionSource(synthetic_rows(8)),
                                sink=CollectionSink(), serving=True)
    assert len(sink2.rows) == 8
    assert {r[0] for r in sink2.rows} == {f"uuid-{i}" for i in range(8)}
    fill = obs.registry().histogram("serve/batch_fill")
    print(f"serving path: {len(sink2.rows)} rows over "
          f"{fill.count} micro-batch(es), mean fill {fill.mean:.1f}")
    print("OK")


if __name__ == "__main__":
    main()
