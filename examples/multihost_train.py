"""Multi-host distributed training example.

Replaces the reference's cluster recipe — ZooKeeper + TF1 PS/worker
(SURVEY.md §2.6) — with the jax.distributed + SPMD mesh stack. Launch the
SAME script on every host of a TPU pod slice:

    # managed TPU environments auto-detect everything:
    python examples/multihost_train.py --data_path=... --vocab_path=... \
        --log_root=gs://bucket/log --exp_name=pod --dp=32 --batch_size=512

    # manual bring-up (the reference's zookeeper_connect_str + worker
    # index, HasClusterConfig.java:15-29) maps to:
    COORD=10.0.0.2:8476 NPROC=4 PROC_ID=0 python examples/multihost_train.py ...

The hps mesh axes (dp/tp/sp) span the GLOBAL device set: with 4 hosts x 8
chips, --dp=32 data-shards the batch over every chip and XLA all-reduces
gradients over ICI/DCN. Only the chief (process 0) writes checkpoints.

Smoke-test on CPU (single process, virtual 8-chip mesh, dp=8):

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/multihost_train.py --smoke

(The REAL 2-process rendezvous path is exercised by
tests/test_multiprocess.py.)
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from textsummarization_on_flink_tpu import cli  # noqa: E402
from textsummarization_on_flink_tpu.config import HParams  # noqa: E402
from textsummarization_on_flink_tpu.data.vocab import Vocab  # noqa: E402
from textsummarization_on_flink_tpu.parallel import distributed  # noqa: E402


SMOKE = [
    "--hidden_dim=16", "--emb_dim=16", "--max_enc_steps=16",
    "--max_dec_steps=8", "--vocab_size=64", "--max_oov_buckets=8",
    "--batch_size=8", "--beam_size=2", "--min_dec_steps=1", "--dp=8",
    "--num_steps=2", "--checkpoint_steps=0",
]


def main(argv):
    if "--smoke" in argv:
        import tempfile

        import numpy as np

        from textsummarization_on_flink_tpu.data.batcher import Batcher
        from textsummarization_on_flink_tpu.train import trainer as trainer_lib

        distributed.initialize()  # single process: no-op rendezvous
        hps = HParams.from_argv(SMOKE).replace(mode="train")
        hps.validate()
        words = [f"w{i}" for i in range(60)]
        vocab = Vocab(words=words, max_size=hps.vocab_size)

        def src():
            rng = np.random.RandomState(0)
            while True:
                yield (" ".join(rng.choice(words[:40], 12)),
                       "<s> " + " ".join(rng.choice(words[:40], 4))
                       + " . </s>")

        batcher = Batcher("", vocab, hps, single_pass=False,
                          example_source=src)
        tr = trainer_lib.Trainer(hps, vocab.size(), batcher,
                                 train_dir=tempfile.mkdtemp())
        state = tr.train(num_steps=hps.num_steps)
        if distributed.is_chief():
            print(f"multihost smoke ok: step={int(state.step)} "
                  f"(dp={hps.dp} over {len(__import__('jax').devices())} "
                  f"devices)")
        return
    distributed.initialize(
        coordinator_address=os.environ.get("COORD"),
        num_processes=(int(os.environ["NPROC"])
                       if "NPROC" in os.environ else None),
        process_id=(int(os.environ["PROC_ID"])
                    if "PROC_ID" in os.environ else None))
    hps = HParams.from_argv(argv).replace(mode="train")
    hps.validate()
    vocab = Vocab(hps.vocab_path, hps.vocab_size)
    # every host runs the same SPMD program; Trainer builds the global
    # (dp, tp, sp) mesh from hps and pjits the step over it
    state = cli.setup_training(hps, vocab)
    if distributed.is_chief():
        print(f"trained to step {int(state.step)}")
    distributed.barrier("train-done")


if __name__ == "__main__":
    main(sys.argv[1:])
