"""Benchmark harness: pointer-generator training throughput on TPU.

The reference publishes no numbers (BASELINE.md); its train loop is
instrumented but CPU-bound TF1 (graph pinned to /cpu:0, model.py:313).  The
operative anchor is the See et al. setup the pretrained checkpoint came
from: 230k iterations at batch 16 in "3 days 4 hours" on a single Tesla
K40m GPU (pointer-generator README) ≈ 0.84 steps/s ≈ 13.5 samples/sec —
that is the `vs_baseline` denominator.

Prints ONE JSON line:
  {"metric": "train_samples_per_sec", "value": N, "unit": "samples/s",
   "vs_baseline": N}

Config: the reference default training scale (hidden 256, emb 128,
vocab 50k, enc 400, dec 100, batch 16, Adagrad lr .15) with bf16 MXU
compute.  Synthetic token data (dataset IO is benched separately in
tests); timing excludes compilation (warmup steps) and uses
block_until_ready.

Env overrides: BENCH_STEPS (default 20), BENCH_WARMUP (3), BENCH_BATCH
(16 — per chip).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.train import trainer as trainer_lib
    from __graft_entry__ import _example_arrays

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    batch = int(os.environ.get("BENCH_BATCH", "16"))

    hps = HParams(batch_size=batch, compute_dtype="bfloat16")

    state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=0)
    step_fn = jax.jit(trainer_lib.make_train_step(hps), donate_argnums=0)
    arrays = _example_arrays(hps, np.random.RandomState(0))
    arrays = jax.device_put(arrays)

    for _ in range(warmup):
        state, metrics = step_fn(state, arrays)
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, arrays)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    loss = float(metrics.loss)
    if not np.isfinite(loss):
        print(json.dumps({"metric": "train_samples_per_sec", "value": 0.0,
                          "unit": "samples/s", "vs_baseline": 0.0,
                          "error": f"non-finite loss {loss}"}))
        sys.exit(1)

    # the un-sharded jit runs on exactly one chip, so the measured
    # throughput IS the per-chip number
    samples_per_sec = steps * batch / dt
    per_chip = samples_per_sec
    baseline = 13.5  # single-GPU K40m anchor, see module docstring
    print(json.dumps({
        "metric": "train_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(per_chip / baseline, 2),
    }))


if __name__ == "__main__":
    main()
