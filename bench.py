"""Benchmark harness: pointer-generator training throughput on TPU.

The reference publishes no numbers (BASELINE.md); its train loop is
instrumented but CPU-bound TF1 (graph pinned to /cpu:0, model.py:313).  The
operative anchor is the See et al. setup the pretrained checkpoint came
from: 230k iterations at batch 16 in "3 days 4 hours" on a single Tesla
K40m GPU (pointer-generator README) ≈ 0.84 steps/s ≈ 13.5 samples/sec —
that is the `vs_baseline` denominator.

Prints ONE JSON line:
  {"metric": "train_samples_per_sec", "value": N, "unit": "samples/s",
   "vs_baseline": N}

Config: the reference default training scale (hidden 256, emb 128,
vocab 50k, enc 400, dec 100, batch 16, Adagrad lr .15) with bf16 MXU
compute.  Synthetic token data (dataset IO is benched separately in
tests); timing excludes compilation (warmup steps) and uses
block_until_ready.

Env overrides: BENCH_STEPS (default 20), BENCH_WARMUP (3), BENCH_BATCH
(16 — per chip).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def main() -> None:
    import jax

    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.train import trainer as trainer_lib
    from __graft_entry__ import _example_arrays

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    warmup = int(os.environ.get("BENCH_WARMUP", "3"))
    batch = int(os.environ.get("BENCH_BATCH", "16"))

    hps = HParams(batch_size=batch, compute_dtype="bfloat16",
                  **_preset_overrides())

    state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=0)
    step_fn = jax.jit(trainer_lib.make_train_step(hps), donate_argnums=0)
    arrays = _example_arrays(hps, np.random.RandomState(0))
    arrays = jax.device_put(arrays)

    for _ in range(warmup):
        state, metrics = step_fn(state, arrays)
    jax.block_until_ready(state.params)

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step_fn(state, arrays)
    jax.block_until_ready(state.params)
    dt = time.perf_counter() - t0

    loss = float(metrics.loss)
    if not np.isfinite(loss):
        print(json.dumps({"metric": "train_samples_per_sec", "value": 0.0,
                          "unit": "samples/s", "vs_baseline": 0.0,
                          "error": f"non-finite loss {loss}"}))
        sys.exit(1)

    # the un-sharded jit runs on exactly one chip, so the measured
    # throughput IS the per-chip number
    samples_per_sec = steps * batch / dt
    per_chip = samples_per_sec
    baseline = 13.5  # single-GPU K40m anchor, see module docstring
    print(json.dumps({
        "metric": "train_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(per_chip / baseline, 2),
    }))


def _preset_overrides() -> dict:
    """BENCH_PRESET=tiny shrinks the model for smoke runs (full-scale
    beam-search compiles take minutes on CPU); default is the reference
    scale."""
    if os.environ.get("BENCH_PRESET") == "tiny":
        return dict(hidden_dim=16, emb_dim=8, vocab_size=200,
                    max_enc_steps=32, max_dec_steps=8, beam_size=2,
                    min_dec_steps=1, max_oov_buckets=8)
    return {}


def bench_decode() -> None:
    """Secondary benchmark (BENCH_MODE=decode): batched beam-search decode
    latency at the reference serving config (batch 4, enc 400, dec 100,
    beam 4, TensorFlowTest.java:40-53).  The reference pays ~100 feed_dict
    round trips per article (SURVEY §3.4); here a batch of articles is one
    device dispatch."""
    import jax

    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.decode import beam_search
    from textsummarization_on_flink_tpu.models import pointer_generator as pg
    from __graft_entry__ import _example_arrays

    iters = int(os.environ.get("BENCH_STEPS", "10"))
    batch = int(os.environ.get("BENCH_BATCH", "4"))
    hps = HParams(batch_size=batch, mode="decode", coverage=True,
                  **_preset_overrides())
    params = pg.init_params(hps, hps.vocab_size, jax.random.PRNGKey(0))
    arrays = _example_arrays(hps, np.random.RandomState(0))
    arrays = {k: v for k, v in arrays.items()
              if not k.startswith(("dec_", "target_"))}
    arrays = jax.device_put(arrays)

    out = beam_search.run_beam_search_jit(params, hps, arrays)  # compile
    jax.block_until_ready(out.tokens)
    lat = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = beam_search.run_beam_search_jit(params, hps, arrays)
        jax.block_until_ready(out.tokens)
        lat.append((time.perf_counter() - t0) / batch)
    p50 = sorted(lat)[len(lat) // 2]
    print(json.dumps({
        "metric": "beam_decode_p50_latency_per_article",
        "value": round(p50 * 1000, 2),
        "unit": "ms",
        "vs_baseline": 0.0,  # the reference publishes no decode latency
    }))


if __name__ == "__main__":
    if os.environ.get("BENCH_MODE", "train") == "decode":
        bench_decode()
    else:
        main()
