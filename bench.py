"""Benchmark harness: pointer-generator throughput/latency/MFU on TPU.

The reference publishes no numbers (BASELINE.md); its train loop is
instrumented but CPU-bound TF1 (graph pinned to /cpu:0, model.py:313,
per-step timing at run_summarization.py:223-226).  The operative anchor
is the See et al. setup the pretrained checkpoint came from: 230k
iterations at batch 16 in "3 days 4 hours" on a single Tesla K40m GPU
(pointer-generator README) = 0.84 steps/s = 13.5 samples/sec — that is
the `vs_baseline` denominator for training throughput.

Prints ONE JSON line on stdout, e.g.
  {"metric": "train_samples_per_sec", "value": N, "unit": "samples/s",
   "vs_baseline": N, "mfu": M, ...}

Tunnel-proofing: the TPU behind the `axon` plugin can hang jax import
indefinitely when its tunnel is down.  The default entry is therefore a
SUPERVISOR that re-execs this file as a child process with a bounded
per-attempt timeout and a couple of retries; on final failure it still
prints one parseable JSON line with an "error" field (never a raw
traceback on stdout).  The child (TS_BENCH_CHILD=1) does the real work.

Modes (BENCH_MODE):
  train (default) — jitted train-step throughput + analytic-FLOPs MFU.
  trainer         — END-TO-END Trainer.train() throughput: threaded
                    batcher + DevicePrefetcher + multi-step dispatch
                    (BENCH_SPD) + windowed metric fetches.  The gap to
                    `train` is the host-side overhead.
  decode          — batched on-device beam search: p50/p99 latency per
                    article + decoded tokens/sec.  (The reference pays
                    ~100 feed_dict round-trips per article, SURVEY §3.4.)
  attention       — A/B the fused Pallas additive-attention kernel vs the
                    XLA formula at reference scale and long-context scale.
  flash           — A/B the transformer's Pallas flash self-attention vs
                    the einsum formula (fwd+bwd) at T=BENCH_FLASH_T
                    (default 2048), head_dim 128.  TPU only.
  input           — host-side input-pipeline throughput: the threaded
                    bucketing Batcher packing synthetic reference-scale
                    articles into static-shape batches (no TPU; compare
                    against the device's train samples/s).
  serve           — concurrent serving (SERVING.md): BENCH_SERVE_REQS
                    requests from BENCH_SERVE_CONCURRENCY submitter
                    threads through ServingServer's admission queue;
                    p50/p99 END-TO-END latency (enqueue -> future
                    resolved, queue wait included), mean batch fill /
                    slot occupancy, and requests/sec.  `python bench.py
                    --serve` is shorthand for BENCH_MODE=serve;
                    `--serve-short-ratio=0.875` the bimodal mix's
                    short-request fraction (BENCH_SERVE_SHORT_RATIO;
                    fingerprinted only when non-default — ISSUE 11's
                    disaggregation axis);
                    `--serve-mode=continuous|microbatch` picks the
                    dispatch engine (BENCH_SERVE_MODE) and
                    `--serve-mix=bimodal` the seeded short/long article
                    mix (BENCH_SERVE_MIX) — the straggler workload the
                    continuous engine exists for;
                    `--serve-tier=beam|greedy|spec|draft`
                    (BENCH_SERVE_TIER, microbatch only) benches one
                    quality tier — spec rows carry measured acceptance
                    rate + the implied expected speedup (SERVING.md
                    "Quality tiers");
                    `--serve-replicas=N` (BENCH_SERVE_REPLICAS, with
                    `--serve-hedge-ms` / BENCH_SERVE_HEDGE_MS) routes
                    the load through the ISSUE-13 FleetRouter over N
                    in-process replicas — fleet rows carry hedge
                    spend/wins and requeue counts (SERVING.md "Elastic
                    fleet") and fingerprint their topology;
                    `--serve-zipf=S` (BENCH_SERVE_ZIPF) draws requests
                    zipf-distributed (p(k) ~ 1/(k+1)^S) over a pool of
                    distinct articles and arms the ISSUE-14 front door
                    (coalescing + the summary cache, capacity
                    BENCH_SERVE_CACHE) — the heavy-tailed trending-
                    article workload (SERVING.md "Front door");
                    fingerprint axis only when non-default;
                    `--serve-hier[=N]` (BENCH_SERVE_HIER, with
                    BENCH_HIER_CHUNKS / BENCH_HIER_APPEND) swaps in
                    the ISSUE-19 long-document map-reduce workload —
                    the row carries the fan-out makespan vs a
                    sequential per-chunk baseline plus the append
                    pass's cache_hit_rate (SERVING.md "Hierarchical
                    summarization"); fingerprint axis only when armed.
                    `--serve-arena-pages=N` (BENCH_SERVE_ARENA_PAGES)
                    runs the continuous engine over the ISSUE-20 paged
                    resident state — an N-page block-granular arena
                    (SERVING.md "Paged resident state"); fingerprint
                    axis only when armed.
                    Every serve row carries `cache_hit_rate`,
                    `coalesced_total`, `decodes_per_submit` (1.0
                    with the door dark — each submit decodes),
                    `arena_fill_mean`, and
                    `resident_bytes_per_slot_mean` (the provisioned
                    dense worst case on unarmed rows).
  bytes           — XLA cost-analysis byte accounting for the train
                    step (no execution; CPU-forced like input mode):
                    bytes accessed + intensity for the baseline config
                    and each byte-diet lever (--loss_chunk streaming
                    loss, bf16 optimizer state, both), with per-lever
                    reduction ratios.  Also emits decode rows (ISSUE 7,
                    PERF.md "Decode byte diet"): bytes per emitted
                    token + peak temp of the compiled beam search, per
                    loop kind and for one slot-kernel chunk
                    (BENCH_DECODE_CHUNK, default 25).  The
                    CPU-verifiable side of the PERF.md byte-diet claims.

Env overrides: BENCH_STEPS (20), BENCH_BATCH (16),
BENCH_PRESET=tiny|scaled (smoke scale / the BASELINE configs[3]
hidden-512 enc-800 shape), BENCH_FAMILY=transformer (bench the
second model family), BENCH_FLASH_T (flash-mode sequence length),
BENCH_SPD (trainer-mode steps_per_dispatch, 8), BENCH_UNROLL
(scan_unroll override), BENCH_LOSS_CHUNK (streaming-loss chunk; train/
trainer/bytes modes), BENCH_OPT_DTYPE (Adagrad accumulator storage
dtype), BENCH_TIMEOUT (600s per attempt),
BENCH_ATTEMPTS (2), BENCH_PLATFORM=cpu (force CPU child for smoke
runs), BENCH_PEAK_TFLOPS (override the per-chip bf16 peak used for
MFU).

Timing methodology: the TPU is reached through a tunnel with a ~10s-100s
of ms host<->device round trip, and `jax.block_until_ready` has been
observed to return EARLY for donated/aliased buffers on the axon
backend.  So (a) the only fence this file trusts is a D2H fetch of a
scalar that data-depends on the timed computation, and (b) the train /
attention / flash measurement loops run ON DEVICE (lax.scan /
lax.fori_loop around the op, one dispatch for the whole loop, iterations
chained through a tiny data-dependent carry so XLA cannot hoist the
body).  decode keeps a host-side per-iteration loop — its p50/p99
latency samples need individual timings, so each sample includes one
dispatch.  Decode reports RAW wall-clock percentiles as the headline
(what a client of this backend observes; immune to RTT-estimate noise)
plus RTT-corrected ones (`*_rtt_corrected_ms`, the device-side
estimate) side by side; the fetch cost on a ready buffer is reported
as `tunnel_rtt_ms`.
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

import numpy as np

# single-GPU K40m training anchor (See et al. setup: 230k iterations at
# batch 16 in "3 days 4 hours" = 13.5 samples/s — module docstring); the
# vs_baseline denominator everywhere
BASELINE_SAMPLES_PER_SEC = 13.5

_METRIC_BY_MODE = {
    "train": "train_samples_per_sec",
    "trainer": "trainer_e2e_samples_per_sec",
    "decode": "beam_decode_p50_latency_per_article",
    "attention": "attention_pallas_speedup_vs_xla",
    "flash": "flash_attention_speedup_vs_xla",
    "input": "input_pipeline_samples_per_sec",
    "serve": "serve_e2e_p50_latency_ms",
    "bytes": "train_step_bytes_accessed",
}


# --------------------------------------------------------------------------
# supervisor
# --------------------------------------------------------------------------

def _child_env() -> dict:
    from __graft_entry__ import (
        set_default_compile_cache,
        strip_tpu_plugin_paths,
    )

    env = dict(os.environ)
    env["TS_BENCH_CHILD"] = "1"
    if _obs_snapshot_requested():
        # --obs-snapshot: the child embeds an obs registry dump in its
        # result row (argv is not forwarded to the re-exec'd child, so
        # the flag rides the environment)
        env["TS_OBS_SNAPSHOT"] = "1"
    repo_root = os.path.dirname(os.path.abspath(__file__))
    set_default_compile_cache(env)
    if env.get("BENCH_MODE") in ("input", "bytes"):
        # host-only modes (bytes = XLA cost analysis, backend-portable by
        # design): never let a down TPU tunnel hang the child
        env["BENCH_PLATFORM"] = "cpu"
    if env.get("BENCH_MODE") == "decode":
        # pin the child's loop kind to the fingerprint's resolution (see
        # _resolved_beam_loop): the measured program and the banked
        # record's beam_loop axis can never diverge
        env["TS_BEAM_LOOP"] = _resolved_beam_loop()
    if env.get("BENCH_PLATFORM", "").lower() == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("JAX_PLATFORM_NAME", None)
        pypath = strip_tpu_plugin_paths(env.get("PYTHONPATH", ""))
        env["PYTHONPATH"] = os.pathsep.join([repo_root] + pypath)
    return env


def _resolved_beam_loop() -> str:
    """The decode loop kind a BENCH_MODE=decode child will actually run,
    resolved jax-free in the supervisor (importing jax here can hang
    when the axon tunnel is down): an explicit TS_BEAM_LOOP wins;
    otherwise forced-cpu children are direct-attached -> 'chunked', and
    any other platform in this deployment reaches the device through
    the RPC-proxied axon plugin -> 'scan' (beam_search._loop_kind's
    ladder).  _child_env PINS the child's TS_BEAM_LOOP to this value,
    so the fingerprint and the executed kind agree by construction —
    the child never falls back to its own backend probe (whose
    failure path resolves 'while') out from under the fingerprint."""
    loop = (os.environ.get("TS_BEAM_LOOP", "auto") or "auto").lower()
    if loop != "auto":
        return loop
    platform = (os.environ.get("BENCH_PLATFORM", "").lower() or "tpu")
    return "chunked" if platform == "cpu" else "scan"


def _env_flag(name: str) -> bool:
    """Boolean env knob: '1'/'on'/'true'/'yes' enable (so '=0' really
    disables — raw truthiness would read '0' as on)."""
    return os.environ.get(name, "").lower() in ("1", "on", "true", "yes")


def _bench_mesh() -> tuple:
    """BENCH_MESH="dpXtp" (e.g. "4x2") -> (dp, tp); (1, 1) when unset.
    Jax-free (the supervisor's fingerprint parses it with the tunnel
    down); a malformed spec fails loudly here, at config time."""
    spec = os.environ.get("BENCH_MESH", "").strip().lower()
    if not spec:
        return (1, 1)
    try:
        dp, tp = (int(x) for x in spec.split("x"))
    except ValueError:
        raise ValueError(
            f"BENCH_MESH must be 'dpXtp' (e.g. '4x2'), got {spec!r}"
        ) from None
    if dp < 1 or tp < 1:
        raise ValueError(f"BENCH_MESH axes must be >= 1, got {spec!r}")
    return (dp, tp)


def _obs_snapshot_requested() -> bool:
    """`python bench.py --obs-snapshot` (or TS_OBS_SNAPSHOT=1): embed a
    compact obs registry dump in the result row so the BENCH trajectory
    carries telemetry (OBSERVABILITY.md)."""
    return "--obs-snapshot" in sys.argv[1:] or _env_flag("TS_OBS_SNAPSHOT")


def _obs_extra() -> dict:
    """The child-side snapshot payload ({} when not requested).  Compact:
    untouched metrics are dropped, so a train row carries the train-layer
    metrics only."""
    if not _obs_snapshot_requested():
        return {}
    from textsummarization_on_flink_tpu import obs

    return {"obs_snapshot": obs.snapshot(compact=True)}


_BIMODAL_POOL = 32  # articles in the generated bimodal mix (bench_serve)


def _bimodal_long_every(short_ratio: float) -> int:
    """The bimodal mix's long-article cadence for a requested short
    fraction: every long_every-th request is long."""
    return max(2, round(1.0 / (1.0 - short_ratio)))


def _effective_short_ratio(short_ratio: float) -> float:
    """The short fraction the generated _BIMODAL_POOL-article mix
    ACTUALLY has: the cadence quantizes the request (0.6 -> every 2nd
    long -> a 0.5 mix) AND the finite pool quantizes the cadence
    (longs sit at indices 0, le, 2le, ... < pool, so 0.8 -> le=5 -> 7
    longs of 32 -> 0.7812).  Both the published row and the
    fingerprint must carry the workload that ran, not the one that was
    asked for — otherwise two asks that generate the identical article
    list (e.g. any cadence > pool places exactly one long) would carry
    different fingerprints and one measured mix could stand in for
    another."""
    n_long = -(-_BIMODAL_POOL // _bimodal_long_every(short_ratio))
    return round(1.0 - n_long / _BIMODAL_POOL, 4)


def _config_fingerprint() -> dict:
    """The config axes that distinguish one sweep row from another, as
    seen from the environment.  Successful records embed this; the stale
    fallback matches on it so e.g. a batch-64 record can never stand in
    for the default batch-16 ask."""
    mode = os.environ.get("BENCH_MODE", "train")
    fp = {"mode": mode}
    # a CPU smoke record must never stand in for a TPU ask (or vice
    # versa); input/bytes modes are host-only by construction
    if mode in ("input", "bytes"):
        fp["platform"] = "cpu"
    else:
        fp["platform"] = (os.environ.get("BENCH_PLATFORM", "").lower()
                          or "tpu")
    mesh = _bench_mesh()
    if mesh != (1, 1):
        # sharded-mesh axis (ISSUE 8): a dp x tp measurement is a
        # different compiled program (registry-driven collectives) and
        # must never stand in for a single-device ask.  Added only when
        # non-default so pre-existing banked records (no such key) keep
        # matching default asks.
        fp["mesh"] = f"{mesh[0]}x{mesh[1]}"
    if mode in ("train", "trainer"):
        # byte-diet lever axes (ISSUE 5): each is a DIFFERENT compiled
        # program, so rows must never cross-substitute.  Added only when
        # non-default so pre-existing banked records (no such keys) keep
        # matching default asks.
        chunk = int(os.environ.get("BENCH_LOSS_CHUNK", "0"))
        if chunk:
            fp["loss_chunk"] = chunk
        opt_dtype = os.environ.get("BENCH_OPT_DTYPE", "") or "float32"
        if opt_dtype != "float32":
            fp["opt_dtype"] = opt_dtype
    if mode == "bytes":
        # the bytes child sweeps the opt-dtype lever internally (and
        # BENCH_LOSS_CHUNK only picks the swept chunk size, carried as
        # "chunk"), so neither train-mode lever axis applies here — a
        # duplicate axis would split identical records across
        # fingerprints and defeat incremental banking
        fp["batch"] = int(os.environ.get("BENCH_BATCH", "16"))
        fp["preset"] = os.environ.get("BENCH_PRESET", "ref") or "ref"
        fp["family"] = (os.environ.get("BENCH_FAMILY", "")
                        or "pointer_generator")
        fp["chunk"] = int(os.environ.get("BENCH_LOSS_CHUNK", "25"))
        # remat/unroll reach the compiled programs via _preset_overrides
        # (e.g. an exported BENCH_REMAT=1 from a sweep): different
        # programs, different record — same rule as train mode
        fp["remat"] = _env_flag("BENCH_REMAT")
        if os.environ.get("BENCH_UNROLL"):
            fp["unroll"] = int(os.environ["BENCH_UNROLL"])
        # the decode rows' slot/chunked programs change with the chunk
        # length; non-default only, so banked records keep matching
        if int(os.environ.get("BENCH_DECODE_CHUNK", "25")) != 25:
            fp["decode_chunk"] = int(os.environ["BENCH_DECODE_CHUNK"])
    if mode in ("train", "trainer", "decode"):
        fp["batch"] = int(os.environ.get(
            "BENCH_BATCH", "4" if mode == "decode" else "16"))
        fp["preset"] = os.environ.get("BENCH_PRESET", "ref") or "ref"
        fp["family"] = (os.environ.get("BENCH_FAMILY", "")
                        or "pointer_generator")
        if mode in ("train", "trainer"):
            # remat trades recompute for bytes — a different program; a
            # remat measurement must never stand in for a non-remat ask
            fp["remat"] = _env_flag("BENCH_REMAT")
        # record the RESOLVED kernel choice, not the raw env string:
        # "auto"'s meaning changed once (pallas-on-tpu -> xla), and a
        # fingerprint of intent would cross-substitute semantically
        # different measurements across that change
        pallas_env = (os.environ.get("TS_PALLAS", "") or "auto").lower()
        fp["pallas"] = "on" if pallas_env in ("1", "on", "true") else "off"
        # transformer flash self-attention routing: record the RESOLVED
        # kernel choice (same rule as pallas above — an intent
        # fingerprint would cross-substitute across any future change
        # to auto's threshold).  The pg family never reads TS_FLASH, so
        # it always resolves 'off'; auto resolves on the ask's encoder
        # shape via _use_flash's frozen rule (aligned T>=1024).
        if fp["family"] != "transformer":
            fp["flash"] = "off"
        else:
            from textsummarization_on_flink_tpu.config import (
                HParams,
                flash_mode_from_env,
            )

            resolved = flash_mode_from_env()
            if resolved == "auto":
                hp = HParams(batch_size=fp["batch"],
                             **_preset_overrides())
                hd = hp.hidden_dim // hp.num_heads
                aligned = hp.max_enc_steps % 128 == 0 and hd % 128 == 0
                resolved = ("on" if aligned and hp.max_enc_steps >= 1024
                            else "off")
            fp["flash"] = resolved
        if os.environ.get("BENCH_UNROLL"):
            fp["unroll"] = int(os.environ["BENCH_UNROLL"])
        else:  # the HParams default (config.py is dependency-light)
            from textsummarization_on_flink_tpu.config import HParams

            fp["unroll"] = HParams.scan_unroll
    if mode == "trainer":
        fp["spd"] = int(os.environ.get("BENCH_SPD", "8"))
    if mode == "serve":
        fp["batch"] = int(os.environ.get("BENCH_BATCH", "4"))
        fp["preset"] = os.environ.get("BENCH_PRESET", "ref") or "ref"
        fp["family"] = (os.environ.get("BENCH_FAMILY", "")
                        or "pointer_generator")
        # the coalescing window trades latency for fill: rows measured
        # under different windows must never cross-substitute
        fp["wait_ms"] = float(os.environ.get("BENCH_SERVE_WAIT_MS", "20"))
        fp["reqs"] = int(os.environ.get("BENCH_SERVE_REQS", "64"))
        fp["concurrency"] = int(
            os.environ.get("BENCH_SERVE_CONCURRENCY", "8"))
        # quality-tier axis (ISSUE 10): each tier runs a DIFFERENT
        # compiled decode program (beam vs beam-1 vs spec vs draft) —
        # rows must never cross-substitute.  Added only when
        # non-default so pre-existing banked records keep matching.
        if os.environ.get("BENCH_SERVE_TIER", "beam") != "beam":
            fp["tier"] = os.environ["BENCH_SERVE_TIER"]
            # distilled-narrow-draft axes (ISSUE 12): a narrow draft
            # (different width + factored head = different compiled
            # programs) and an adaptive controller (host-stepped cycle
            # loop vs one dispatch) must never cross-substitute —
            # added only when non-default, per house convention, so
            # banked equal-width spec records keep matching.  The
            # EFFECTIVE rank rides along whenever a factored head is in
            # play (explicit BENCH_DRAFT_RANK, or the width-derived
            # default — same resolution bench_serve applies), so two
            # ranks can never share a fingerprint.  Guarded to the
            # tiers that BUILD a draft (spec/draft): greedy/legacy runs
            # ignore BENCH_DRAFT_*, and a stray env var must not split
            # identical workloads across fingerprints (the PR-11
            # short_ratio rule).
            if os.environ["BENCH_SERVE_TIER"] in ("spec", "draft"):
                dh = int(os.environ.get("BENCH_DRAFT_HIDDEN", "0"))
                dr = int(os.environ.get("BENCH_DRAFT_RANK", str(dh // 2)))
                if dh:
                    fp["draft_hidden"] = dh
                if dr:
                    fp["draft_rank"] = dr
                if os.environ.get("BENCH_SPEC_ADAPTIVE", "").lower() in \
                        ("1", "on", "true", "yes"):
                    fp["spec_k_adaptive"] = True
        # bimodal short-request fraction (ISSUE 11): a different mix is
        # a different workload — a 7/8-short measurement must never
        # stand in for the default 3/4-short ask.  Recorded as the
        # EFFECTIVE (cadence- and pool-quantized) fraction the mix
        # actually has, only on the bimodal mix (the ratio has no
        # effect on other workloads — a stray env var must not split
        # identical uniform-mix records across fingerprints), and only
        # when non-default so pre-existing bimodal records keep
        # matching.
        if os.environ.get("BENCH_SERVE_MIX", "buckets") == "bimodal":
            sr = _effective_short_ratio(
                float(os.environ.get("BENCH_SERVE_SHORT_RATIO", "0.75")))
            if sr != 0.75:
                fp["short_ratio"] = sr
        # front-door axis (ISSUE 14): a zipf mix with the door armed
        # does fundamentally less work than a uniform mix (coalesced
        # followers and cache hits never decode) — zipf rows must never
        # stand in for non-zipf rows.  Non-default only, house
        # convention; the cache capacity rides along because a smaller
        # cache means more re-decodes under the same S.
        if float(os.environ.get("BENCH_SERVE_ZIPF", "0") or 0) > 0:
            fp["zipf"] = float(os.environ["BENCH_SERVE_ZIPF"])
            fp["cache"] = int(os.environ.get("BENCH_SERVE_CACHE", "256"))
        # elastic-fleet axis (ISSUE 13): N routed replicas run a
        # DIFFERENT serving topology than one server (router hop,
        # hedging, per-replica queues) — fleet rows must never stand in
        # for single-server rows.  Non-default only, per house
        # convention, so banked records keep matching; the hedge budget
        # rides along whenever it is armed (hedged and unhedged fleets
        # do different work).
        if os.environ.get("BENCH_SERVE_REPLICAS", "1") not in ("", "1"):
            fp["replicas"] = int(os.environ["BENCH_SERVE_REPLICAS"])
            if float(os.environ.get("BENCH_SERVE_HEDGE_MS", "0") or 0):
                fp["hedge_ms"] = float(os.environ["BENCH_SERVE_HEDGE_MS"])
        # hierarchical long-document axis (ISSUE 19): the map-reduce
        # fan-out is a DIFFERENT workload than the request-stream
        # benches (one parent per document, chunk-tier decodes + one
        # reduce, an append pass that mostly cache-hits) — hier rows
        # must never stand in for plain serve rows.  Non-default only,
        # house convention; the fan-out width rides along because
        # makespans scale with it.
        if os.environ.get("BENCH_SERVE_HIER", "").lower() in \
                ("1", "on", "true", "yes"):
            fp["hier_chunks"] = int(os.environ.get("BENCH_HIER_CHUNKS",
                                                   "6"))
        # paged-arena axis (ISSUE 20): an armed arena runs the PAGED
        # slot kernels (page-table gathers, pooled encoder leaves) and
        # admission is gated by free pages — a different memory story
        # AND a different admission policy than dense residents, so
        # arena rows must never stand in for dense rows.  Non-default
        # only, house convention, so banked dense records keep matching;
        # the page count IS the axis (capacity changes backpressure).
        if int(os.environ.get("BENCH_SERVE_ARENA_PAGES", "0") or 0) > 0:
            fp["arena"] = int(os.environ["BENCH_SERVE_ARENA_PAGES"])
    if mode == "decode":
        # while vs scan vs chunked decode loops differ by ~1.4 ms per
        # dynamic iteration on the tunneled backend — never
        # cross-substitute their latencies (nor chunk sizes: C=1 is
        # per-step dynamic cost, C=T degenerates to scan)
        # record the RESOLVED kind, not "auto" (same rule as the
        # pallas/flash axes): auto's meaning changed in ISSUE 7
        # (attached backends now get chunked, not while), and an intent
        # fingerprint would let a pre-change while record stand in for
        # a chunked ask.  _child_env pins the child to this exact
        # resolution, so measurement and fingerprint cannot diverge.
        loop = _resolved_beam_loop()
        fp["beam_loop"] = loop
        # decode params source (VERDICT r4 weak #1): a trained fixture
        # and a STOP-biased init produce different generated-step counts,
        # so their latencies must never cross-substitute — and neither
        # may stand in for the old random-init worst case
        fp["params"] = _decode_params_spec(fp["family"])
        if loop == "chunked":
            # same env resolution beam_search.resolved_chunk uses; lives
            # in config.py because this supervisor must not import
            # jax-importing modules (with the axon plugin on PYTHONPATH
            # and the tunnel down, jax import hangs)
            from textsummarization_on_flink_tpu.config import (
                beam_chunk_from_env,
            )

            fp["chunk"] = beam_chunk_from_env()
    elif mode == "flash":
        fp["flash_t"] = int(os.environ.get("BENCH_FLASH_T", "2048"))
    elif mode == "input":
        fp["batch"] = int(os.environ.get("BENCH_BATCH", "16"))
    return fp


_digest_cache: dict = {}


def _file_digest(path: str) -> str:
    """Short content digest of a fixture file, cached on
    (size, mtime_ns) so the per-row sweep liveness checks don't re-hash
    tens of MB.  Nanosecond mtime (advisor r5 #3): a same-second,
    same-size fixture regen must invalidate the cache, not serve the
    previous content's digest."""
    import hashlib

    st = os.stat(path)
    key = (path, st.st_size, st.st_mtime_ns)
    if key not in _digest_cache:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        _digest_cache.clear()  # one fixture per process in practice
        _digest_cache[key] = h.hexdigest()[:12]
    return _digest_cache[key]


def _decode_fixture_path(family: str) -> str:
    """Trained decode fixture for BENCH_MODE=decode (generated by
    exp/train_decode_fixture.py; deliberately untracked — the script is
    the committed recipe).  BENCH_DECODE_FIXTURE overrides the path, or
    disables the fixture entirely with ''/'0'/'none'."""
    repo_root = os.path.dirname(os.path.abspath(__file__))
    return os.environ.get(
        "BENCH_DECODE_FIXTURE",
        os.path.join(repo_root, "exp", f"decode_fixture_{family}.npz"))


def _decode_params_spec(family: str) -> str:
    """How BENCH_MODE=decode obtains STOP-capable params (VERDICT r4
    weak #1: random init never emits STOP, so every beam ran all
    max_dec_steps and the loop A/B could only measure overhead).
    'fixture' when the trained fixture file exists, else
    'stop_bias:<b>' — init params with BENCH_STOP_BIAS (default 6.0,
    calibrated on CPU at reference scale: pg finishes at the
    min_dec_steps floor of 36 generated steps, transformer spreads
    36-100 with p50 45) added to the STOP logit of every vocab-sized
    bias vector.  Dependency-light: callable from the supervisor's
    fingerprint with the tunnel down."""
    path = _decode_fixture_path(family)
    # default-path auto-detection only applies at the reference preset:
    # the fixture is trained at reference scale, so a tiny/scaled-preset
    # run must not pick it up (shape-guard failure on every smoke run).
    # An EXPLICIT BENCH_DECODE_FIXTURE is honored as asked — a mismatch
    # fails loudly in _load_decode_fixture.
    explicit = os.environ.get("BENCH_DECODE_FIXTURE") is not None
    preset_ok = (explicit
                 or (os.environ.get("BENCH_PRESET", "ref") or "ref") == "ref")
    if preset_ok and path and path.lower() not in ("0", "none"):
        if os.path.exists(path):
            # the spec carries the fixture's content identity: a
            # REGENERATED fixture (different --steps/--seed => different
            # gen-step distribution and latency) must invalidate banked
            # decode rows, not cross-substitute them
            return f"fixture:{_file_digest(path)}"
        if explicit:
            # an explicitly requested fixture must never silently degrade
            # to stop-bias params — the banked rows would masquerade as
            # trained-fixture numbers
            raise ValueError(
                f"BENCH_DECODE_FIXTURE={path} does not exist "
                f"(generate it: exp/train_decode_fixture.py, or set "
                f"BENCH_DECODE_FIXTURE=none for STOP-biased init params)")
    return "stop_bias:%g" % float(os.environ.get("BENCH_STOP_BIAS", "6.0"))


def _records_path() -> str:
    repo_root = os.path.dirname(os.path.abspath(__file__))
    return os.environ.get("BENCH_STALE_FILE",
                          os.path.join(repo_root, "BENCH_ALL.jsonl"))


def _record_success(rec: dict) -> None:
    """Append a fresh successful record to the shared JSONL so it becomes
    permanent stale-fallback material (VERDICT r3 missing#4): a driver
    run or ad-hoc probe during a brief tunnel window must not evaporate
    with its stdout.  Only live measurements are recorded — stale
    fallbacks and error stubs never re-enter the file through this path.
    Disable with BENCH_NO_RECORD=1 (e.g. throwaway smoke runs)."""
    if os.environ.get("BENCH_NO_RECORD"):
        return
    path = _records_path()
    try:
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError as e:
        sys.stderr.write(f"[bench] could not record to {path}: {e}\n")


def _stale_fallback(metric: str, last_err: str) -> dict | None:
    """When every live attempt TIMES OUT (tunnel down at capture time),
    fall back to the newest matching record in BENCH_ALL.jsonl — a real
    measurement taken earlier in the round — marked "stale": true with
    its capture timestamp.  VERDICT r2 #1: the driver record must never
    again be an empty error stub while real measurements exist on disk.
    Only timeouts qualify: a crash/import error is a code regression and
    must surface, not be papered over (see supervise())."""
    path = _records_path()
    if not os.path.exists(path):
        return None
    want = _config_fingerprint()
    best = None
    best_at = ""
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) or rec.get("metric") != metric \
                        or "error" in rec or rec.get("stale"):
                    continue
                # exact fingerprint match only: a record that cannot
                # prove its config (legacy, pre-fingerprint) must not
                # stand in for any ask — run tags like "train_b64" all
                # contain "train" and would cross-match configs
                if rec.get("config_fingerprint") != want:
                    continue
                # the fingerprint records env INTENT; rec["platform"] is
                # the backend the child actually measured on (a TPU ask
                # can silently fall back to CPU when the plugin is
                # missing).  CPU-ness must agree between ask and record.
                measured = str(rec.get("platform", "")).lower()
                if measured and ((measured == "cpu")
                                 != (want["platform"] == "cpu")):
                    continue
                # newest match wins.  Records carry captured_at (ISO-8601
                # UTC, lexicographically ordered); prefer the max of it so
                # interleaved appends from concurrent/interrupted sweeps
                # cannot make an older record win on file position alone.
                # Ties and legacy stamp-less lines fall back to file order
                # (== capture order under a single writer).
                at = str(rec.get("captured_at", ""))
                if best is None or at >= best_at:
                    best, best_at = rec, at
    except OSError:
        return None
    if best is None:
        return None
    best = dict(best)
    best["stale"] = True
    best["stale_source"] = os.path.basename(path)
    best["live_error"] = last_err
    return best


def supervise() -> None:
    mode = os.environ.get("BENCH_MODE", "train")
    metric = _METRIC_BY_MODE.get(mode, f"bench_{mode}")
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "2"))
    # the full-scale beam-search while_loop takes a long first compile;
    # give non-train modes more headroom by default
    default_timeout = "600" if mode == "train" else "1200"
    timeout = float(os.environ.get("BENCH_TIMEOUT", default_timeout))
    repo_root = os.path.dirname(os.path.abspath(__file__))
    last_err = "no attempts made"
    all_timeouts = True  # stale fallback is for tunnel hangs ONLY
    for attempt in range(1, attempts + 1):
        try:
            proc = subprocess.run(
                [sys.executable, "-u", os.path.abspath(__file__)],
                env=_child_env(), cwd=repo_root, timeout=timeout,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        except subprocess.TimeoutExpired as e:
            out = e.output or ""
            if isinstance(out, bytes):
                out = out.decode("utf-8", "replace")
            last_err = (f"attempt {attempt}/{attempts} timed out after "
                        f"{timeout:.0f}s (TPU tunnel down?)")
            sys.stderr.write(f"[bench] {last_err}\n{out[-1500:]}\n")
            continue
        all_timeouts = False
        # the child's LAST parseable JSON line with "metric" is the result
        result = None
        for line in (proc.stdout or "").splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if isinstance(obj, dict) and "metric" in obj:
                    result = obj
        if result is not None and "error" not in result:
            result.setdefault(
                "captured_at",
                datetime.datetime.now(datetime.timezone.utc)
                .strftime("%Y-%m-%dT%H:%M:%SZ"))
            result.setdefault("config_fingerprint", _config_fingerprint())
            if os.environ.get("BENCH_RUN_TAG"):
                result.setdefault("run", os.environ["BENCH_RUN_TAG"])
            _record_success(result)
            print(json.dumps(result))
            return
        last_err = (f"attempt {attempt}/{attempts}: child rc="
                    f"{proc.returncode}, "
                    + (result.get("error", "no JSON result line")
                       if result else "no JSON result line"))
        sys.stderr.write(f"[bench] {last_err}\n"
                         f"{(proc.stdout or '')[-1500:]}\n")
        if result is not None and result.get("retryable") is False:
            # deterministic failure (bad mode, kernel mismatch): a code
            # regression, not a tunnel flake — an old good record must
            # NOT paper over it, so no stale fallback on this path
            print(json.dumps({"metric": metric, "value": 0.0,
                              "unit": "n/a", "vs_baseline": 0.0,
                              "error": last_err}))
            sys.exit(1)
    stale = _stale_fallback(metric, last_err) if all_timeouts else None
    if stale is not None:
        sys.stderr.write("[bench] live attempts failed; emitting stale "
                         f"record captured at "
                         f"{stale.get('captured_at', '?')}\n")
        print(json.dumps(stale))
        return
    print(json.dumps({"metric": metric, "value": 0.0, "unit": "n/a",
                      "vs_baseline": 0.0, "error": last_err}))
    sys.exit(1)


# --------------------------------------------------------------------------
# analytic FLOPs model (for MFU)
# --------------------------------------------------------------------------

def transformer_flops_per_step(hps) -> float:
    """Analytic training FLOPs/step for the transformer family: per-layer
    attention projections + score/value matmuls + FFN, plus the tied
    [H, V] output projection; training = 3x forward."""
    B, Te, Td = hps.batch_size, hps.max_enc_steps, hps.max_dec_steps
    H, V = hps.hidden_dim, hps.vocab_size
    F = hps.ffn_width
    enc_layer = 4 * Te * H * H + 2 * Te * Te * H + 2 * Te * H * F
    dec_layer = (4 * Td * H * H + 2 * Td * Td * H       # causal self-attn
                 + 2 * Td * H * H + 2 * Te * H * H      # cross q,o + k,v
                 + 2 * Td * Te * H                      # cross scores+ctx
                 + 2 * Td * H * F)                      # ffn
    macs = B * (hps.enc_layers * enc_layer + hps.dec_layers * dec_layer
                + Td * H * V)
    return float(3 * 2 * macs)


def train_flops_per_step(hps) -> float:
    """Analytic training FLOPs/step for the pointer-generator.

    MAC counts per sample, forward pass (model shapes per
    /root/reference/src/main/python/pointer-generator/model.py:76-238,
    attention_decoder.py:58-174); training = 3x forward (backward ~= 2x).
    The H x vocab output projection dominates at reference scale.
    """
    B, Te, Td = hps.batch_size, hps.max_enc_steps, hps.max_dec_steps
    H, E, V = hps.hidden_dim, hps.emb_dim, hps.vocab_size
    D = 2 * H  # biLSTM state width == attention feature width
    enc_lstm = 2 * Te * (E + H) * 4 * H       # two directions
    reduce_states = 2 * D * H                 # c and h bi->uni reductions
    enc_feats = Te * D * D                    # W_h h_i, hoisted per sequence
    dec_per_step = (
        (E + D) * E          # input+context merge linear
        + (E + H) * 4 * H    # decoder LSTM cell
        + D * D              # W_s state projection ([c,h] -> D)
        + Te * D             # v . tanh(feats) energy reduction
        + Te * D             # context = attn @ enc_states
        + (2 * D + E)        # p_gen linear
        + (H + D) * H        # output merge ([cell_out, ctx] -> H)
        + H * V              # output projection (dominant)
    )
    macs = B * (enc_lstm + reduce_states + enc_feats + Td * dec_per_step)
    return float(3 * 2 * macs)  # 2 FLOPs/MAC; fwd+bwd ~= 3x fwd


_PEAK_BF16_TFLOPS = {
    # per-chip bf16 peaks (public TPU specs)
    "v2": 45.0, "v3": 123.0, "v4": 275.0,
    "v5 lite": 197.0, "v5e": 197.0, "v5p": 459.0,
    "v6 lite": 918.0, "v6e": 918.0,
}


def peak_flops_for(device) -> float | None:
    env = os.environ.get("BENCH_PEAK_TFLOPS")
    if env:
        return float(env) * 1e12
    kind = (getattr(device, "device_kind", "") or "").lower()
    for key in sorted(_PEAK_BF16_TFLOPS, key=len, reverse=True):
        if key in kind:
            return _PEAK_BF16_TFLOPS[key] * 1e12
    return None


def _device_info():
    import jax

    dev = jax.devices()[0]
    return dev, {"platform": jax.default_backend(),
                 "device": getattr(dev, "device_kind", str(dev))}


def _fence(x) -> float:
    """D2H fetch of one scalar — the only reliable execution fence over
    the tunneled backend (see module docstring)."""
    import jax

    return float(np.asarray(jax.device_get(x)).ravel()[0])


def _tunnel_rtt() -> float:
    """Cost of one fence on an already-materialized buffer: the pure
    host<->device round trip, to subtract from timed windows."""
    import jax
    import jax.numpy as jnp

    x = jax.device_put(jnp.float32(0.0))
    _fence(x)
    samples = []
    for _ in range(5):
        t0 = time.perf_counter()
        _fence(x)
        samples.append(time.perf_counter() - t0)
    return min(samples)


# --------------------------------------------------------------------------
# children
# --------------------------------------------------------------------------

def _preset_overrides() -> dict:
    """BENCH_PRESET=tiny shrinks the model for smoke runs (full-scale
    beam-search compiles take minutes on CPU); =scaled is the
    BASELINE.json configs[3] long-input shape (hidden 512, enc 800);
    default is the reference scale.  BENCH_FAMILY=transformer benches
    the second model family (BART-class; 6+6 layers at hidden_dim
    width)."""
    out = {}
    if os.environ.get("BENCH_PRESET") == "tiny":
        out.update(hidden_dim=16, emb_dim=8, vocab_size=200,
                   max_enc_steps=32, max_dec_steps=8, beam_size=2,
                   min_dec_steps=1, max_oov_buckets=8)
    elif os.environ.get("BENCH_PRESET") == "scaled":
        out.update(hidden_dim=512, max_enc_steps=800)
    if os.environ.get("BENCH_UNROLL"):
        out["scan_unroll"] = int(os.environ["BENCH_UNROLL"])
    if os.environ.get("BENCH_LOSS_CHUNK"):
        # streaming chunked vocab loss (ISSUE 5 byte diet): the
        # [T_dec, B, V] scores tensor never materializes
        out["loss_chunk"] = int(os.environ["BENCH_LOSS_CHUNK"])
    if os.environ.get("BENCH_OPT_DTYPE"):
        # bf16 Adagrad accumulator storage (half the optimizer-state HBM)
        out["opt_state_dtype"] = os.environ["BENCH_OPT_DTYPE"]
    if _env_flag("BENCH_REMAT"):
        # roofline-motivated A/B (BASELINE.md): on a bandwidth-bound step
        # recomputing the [T_dec, B, V] scores block in backward may SAVE
        # time, not just memory
        out["remat"] = True
    mesh = _bench_mesh()
    if mesh != (1, 1):
        # (dp, tp) mesh axes for the unified sharded step (ISSUE 8):
        # the registry-driven layouts are different compiled programs,
        # fingerprinted via the `mesh` axis below
        out["dp"], out["tp"] = mesh
    family = os.environ.get("BENCH_FAMILY", "")
    if family:
        out["model_family"] = family
        if family == "transformer" \
                and os.environ.get("BENCH_PRESET") == "tiny":
            out["num_heads"] = 4  # tiny preset: 16/4 heads
            out["enc_layers"] = out["dec_layers"] = 2
    return out


def bench_train() -> None:
    import functools

    import jax

    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.train import trainer as trainer_lib
    from __graft_entry__ import _example_arrays

    steps = int(os.environ.get("BENCH_STEPS", "20"))
    batch = int(os.environ.get("BENCH_BATCH", "16"))

    hps = HParams(batch_size=batch, compute_dtype="bfloat16",
                  **_preset_overrides())

    state = trainer_lib.init_train_state(hps, hps.vocab_size, seed=0)
    step_fn = trainer_lib.make_train_step(hps)
    arrays = _example_arrays(hps, np.random.RandomState(0))
    arrays = jax.device_put(arrays)

    def k_steps(state, arrays, k):
        def body(s, _):
            s, m = step_fn(s, arrays)
            return s, m.loss
        state, losses = jax.lax.scan(body, state, None, length=k)
        return state, losses[-1]

    run = jax.jit(functools.partial(k_steps, k=steps), donate_argnums=0)
    state, loss0 = run(state, arrays)   # compile + warm (steps real steps)
    _fence(loss0)
    rtt = _tunnel_rtt()

    t0 = time.perf_counter()
    state, loss_last = run(state, arrays)
    loss = _fence(loss_last)
    dt = max(time.perf_counter() - t0 - rtt, 1e-9)

    if not np.isfinite(loss):
        print(json.dumps({"metric": "train_samples_per_sec", "value": 0.0,
                          "unit": "samples/s", "vs_baseline": 0.0,
                          "error": f"non-finite loss {loss}"}))
        sys.exit(1)

    # the un-sharded jit runs on exactly one chip, so the measured
    # throughput IS the per-chip number
    samples_per_sec = steps * batch / dt
    step_time = dt / steps
    baseline = BASELINE_SAMPLES_PER_SEC
    dev, info = _device_info()
    flops = (transformer_flops_per_step(hps)
             if hps.model_family == "transformer"
             else train_flops_per_step(hps))
    peak = peak_flops_for(dev)
    rec = {
        "metric": "train_samples_per_sec",
        "value": round(samples_per_sec, 2),
        "unit": "samples/s",
        "vs_baseline": round(samples_per_sec / baseline, 2),
        "step_time_ms": round(step_time * 1e3, 3),
        "flops_per_step": flops,
        "mfu": (round(flops / step_time / peak, 4)
                if peak else None),
        "peak_tflops": (peak / 1e12 if peak else None),
        "loss": round(loss, 4),
        "model_family": hps.model_family,
        "tunnel_rtt_ms": round(rtt * 1e3, 2),
        "timing": f"on-device lax.scan of {steps} steps, scalar-fetch fence",
    }
    rec.update(info)
    rec.update(_obs_extra())
    print(json.dumps(rec))


def _stop_biased(params, vsize: int, bias: float):
    """STOP-capable params from a random init: add `bias` to the STOP
    logit of every vocab-sized bias vector (pg output_projection.v,
    transformer out_bias).  Random-init logits are effectively
    stationary per article, so an article either emits STOP as soon as
    min_dec_steps allows or never — the calibrated default (see
    _decode_params_spec) puts finishes in the realistic band instead of
    the all-100-steps worst case."""
    import jax

    from textsummarization_on_flink_tpu.data.vocab import STOP_ID

    def bump(x):
        if getattr(x, "shape", None) == (vsize,):
            return x.at[STOP_ID].add(bias)
        return x

    return jax.tree_util.tree_map(bump, params)


def _load_decode_fixture(path: str, init):
    """Load a trained decode fixture (npz of keystr->array, written by
    exp/train_decode_fixture.py) into init_params' tree structure,
    validated leaf-for-leaf so a stale or wrong-scale fixture fails
    loudly instead of silently measuring a different model."""
    import jax

    data = np.load(path)
    flat, treedef = jax.tree_util.tree_flatten_with_path(init)
    extra = set(data.files) - {jax.tree_util.keystr(k) for k, _ in flat}
    if extra:
        raise ValueError(
            f"decode fixture {path} has keys the model does not: "
            f"{sorted(extra)[:4]} — trained under a different config "
            f"(e.g. coverage)? regenerate: exp/train_decode_fixture.py")
    leaves = []
    for key_path, leaf in flat:
        key = jax.tree_util.keystr(key_path)
        if key not in data:
            raise ValueError(f"decode fixture {path} is missing {key!r} "
                             f"(regenerate: exp/train_decode_fixture.py)")
        arr = np.asarray(data[key])
        if arr.shape != leaf.shape:
            raise ValueError(
                f"decode fixture {path} leaf {key!r} has shape {arr.shape}, "
                f"model expects {leaf.shape} (wrong scale? regenerate)")
        leaves.append(arr.astype(leaf.dtype))
    return jax.tree_util.tree_unflatten(treedef, leaves)


def bench_decode() -> None:
    """BENCH_MODE=decode: batched beam-search decode at the reference
    serving config (batch 4, enc 400, dec 100, beam 4,
    TensorFlowTest.java:40-53).  One device dispatch per batch of
    articles vs the reference's ~100 feed_dict round trips per article."""
    import jax

    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.decode import beam_search
    from textsummarization_on_flink_tpu.models import get_family
    from __graft_entry__ import _example_arrays

    iters = int(os.environ.get("BENCH_STEPS", "10"))
    batch = int(os.environ.get("BENCH_BATCH", "4"))
    hps = HParams(batch_size=batch, mode="decode", coverage=True,
                  **_preset_overrides())
    # coverage mirrors the reference decode config for the pg family
    # (TensorFlowTest.java:40-53); the transformer decode path never
    # reads it
    if hps.model_family == "transformer":
        hps = hps.replace(coverage=False)
    family = get_family(hps.model_family)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(0))
    params_spec = _decode_params_spec(hps.model_family)
    if params_spec.startswith("fixture"):
        params = _load_decode_fixture(
            _decode_fixture_path(hps.model_family), params)
    else:
        params = _stop_biased(params, hps.vocab_size,
                              float(params_spec.split(":", 1)[1]))
    arrays = _example_arrays(hps, np.random.RandomState(0))
    arrays = {k: v for k, v in arrays.items()
              if not k.startswith(("dec_", "target_"))}
    arrays = jax.device_put(arrays)

    beam_loop = beam_search._loop_kind()  # TS_BEAM_LOOP env override
    chunk = beam_search.resolved_chunk(beam_loop)  # part of the cache key
    out = beam_search.run_beam_search_jit(params, hps, arrays,
                                          loop=beam_loop,
                                          chunk=chunk)  # compile
    np.asarray(jax.device_get(out.length))
    rtt = _tunnel_rtt()
    lat_raw = []
    tokens = 0
    t_total = 0.0
    all_lengths = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = beam_search.run_beam_search_jit(params, hps, arrays,
                                              loop=beam_loop, chunk=chunk)
        # fetching the lengths (data-dependent on the whole decode loop)
        # is the fence
        lengths = np.asarray(jax.device_get(out.length))
        dt = time.perf_counter() - t0
        lat_raw.append(dt / batch)
        t_total += dt
        # length includes START (beam_search.py:57-58); generated = len-1
        tokens += int(np.sum(lengths - 1))
        all_lengths.extend(int(x) for x in lengths)

    def pct(xs, q):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(len(xs) * q))]

    # ADVICE r2: on a flaky tunnel the RTT variance can rival the decode
    # latency itself, so a min-of-5 RTT subtraction can skew or collapse
    # the corrected numbers.  Report BOTH: raw wall-clock percentiles
    # (what a client of this backend actually observes) and
    # RTT-corrected ones (the device-side estimate).  Raw is the
    # headline value — it cannot be an artifact of the correction.
    corr = [max(x - rtt / batch, 1e-9) for x in lat_raw]
    _, info = _device_info()
    rec = {
        "metric": "beam_decode_p50_latency_per_article",
        "value": round(pct(lat_raw, 0.5) * 1000, 2),
        "unit": "ms",
        "vs_baseline": 0.0,  # the reference publishes no decode latency
        "p99_ms": round(pct(lat_raw, 0.99) * 1000, 2),
        "p50_rtt_corrected_ms": round(pct(corr, 0.5) * 1000, 2),
        "p99_rtt_corrected_ms": round(pct(corr, 0.99) * 1000, 2),
        "tokens_per_sec": round(tokens / t_total, 1),
        # null rather than a nonsense huge number when the RTT estimate
        # swallows the whole window (flaky-tunnel RTT >= decode time)
        "tokens_per_sec_rtt_corrected": (
            round(tokens / (t_total - iters * rtt), 1)
            if t_total > iters * rtt else None),
        "beam_size": hps.beam_size,
        "batch": batch,
        "beam_loop": beam_loop,
        "params_source": params_spec,
        "tunnel_rtt_ms": round(rtt * 1e3, 2),
        # generated steps of each best hypothesis (length-1): the proxy
        # for how much of max_dec_steps early-exit loops (while/chunked)
        # can save vs scan's fixed iteration count — the data the
        # TS_BEAM_LOOP auto-choice decision needs (PERF.md decode rows)
        "gen_steps_p50": int(np.median(all_lengths)) - 1,
        "gen_steps_max": max(all_lengths) - 1,
        "max_dec_steps": hps.max_dec_steps,
    }
    rec.update(info)
    rec.update(_obs_extra())
    print(json.dumps(rec))


def bench_attention() -> None:
    """BENCH_MODE=attention: A/B the fused Pallas kernel (simple + blocked
    long-context variants, ops/pallas_attention.py) against the XLA
    formula — same-output check plus a timing ratio (VERDICT r1 #5)."""
    import jax
    import jax.numpy as jnp

    from textsummarization_on_flink_tpu.ops import pallas_attention as pa

    iters = int(os.environ.get("BENCH_STEPS", "50"))
    on_tpu = jax.default_backend() == "tpu"
    rng = np.random.RandomState(0)

    def make_args(B, T, D):
        es = rng.randn(B, T, D).astype(np.float32)
        ef = rng.randn(B, T, D).astype(np.float32)
        lens = rng.randint(T // 2, T + 1, size=(B,))
        mask = (np.arange(T)[None, :] < lens[:, None]).astype(np.float32)
        df = rng.randn(B, D).astype(np.float32)
        cov = np.abs(rng.randn(B, T)).astype(np.float32)
        v = rng.randn(D).astype(np.float32)
        wc = rng.randn(D).astype(np.float32)
        return tuple(jax.device_put(x) for x in (es, ef, mask, df, cov, v, wc))

    rtt = _tunnel_rtt()

    def timed(fn, args):
        """iters calls chained ON DEVICE: one fori_loop dispatch, each
        iteration's dec_feats perturbed by a tiny carry computed from the
        previous context so XLA cannot hoist the loop body."""
        es, ef, mask, df, cov, v, wc = args

        @jax.jit
        def run_many():
            def body(i, carry):
                ctx, _ = fn(es, ef, mask, df + carry, cov, v, wc)
                return ctx[:1, :1] * 1e-30
            return jax.lax.fori_loop(0, iters, body,
                                     jnp.zeros((1, 1), jnp.float32))

        _fence(run_many())  # compile + warm
        t0 = time.perf_counter()
        out = run_many()
        _fence(out)
        return max(time.perf_counter() - t0 - rtt, 1e-9) / iters

    results = {}
    speedups = []
    # reference scale (B16 T400 D512) f32 + bf16 encoder streams (the
    # compute_dtype=bfloat16 train path hands the op bf16 es/ef), and
    # long-context (T4096 -> blocked kernel)
    scales = {"ref": (16, 400, 512, False),
              "ref_bf16": (16, 400, 512, True),
              "longctx": (4, 4096, 512, False)}
    for name, (B, T, D, bf16_stream) in scales.items():
        args = make_args(B, T, D)
        if bf16_stream:
            args = (args[0].astype(jnp.bfloat16),
                    args[1].astype(jnp.bfloat16)) + args[2:]
        xla = jax.jit(lambda *a: pa._attention_xla(*a, True))
        if T * D > pa._SIMPLE_KERNEL_MAX_ELEMS:
            kern = jax.jit(lambda *a: pa._attention_pallas_blocked(
                *a, True, interpret=not on_tpu))
        else:
            kern = jax.jit(lambda *a: pa._attention_pallas(
                *a, True, interpret=not on_tpu))
        # correctness BEFORE the timing loops (a mismatch is deterministic
        # — fail fast and tell the supervisor not to retry)
        out_xla = jax.block_until_ready(xla(*args))
        out_pal = jax.block_until_ready(kern(*args))
        ctx_err = float(jnp.max(jnp.abs(out_xla[0] - out_pal[0])))
        attn_err = float(jnp.max(jnp.abs(out_xla[1] - out_pal[1])))
        if ctx_err > 2e-2 or attn_err > 1e-3:
            print(json.dumps({
                "metric": "attention_pallas_speedup_vs_xla", "value": 0.0,
                "unit": "x", "vs_baseline": 0.0, "retryable": False,
                "error": f"pallas/xla mismatch at {name}: "
                         f"ctx {ctx_err} attn {attn_err}"}))
            sys.exit(1)
        t_xla = timed(xla, args)
        t_pal = timed(kern, args)
        results[name] = {
            "xla_us": round(t_xla * 1e6, 1),
            "pallas_us": round(t_pal * 1e6, 1),
            "speedup": round(t_xla / t_pal, 3),
            "max_ctx_err": ctx_err,
            "max_attn_err": attn_err,
        }
        speedups.append(t_xla / t_pal)
    _, info = _device_info()
    rec = {
        "metric": "attention_pallas_speedup_vs_xla",
        "value": round(speedups[0], 3),  # reference scale is the headline
        "unit": "x",
        "vs_baseline": round(speedups[0], 3),
        "interpret_mode": not on_tpu,
        "scales": results,
        "tunnel_rtt_ms": round(rtt * 1e3, 2),
        "timing": f"on-device fori_loop of {iters} iters, carry-chained",
    }
    rec.update(info)
    print(json.dumps(rec))


def bench_flash() -> None:
    """BENCH_MODE=flash: A/B the transformer's Pallas flash self-attention
    against the einsum formula at a long-context, lane-aligned scale
    (T=2048, hd=128) — same-output gate, then a fwd+bwd timing ratio."""
    import jax
    import jax.numpy as jnp

    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.models import transformer as tfm

    iters = int(os.environ.get("BENCH_STEPS", "30"))
    if jax.default_backend() != "tpu":
        # _use_flash refuses non-TPU backends even when forced (the
        # kernel has no CPU/GPU lowering), so both timed paths would be
        # the einsum formula and the ratio would be meaningless ~1.0
        print(json.dumps({"metric": "flash_attention_speedup_vs_xla",
                          "value": 0.0, "unit": "x", "vs_baseline": 0.0,
                          "retryable": False,
                          "error": "flash mode requires a TPU backend "
                                   f"(have {jax.default_backend()!r})"}))
        sys.exit(1)
    B, T = 4, int(os.environ.get("BENCH_FLASH_T", "2048"))
    hps = HParams(model_family="transformer", hidden_dim=1024, num_heads=8,
                  max_enc_steps=T, batch_size=B)
    rng = np.random.RandomState(0)
    p = {k: jnp.asarray(rng.randn(1024, 1024) * 0.02, jnp.float32)
         for k in ("wq", "wk", "wv", "wo")}
    x = jnp.asarray(rng.randn(B, T, 1024) * 0.1, jnp.float32)
    lens = rng.randint(T // 2, T + 1, size=(B,))
    mask = jnp.asarray((np.arange(T)[None] < lens[:, None]), jnp.float32)

    def f(x):
        out = tfm._self_attention(hps, p, x, mask, causal=False)
        # mask the LOSS: padding-query rows legitimately differ between
        # the two paths and must not leak gradient into the real rows
        # being compared
        return jnp.sum((out * mask[:, :, None]) ** 2)

    def run(flag):
        os.environ["TS_FLASH"] = flag
        # compile NOW, while the env flag is set — jit traces lazily and
        # _use_flash reads TS_FLASH at trace time
        return jax.jit(lambda x: jax.grad(f)(x)).lower(x).compile()

    f_xla, f_flash = run("off"), run("on")
    g0 = jax.block_until_ready(f_xla(x))
    g1 = jax.block_until_ready(f_flash(x))
    # gate correctness on REAL rows only (flash leaves padding-query rows
    # undefined by design; downstream masks discard them)
    real = np.asarray(mask)[:, :, None] > 0
    err = float(jnp.max(jnp.abs(jnp.where(real, g0 - g1, 0.0))))
    scale = float(jnp.max(jnp.abs(jnp.where(real, g0, 0.0))))
    if err > 1e-2 * max(scale, 1.0):
        print(json.dumps({"metric": "flash_attention_speedup_vs_xla",
                          "value": 0.0, "unit": "x", "vs_baseline": 0.0,
                          "retryable": False,
                          "error": f"flash/xla grad mismatch {err} "
                                   f"(scale {scale})"}))
        sys.exit(1)

    rtt = _tunnel_rtt()

    def timed(flag):
        """iters fwd+bwd passes of the same `f` chained on device; the
        input is perturbed by a carry from the previous gradient so XLA
        cannot hoist the body.  Traced+compiled while TS_FLASH is set
        (read at trace time)."""
        os.environ["TS_FLASH"] = flag

        @jax.jit
        def run_many(x):
            def body(i, carry):
                g = jax.grad(f)(x + carry)
                return g[:1, :1, :1] * 1e-30
            return jax.lax.fori_loop(0, iters, body,
                                     jnp.zeros((1, 1, 1), jnp.float32))

        _fence(run_many(x))  # compile + warm, flag still set
        t0 = time.perf_counter()
        out = run_many(x)
        _fence(out)
        return max(time.perf_counter() - t0 - rtt, 1e-9) / iters

    t_xla, t_flash = timed("off"), timed("on")
    _, info = _device_info()
    rec = {
        "metric": "flash_attention_speedup_vs_xla",
        "value": round(t_xla / t_flash, 3),
        "unit": "x",
        "vs_baseline": round(t_xla / t_flash, 3),
        "xla_ms": round(t_xla * 1e3, 3),
        "flash_ms": round(t_flash * 1e3, 3),
        "T": T, "head_dim": 128, "max_grad_err": err,
        "tunnel_rtt_ms": round(rtt * 1e3, 2),
    }
    rec.update(info)
    print(json.dumps(rec))


def _synthetic_dataset(tmp: str, hps, n_examples: int = 512):
    """Write a synthetic chunked CNN/DM-scale dataset under tmp and
    return its (glob_pattern, vocab).  The vocab is sized to
    hps.vocab_size (words + 4 specials) so model shapes — above all the
    FLOP-dominant [H, vocab] projection — match the non-synthetic
    benches; article text samples a 2k-word subset (ids must recur for
    the bucketing/OOV machinery to do real work)."""
    from textsummarization_on_flink_tpu.data import TFExample, Vocab
    from textsummarization_on_flink_tpu.data.chunks import write_chunked

    rng = np.random.RandomState(0)
    n_words = max(hps.vocab_size - 4, 100)  # 4 specials complete the size
    words = [f"w{i}" for i in range(n_words)]
    vocab = Vocab(words=words)
    words = words[:2000]  # text draws from a recurring subset
    exs = []
    for _ in range(n_examples):
        art_len = rng.randint(hps.max_enc_steps // 2,
                              hps.max_enc_steps + 100)
        art = " ".join(rng.choice(words, size=art_len))
        abs_len = rng.randint(hps.max_dec_steps // 2, hps.max_dec_steps)
        abstract = "<s> " + " ".join(rng.choice(words, size=abs_len)) \
            + " . </s>"
        exs.append(TFExample()
                   .set_bytes("article", art.encode())
                   .set_bytes("abstract", abstract.encode()))
    write_chunked(os.path.join(tmp, "train"), exs, chunk_size=128)
    return os.path.join(tmp, "train_*.bin"), vocab


def bench_input() -> None:
    """BENCH_MODE=input: host-side input-pipeline throughput — the
    threaded bucketing Batcher (16+4 producer threads, reference
    batcher.py:252-253 parity) packing a synthetic chunked CNN/DM-scale
    dataset into static-shape train batches.  No TPU involved; the
    number to compare against is the device's train samples/s (the
    pipeline must exceed it to keep the chip busy)."""
    import shutil
    import tempfile

    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.data.batcher import Batcher

    batch = int(os.environ.get("BENCH_BATCH", "16"))
    hps = HParams(batch_size=batch, **_preset_overrides())

    tmp = tempfile.mkdtemp(prefix="bench_input_")
    try:
        pattern, vocab = _synthetic_dataset(tmp, hps)
        b = Batcher(pattern, vocab, hps, single_pass=False)
        b.next_batch()  # wait for the producer threads to come up
        # the batch queue holds up to 100 pre-built batches; timing a
        # drain of that backlog would measure Queue.get, not pipeline
        # throughput.  Pull until the queue is momentarily empty so the
        # clock starts from ~zero backlog, then count batches produced
        # during a fixed window (consumed ≈ produced from an empty
        # start — any end-of-window backlog is uncounted, so the number
        # errs low, never high).
        drained = 0
        while b.queued_batches() > 0 and drained < 300:
            b.next_batch()
            drained += 1
        seconds = float(os.environ.get("BENCH_SECONDS", "3"))
        t0 = time.perf_counter()
        n_batches = 0
        while time.perf_counter() - t0 < seconds:
            b.next_batch()
            n_batches += 1
        dt = time.perf_counter() - t0
        rate = n_batches * batch / dt
        rec = {
            "metric": "input_pipeline_samples_per_sec",
            "value": round(rate, 1),
            "unit": "samples/s",
            "vs_baseline": round(rate / BASELINE_SAMPLES_PER_SEC, 2),
            "batch": batch,
            "batches_timed": n_batches,
            "note": "host-only; must exceed device train samples/s",
        }
        rec.update(_obs_extra())
        print(json.dumps(rec))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_serve_hier() -> None:
    """--serve-hier: the ISSUE-19 long-document workload — ONE
    multi-chunk document map-reduced through HierarchicalSummarizer
    over a live server, against a sequential per-chunk baseline, plus
    an APPEND re-summarize whose cache-hit rate is the row's dedup
    evidence.  The headline is the fan-out makespan (parent submit ->
    HierResult, reduce included); `sequential_ms` is the same chunk
    set decoded one-at-a-time on the same warm server (distinct
    articles, so the front door cannot help it)."""
    import shutil
    import tempfile

    import jax

    from textsummarization_on_flink_tpu import obs
    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.data.vocab import Vocab
    from textsummarization_on_flink_tpu.decode.decoder import (
        BeamSearchDecoder,
    )
    from textsummarization_on_flink_tpu.models import get_family
    from textsummarization_on_flink_tpu.serve.hiersum import (
        DocumentSession,
        HierarchicalSummarizer,
    )
    from textsummarization_on_flink_tpu.serve.server import ServingServer

    chunks_n = int(os.environ.get("BENCH_HIER_CHUNKS", "6"))
    append_n = int(os.environ.get("BENCH_HIER_APPEND", "2"))
    if chunks_n < 2 or append_n < 1:
        raise ValueError(
            f"BENCH_HIER_CHUNKS must be >= 2 and BENCH_HIER_APPEND >= 1, "
            f"got {chunks_n}/{append_n}")
    serve_mode = os.environ.get("BENCH_SERVE_MODE", "microbatch")
    wait_ms = float(os.environ.get("BENCH_SERVE_WAIT_MS", "20"))
    hps = HParams(batch_size=int(os.environ.get("BENCH_BATCH", "4")),
                  mode="decode", coverage=True,
                  serve_max_wait_ms=wait_ms, serve_mode=serve_mode,
                  serve_max_queue=max(256, 2 * chunks_n),
                  serve_coalesce=True, serve_cache_entries=256,
                  **_preset_overrides())
    hps.validate()
    if hps.model_family == "transformer":
        hps = hps.replace(coverage=False)
    # full-width chunks (hier_chunk_words=0 -> max_enc_steps): every
    # chunk runs the same encoder shape, so sequential-vs-fan-out is a
    # scheduling comparison, not a padding artifact
    cw = hps.max_enc_steps
    n_words = max(hps.vocab_size - 4, 100)
    vocab = Vocab(words=[f"w{i}" for i in range(n_words)])
    pool = [f"w{i}" for i in range(min(n_words, 2000))]

    def words(start: int, count: int) -> str:
        # deterministic distinct-ish streams: doc A, doc B (the
        # sequential baseline), and the appended tail never share a
        # chunk, so the cache only ever helps the APPEND pass
        return " ".join(pool[(start + i) % len(pool)]
                        for i in range(count))

    doc = words(0, chunks_n * cw)
    seq_chunks = [words(7 + (chunks_n + i) * cw, cw)
                  for i in range(chunks_n)]
    tail = words(3 + 2 * chunks_n * cw, append_n * cw)
    family = get_family(hps.model_family)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(0))
    params = _stop_biased(params, hps.vocab_size,
                          float(os.environ.get("BENCH_STOP_BIAS", "6.0")))
    tmp = tempfile.mkdtemp(prefix="bench_serve_hier_")
    try:
        decoder = BeamSearchDecoder(hps, vocab, batcher=None,
                                    params=params, decode_root=tmp)
        server = ServingServer(hps, vocab, decoder=decoder)
        reg = obs.registry()
        hs = HierarchicalSummarizer(server, hps)
        with server:
            # compile both tiers the workload uses (chunk tier +
            # reduce tier) before any timed phase
            server.submit(words(11, cw), uuid="warm-g",
                          tier="" if serve_mode == "continuous"
                          else "greedy").result(timeout=1200)
            server.submit(words(13, cw), uuid="warm-b").result(timeout=1200)

            t0 = time.perf_counter()
            for i, chunk in enumerate(seq_chunks):
                server.submit(chunk, uuid=f"seq{i}", block=True,
                              tier="" if serve_mode == "continuous"
                              else "greedy").result(timeout=1200)
            sequential_s = time.perf_counter() - t0

            sess = DocumentSession("bench-doc", doc)
            t0 = time.perf_counter()
            hs.summarize("", session=sess, block=True).result(timeout=1200)
            fanout_s = time.perf_counter() - t0

            hits0 = reg.counter("serve/hier_chunk_cache_hits_total").value
            done0 = reg.counter("serve/completed_total").value
            sess.append(tail)
            t0 = time.perf_counter()
            hs.summarize("", session=sess, block=True).result(timeout=1200)
            append_s = time.perf_counter() - t0
            hits = reg.counter(
                "serve/hier_chunk_cache_hits_total").value - hits0
            append_decodes = reg.counter(
                "serve/completed_total").value - done0
        fid = reg.histogram("serve/hier_copy_fidelity")
        rec = {
            "metric": "serve_hier_fanout_makespan_ms",
            "value": round(fanout_s * 1000, 2),
            "unit": "ms",
            "vs_baseline": 0.0,  # the reference publishes no serving numbers
            "serve_mode": serve_mode,
            "hier_chunks": chunks_n,
            "chunk_words": cw,
            "sequential_ms": round(sequential_s * 1000, 2),
            # < 1.0 == the fan-out beat decoding the chunks one at a
            # time (the committed virtual-time ceiling lives in
            # SERVE_SLO.json "hierarchical"; this is the wall-clock
            # evidence at bench scale)
            "makespan_ratio": round(fanout_s / sequential_s, 4)
            if sequential_s else 0.0,
            "append_ms": round(append_s * 1000, 2),
            "append_chunks": append_n,
            # dedup by construction: pre-append chunks / resubmitted
            # chunks served from the front-door cache on the append pass
            "append_cache_hit_rate": round(
                hits / (chunks_n + append_n), 4),
            "append_decodes": int(append_decodes),
            "copy_fidelity_mean": round(fid.mean, 4),
            "wait_ms": wait_ms,
            "model_family": hps.model_family,
            "timing": "wall-clock makespan, parent submit -> HierResult "
                      "(reduce included); sequential = same-width chunks "
                      "decoded one at a time on the same warm server",
        }
        print(json.dumps(rec))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_serve() -> None:
    """BENCH_MODE=serve: concurrent serving end-to-end — submitter
    threads push requests through the ServingServer's admission queue
    and dynamic micro-batcher (SERVING.md) against a STOP-capable
    tiny-or-reference model; the headline is the p50 END-TO-END latency
    a caller observes (enqueue -> resolved future, queue wait and
    coalescing window included), alongside p99, mean batch fill, and
    aggregate requests/sec.  `--serve-hier` (BENCH_SERVE_HIER=1)
    swaps in the ISSUE-19 long-document map-reduce workload instead
    (bench_serve_hier)."""
    if os.environ.get("BENCH_SERVE_HIER", "").lower() in \
            ("1", "on", "true", "yes"):
        bench_serve_hier()
        return
    import shutil
    import tempfile
    from concurrent.futures import ThreadPoolExecutor

    import jax

    from textsummarization_on_flink_tpu import obs
    from textsummarization_on_flink_tpu.config import (
        HParams,
        resolve_refill_chunk,
        resolve_serve_slots,
    )
    from textsummarization_on_flink_tpu.data.vocab import Vocab
    from textsummarization_on_flink_tpu.decode.decoder import (
        BeamSearchDecoder,
    )
    from textsummarization_on_flink_tpu.models import get_family
    from textsummarization_on_flink_tpu.serve.batcher import resolve_buckets
    from textsummarization_on_flink_tpu.serve.server import ServingServer

    from textsummarization_on_flink_tpu.config import SERVE_TIERS

    reqs = int(os.environ.get("BENCH_SERVE_REQS", "64"))
    conc = int(os.environ.get("BENCH_SERVE_CONCURRENCY", "8"))
    batch = int(os.environ.get("BENCH_BATCH", "4"))
    wait_ms = float(os.environ.get("BENCH_SERVE_WAIT_MS", "20"))
    serve_mode = os.environ.get("BENCH_SERVE_MODE", "microbatch")
    mix = os.environ.get("BENCH_SERVE_MIX", "buckets")
    if mix not in ("buckets", "bimodal"):
        # serve_mode is validated by hps.validate(); the mix needs its
        # own guard or a typo silently runs the wrong workload under
        # the requested label
        raise ValueError(
            f"BENCH_SERVE_MIX must be 'buckets' or 'bimodal', got {mix!r}")
    tier = os.environ.get("BENCH_SERVE_TIER", "beam")
    if tier not in SERVE_TIERS:
        raise ValueError(
            f"BENCH_SERVE_TIER must be one of {SERVE_TIERS}, got {tier!r}")
    if serve_mode == "continuous" and tier != "beam":
        raise ValueError(
            "continuous serving decodes at the beam tier only; drop "
            "BENCH_SERVE_TIER or use BENCH_SERVE_MODE=microbatch")
    slots = int(os.environ.get("BENCH_SERVE_SLOTS", "0"))
    refill_chunk = int(os.environ.get("BENCH_SERVE_CHUNK", "0"))
    replicas_n = int(os.environ.get("BENCH_SERVE_REPLICAS", "1"))
    hedge_ms = float(os.environ.get("BENCH_SERVE_HEDGE_MS", "0"))
    # the ISSUE-14 front door: a zipf exponent > 0 draws the request
    # stream heavy-tailed over a pool of DISTINCT articles and arms
    # coalescing + the summary cache (capacity BENCH_SERVE_CACHE) —
    # the duplicate-heavy trending-article workload
    zipf_s = float(os.environ.get("BENCH_SERVE_ZIPF", "0") or 0)
    if zipf_s < 0:
        raise ValueError(
            f"BENCH_SERVE_ZIPF must be >= 0 (0 = off), got {zipf_s}")
    cache_entries = int(os.environ.get("BENCH_SERVE_CACHE", "256")) \
        if zipf_s > 0 else 0
    # paged resident state (ISSUE 20): BENCH_SERVE_ARENA_PAGES=N arms
    # the block-granular page arena — continuous mode decodes through
    # the paged slot kernels and admission waits on free pages
    arena_pages = int(os.environ.get("BENCH_SERVE_ARENA_PAGES", "0") or 0)
    if arena_pages < 0:
        raise ValueError(
            f"BENCH_SERVE_ARENA_PAGES must be >= 0 (0 = dense), got "
            f"{arena_pages}")
    if arena_pages and serve_mode != "continuous":
        raise ValueError(
            "the page arena serves the continuous engine's residents; "
            "drop BENCH_SERVE_ARENA_PAGES or use "
            "BENCH_SERVE_MODE=continuous")
    hps = HParams(batch_size=batch, mode="decode", coverage=True,
                  serve_max_wait_ms=wait_ms, serve_mode=serve_mode,
                  serve_slots=slots, serve_refill_chunk=refill_chunk,
                  serve_max_queue=max(256, reqs),
                  serve_replicas=replicas_n, serve_hedge_ms=hedge_ms,
                  serve_coalesce=zipf_s > 0,
                  serve_cache_entries=cache_entries,
                  serve_arena_pages=arena_pages,
                  **_preset_overrides())
    if tier in ("spec", "draft"):
        # the draft model source: the mapped bootstrap for the
        # transformer family (the real serving recipe), fresh init for
        # the others (exactness holds either way; acceptance is the
        # row's evidence, not an assumption).  BENCH_DRAFT_HIDDEN /
        # BENCH_DRAFT_RANK / BENCH_SPEC_ADAPTIVE bench the ISSUE-12
        # narrow draft + adaptive controller (fingerprinted above when
        # non-default).
        draft_hidden = int(os.environ.get("BENCH_DRAFT_HIDDEN", "0"))
        draft_rank = int(os.environ.get(
            "BENCH_DRAFT_RANK", str(draft_hidden // 2)))
        adaptive = os.environ.get("BENCH_SPEC_ADAPTIVE", "").lower() in \
            ("1", "on", "true", "yes")
        hps = hps.replace(
            spec_draft="map" if hps.model_family == "transformer"
            else "fresh",
            draft_hidden=draft_hidden, draft_vocab_rank=draft_rank,
            spec_k_adaptive=adaptive)
    hps.validate()
    if hps.model_family == "transformer":
        hps = hps.replace(coverage=False)
    rng = np.random.RandomState(0)
    n_words = max(hps.vocab_size - 4, 100)
    vocab = Vocab(words=[f"w{i}" for i in range(n_words)])
    pool = [f"w{i}" for i in range(min(n_words, 2000))]
    buckets = resolve_buckets(hps)
    # short-request fraction of the bimodal mix (ISSUE 11): default
    # 0.75 = the historical every-4th-long shape; fingerprinted only
    # when non-default so banked bimodal records keep matching.  The
    # row carries the EFFECTIVE (cadence-quantized) fraction — see
    # _effective_short_ratio.
    asked_ratio = float(os.environ.get("BENCH_SERVE_SHORT_RATIO",
                                       "0.75"))
    if not 0.0 < asked_ratio < 1.0:
        raise ValueError(
            f"BENCH_SERVE_SHORT_RATIO must be in (0, 1), got "
            f"{asked_ratio}")
    short_ratio = _effective_short_ratio(asked_ratio)
    articles = []
    if mix == "bimodal":
        # the straggler workload (SERVE_SLO.json shape): every
        # long_every-th request a max-length article, the rest short —
        # the load where the micro-batch dispatch barrier hurts, slot
        # refill wins, and (ISSUE 11) disaggregation stops the shorts
        # from paying the longs' encoder shapes
        long_every = _bimodal_long_every(asked_ratio)
        short_n = max(4, hps.max_enc_steps // 8)
        for i in range(_BIMODAL_POOL):
            n = hps.max_enc_steps if i % long_every == 0 else \
                rng.randint(max(short_n // 2, 1), short_n + 1)
            articles.append(" ".join(rng.choice(pool, size=n)))
        rng.shuffle(articles)
    else:
        # one article per bucket length plus a mixed request stream, so
        # the warm phase compiles EVERY bucket and the timed phase
        # exercises bucket routing instead of a single shape
        for i in range(32):
            limit = buckets[i % len(buckets)]
            n = rng.randint(max(limit // 2, 1), limit + 1)
            articles.append(" ".join(rng.choice(pool, size=n)))
    # zipf request ORDER over whichever article pool the mix built:
    # p(k) ~ 1/(k+1)^S, seeded — the same heavy-tailed draw as the
    # SERVE_SLO.json front_door gate, at bench scale
    zipf_order = None
    if zipf_s > 0:
        weights = np.array([1.0 / (k + 1) ** zipf_s
                            for k in range(len(articles))])
        zipf_order = rng.choice(len(articles), size=reqs,
                                p=weights / weights.sum())
    family = get_family(hps.model_family)
    params = family.init_params(hps, hps.vocab_size, jax.random.PRNGKey(0))
    params = _stop_biased(params, hps.vocab_size,
                          float(os.environ.get("BENCH_STOP_BIAS", "6.0")))
    tmp = tempfile.mkdtemp(prefix="bench_serve_")
    try:
        decoder = BeamSearchDecoder(hps, vocab, batcher=None, params=params,
                                    decode_root=tmp)
        if replicas_n > 1:
            # the elastic fleet (ISSUE 13; --serve-replicas): N
            # in-process replicas behind the REAL FleetRouter, sharing
            # the process registry (counters/histograms aggregate
            # across replicas; the per-replica gauges last-writer-win —
            # routing reads each replica's live stats() surface, not
            # the gauges) and the ONE decoder (shared jit cache: the
            # fleet row benches routing + dispatch concurrency, not N
            # redundant compiles)
            from textsummarization_on_flink_tpu.serve.fleet import (
                FleetRouter,
            )

            server = FleetRouter(
                [ServingServer(hps, vocab, decoder=decoder)
                 for _ in range(replicas_n)], hps)
        else:
            server = ServingServer(hps, vocab, decoder=decoder)
        reg = obs.registry()
        fill_h = reg.histogram("serve/batch_fill")
        occ_h = reg.histogram("serve/slot_occupancy")
        with server:
            if serve_mode == "continuous":
                # the decode kernels warm on the first request (ONE
                # resident shape: init/pack/step/unpack), but prefill
                # compiles once per BUCKET (ISSUE 11) — warm every
                # bucket with an exactly-b-word article so no prefill
                # compile lands in the timed run.  Submitted together:
                # the slot engine decodes the warmers concurrently, so
                # warmup costs ~one decode, not len(buckets) decodes
                warm_futs = [
                    server.submit(
                        " ".join(pool[i % len(pool)] for i in range(b)),
                        uuid=f"warm{b}")
                    for b in buckets]
                for f in warm_futs:
                    f.result(timeout=1200)
            else:
                for b in buckets:  # compile every bucket before timing
                    # exactly b words -> enc_len == b -> bucket_for
                    # picks bucket b itself (a shorter article would
                    # warm a SMALLER bucket and leave b's compile in
                    # the timed run)
                    words = [pool[i % len(pool)] for i in range(b)]
                    server.submit(" ".join(words), uuid=f"warm{b}",
                                  tier=tier).result(timeout=1200)
            fills0 = (fill_h.count, fill_h.sum)
            occ0 = (occ_h.count, occ_h.sum)
            # counters snapshot AFTER warm-up, like the histograms: the
            # published row must carry the TIMED run only, on one
            # measurement basis
            refills0 = reg.counter("serve/slot_refills_total").value
            prefill0 = reg.counter("serve/prefill_total").value
            # profiler phase snapshot (obs/profile.py, ISSUE 16): the
            # timed window's per-phase means ride the row as evidence
            # fields — fingerprint-neutral, like the trace split below
            from textsummarization_on_flink_tpu.obs import (
                profile as profile_lib,
            )

            phases0 = profile_lib.profiler_for(reg).phase_stats()
            evict0 = reg.counter("serve/deadline_evictions_total").value
            shed0 = reg.counter("serve/shed_total").value
            degraded0 = reg.counter("serve/degraded_total").value
            drafted0 = reg.counter("decode/spec_draft_tokens_total").value
            accepted0 = reg.counter(
                "decode/spec_accepted_tokens_total").value
            cycles0 = reg.counter("decode/spec_cycles_total").value
            # front-door accounting (ISSUE 14): completed counts only
            # requests that actually DECODED (cache hits resolve at
            # submit, followers from their leader), so decodes/submit
            # is the redundant-work ratio the zipf row exists to show
            completed0 = reg.counter("serve/completed_total").value
            hits0 = reg.counter("serve/cache_hits_total").value
            misses0 = reg.counter("serve/cache_misses_total").value
            coalesced0 = reg.counter("serve/coalesced_total").value
            # arena evidence snapshots (ISSUE 20): the fill histogram
            # gets one observation per refill tick, so the timed
            # delta's mean is the run's mean arena occupancy
            arena_h = reg.histogram("serve/arena_fill")
            arena_fill0 = (arena_h.count, arena_h.sum)
            arena_fail0 = reg.counter(
                "serve/arena_alloc_failures_total").value
            lat: list = []
            # trace-derived per-request breakdown (ISSUE 9 satellite):
            # TEE the timed phase's lifecycle events into memory (an
            # installed EventSink keeps receiving everything — the
            # capture must not eat the run's events.jsonl) and split
            # every e2e latency into queue wait vs resident/decode
            # time — row fields only, fingerprint-neutral
            from textsummarization_on_flink_tpu.obs.export import MemorySink

            prev_sink, trace_sink = reg.event_sink, MemorySink()

            class _Tee:
                def emit(self, rec):
                    ok = trace_sink.emit(rec)
                    if prev_sink is not None:
                        ok = prev_sink.emit(rec) and ok
                    return ok

            def one(i: int) -> None:
                art = articles[int(zipf_order[i])] if zipf_order \
                    is not None else articles[i % len(articles)]
                t0 = time.perf_counter()
                server.submit(art, uuid=f"r{i}",
                              block=True, tier=tier).result(timeout=1200)
                lat.append(time.perf_counter() - t0)

            reg.event_sink = _Tee()
            try:
                t0 = time.perf_counter()
                with ThreadPoolExecutor(max_workers=conc) as ex:
                    list(ex.map(one, range(reqs)))
                wall = time.perf_counter() - t0
            finally:
                reg.event_sink = prev_sink
        # continuous mode dispatches chunks, not micro-batches: report
        # the batch stats as zero rather than clamping to a fabricated
        # one-batch row
        n_batches = fill_h.count - fills0[0]
        fill_mean = ((fill_h.sum - fills0[1]) / n_batches) if n_batches \
            else 0.0
        n_chunks = occ_h.count - occ0[0]
        if serve_mode == "continuous":
            # mean fraction of slots doing useful work per chunk step
            occupancy = ((occ_h.sum - occ0[1]) / n_chunks) if n_chunks \
                else 0.0
        else:
            # micro-batch analogue: mean dispatch fill over the device
            # batch shape (hides straggler waste — the honest
            # per-step utilization comparison lives in SERVE_SLO.json)
            occupancy = fill_mean / hps.batch_size

        def pct(xs, q):
            xs = sorted(xs)
            return xs[min(len(xs) - 1, int(len(xs) * q))]

        # profiler-derived phase means over the timed window: the
        # continuous path's serve/prefill + serve/dispatch (one sample
        # per decode chunk), the micro-batch path's per-tier
        # serve/dispatch (prefill stays 0 there — no prefill stage)
        phases1 = profile_lib.profiler_for(reg).phase_stats()

        def phase_ms_mean(name: str) -> float:
            c1, s1, _ = phases1.get(name, (0, 0.0, 0.0))
            c0, s0, _ = phases0.get(name, (0, 0.0, 0.0))
            n = c1 - c0
            return round(1e3 * (s1 - s0) / n, 3) if n else 0.0

        # arena occupancy over the timed window + the resident-bytes
        # accounting it implies (ISSUE 20).  decode_resident_bytes is
        # eval_shape only (no compile) at the ENGINE's slot count; the
        # paged mean prices the fixed per-slot share plus the measured
        # mean pages in use per slot — the same accounting the
        # BYTE_BUDGET decode.resident gate commits, fed with this run's
        # observed fill instead of an assumed mix.
        arena_ticks = arena_h.count - arena_fill0[0]
        arena_fill_mean = round(
            (arena_h.sum - arena_fill0[1]) / arena_ticks, 4) \
            if arena_ticks else 0.0
        from __graft_entry__ import decode_resident_bytes

        slots_n = resolve_serve_slots(hps)
        rb = decode_resident_bytes(hps.replace(batch_size=slots_n),
                                   pages=arena_pages or None)
        if arena_pages:
            resident_mean = int(
                rb["paged_fixed_bytes_per_slot"]
                + arena_fill_mean * arena_pages * rb["page_bytes"]
                / slots_n)
        else:
            resident_mean = int(rb["dense_bytes_per_slot"])

        # per-uuid first-occurrence timestamps of each lifecycle stage
        per_req: dict = {}
        for ev in trace_sink.records():
            if ev.get("kind") != "request":
                continue
            stages = per_req.setdefault(ev.get("uuid", ""), {})
            stages.setdefault(ev.get("event"), ev.get("ts_us", 0))
        queue_ms, resident_ms = [], []
        for uuid, st in per_req.items():
            if not uuid.startswith("r"):
                continue  # timed requests only (warm-up is w/"warm*")
            if "enqueue" in st and "admit" in st:
                queue_ms.append((st["admit"] - st["enqueue"]) / 1e3)
            end = st.get("finish", st.get("resolve"))
            if "admit" in st and end is not None:
                resident_ms.append((end - st["admit"]) / 1e3)

        _, info = _device_info()
        rec = {
            "metric": "serve_e2e_p50_latency_ms",
            "value": round(pct(lat, 0.5) * 1000, 2),
            "unit": "ms",
            "vs_baseline": 0.0,  # the reference publishes no serving numbers
            "p99_ms": round(pct(lat, 0.99) * 1000, 2),
            "serve_mode": serve_mode,
            "tier": tier,
            "mix": mix,
            "short_ratio": short_ratio if mix == "bimodal" else None,
            "batch_fill_mean": round(fill_mean, 2),
            "occupancy_mean": round(occupancy, 3),
            "batches": n_batches,
            "chunks": n_chunks,
            "slot_refills_total": int(
                reg.counter("serve/slot_refills_total").value - refills0),
            # the disaggregation evidence (ISSUE 11): timed requests
            # through the bucketed prefill stage (0 in microbatch mode)
            "prefill_total": int(
                reg.counter("serve/prefill_total").value - prefill0),
            "deadline_evictions_total": int(
                reg.counter("serve/deadline_evictions_total").value
                - evict0),
            "requests_per_sec": round(reqs / wall, 2),
            # the trace-derived split of the e2e latency above: where a
            # request's time went (queue wait vs resident/decode) —
            # mean + p99 over the timed requests, from the same
            # lifecycle events scripts/trace_summary.py --request reads
            "queue_ms_mean": round(sum(queue_ms) / len(queue_ms), 2)
            if queue_ms else 0.0,
            "queue_ms_p99": round(pct(queue_ms, 0.99), 2)
            if queue_ms else 0.0,
            "resident_ms_mean": round(sum(resident_ms) / len(resident_ms),
                                      2) if resident_ms else 0.0,
            "resident_ms_p99": round(pct(resident_ms, 0.99), 2)
            if resident_ms else 0.0,
            # profiler phase means (ISSUE 16; evidence only): encoder
            # prefill per request vs decode wall per dispatch/chunk
            "prefill_ms_mean": phase_ms_mean("serve/prefill"),
            "decode_ms_mean": phase_ms_mean("serve/dispatch"),
            "traced_requests": len(queue_ms),
            "reqs": reqs,
            "concurrency": conc,
            "batch": batch,
            # through the config resolvers, so the published record
            # carries the slot count / chunk the engine ACTUALLY ran
            # (serve_slots=0 / serve_refill_chunk=0 are sentinels)
            "slots": resolve_serve_slots(hps),
            "refill_chunk": resolve_refill_chunk(hps),
            "wait_ms": wait_ms,
            "buckets": buckets,
            "shed_total": int(reg.counter("serve/shed_total").value - shed0),
            "degraded_total": int(
                reg.counter("serve/degraded_total").value - degraded0),
            # front-door row fields (ISSUE 14): present on every serve
            # row — a dark door reads hit_rate 0, coalesced 0,
            # decodes_per_submit 1.0 (every submit decoded)
            "cache_hit_rate": round(
                (reg.counter("serve/cache_hits_total").value - hits0)
                / max(1.0, (reg.counter("serve/cache_hits_total").value
                            - hits0)
                      + (reg.counter("serve/cache_misses_total").value
                         - misses0)), 4),
            "coalesced_total": int(
                reg.counter("serve/coalesced_total").value - coalesced0),
            "decodes_per_submit": round(
                (reg.counter("serve/completed_total").value - completed0)
                / reqs, 4),
            # paged-arena evidence (ISSUE 20; every serve row, like
            # cache_hit_rate): mean arena occupancy over the timed
            # window (one fill observation per refill tick; 0.0 on
            # dense rows — the histogram never fires) and the MEAN
            # resident bytes one slot actually held — dense rows report
            # the provisioned worst case, arena rows price the fixed
            # share plus the measured mean pages in use.  Fields ride
            # the row; only BENCH_SERVE_ARENA_PAGES is a fingerprint
            # axis.
            "arena_fill_mean": arena_fill_mean,
            "resident_bytes_per_slot_mean": resident_mean,
            "arena_alloc_failures_total": int(
                reg.counter("serve/arena_alloc_failures_total").value
                - arena_fail0),
            # telemetry-plane evidence (ISSUE 15): per-tier fast-window
            # burn rates off the installed SLO engine (SLO_POLICY.json
            # tier_latency objective; {} when no engine installed) and
            # the number of latency buckets carrying a trace exemplar —
            # a row with exemplars is a row whose p99 names a concrete
            # request.  Row fields only, fingerprint-neutral.
            "slo_burn_fast_by_tier": {
                row["key"]: row["burn_fast"]
                for row in (reg.slo.evaluate() if reg.slo is not None
                            else ())
                if row["objective"] == "tier_latency"},
            "exemplar_count": sum(
                len(m.exemplars())
                for m in (reg.get("serve/e2e_latency_seconds"),)
                if m is not None),
            "model_family": hps.model_family,
            "spec_k": int(hps.spec_k),
            "timing": "wall-clock per request, enqueue -> resolved future "
                      "(queue wait + coalescing window included)",
        }
        if replicas_n > 1:
            # fleet evidence (ISSUE 13): hedge spend/wins and requeues
            # ride the row so a fleet measurement carries its own
            # redundant-work accounting (FastSeq's lesson, priced)
            rec["replicas"] = replicas_n
            rec["hedge_ms"] = hedge_ms
            rec["hedges_total"] = int(
                reg.counter("serve/hedges_total").value)
            rec["hedge_wins_total"] = int(
                reg.counter("serve/hedge_wins_total").value)
            rec["requeued_total"] = int(
                reg.counter("serve/requeued_total").value)
        if tier == "spec":
            # measured acceptance -> expected speedup (the BYTE_BUDGET
            # "spec" evidence trail): acceptance comes from THIS run's
            # verifier; the draft/full cost ratio is the committed
            # ceiling, so the published number is conservative
            from textsummarization_on_flink_tpu.decode.speculative import (
                expected_speedup,
            )

            drafted = int(reg.counter(
                "decode/spec_draft_tokens_total").value - drafted0)
            accepted = int(reg.counter(
                "decode/spec_accepted_tokens_total").value - accepted0)
            cycles = int(reg.counter(
                "decode/spec_cycles_total").value - cycles0)
            accept_rate = (accepted / drafted) if drafted else 0.0
            rec["draft_tokens"] = drafted
            rec["accepted_tokens"] = accepted
            rec["accept_rate"] = round(accept_rate, 4)
            # realized mean spec_k (ISSUE 12): drafted tokens are the
            # per-cycle k summed, so the mean k the engine ACTUALLY ran
            # — equals hps.spec_k statically, walks the committed
            # bounds under the adaptive controller
            rec["spec_cycles"] = cycles
            rec["spec_k_mean"] = (round(drafted / cycles, 3) if cycles
                                  else 0.0)
            try:
                budget_path = os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "BYTE_BUDGET.json")
                with open(budget_path) as f:
                    ratio = json.load(f)["spec"]["max_draft_flops_ratio"][
                        hps.model_family]
                rec["expected_speedup_vs_greedy"] = round(
                    expected_speedup(accept_rate, hps.spec_k, ratio), 3)
            except (OSError, KeyError, ValueError):
                pass  # no committed ratio for this family: rate-only row
        rec.update(info)
        rec.update(_obs_extra())
        print(json.dumps(rec))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_bytes() -> None:
    """BENCH_MODE=bytes: roofline byte accounting for the train step from
    XLA's own cost model — runnable on CPU with the TPU tunnel down
    (cost_analysis is computed from the optimized HLO, no execution).

    Compiles the REAL train step at the ask's scale (BENCH_PRESET /
    BENCH_BATCH / BENCH_FAMILY) for the baseline config and each
    byte-diet lever (PERF.md "Byte diet"):

      * ``loss_chunk``  — streaming chunked vocab loss
        (--loss_chunk=BENCH_LOSS_CHUNK, default 25);
      * ``opt_bf16``    — bf16 Adagrad accumulator storage;
      * ``combined``    — both levers together;

    and reports bytes accessed, arithmetic intensity, and each lever's
    reduction vs baseline.  The dp gradient all-reduce lever is reported
    analytically (collective bytes = gradient-tree bytes per step, halved
    by the bf16 wire dtype) — cost_analysis never sees collectives on a
    single-device compile.  The headline value is the BASELINE config's
    bytes/step; reduction_* fields carry the lever claims the byte-budget
    gate (BYTE_BUDGET.json, tests/test_bytes_gate.py) enforces in tier-1.
    """
    from textsummarization_on_flink_tpu.config import HParams
    from __graft_entry__ import train_step_cost as cost_of

    batch = int(os.environ.get("BENCH_BATCH", "16"))
    chunk = int(os.environ.get("BENCH_LOSS_CHUNK", "25"))
    overrides = _preset_overrides()
    overrides.pop("loss_chunk", None)  # the lever axis is swept below
    overrides.pop("opt_state_dtype", None)
    hps0 = HParams(batch_size=batch, compute_dtype="bfloat16", **overrides)

    configs = {
        "baseline": hps0,
        "loss_chunk": hps0.replace(loss_chunk=chunk),
        "opt_bf16": hps0.replace(opt_state_dtype="bfloat16"),
        "combined": hps0.replace(loss_chunk=chunk,
                                 opt_state_dtype="bfloat16"),
    }
    costs = {}
    for name, hps in configs.items():
        sys.stderr.write(f"[bytes] compiling {name} ...\n")
        costs[name] = cost_of(hps)
    base = costs["baseline"]["bytes"]

    # decode rows (ISSUE 7, PERF.md "Decode byte diet"): bytes per
    # emitted token + peak temp of the compiled beam search at the same
    # ask scale — the batch path per loop kind and one step_slots_jit
    # slot chunk (the continuous-serving kernel).  Same single-counted
    # loop-body caveat as the train rows; the committed gate-scale
    # claims live in BYTE_BUDGET.json's decode section.
    from __graft_entry__ import decode_step_cost

    dec_hps = hps0.replace(mode="decode")
    dec_chunk = int(os.environ.get("BENCH_DECODE_CHUNK", "25"))
    decode_rows = {}
    for kind in ("scan", "chunked"):
        sys.stderr.write(f"[bytes] compiling decode/{kind} ...\n")
        c = decode_step_cost(dec_hps, loop=kind,
                             chunk=dec_chunk if kind == "chunked" else None)
        decode_rows[kind] = {
            "bytes": c["bytes"],
            "bytes_per_token": round(c["bytes_per_token"], 1),
            "temp_bytes": c["temp_bytes"],
        }
    sys.stderr.write("[bytes] compiling decode/slot ...\n")
    c = decode_step_cost(dec_hps, path="slot", chunk=dec_chunk)
    decode_rows["slot"] = {
        "bytes": c["bytes"],
        "bytes_per_token": round(c["bytes_per_token"], 1),
        "temp_bytes": c["temp_bytes"],
    }
    # analytic collective bytes from the sharding registry (ISSUE 8):
    # the dp gradient all-reduce moves the registry's per-device
    # reduction set each step (2x on the wire for a ring, but the RATIO
    # is what matters); on a tp mesh (BENCH_MESH) sharded leaves ride
    # the wire as shards
    from textsummarization_on_flink_tpu.parallel import (
        sharding as sharding_lib,
    )

    comms_f32 = sharding_lib.analytic_comms(
        hps0.replace(grad_allreduce_dtype="float32"))
    comms_bf16 = sharding_lib.analytic_comms(
        hps0.replace(grad_allreduce_dtype="bfloat16"))
    _, info = _device_info()
    rec = {
        "metric": "train_step_bytes_accessed",
        "value": base,
        "unit": "bytes",
        "vs_baseline": 0.0,  # the reference publishes no byte accounting
        "levers": {
            name: {
                "bytes": c["bytes"],
                "flops": c["flops"],
                "temp_bytes": c["temp_bytes"],
                "intensity_flops_per_byte": round(
                    c["flops"] / max(c["bytes"], 1.0), 2),
                "reduction_vs_baseline": round(1.0 - c["bytes"] / base, 4),
            } for name, c in costs.items()
        },
        "reduction_loss_chunk": round(
            1.0 - costs["loss_chunk"]["bytes"] / base, 4),
        "reduction_opt_bf16": round(
            1.0 - costs["opt_bf16"]["bytes"] / base, 4),
        "reduction_combined": round(
            1.0 - costs["combined"]["bytes"] / base, 4),
        "grad_allreduce_bytes_f32": comms_f32["dp_wire_bytes"],
        "grad_allreduce_bytes_bf16": comms_bf16["dp_wire_bytes"],
        "decode": decode_rows,
        "decode_chunk": dec_chunk,
        "loss_chunk": chunk,
        "batch": batch,
        "model_family": hps0.model_family,
        "note": "XLA cost_analysis on the optimized HLO (CPU-compiled; "
                "no execution).  Caveats: bytes depend on fusion "
                "decisions, and HloCostAnalysis counts a loop BODY once "
                "(both configs' decoder scans are counted once, so that "
                "cancels in the ratio, but the chunked loss scan's "
                "per-chunk traffic is also single-counted) — treat the "
                "ratios as the cost-model claim; temp_bytes (peak live "
                "temp from memory_analysis) is the loop-independent "
                "evidence the scores value+residual are gone",
    }
    rec.update(info)
    print(json.dumps(rec))


def bench_trainer() -> None:
    """BENCH_MODE=trainer: END-TO-END production-path training
    throughput — the real Trainer.train() over the threaded bucketing
    Batcher, DevicePrefetcher, multi-step dispatch
    (BENCH_SPD=steps_per_dispatch, default 8), windowed metric fetches
    included.  Unlike BENCH_MODE=train (the pure on-device step loop)
    this number pays every real cost a user pays; the gap between the
    two IS the host-side overhead."""
    import shutil
    import tempfile

    import jax

    from textsummarization_on_flink_tpu.config import HParams
    from textsummarization_on_flink_tpu.data.batcher import Batcher
    from textsummarization_on_flink_tpu.train import trainer as trainer_lib

    # default higher than train mode: the timed window deliberately
    # includes the fresh prefetcher's cold start (each train() call
    # builds its own — that ramp IS a real cost of the loop), so enough
    # dispatches must follow to amortize it the way a long run would
    steps = int(os.environ.get("BENCH_STEPS", "120"))
    batch = int(os.environ.get("BENCH_BATCH", "16"))
    spd = int(os.environ.get("BENCH_SPD", "8"))
    # the multi-step executable is specialized per dispatch width k: warm
    # with exactly one full-spd dispatch and round the measured steps to
    # a multiple of spd, so no compile ever lands in the timed window
    warm = spd
    steps = max(steps // spd, 1) * spd
    hps = HParams(batch_size=batch, compute_dtype="bfloat16",
                  steps_per_dispatch=spd, **_preset_overrides())

    tmp = tempfile.mkdtemp(prefix="bench_trainer_")
    try:
        pattern, vocab = _synthetic_dataset(tmp, hps)
        # vocab is sized to hps.vocab_size, so model shapes (and the
        # dominant vocab projection) match BENCH_MODE=train — the gap
        # between the two modes is purely host-side overhead
        assert vocab.size() == hps.vocab_size, (vocab.size(), hps.vocab_size)
        hps = hps.replace(log_root=tmp, exp_name="bench")
        batcher = Batcher(pattern, vocab, hps, single_pass=False)
        trainer = trainer_lib.Trainer(hps, vocab.size(), batcher,
                                      metrics_every=10)
        trainer.train(num_steps=warm)  # compile + queue warm-up
        t0 = time.perf_counter()
        state = trainer.train(num_steps=warm + steps)
        # train() already synced on the final metrics flush; the step
        # fetch closes any remaining gap and doubles as a sanity check
        step_now = int(np.asarray(jax.device_get(state.step)))
        dt = max(time.perf_counter() - t0, 1e-9)
        assert step_now == warm + steps, (step_now, warm, steps)
        samples_per_sec = steps * batch / dt
        dev, info = _device_info()
        flops = (transformer_flops_per_step(hps)
                 if hps.model_family == "transformer"
                 else train_flops_per_step(hps))
        peak = peak_flops_for(dev)
        step_time = dt / steps
        rec = {
            "metric": "trainer_e2e_samples_per_sec",
            "value": round(samples_per_sec, 2),
            "unit": "samples/s",
            "vs_baseline": round(samples_per_sec / BASELINE_SAMPLES_PER_SEC, 2),
            "step_time_ms": round(step_time * 1e3, 3),
            "mfu": (round(flops / step_time / peak, 4) if peak else None),
            "steps_per_dispatch": spd,
            "batch": batch,
            "steps": steps,  # BENCH_STEPS rounded to a multiple of spd
            "warmup_steps": warm,
            "note": "real Trainer loop: batcher + prefetch + dispatch "
                    "+ windowed metric fetches; includes one fresh-"
                    "prefetcher cold start (amortized over `steps`)",
        }
        rec.update(info)
        rec.update(_obs_extra())
        print(json.dumps(rec))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def child_main() -> None:
    if os.environ.get("BENCH_SLEEP_FOR_TEST"):
        # test hook: stand in for a hung TPU tunnel so the supervisor's
        # timeout/stale-fallback path can be exercised without hardware
        time.sleep(float(os.environ["BENCH_SLEEP_FOR_TEST"]))
    mode = os.environ.get("BENCH_MODE", "train")
    if mode == "decode":
        bench_decode()
    elif mode == "attention":
        bench_attention()
    elif mode == "flash":
        bench_flash()
    elif mode == "input":
        bench_input()
    elif mode == "trainer":
        bench_trainer()
    elif mode == "serve":
        bench_serve()
    elif mode == "bytes":
        bench_bytes()
    elif mode == "train":
        bench_train()
    else:
        print(json.dumps({"metric": f"bench_{mode}", "value": 0.0,
                          "unit": "n/a", "vs_baseline": 0.0,
                          "retryable": False,
                          "error": f"unknown BENCH_MODE={mode!r} (train/"
                                   f"trainer/decode/attention/flash/input/"
                                   f"serve/bytes)"}))
        sys.exit(2)


if __name__ == "__main__":
    if "--serve" in sys.argv[1:]:
        # `python bench.py --serve` == BENCH_MODE=serve; set via env so
        # the supervisor's fingerprint AND the re-exec'd child (which
        # never sees argv) both agree on the mode
        os.environ["BENCH_MODE"] = "serve"
    for arg in sys.argv[1:]:
        # serve-mode sub-flags ride the same env hand-off (the child
        # never sees argv): --serve-mode=continuous|microbatch,
        # --serve-mix=bimodal|buckets
        if arg.startswith("--serve-mode="):
            os.environ["BENCH_MODE"] = "serve"
            os.environ["BENCH_SERVE_MODE"] = arg.split("=", 1)[1]
        elif arg.startswith("--serve-mix="):
            os.environ["BENCH_MODE"] = "serve"
            os.environ["BENCH_SERVE_MIX"] = arg.split("=", 1)[1]
        elif arg.startswith("--serve-tier="):
            os.environ["BENCH_MODE"] = "serve"
            os.environ["BENCH_SERVE_TIER"] = arg.split("=", 1)[1]
        elif arg.startswith("--serve-short-ratio="):
            os.environ["BENCH_MODE"] = "serve"
            os.environ["BENCH_SERVE_SHORT_RATIO"] = arg.split("=", 1)[1]
        elif arg.startswith("--serve-replicas="):
            os.environ["BENCH_MODE"] = "serve"
            os.environ["BENCH_SERVE_REPLICAS"] = arg.split("=", 1)[1]
        elif arg.startswith("--serve-hedge-ms="):
            os.environ["BENCH_MODE"] = "serve"
            os.environ["BENCH_SERVE_HEDGE_MS"] = arg.split("=", 1)[1]
        elif arg.startswith("--serve-zipf="):
            os.environ["BENCH_MODE"] = "serve"
            os.environ["BENCH_SERVE_ZIPF"] = arg.split("=", 1)[1]
        elif arg == "--serve-hier" or arg.startswith("--serve-hier="):
            # `--serve-hier[=N]`: the ISSUE-19 long-document map-reduce
            # workload, N chunks wide (BENCH_HIER_CHUNKS)
            os.environ["BENCH_MODE"] = "serve"
            os.environ["BENCH_SERVE_HIER"] = "1"
            if "=" in arg:
                os.environ["BENCH_HIER_CHUNKS"] = arg.split("=", 1)[1]
        elif arg.startswith("--serve-arena-pages="):
            # `--serve-arena-pages=N`: the ISSUE-20 paged resident
            # state — continuous engine over an N-page arena
            os.environ["BENCH_MODE"] = "serve"
            os.environ["BENCH_SERVE_MODE"] = "continuous"
            os.environ["BENCH_SERVE_ARENA_PAGES"] = arg.split("=", 1)[1]
    if os.environ.get("TS_BENCH_CHILD") == "1":
        child_main()
    else:
        supervise()
