"""Estimator/Model pipeline stages (TFEstimator/TFModel parity, TPU-native).

Rebuilds the reference's generic pipeline classes
(/root/reference/src/main/java/org/apache/flink/table/ml/lib/tensorflow/
TFEstimator.java, TFModel.java) without the Flink runtime:

  * `SummarizationEstimator.fit(source) -> SummarizationModel` selects the
    train columns (TFEstimator.java:32-38), parses the hyperparameter argv
    string from the params (the `TF_Hyperparameter` hand-off,
    TFEstimator.java:52 -> run_summarization.py:418-420), streams rows
    through the bridge as serialized tf.Example records (the example-coding
    data plane, CodingUtils.java), trains, and returns a model configured
    with the inference params (TFEstimator.java:86-96).
  * The returned model carries CONFIG ONLY — weights travel via the
    checkpoint directory (`log_root/exp_name/train`), exactly like the
    reference (SURVEY.md §3.1 "Important semantics").
  * `SummarizationModel.transform(source, sink)` mirrors
    TFModel.transform (TFModel.java:56-76): select inference cols
    (uuid, article, reference), decode, emit
    (uuid, article, summary, reference) rows — each flushed to the sink
    immediately (the Issue-6 fix).
  * `to_json()`/`load_json()` persist params-JSON only
    (TensorFlowTest.testJsonExportImport, :142-168).

Deliberate fix over the reference: fit() and transform() work in ONE
process/job, so `Pipeline(estimator -> model)` composes — the reference
could run only one TFUtils call per Flink job (Integration Report:9,
260-282; TensorFlowTest.testPipeline's commented-out half, :170-202).

Execution is eager (fit trains when called); the reference's lazy
job-graph + streamEnv.execute() split has no Flink equivalent here.
"""

from __future__ import annotations

import logging
import os
import re
import threading
from typing import Iterator, List, Optional, Tuple

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.checkpoint import checkpointer as ckpt_lib
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batcher import Batcher
from textsummarization_on_flink_tpu.data.vocab import (
    SENTENCE_END,
    SENTENCE_START,
    Vocab,
)
from textsummarization_on_flink_tpu.decode.decoder import BeamSearchDecoder
from textsummarization_on_flink_tpu.pipeline import bridge as bridge_lib
from textsummarization_on_flink_tpu.pipeline import params as P
from textsummarization_on_flink_tpu.pipeline.codec import ExampleCoding
from textsummarization_on_flink_tpu.pipeline.io import (
    CollectionSink,
    Row,
    RowSchema,
    Sink,
    Source,
)
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

log = logging.getLogger(__name__)

_SENT_RE = re.compile(r"(?<=[.!?])\s+")


def sent_tokenize(text: str) -> List[str]:
    """Sentence split for streamed reference summaries
    (FlinkTrainBatcher's nltk sent_tokenize, batcher.py:643).  Tries nltk,
    falls back to a punctuation split (nltk's punkt data may be absent)."""
    text = text.strip()
    if not text:
        return []
    try:  # pragma: no cover - depends on nltk data presence
        import nltk

        return nltk.tokenize.sent_tokenize(text)
    except (ImportError, LookupError):
        return [s for s in _SENT_RE.split(text) if s]


def reference_to_abstract(reference: str) -> str:
    """'<s> sent </s>'-wrap each sentence (batcher.py:642-644)."""
    return " ".join(f"{SENTENCE_START} {s} {SENTENCE_END}"
                    for s in sent_tokenize(reference))


def rows_to_examples(rows: Iterator[Row]) -> Iterator[Tuple[str, str, str, str]]:
    """(uuid, article, reference) rows -> batcher 4-tuples (the one
    adapter between the streaming row schema and SummaryExample)."""
    for row in rows:
        uuid, article, reference = str(row[0]), str(row[1]), str(row[2])
        yield uuid, article, reference_to_abstract(reference), reference


def train_dir_for(hps: HParams) -> str:
    """`<log_root>/<exp_name>/train` — the weights hand-off directory
    (train.py:64; SURVEY §3.1 'Important semantics')."""
    return os.path.join(hps.log_root or ".", hps.exp_name or "exp", "train")


class PipelineStage(P.WithParams):
    """Base with params-JSON persistence (PipelineStage.toJson parity)."""

    def to_json(self) -> str:
        return self.params.to_json()

    def load_json(self, s: str) -> "PipelineStage":
        self.load_params_json(s)  # typed re-validation of declared params
        return self


class Estimator(PipelineStage):
    """flink-ml Estimator: fit(source) -> Model."""

    def fit(self, source: Source) -> "Model":
        raise NotImplementedError


class Model(PipelineStage):
    """flink-ml Model/Transformer: transform(source) -> rows."""

    def transform(self, source: Source, sink: Optional[Sink] = None) -> Sink:
        raise NotImplementedError

    def output_schema(self, input_schema: "RowSchema") -> "RowSchema":
        """Schema of transform()'s output rows, given the input table's —
        Pipeline chaining wraps intermediate rows in a CollectionSource
        with this.  Pass-through by default (column-preserving
        transformers); stages that reshape the table override
        (SummarizationModel emits the 4-col article-output schema;
        a column-subset transformer narrows it)."""
        return input_schema


class _BridgeFeeder:
    """Driver-side feed pump: source rows -> coded records -> RecordQueue.

    The reference equivalent is Flink streaming `Row`s into AI-Extended's
    example-coding queue toward the python worker (SURVEY.md §2.6 item 3).

    A source error (socket drop, bad JSON, Kafka failure) is captured and
    re-raised on the CONSUMER side after the queue drains — a failed stream
    must fail the job, not silently truncate the dataset.
    """

    def __init__(self, source: Source, selected_cols: List[str],
                 coding: ExampleCoding, q: bridge_lib.RecordQueue,
                 registry=None):
        self._source = source
        self._cols = selected_cols
        self._coding = coding
        self._q = q
        # the job's registry, not the process default — HParams(obs=False)
        # must run the feeder dark too
        self._reg = registry if registry is not None else obs.registry()
        self.error: Optional[BaseException] = None
        self.thread = threading.Thread(target=self._run, daemon=True)

    def start(self) -> "_BridgeFeeder":
        self.thread.start()
        return self

    def _run(self) -> None:
        c_rows = self._reg.counter("pipeline/rows_in_total")
        c_codec = self._reg.counter("pipeline/codec_errors_total")
        try:
            for row in self._source.rows():
                projected = self._source.schema.project_row(row, self._cols)
                try:
                    rec = self._coding.encode(projected)
                except (TypeError, ValueError, KeyError):
                    # a row the codec cannot encode is a poisoned stream,
                    # not a skippable record — count it, then fail the job
                    # through the established raise_if_failed path
                    c_codec.inc()
                    raise
                c_rows.inc()
                if not self._q.put(rec):
                    if self._q.closed:  # consumer finished early: cancel
                        log.info("record queue closed by consumer; "
                                 "cancelling source stream")
                        return
        except BaseException as e:  # propagated via raise_if_failed
            self.error = e
            self._reg.counter("pipeline/feeder_errors_total").inc()
            log.exception("source stream failed")
        finally:
            self._q.close()

    def finish(self) -> None:
        """Cancel any remaining stream, reap the thread, surface errors.
        Callers run this after the consumer stops (early or not) so a
        stopped job neither leaks the feeder nor hides a source failure."""
        self._q.close()
        self.thread.join(timeout=10.0)
        if self.thread.is_alive():  # pragma: no cover - defensive
            log.warning("bridge feeder did not stop within 10s")
        self.raise_if_failed()

    def raise_if_failed(self) -> None:
        if self.error is not None:
            raise RuntimeError("source stream failed mid-job; partial data "
                               "would corrupt the result") from self.error


def _rows_from_queue(q: bridge_lib.RecordQueue, coding: ExampleCoding,
                     ) -> Iterator[Row]:
    while True:
        rec = q.get(timeout=1.0)
        if rec is None:
            if q.closed and len(q) == 0:
                return
            continue
        yield coding.decode(rec)


class SummarizationModel(Model,
                         P.HasClusterConfig,
                         P.HasInferencePythonConfig,
                         P.HasInferenceSelectedCols,
                         P.HasInferenceOutputCols,
                         P.HasInferenceOutputTypes):
    """Generic inference stage (TFModel.java parity).

    transform() consumes (uuid, article, reference) rows, beam-decodes each
    article, and emits (uuid, article, summary, reference) — the
    write_for_flink row (decode.py:159-185, flink_writer.py:22-34).
    """

    def __init__(self) -> None:
        P.WithParams.__init__(self)
        self._vocab_override: Optional[Vocab] = None

    # test/embedding hook: skip reading vocab_path from disk
    def with_vocab(self, vocab: Vocab) -> "SummarizationModel":
        self._vocab_override = vocab
        return self

    def _hps(self) -> HParams:
        argv = self.get_inference_hyper_params() or []
        hps = HParams.from_string(" ".join(argv))
        return hps.replace(mode="decode")

    def _vocab(self, hps: HParams) -> Vocab:
        if self._vocab_override is not None:
            return self._vocab_override
        return Vocab(hps.vocab_path, hps.vocab_size)

    def output_schema(self, input_schema: RowSchema) -> RowSchema:
        from textsummarization_on_flink_tpu.pipeline.io import (
            ARTICLE_OUTPUT_SCHEMA,
        )

        return ARTICLE_OUTPUT_SCHEMA  # (uuid, article, summary, reference)

    def transform(self, source: Source, sink: Optional[Sink] = None,
                  max_batches: int = 0, serving: bool = False,
                  hierarchical: bool = False) -> Sink:
        """serving=False (default): the original synchronous path —
        bridge feeder -> threaded Batcher -> decoder.decode() loop.
        serving=True: route the same rows through the concurrent
        ``serve.ServingServer`` (admission-controlled queue + dynamic
        micro-batching + shape buckets, SERVING.md) — same
        (uuid, article, summary, reference) rows out, but sink order
        follows completion, not arrival (rows are uuid-keyed).
        hierarchical=True: the long-document stage (SERVING.md
        "Hierarchical summarization") — framed input rows reassemble
        into whole documents (pipeline/codec.py DocumentAssembler),
        each document map-reduces over the serving fleet
        (serve/hiersum.py), and one summary row per document REVISION
        comes out; a later frame-set for a known doc id is appended
        text, re-summarized with every unchanged chunk cache-hitting."""
        if hierarchical:
            return self._transform_hierarchical(source, sink,
                                                max_batches=max_batches)
        if serving:
            return self._transform_serving(source, sink,
                                           max_batches=max_batches)
        hps = self._hps()
        hps.validate()
        vocab = self._vocab(hps)
        out_sink = sink if sink is not None else CollectionSink()
        sel = self.get_inference_selected_cols()  # uuid, article, reference
        in_schema = source.schema.select(sel)
        coding = ExampleCoding(in_schema, in_schema)
        q = bridge_lib.make_record_queue()
        reg = obs.registry_for(hps)
        feeder = _BridgeFeeder(source, sel, coding, q, registry=reg).start()

        def example_source():
            # inference has no gold abstract; reference text rides along
            return rows_to_examples(_rows_from_queue(q, coding))

        batcher = Batcher("", vocab, hps, single_pass=True,
                          decode_batch_mode="distinct",
                          example_source=example_source)
        train_dir = train_dir_for(hps)
        decoder = BeamSearchDecoder(
            hps.replace(single_pass=False), vocab, batcher,
            train_dir=train_dir,
            decode_root=os.path.join(hps.log_root or ".",
                                     hps.exp_name or "exp"))
        c_out = reg.counter("pipeline/rows_out_total")

        def emit(res):
            out_sink.write(res.as_row())
            c_out.inc()

        try:
            with obs.spans.span(reg, "pipeline/transform"):
                decoder.decode(result_sink=emit, max_batches=max_batches,
                               log_results=False)
        finally:
            feeder.finish()
        return out_sink

    def _transform_serving(self, source: Source,
                           sink: Optional[Sink] = None,
                           max_batches: int = 0) -> Sink:
        """Concurrent transform: ServingServer drives the source/sink
        pair through the admission-controlled queue (SERVING.md).

        ``max_batches`` keeps its synchronous-path meaning of bounding
        work against an unbounded source: serving batches are dynamic,
        so the bound maps to at most ``max_batches * batch_size`` rows
        (== max_batches FULL device batches' worth)."""
        from textsummarization_on_flink_tpu.serve.server import ServingServer

        hps = self._hps()
        hps.validate()
        vocab = self._vocab(hps)
        out_sink = sink if sink is not None else CollectionSink()
        reg = obs.registry_for(hps)
        c_out = reg.counter("pipeline/rows_out_total")

        class _CountedSink(Sink):
            # keep the pipeline-layer row accounting identical to the
            # synchronous path while returning the caller's own sink
            def write(self, row: Row) -> None:
                out_sink.write(row)
                c_out.inc()

        server = ServingServer(
            hps.replace(single_pass=False), vocab,
            train_dir=train_dir_for(hps),
            decode_root=os.path.join(hps.log_root or ".",
                                     hps.exp_name or "exp"),
            registry=reg)
        with obs.spans.span(reg, "pipeline/transform_serving"):
            with server:
                server.serve(source, _CountedSink(),
                             cols=self.get_inference_selected_cols(),
                             max_count=max_batches * hps.batch_size)
        return out_sink

    def _transform_hierarchical(self, source: Source,
                                sink: Optional[Sink] = None,
                                max_batches: int = 0) -> Sink:
        """Long-document transform: frames -> documents -> map-reduce.

        The stage owns the driver-side streaming state the server must
        not know about: the ``DocumentAssembler`` (frame reassembly) and
        one ``DocumentSession`` per doc id, so a doc id whose frame-set
        completes AGAIN is an append + re-summarize — the session's
        prior chunk keys make the front door dedup pinnable.  Chunk
        submits use block=True (pipeline backpressure, same stance as
        ``server.serve``); completed documents write to the sink from
        the parent future's done-callback, so sink order follows
        completion.  ``max_batches`` bounds completed DOCUMENT
        revisions (``max_batches * batch_size``), mirroring the other
        transform paths' row bound."""
        from textsummarization_on_flink_tpu.pipeline.codec import (
            DocumentAssembler,
        )
        from textsummarization_on_flink_tpu.serve.hiersum import (
            DocumentSession,
            HierarchicalSummarizer,
        )
        from textsummarization_on_flink_tpu.serve.server import ServingServer

        hps = self._hps()
        hps.validate()
        vocab = self._vocab(hps)
        out_sink = sink if sink is not None else CollectionSink()
        reg = obs.registry_for(hps)
        c_out = reg.counter("pipeline/rows_out_total")
        sel = self.get_inference_selected_cols()  # uuid, article, reference
        max_docs = max_batches * hps.batch_size
        assembler = DocumentAssembler(registry=reg)
        sessions = {}  # doc id -> DocumentSession
        last = {}  # doc id -> most recent revision's parent future
        futures: List = []
        errors: List[BaseException] = []
        lock = threading.Lock()

        def _emit(fut) -> None:
            # runs on the server's resolve thread — cheap append only
            if fut.error is not None:
                with lock:
                    errors.append(fut.error)
                return
            row = fut.result(timeout=0).as_row()
            with lock:
                out_sink.write(row)
                c_out.inc()

        server = ServingServer(
            hps.replace(single_pass=False), vocab,
            train_dir=train_dir_for(hps),
            decode_root=os.path.join(hps.log_root or ".",
                                     hps.exp_name or "exp"),
            registry=reg)
        truncated = False
        with obs.spans.span(reg, "pipeline/transform_hierarchical"):
            with server:
                hs = HierarchicalSummarizer(server, hps, registry=reg)
                for row in source.rows():
                    doc = assembler.feed(
                        source.schema.project_row(row, sel))
                    if doc is None:
                        continue
                    doc_id, article, reference = doc
                    sess = sessions.get(doc_id)
                    if sess is None:
                        sess = sessions[doc_id] = DocumentSession(
                            doc_id, article)
                    else:
                        # a revision: the new frame-set is APPENDED text.
                        # Serialize revisions per stream first — revision
                        # N+1's dedup rides the front-door CACHE, which
                        # only holds a chunk's entry once revision N's
                        # copy retired; overlapping in-flight revisions
                        # would coalesce instead of cache-hit.  One open
                        # document is one in-order stream.
                        prev = last.get(doc_id)
                        if prev is not None:
                            try:
                                prev.result()
                            except Exception:  # tslint: disable=TS005 — barrier only: the typed cause already landed in `errors` via _emit's done-callback and re-raises after the drain
                                pass
                        sess.append(article)
                    fut = hs.summarize("", reference=reference,
                                       session=sess, block=True)
                    last[doc_id] = fut
                    fut.add_done_callback(_emit)
                    futures.append(fut)
                    if max_docs and len(futures) >= max_docs:
                        truncated = True
                        break
                for fut in futures:
                    try:
                        fut.result()  # errors re-raise below, in order
                    except Exception:  # tslint: disable=TS005 — drain barrier: every rejection was captured typed in `errors` by _emit and the first re-raises after the loop
                        pass
        pending = assembler.pending()
        if pending and not truncated:
            raise RuntimeError(
                f"source stream ended with incomplete document "
                f"frame-sets: {pending}; partial documents would "
                f"corrupt the result")
        if errors:
            raise errors[0]
        return out_sink


class SummarizationEstimator(Estimator,
                             P.HasClusterConfig,
                             P.HasTrainPythonConfig,
                             P.HasInferencePythonConfig,
                             P.HasTrainSelectedCols,
                             P.HasTrainOutputCols,
                             P.HasTrainOutputTypes,
                             P.HasInferenceSelectedCols,
                             P.HasInferenceOutputCols,
                             P.HasInferenceOutputTypes):
    """Generic trainable stage (TFEstimator.java parity)."""

    def __init__(self) -> None:
        P.WithParams.__init__(self)
        self._vocab_override: Optional[Vocab] = None

    def with_vocab(self, vocab: Vocab) -> "SummarizationEstimator":
        self._vocab_override = vocab
        return self

    def _hps(self) -> HParams:
        argv = self.get_train_hyper_params() or []
        hps = HParams.from_string(" ".join(argv))
        return hps.replace(mode="train")

    def _vocab(self, hps: HParams) -> Vocab:
        if self._vocab_override is not None:
            return self._vocab_override
        return Vocab(hps.vocab_path, hps.vocab_size)

    def fit(self, source: Source) -> SummarizationModel:
        from textsummarization_on_flink_tpu.utils import apply_debug_mode

        hps = self._hps()
        hps.validate()
        apply_debug_mode(hps)  # --debug -> jax_debug_nans (ref :216-218)
        vocab = self._vocab(hps)
        sel = self.get_train_selected_cols()  # uuid, article, reference
        in_schema = source.schema.select(sel)
        coding = ExampleCoding(in_schema, in_schema)
        q = bridge_lib.make_record_queue()
        feeder = _BridgeFeeder(source, sel, coding, q,
                               registry=obs.registry_for(hps)).start()

        def example_source():
            return rows_to_examples(_rows_from_queue(q, coding))

        batcher = Batcher("", vocab, hps, single_pass=True,
                          example_source=example_source)
        train_dir = train_dir_for(hps)
        checkpointer = ckpt_lib.Checkpointer(train_dir, hps=hps)
        prev = checkpointer.restore()
        state = None
        if prev is not None:
            log.info("resuming training from step %d", int(prev.step))
            state = prev
        trainer = trainer_lib.Trainer(hps, vocab.size(), batcher,
                                      state=state, checkpointer=checkpointer,
                                      train_dir=train_dir)
        try:
            with obs.spans.span(obs.registry_for(hps), "pipeline/fit"):
                trainer.train(num_steps=hps.num_steps)
        finally:
            feeder.finish()

        # configure the model with the inference side of our params
        # (TFEstimator.java:86-96)
        model = SummarizationModel()
        model.set_coordinator_address(self.get_coordinator_address())
        model.set_worker_num(self.get_worker_num())
        model.set_ps_num(self.get_ps_num())
        if self.get_inference_scripts() is not None:
            model.set_inference_scripts(self.get_inference_scripts())
        model.set_inference_map_func(self.get_inference_map_func())
        model.set_inference_hyper_params_key(
            self.get_inference_hyper_params_key())
        if self.get_inference_hyper_params() is not None:
            model.set_inference_hyper_params(self.get_inference_hyper_params())
        if self.get_inference_env_path() is not None:
            model.set_inference_env_path(self.get_inference_env_path())
        model.set_inference_selected_cols(self.get_inference_selected_cols())
        model.set_inference_output_cols(self.get_inference_output_cols())
        model.set_inference_output_types(self.get_inference_output_types())
        if self._vocab_override is not None:
            model.with_vocab(self._vocab_override)
        return model


class Pipeline:
    """Minimal Pipeline(stages) with appendStage/fit semantics — the thing
    TensorFlowTest.testPipeline (:170-202) could only half-exercise; here
    an Estimator inside a pipeline works because fit+transform share one
    process."""

    def __init__(self, stages: Optional[List[PipelineStage]] = None):
        self.stages: List[PipelineStage] = list(stages or [])

    def append_stage(self, stage: PipelineStage) -> "Pipeline":
        self.stages.append(stage)
        return self

    @staticmethod
    def _apply(stage: Model, source: Source) -> Source:
        """Run one Model/Transformer stage, materializing its output rows
        as the next stage's source (the reference pipeline re-streams
        tables between stages)."""
        from textsummarization_on_flink_tpu.pipeline.io import (
            CollectionSource,
        )

        mid = stage.transform(source, CollectionSink())
        return CollectionSource(mid.rows,
                                schema=stage.output_schema(source.schema))

    def fit(self, source: Source) -> "Pipeline":
        """Fit every Estimator on the table as transformed by every
        PRECEDING stage — flink-ml Pipeline.fit semantics, and the
        SelectColTransformer->estimator shape TensorFlowTest.testPipeline
        (:170-202) wanted but had to comment out.  Chaining is lazy: the
        preceding Transformers/Models materialize into a CollectionSource
        only when a later Estimator actually fits, so the common
        estimator->model pipeline never beam-decodes its own training
        set just to produce an output nobody consumes."""
        fitted: List[PipelineStage] = []
        cur_source = source
        pending: List[Model] = []  # stages not yet applied to cur_source
        for stage in self.stages:
            if isinstance(stage, Estimator):
                for prior in pending:
                    cur_source = self._apply(prior, cur_source)
                pending = []
                model = stage.fit(cur_source)
                fitted.append(model)
                pending.append(model)
            else:
                fitted.append(stage)
                pending.append(stage)
        return Pipeline(fitted)

    def transform(self, source: Source, sink: Optional[Sink] = None) -> Sink:
        """Chain every Model stage: each stage's output rows become the
        next stage's source; the last stage writes into `sink`."""
        models = [s for s in self.stages if isinstance(s, Model)]
        if not models:
            raise ValueError("pipeline has no Model stage to transform with")
        out = sink if sink is not None else CollectionSink()
        cur_source = source
        for stage in models[:-1]:
            cur_source = self._apply(stage, cur_source)
        return models[-1].transform(cur_source, out)
