"""Row <-> tf.Example wire codec driven by schemas (CodingUtils parity).

The reference configures Flink-AI-Extended's `ExampleCoding` from table
schemas (/root/reference/src/main/java/org/apache/flink/table/ml/lib/
tensorflow/util/CodingUtils.java): each side of the Java<->Python data
plane gets an encode and/or decode config derived from the column
names/types (:131-145), with null schemas tolerated on either side
(:196-206) — the encode-only/decode-only/neither matrix that
InputOutputTest.java exercises.

Here the data plane is the pipeline driver <-> worker bridge, and the wire
format is the same serialized tf.Example (data/tfexample.py).  Type mapping
follows CodingUtils.java:25-129: ints (and BOOL as 0/1) ride the int64
list, floats the float list, STRING the bytes list, FLOAT_32_ARRAY a
multi-valued float list; unsupported types raise.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from textsummarization_on_flink_tpu.data.tfexample import Example
from textsummarization_on_flink_tpu.pipeline.io import DataTypes, Row, RowSchema


def encode_row(schema: RowSchema, row: Row) -> bytes:
    """Row -> serialized tf.Example (ExampleCodingConfig.createRowToExample)."""
    if len(row) != len(schema):
        raise ValueError(f"row arity {len(row)} != schema arity {len(schema)}")
    ex = Example()
    for name, typ, val in zip(schema.names, schema.types, row):
        if typ == DataTypes.STRING:
            ex.set_bytes(name, str(val).encode("utf-8"))
        elif typ in DataTypes._INTS:
            ex.set_ints(name, int(val))
        elif typ in DataTypes._FLOATS:
            ex.set_floats(name, float(val))
        elif typ == DataTypes.FLOAT_32_ARRAY:
            ex.set_floats(name, *[float(v) for v in val])
        else:  # pragma: no cover - validate() blocks earlier
            raise ValueError(f"Unsupported data type for example coding: {typ}")
    return ex.serialize()


def decode_example(schema: RowSchema, data: bytes) -> Row:
    """Serialized tf.Example -> Row in schema column order."""
    ex = Example.parse(data)
    out: List = []
    for name, typ in zip(schema.names, schema.types):
        vals = ex.features.get(name, [])
        if typ == DataTypes.STRING:
            out.append(ex.get_str(name))
        elif typ == DataTypes.BOOL:
            out.append(bool(vals[0]) if vals else False)
        elif typ in DataTypes._INTS:
            out.append(int(vals[0]) if vals else 0)
        elif typ in DataTypes._FLOATS:
            out.append(float(vals[0]) if vals else 0.0)
        elif typ == DataTypes.FLOAT_32_ARRAY:
            out.append([float(v) for v in vals])
        else:  # pragma: no cover
            raise ValueError(f"Unsupported data type for example coding: {typ}")
    return tuple(out)


class ExampleCoding:
    """Both directions with the null-schema tolerance of
    CodingUtils.configureExampleCoding (:196-206): a missing input schema
    disables encoding, a missing output schema disables decoding — rows
    then pass through untouched (the fix for AI-Extended Issue-7 NPEs,
    Integration Report:620-672)."""

    def __init__(self, input_schema: Optional[RowSchema],
                 output_schema: Optional[RowSchema]):
        self.input_schema = input_schema
        self.output_schema = output_schema

    def encode(self, row: Row):
        if self.input_schema is None:
            return row  # pass-through (encode not configured)
        return encode_row(self.input_schema, row)

    def decode(self, data):
        if self.output_schema is None:
            return data  # pass-through (decode not configured)
        return decode_example(self.output_schema, data)


# --------------------------------------------------------------------------
# Multi-row document framing (ISSUE 19)
#
# The row wire format caps practical article size at one row's payload; a
# long document rides the SAME transport as N framed rows whose uuids carry
# the reassembly key: "{doc}#{i}/{n}" with 1-based part index i.  Framing is
# TRANSPORT, not summarization — frame width has no semantic meaning, while
# hiersum's chunk width does (overlap, cache keys).  The assembler therefore
# re-joins the full article before the hierarchical stage re-chunks it.

_FRAME_RE = re.compile(r"^(?P<doc>.+)#(?P<i>\d+)/(?P<n>\d+)$")


class DocumentFramingError(ValueError):
    """A framed row violates the reassembly contract (inconsistent total,
    duplicate or out-of-range part index).  A corrupt frame stream must
    fail the job, not emit a silently-truncated document — the same
    poisoned-stream stance as the codec itself."""


def parse_document_frame(uuid: str) -> Optional[Tuple[str, int, int]]:
    """"doc#i/n" -> (doc, i, n) with 1-based i; None for unframed uuids.
    Zero/overflowing indices are NOT silently unframed — a uuid that looks
    framed but is malformed is an error the assembler raises on."""
    m = _FRAME_RE.match(uuid)
    if m is None:
        return None
    return m.group("doc"), int(m.group("i")), int(m.group("n"))


def frame_document_rows(uuid: str, article: str, reference: str,
                        frame_words: int) -> List[Row]:
    """Producer-side split of one document into framed
    (uuid, article, reference) rows of at most ``frame_words`` words each.
    The reference rides only the first frame (the assembler takes the
    first non-empty one); a document that fits one frame still gets the
    "#1/1" suffix so append frames for the same doc id compose."""
    if frame_words < 1:
        raise ValueError(f"frame_words must be >= 1, got {frame_words}")
    words = article.split()
    if not words:
        raise ValueError(f"document {uuid!r} has no words to frame")
    parts = [words[i:i + frame_words]
             for i in range(0, len(words), frame_words)]
    n = len(parts)
    return [(f"{uuid}#{i + 1}/{n}", " ".join(p),
             reference if i == 0 else "")
            for i, p in enumerate(parts)]


class DocumentAssembler:
    """Streaming reassembly of framed rows into whole-document rows.

    ``feed(row)`` buffers framed parts per doc id and returns the
    completed (doc_id, article, reference) row when the last part lands;
    unframed rows pass through unchanged (mixed streams are legal —
    framing is opt-in per document).  Parts may arrive out of order
    WITHIN a document (the buffer is index-keyed); what raises is
    contract violation: a part total disagreeing with earlier frames of
    the same doc, a duplicate index, or an index outside 1..n — each
    counted in ``pipeline/codec_errors_total`` before raising, so the
    poisoned-stream metric covers framing corruption too.

    A doc id may complete MORE than once: each completed frame-set is
    one revision, and the hierarchical stage treats revisions after the
    first as appended text (pipeline/estimator.py)."""

    def __init__(self, registry=None):
        from textsummarization_on_flink_tpu import obs

        self._reg = registry if registry is not None else obs.registry()
        self._c_err = self._reg.counter("pipeline/codec_errors_total")
        # doc -> (total, {index: article part}, reference)
        self._parts: Dict[str, Tuple[int, Dict[int, str], str]] = {}

    def _fail(self, msg: str) -> None:
        self._c_err.inc()
        raise DocumentFramingError(msg)

    def feed(self, row: Row) -> Optional[Row]:
        uuid, article, reference = str(row[0]), str(row[1]), str(row[2])
        frame = parse_document_frame(uuid)
        if frame is None:
            return row
        doc, i, n = frame
        if n < 1 or not (1 <= i <= n):
            self._fail(f"frame {uuid!r}: index {i} outside 1..{n}")
        total, buf, ref = self._parts.get(doc, (n, {}, ""))
        if total != n:
            self._fail(f"frame {uuid!r}: part total {n} != {total} "
                       f"seen earlier for doc {doc!r}")
        if i in buf:
            self._fail(f"frame {uuid!r}: duplicate part index")
        buf[i] = article
        if not ref and reference:
            ref = reference
        if len(buf) < n:
            self._parts[doc] = (total, buf, ref)
            return None
        # a single-frame doc completes without ever buffering; either
        # way the doc id may start a NEW frame-set (revision) after this
        self._parts.pop(doc, None)
        joined = " ".join(buf[k] for k in range(1, n + 1))
        return (doc, joined, ref)

    def pending(self) -> List[str]:
        """Doc ids with buffered but incomplete frame-sets — non-empty at
        natural stream end means a truncated stream (caller raises)."""
        return sorted(self._parts)
