"""Row <-> tf.Example wire codec driven by schemas (CodingUtils parity).

The reference configures Flink-AI-Extended's `ExampleCoding` from table
schemas (/root/reference/src/main/java/org/apache/flink/table/ml/lib/
tensorflow/util/CodingUtils.java): each side of the Java<->Python data
plane gets an encode and/or decode config derived from the column
names/types (:131-145), with null schemas tolerated on either side
(:196-206) — the encode-only/decode-only/neither matrix that
InputOutputTest.java exercises.

Here the data plane is the pipeline driver <-> worker bridge, and the wire
format is the same serialized tf.Example (data/tfexample.py).  Type mapping
follows CodingUtils.java:25-129: ints (and BOOL as 0/1) ride the int64
list, floats the float list, STRING the bytes list, FLOAT_32_ARRAY a
multi-valued float list; unsupported types raise.
"""

from __future__ import annotations

from typing import List, Optional

from textsummarization_on_flink_tpu.data.tfexample import Example
from textsummarization_on_flink_tpu.pipeline.io import DataTypes, Row, RowSchema


def encode_row(schema: RowSchema, row: Row) -> bytes:
    """Row -> serialized tf.Example (ExampleCodingConfig.createRowToExample)."""
    if len(row) != len(schema):
        raise ValueError(f"row arity {len(row)} != schema arity {len(schema)}")
    ex = Example()
    for name, typ, val in zip(schema.names, schema.types, row):
        if typ == DataTypes.STRING:
            ex.set_bytes(name, str(val).encode("utf-8"))
        elif typ in DataTypes._INTS:
            ex.set_ints(name, int(val))
        elif typ in DataTypes._FLOATS:
            ex.set_floats(name, float(val))
        elif typ == DataTypes.FLOAT_32_ARRAY:
            ex.set_floats(name, *[float(v) for v in val])
        else:  # pragma: no cover - validate() blocks earlier
            raise ValueError(f"Unsupported data type for example coding: {typ}")
    return ex.serialize()


def decode_example(schema: RowSchema, data: bytes) -> Row:
    """Serialized tf.Example -> Row in schema column order."""
    ex = Example.parse(data)
    out: List = []
    for name, typ in zip(schema.names, schema.types):
        vals = ex.features.get(name, [])
        if typ == DataTypes.STRING:
            out.append(ex.get_str(name))
        elif typ == DataTypes.BOOL:
            out.append(bool(vals[0]) if vals else False)
        elif typ in DataTypes._INTS:
            out.append(int(vals[0]) if vals else 0)
        elif typ in DataTypes._FLOATS:
            out.append(float(vals[0]) if vals else 0.0)
        elif typ == DataTypes.FLOAT_32_ARRAY:
            out.append([float(v) for v in vals])
        else:  # pragma: no cover
            raise ValueError(f"Unsupported data type for example coding: {typ}")
    return tuple(out)


class ExampleCoding:
    """Both directions with the null-schema tolerance of
    CodingUtils.configureExampleCoding (:196-206): a missing input schema
    disables encoding, a missing output schema disables decoding — rows
    then pass through untouched (the fix for AI-Extended Issue-7 NPEs,
    Integration Report:620-672)."""

    def __init__(self, input_schema: Optional[RowSchema],
                 output_schema: Optional[RowSchema]):
        self.input_schema = input_schema
        self.output_schema = output_schema

    def encode(self, row: Row):
        if self.input_schema is None:
            return row  # pass-through (encode not configured)
        return encode_row(self.input_schema, row)

    def decode(self, data):
        if self.output_schema is None:
            return data  # pass-through (decode not configured)
        return decode_example(self.output_schema, data)
