"""Streaming summarization application (me/littlebo/App.java parity).

The reference App wires two Kafka-driven jobs (App.java:202-207):
  1. `start_training()`: consume `flink_train`, fit the estimator, return
     the model's config JSON (App.java:83-106);
  2. `start_inference(model_json)`: consume `flink_input`, transform, sink
     summaries to `flink_output` (App.java:108-132).

They run sequentially in the reference because one Flink job could hold
only one TFUtils call; here they share a process and could equally run as
one pipeline (pipeline/estimator.Pipeline).  Sources/sinks are pluggable:
Kafka by default (topics App.java:32-34), or any Source/Sink for tests —
the reference's socket test path (TensorFlowTest.java:123-140).

Hyperparameters follow App.java:40-81: one argv string per role, built
from an HParams; the defaults here mirror the reference's app settings
(batch 2/4, enc 50/400, dec 10/100, beam 4, vocab 50k, single worker).
"""

from __future__ import annotations

import logging
import shlex
from typing import Optional

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.pipeline.estimator import (
    SummarizationEstimator,
    SummarizationModel,
)
from textsummarization_on_flink_tpu.pipeline.io import (
    DataTypes,
    KafkaSink,
    KafkaSource,
    PrintSink,
    Sink,
    Source,
)

log = logging.getLogger(__name__)

# App.java:32-34
TRAIN_TOPIC = "flink_train"
INPUT_TOPIC = "flink_input"
OUTPUT_TOPIC = "flink_output"


def default_train_hps(log_root: str, exp_name: str = "exp",
                      vocab_path: str = "", num_steps: int = 0) -> HParams:
    """App.java:55-68 training hyperparameters (train side)."""
    return HParams(mode="train", num_steps=num_steps, batch_size=2,
                   max_enc_steps=50, max_dec_steps=10, vocab_size=50000,
                   log_root=log_root, exp_name=exp_name,
                   vocab_path=vocab_path, coverage=True)


def default_inference_hps(log_root: str, exp_name: str = "exp",
                          vocab_path: str = "") -> HParams:
    """App.java:69-81 inference hyperparameters (decode side)."""
    return HParams(mode="decode", batch_size=4, max_enc_steps=400,
                   max_dec_steps=100, beam_size=4, min_dec_steps=35,
                   vocab_size=50000, log_root=log_root, exp_name=exp_name,
                   vocab_path=vocab_path, coverage=True, single_pass=False)


class App:
    """End-to-end driver.  Construct with explicit sources/sinks for tests,
    or rely on the Kafka defaults (App.java topology)."""

    def __init__(self, train_hps: HParams, inference_hps: HParams,
                 vocab: Optional[Vocab] = None,
                 bootstrap_servers: str = "localhost:9092"):
        self.train_hps = train_hps
        self.inference_hps = inference_hps
        self.vocab = vocab
        self.bootstrap_servers = bootstrap_servers

    # -- wiring (createEstimator / createModel, App.java:147-200) --
    def create_estimator(self) -> SummarizationEstimator:
        e = SummarizationEstimator()
        e.set_worker_num(1).set_ps_num(0)  # App.java:148-149
        (e.set_train_selected_cols(["uuid", "article", "reference"])
          .set_train_output_cols(["uuid"])
          .set_train_output_types([DataTypes.STRING]))
        e.set_train_hyper_params(shlex.split(self.train_hps.to_argv()))
        (e.set_inference_selected_cols(["uuid", "article", "reference"])
          .set_inference_output_cols(["uuid", "article", "summary",
                                      "reference"])
          .set_inference_output_types([DataTypes.STRING] * 4))
        e.set_inference_hyper_params(shlex.split(self.inference_hps.to_argv()))
        if self.vocab is not None:
            e.with_vocab(self.vocab)
        return e

    def create_model(self) -> SummarizationModel:
        m = SummarizationModel()
        m.set_worker_num(1).set_ps_num(0)
        (m.set_inference_selected_cols(["uuid", "article", "reference"])
          .set_inference_output_cols(["uuid", "article", "summary",
                                      "reference"])
          .set_inference_output_types([DataTypes.STRING] * 4))
        m.set_inference_hyper_params(shlex.split(self.inference_hps.to_argv()))
        if self.vocab is not None:
            m.with_vocab(self.vocab)
        return m

    # -- jobs --
    def start_training(self, source: Optional[Source] = None,
                       max_count: int = 1000) -> str:
        """Train from the stream; returns the fitted model's config JSON
        (App.startTraining, :83-106; maxCount bounds the stream like
        MessageDeserializationSchema.java:34-40)."""
        src = source or KafkaSource(TRAIN_TOPIC, self.bootstrap_servers,
                                    max_count=max_count)
        estimator = self.create_estimator()
        reg = obs.registry_for(self.train_hps)
        # end-to-end job span (the fit/transform stage spans nest inside)
        with obs.spans.span(reg, "pipeline/train_job"):
            model = estimator.fit(src)
        reg.counter("pipeline/train_jobs_total").inc()
        model_json = model.to_json()
        log.info("trained model config: %s", model_json)
        return model_json

    def start_inference(self, model_json: Optional[str] = None,
                        source: Optional[Source] = None,
                        sink: Optional[Sink] = None,
                        max_count: int = 0, serving: bool = False) -> Sink:
        """Serve summaries from the stream (App.startInference, :108-132).

        serving=True routes through the concurrent ``serve/`` subsystem
        (dynamic micro-batching + admission control, SERVING.md) instead
        of the synchronous decode loop — same sources/sinks, same output
        rows, no API break for existing callers."""
        src = source or KafkaSource(INPUT_TOPIC, self.bootstrap_servers,
                                    max_count=max_count)
        out = sink or KafkaSink(OUTPUT_TOPIC, self.bootstrap_servers)
        if model_json is not None:
            model = SummarizationModel().load_json(model_json)
            if self.vocab is not None:
                model.with_vocab(self.vocab)
        else:
            model = self.create_model()
        reg = obs.registry_for(self.inference_hps)
        with obs.spans.span(reg, "pipeline/inference_job"):
            result = model.transform(src, out, serving=serving)
        reg.counter("pipeline/inference_jobs_total").inc()
        return result

    def main(self, train_source: Optional[Source] = None,
             infer_source: Optional[Source] = None,
             sink: Optional[Sink] = None) -> Sink:
        """Sequential train-then-serve (App.main, :202-207)."""
        model_json = self.start_training(train_source)
        return self.start_inference(model_json, infer_source, sink)
