"""DEPRECATED pre-pipeline raw driver (me/littlebo/Summarization.java parity).

The reference keeps an @Deprecated driver that calls TFUtils.train /
TFUtils.inference directly with a hand-built TFConfig, bypassing the
Estimator/Model param system (Summarization.java:28,79-155).  This module
is its equivalent: direct training()/inference() calls wiring sources to
the trainer/decoder with explicit HParams — kept for surface parity and as
the minimal example of driving the engine without the pipeline layer.
Prefer pipeline.estimator / pipeline.app.

Deprecated mirror of the reference; not used by anything else in-tree.
"""

from __future__ import annotations

import logging
import os
import warnings
from typing import Optional

from textsummarization_on_flink_tpu.checkpoint import checkpointer as ckpt_lib
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batcher import Batcher
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import BeamSearchDecoder
from textsummarization_on_flink_tpu.pipeline.estimator import (
    rows_to_examples,
    train_dir_for,
)
from textsummarization_on_flink_tpu.pipeline.io import (
    CollectionSink,
    Sink,
    Source,
)
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

log = logging.getLogger(__name__)


def _deprecated() -> None:
    warnings.warn(
        "pipeline.raw_driver mirrors the reference's @Deprecated "
        "Summarization driver; use pipeline.estimator / pipeline.app",
        DeprecationWarning, stacklevel=3)


def training(hps: HParams, source: Source,
             vocab: Optional[Vocab] = None) -> trainer_lib.TrainState:
    """Summarization.training() parity (:79-118): train directly from a
    row stream, no param system."""
    _deprecated()
    vocab = vocab or Vocab(hps.vocab_path, hps.vocab_size)

    def example_source():
        # accept 3-col (uuid, article, reference) or 4-col rows with the
        # summary column in between — reference is always the LAST column
        return rows_to_examples(
            (r[0], r[1], r[-1]) for r in source.rows())

    batcher = Batcher("", vocab, hps.replace(mode="train"), single_pass=True,
                      example_source=example_source)
    train_dir = train_dir_for(hps)
    trainer = trainer_lib.Trainer(hps, vocab.size(), batcher,
                                  checkpointer=ckpt_lib.Checkpointer(
                                      train_dir, hps=hps),
                                  train_dir=train_dir)
    return trainer.train(num_steps=hps.num_steps)


def inference(hps: HParams, source: Source, sink: Optional[Sink] = None,
              vocab: Optional[Vocab] = None) -> Sink:
    """Summarization.inference() parity (:120-155)."""
    _deprecated()
    vocab = vocab or Vocab(hps.vocab_path, hps.vocab_size)
    out = sink if sink is not None else CollectionSink()

    def example_source():
        return rows_to_examples(
            (r[0], r[1], r[-1]) for r in source.rows())

    dec_hps = hps.replace(mode="decode", single_pass=False)
    batcher = Batcher("", vocab, dec_hps, single_pass=True,
                      decode_batch_mode="distinct",
                      example_source=example_source)
    train_dir = train_dir_for(hps)
    decoder = BeamSearchDecoder(dec_hps, vocab, batcher, train_dir=train_dir,
                                decode_root=os.path.join(
                                    hps.log_root or ".",
                                    hps.exp_name or "exp"))
    decoder.decode(result_sink=lambda r: out.write(r.as_row()),
                   log_results=False)
    return out
