"""Row schemas, wire-type mapping, sources/sinks, and the message codec.

Rebuilds the reference's streaming I/O surface without the Flink runtime:

  * `DataTypes`/`RowSchema`: the supported wire types and schema <-> codec
    config mapping of CodingUtils.java:25-129 (STRING, BOOL, INT8/16/32/64,
    FLOAT32/64, UINT16, FLOAT32_ARRAY; anything else raises).
  * `Message`: the Kafka JSON payload (uuid, article, summary, reference)
    of me/littlebo/Message.java:1-71.
  * Sources: collection (test rows, TensorFlowTest.java:204-217), socket
    (testInferenceFromSocket, TensorFlowTest.java:123-140), Kafka adapter
    (App.java:134-152; optional dependency, gated), each with the
    bounded-stream `max_count` semantics of
    MessageDeserializationSchema.java:34-40.
  * Sinks: collection, print (App.java:100), socket, Kafka — all flushed
    per record: the reference's AI-Extended bridge only flushed a result
    when the NEXT record arrived (Integration Report Issue 6, :879-941);
    our sinks forward immediately by design.
"""

from __future__ import annotations

import json
import logging
import queue
import socket as socket_lib
import threading
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

from textsummarization_on_flink_tpu import obs

log = logging.getLogger(__name__)

Row = Tuple[Any, ...]


def _count_source_row() -> None:
    obs.counter("pipeline/source_rows_total").inc()


def _count_sink_row() -> None:
    obs.counter("pipeline/sink_rows_total").inc()


# --------------------------------------------------------------------------
# Wire types (CodingUtils.java:25-129 support matrix)
# --------------------------------------------------------------------------

class DataTypes:
    STRING = "STRING"
    BOOL = "BOOL"
    INT_8 = "INT_8"
    INT_16 = "INT_16"
    INT_32 = "INT_32"
    INT_64 = "INT_64"
    UINT_16 = "UINT_16"
    FLOAT_32 = "FLOAT_32"
    FLOAT_64 = "FLOAT_64"
    FLOAT_32_ARRAY = "FLOAT_32_ARRAY"

    _ALL = (STRING, BOOL, INT_8, INT_16, INT_32, INT_64, UINT_16,
            FLOAT_32, FLOAT_64, FLOAT_32_ARRAY)
    _INTS = (INT_8, INT_16, INT_32, INT_64, UINT_16, BOOL)
    _FLOATS = (FLOAT_32, FLOAT_64)

    @classmethod
    def validate(cls, name: str) -> str:
        if name not in cls._ALL:
            # CodingUtils throws RuntimeException("Unsupported data type")
            raise ValueError(f"Unsupported data type for example coding: {name}")
        return name


class RowSchema:
    """Named, typed columns (TableSchema parity, CodingUtils.java:147-194)."""

    def __init__(self, names: Sequence[str], types: Sequence[str]):
        if len(names) != len(types):
            raise ValueError("names/types length mismatch")
        self.names = list(names)
        self.types = [DataTypes.validate(t) for t in types]

    def __len__(self) -> int:
        return len(self.names)

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, RowSchema) and self.names == other.names
                and self.types == other.types)

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}:{t}" for n, t in zip(self.names, self.types))
        return f"RowSchema({cols})"

    def select(self, cols: Sequence[str]) -> "RowSchema":
        idx = [self.names.index(c) for c in cols]
        return RowSchema([self.names[i] for i in idx],
                         [self.types[i] for i in idx])

    def project_row(self, row: Row, cols: Sequence[str]) -> Row:
        idx = [self.names.index(c) for c in cols]
        return tuple(row[i] for i in idx)


# The article-summarization row schemas (App.java:94,158-159)
ARTICLE_INPUT_SCHEMA = RowSchema(
    ["uuid", "article", "summary", "reference"], [DataTypes.STRING] * 4)
ARTICLE_OUTPUT_SCHEMA = RowSchema(
    ["uuid", "article", "summary", "reference"], [DataTypes.STRING] * 4)


# --------------------------------------------------------------------------
# Message codec (me/littlebo/Message.java + JSON schemas)
# --------------------------------------------------------------------------

class Message:
    """Kafka JSON payload <-> Row(uuid, article, summary, reference)."""

    def __init__(self, uuid: str = "", article: str = "", summary: str = "",
                 reference: str = ""):
        self.uuid = uuid
        self.article = article
        self.summary = summary
        self.reference = reference

    def to_row(self) -> Row:
        return (self.uuid, self.article, self.summary, self.reference)

    @classmethod
    def from_row(cls, row: Row) -> "Message":
        return cls(*[str(v) for v in row])

    def to_json(self) -> str:
        return json.dumps({"uuid": self.uuid, "article": self.article,
                           "summary": self.summary,
                           "reference": self.reference}, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Message":
        d = json.loads(s)
        return cls(uuid=d.get("uuid", ""), article=d.get("article", ""),
                   summary=d.get("summary", ""),
                   reference=d.get("reference", ""))


# --------------------------------------------------------------------------
# Sources
# --------------------------------------------------------------------------

class Source:
    """A bounded or unbounded row stream."""

    schema: RowSchema

    def rows(self) -> Iterator[Row]:
        raise NotImplementedError


class CollectionSource(Source):
    """In-memory rows (the 8-row synthetic tables of
    TensorFlowTest.createArticleData, :204-217)."""

    def __init__(self, rows: Sequence[Row], schema: Optional[RowSchema] = None):
        self._rows = list(rows)
        self.schema = schema or ARTICLE_INPUT_SCHEMA

    def rows(self) -> Iterator[Row]:
        for row in self._rows:
            _count_source_row()
            yield row


class SocketSource(Source):
    """Line-JSON messages from a TCP socket
    (testInferenceFromSocket, TensorFlowTest.java:123-140).

    max_count bounds the stream like MessageDeserializationSchema's record
    counter (:34-40) — the reference's hack to end a Kafka stream is a
    first-class bound here.
    """

    def __init__(self, host: str, port: int, max_count: int = 0,
                 schema: Optional[RowSchema] = None, timeout: float = 30.0):
        self._host = host
        self._port = port
        self._max = max_count
        self._timeout = timeout
        self.schema = schema or ARTICLE_INPUT_SCHEMA

    def rows(self) -> Iterator[Row]:
        n = 0
        with socket_lib.create_connection((self._host, self._port),
                                          timeout=self._timeout) as sock:
            # the timeout governs CONNECT only; a long-lived stream may
            # legitimately idle between records indefinitely
            sock.settimeout(None)
            f = sock.makefile("r", encoding="utf-8")
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = Message.from_json(line).to_row()
                except (ValueError, TypeError):
                    # a malformed line must not kill a long-lived stream;
                    # counted so a lossy producer is visible
                    obs.counter("pipeline/codec_errors_total").inc()
                    log.warning("dropping malformed socket line: %.80r", line)
                    continue
                _count_source_row()
                yield row
                n += 1
                if self._max and n >= self._max:
                    return


class IteratorSource(Source):
    """Wrap any row iterator/callable (streaming-bridge hook)."""

    def __init__(self, it: Callable[[], Iterator[Row]],
                 schema: Optional[RowSchema] = None):
        self._it = it
        self.schema = schema or ARTICLE_INPUT_SCHEMA

    def rows(self) -> Iterator[Row]:
        return self._it()


class KafkaSource(Source):
    """Kafka topic consumer (App.java:134-143). Optional dependency: raises
    a clear error at use time when kafka-python is unavailable."""

    def __init__(self, topic: str, bootstrap_servers: str = "localhost:9092",
                 group_id: str = "summarization", max_count: int = 0,
                 schema: Optional[RowSchema] = None):
        self.topic = topic
        self.bootstrap_servers = bootstrap_servers
        self.group_id = group_id
        self._max = max_count
        self.schema = schema or ARTICLE_INPUT_SCHEMA

    def rows(self) -> Iterator[Row]:
        try:
            from kafka import KafkaConsumer  # type: ignore
        except ImportError as e:  # pragma: no cover - env without kafka
            raise RuntimeError(
                "KafkaSource needs the kafka-python package; use "
                "CollectionSource/SocketSource or install kafka-python") from e
        consumer = KafkaConsumer(
            self.topic, bootstrap_servers=self.bootstrap_servers,
            group_id=self.group_id, value_deserializer=lambda b: b)
        n = 0
        for msg in consumer:  # pragma: no cover - needs a broker
            try:
                row = Message.from_json(msg.value.decode("utf-8")).to_row()
            except (ValueError, TypeError):
                obs.counter("pipeline/codec_errors_total").inc()
                log.warning("dropping malformed kafka message")
                continue
            _count_source_row()
            yield row
            n += 1
            if self._max and n >= self._max:
                return


# --------------------------------------------------------------------------
# Sinks (all flush per record — the Issue-6 fix)
# --------------------------------------------------------------------------

class Sink:
    def write(self, row: Row) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CollectionSink(Sink):
    def __init__(self) -> None:
        self.rows: List[Row] = []
        self._lock = threading.Lock()

    def write(self, row: Row) -> None:
        with self._lock:
            self.rows.append(row)
        _count_sink_row()


class PrintSink(Sink):
    """print().setParallelism(1) parity (App.java:100,121,129)."""

    def write(self, row: Row) -> None:
        print(row, flush=True)
        _count_sink_row()


class SocketSink(Sink):
    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._sock = socket_lib.create_connection((host, port), timeout=timeout)

    def write(self, row: Row) -> None:
        data = (Message.from_row(row).to_json() + "\n").encode("utf-8")
        self._sock.sendall(data)  # immediate flush
        _count_sink_row()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class KafkaSink(Sink):
    """Kafka topic producer (App.java:145-152); optional dependency."""

    def __init__(self, topic: str, bootstrap_servers: str = "localhost:9092"):
        self.topic = topic
        self.bootstrap_servers = bootstrap_servers
        self._producer = None

    def _ensure(self):
        if self._producer is None:
            try:
                from kafka import KafkaProducer  # type: ignore
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "KafkaSink needs the kafka-python package") from e
            self._producer = KafkaProducer(
                bootstrap_servers=self.bootstrap_servers)
        return self._producer

    def write(self, row: Row) -> None:  # pragma: no cover - needs a broker
        p = self._ensure()
        p.send(self.topic, Message.from_row(row).to_json().encode("utf-8"))
        p.flush()  # immediate flush
        _count_sink_row()

    def close(self) -> None:  # pragma: no cover
        if self._producer is not None:
            self._producer.close()


class QueueSink(Sink):
    """Push rows into a thread-safe queue (bridge glue)."""

    def __init__(self, q: Optional["queue.Queue[Row]"] = None):
        self.queue: "queue.Queue[Row]" = q if q is not None else queue.Queue()

    def write(self, row: Row) -> None:
        self.queue.put(row)
        _count_sink_row()
