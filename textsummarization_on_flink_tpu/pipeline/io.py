"""Row schemas, wire-type mapping, sources/sinks, and the message codec.

Rebuilds the reference's streaming I/O surface without the Flink runtime:

  * `DataTypes`/`RowSchema`: the supported wire types and schema <-> codec
    config mapping of CodingUtils.java:25-129 (STRING, BOOL, INT8/16/32/64,
    FLOAT32/64, UINT16, FLOAT32_ARRAY; anything else raises).
  * `Message`: the Kafka JSON payload (uuid, article, summary, reference)
    of me/littlebo/Message.java:1-71.
  * Sources: collection (test rows, TensorFlowTest.java:204-217), socket
    (testInferenceFromSocket, TensorFlowTest.java:123-140), Kafka adapter
    (App.java:134-152; optional dependency, gated), each with the
    bounded-stream `max_count` semantics of
    MessageDeserializationSchema.java:34-40.
  * Sinks: collection, print (App.java:100), socket, Kafka — all flushed
    per record: the reference's AI-Extended bridge only flushed a result
    when the NEXT record arrived (Integration Report Issue 6, :879-941);
    our sinks forward immediately by design.

Resilience (ISSUE 2, RESILIENCE.md):
  * stream sources never idle unbounded — ``settimeout(None)`` on a
    long-lived socket let one dead peer hang the job forever; reads now
    carry an ``idle_timeout`` and raise the typed ``StreamIdleError``;
  * ``ResilientSource`` wraps any source factory (socket, Kafka,
    iterator) with reconnect-with-backoff and uuid-keyed dedup, so a
    flapping peer delivers every row exactly once downstream;
  * ``BreakerSink`` wraps any sink with a circuit breaker: a down broker
    sheds rows (counted) instead of blocking the pipeline;
  * injection points ``io.connect`` / ``io.read`` / ``io.write`` drive
    the chaos suite through these paths deterministically.
"""

from __future__ import annotations

import collections
import json
import logging
import queue
import socket as socket_lib
import threading
import time
from typing import Any, Callable, Iterator, List, Optional, Sequence, Tuple

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.resilience.errors import StreamIdleError
from textsummarization_on_flink_tpu.resilience.policy import (
    CircuitBreaker,
    RetryPolicy,
)

log = logging.getLogger(__name__)

# the failure classes a reconnect can fix: connection/socket errors, the
# typed idle timeout, and — when kafka-python is present — KafkaError,
# which subclasses RuntimeError rather than OSError (NoBrokersAvailable
# et al. must reconnect, not kill the job)
try:  # pragma: no cover - optional dependency
    from kafka.errors import KafkaError as _KafkaError

    _RECONNECT_ERRORS: Tuple[type, ...] = (
        OSError, StreamIdleError, _KafkaError)
except ImportError:
    _RECONNECT_ERRORS = (OSError, StreamIdleError)

Row = Tuple[Any, ...]


class SchemaProjectionError(ValueError):
    """A wire row cannot be projected onto the consumer's schema (wrong
    arity, non-object payload, missing columns).

    Distinct from a *malformed* line (bad JSON — dropped and counted in
    ``pipeline/codec_errors_total``: a lossy producer must not kill a
    long-lived stream): a row that PARSES but cannot match the declared
    schema means the producer and consumer disagree about the contract,
    and silently stopping (or dropping) would truncate the dataset with
    no signal.  Typed so stream supervisors can route on it; every
    raise is counted in ``pipeline/feeder_errors_total``."""



def _count_source_row() -> None:
    obs.counter("pipeline/source_rows_total").inc()


def _count_sink_row() -> None:
    obs.counter("pipeline/sink_rows_total").inc()


# --------------------------------------------------------------------------
# Wire types (CodingUtils.java:25-129 support matrix)
# --------------------------------------------------------------------------

class DataTypes:
    STRING = "STRING"
    BOOL = "BOOL"
    INT_8 = "INT_8"
    INT_16 = "INT_16"
    INT_32 = "INT_32"
    INT_64 = "INT_64"
    UINT_16 = "UINT_16"
    FLOAT_32 = "FLOAT_32"
    FLOAT_64 = "FLOAT_64"
    FLOAT_32_ARRAY = "FLOAT_32_ARRAY"

    _ALL = (STRING, BOOL, INT_8, INT_16, INT_32, INT_64, UINT_16,
            FLOAT_32, FLOAT_64, FLOAT_32_ARRAY)
    _INTS = (INT_8, INT_16, INT_32, INT_64, UINT_16, BOOL)
    _FLOATS = (FLOAT_32, FLOAT_64)

    @classmethod
    def validate(cls, name: str) -> str:
        if name not in cls._ALL:
            # CodingUtils throws RuntimeException("Unsupported data type")
            raise ValueError(f"Unsupported data type for example coding: {name}")
        return name


class RowSchema:
    """Named, typed columns (TableSchema parity, CodingUtils.java:147-194)."""

    def __init__(self, names: Sequence[str], types: Sequence[str]):
        if len(names) != len(types):
            raise ValueError("names/types length mismatch")
        self.names = list(names)
        self.types = [DataTypes.validate(t) for t in types]

    def __len__(self) -> int:
        return len(self.names)

    def __eq__(self, other: object) -> bool:
        return (isinstance(other, RowSchema) and self.names == other.names
                and self.types == other.types)

    def __repr__(self) -> str:
        cols = ", ".join(f"{n}:{t}" for n, t in zip(self.names, self.types))
        return f"RowSchema({cols})"

    def select(self, cols: Sequence[str]) -> "RowSchema":
        idx = [self.names.index(c) for c in cols]
        return RowSchema([self.names[i] for i in idx],
                         [self.types[i] for i in idx])

    def project_row(self, row: Row, cols: Sequence[str]) -> Row:
        idx = [self.names.index(c) for c in cols]
        return tuple(row[i] for i in idx)


# The article-summarization row schemas (App.java:94,158-159)
ARTICLE_INPUT_SCHEMA = RowSchema(
    ["uuid", "article", "summary", "reference"], [DataTypes.STRING] * 4)
ARTICLE_OUTPUT_SCHEMA = RowSchema(
    ["uuid", "article", "summary", "reference"], [DataTypes.STRING] * 4)


# --------------------------------------------------------------------------
# Message codec (me/littlebo/Message.java + JSON schemas)
# --------------------------------------------------------------------------

class Message:
    """Kafka JSON payload <-> Row(uuid, article, summary, reference).

    ``tier``/``error`` (ISSUE 17) extend the frame for the
    multi-process serving transport: a request frame carries its
    quality tier, a reply frame carries a typed submit failure as
    ``"ExcType: message"``.  Both serialize ONLY when non-empty, so the
    classic 4-field wire format (and its committed byte accounting) is
    unchanged for every pre-existing producer; ``from_json`` ignores
    unknown keys as before, so mixed-version peers interoperate.
    ``to_row``/``from_row`` stay 4 columns — the extras are transport
    envelope, not schema columns."""

    def __init__(self, uuid: str = "", article: str = "", summary: str = "",
                 reference: str = "", tier: str = "", error: str = ""):
        self.uuid = uuid
        self.article = article
        self.summary = summary
        self.reference = reference
        self.tier = tier
        self.error = error

    def to_row(self) -> Row:
        return (self.uuid, self.article, self.summary, self.reference)

    @classmethod
    def from_row(cls, row: Row) -> "Message":
        return cls(*[str(v) for v in row])

    def to_json(self) -> str:
        d = {"uuid": self.uuid, "article": self.article,
             "summary": self.summary, "reference": self.reference}
        if self.tier:
            d["tier"] = self.tier
        if self.error:
            d["error"] = self.error
        return json.dumps(d, sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "Message":
        d = json.loads(s)
        return cls(uuid=d.get("uuid", ""), article=d.get("article", ""),
                   summary=d.get("summary", ""),
                   reference=d.get("reference", ""),
                   tier=d.get("tier", ""), error=d.get("error", ""))


# --------------------------------------------------------------------------
# Sources
# --------------------------------------------------------------------------

class Source:
    """A bounded or unbounded row stream."""

    schema: RowSchema

    def rows(self) -> Iterator[Row]:
        raise NotImplementedError


class CollectionSource(Source):
    """In-memory rows (the 8-row synthetic tables of
    TensorFlowTest.createArticleData, :204-217)."""

    def __init__(self, rows: Sequence[Row], schema: Optional[RowSchema] = None):
        self._rows = list(rows)
        self.schema = schema or ARTICLE_INPUT_SCHEMA

    def rows(self) -> Iterator[Row]:
        for row in self._rows:
            _count_source_row()
            yield row


class SocketSource(Source):
    """Line-JSON messages from a TCP socket
    (testInferenceFromSocket, TensorFlowTest.java:123-140).

    max_count bounds the stream like MessageDeserializationSchema's record
    counter (:34-40) — the reference's hack to end a Kafka stream is a
    first-class bound here.

    A long-lived stream read is NEVER left with ``settimeout(None)``
    (the dead-peer hang, ISSUE 2 satellite 1): a connection that goes
    ``idle_timeout`` seconds without delivering a byte raises the typed
    ``StreamIdleError``.  Wrap in ``ResilientSource`` for
    reconnect-with-backoff on top.

    Schema contract (ISSUE 4 satellite): a payload that parses but
    cannot project onto the declared schema raises the typed
    ``SchemaProjectionError`` (counted in
    ``pipeline/feeder_errors_total``) instead of silently ending the
    stream; malformed JSON lines are still dropped-and-counted
    (``pipeline/codec_errors_total``) as before.
    """

    def __init__(self, host: str, port: int, max_count: int = 0,
                 schema: Optional[RowSchema] = None, timeout: float = 30.0,
                 idle_timeout: float = 300.0):
        self._host = host
        self._port = port
        self._max = max_count
        self._timeout = timeout
        self._idle_timeout = idle_timeout
        self._faults = faultinject.plan()
        self.schema = schema or ARTICLE_INPUT_SCHEMA

    def rows(self) -> Iterator[Row]:
        n = 0
        if self._faults.fire("io.connect"):
            raise ConnectionRefusedError(
                f"injected io.connect fault for {self._host}:{self._port}")
        with socket_lib.create_connection((self._host, self._port),
                                          timeout=self._timeout) as sock:
            # `timeout` governed CONNECT; from here the idle window
            # bounds every read — a silent peer surfaces as a typed
            # error instead of parking the source forever
            sock.settimeout(self._idle_timeout or None)
            f = sock.makefile("r", encoding="utf-8")
            while True:
                if self._faults.fire("io.read"):
                    raise ConnectionResetError(
                        f"injected io.read fault after {n} row(s)")
                try:
                    line = f.readline()
                except TimeoutError as e:  # socket.timeout alias (py3.10+)
                    raise StreamIdleError(
                        f"no data from {self._host}:{self._port} in "
                        f"{self._idle_timeout:.0f}s (dead peer?)") from e
                if not line:  # EOF: peer closed cleanly
                    return
                line = line.strip()
                if not line:
                    continue
                try:
                    row = Message.from_json(line).to_row()
                except (ValueError, TypeError):
                    # a malformed line must not kill a long-lived stream;
                    # counted so a lossy producer is visible
                    obs.counter("pipeline/codec_errors_total").inc()
                    log.warning("dropping malformed socket line: %.80r", line)
                    continue
                except AttributeError as e:
                    # valid JSON but not an object-shaped message (a
                    # bare list/number): the row can never project onto
                    # the schema — a contract violation, not line noise.
                    # Surface it typed instead of the old silent stop.
                    obs.counter("pipeline/feeder_errors_total").inc()
                    raise SchemaProjectionError(
                        f"socket payload is not a message object and "
                        f"cannot project onto {self.schema!r}: "
                        f"{line[:80]!r}") from e
                if len(row) != len(self.schema):
                    obs.counter("pipeline/feeder_errors_total").inc()
                    raise SchemaProjectionError(
                        f"socket row has {len(row)} column(s) but the "
                        f"declared schema is {self.schema!r}")
                _count_source_row()
                yield row
                n += 1
                if self._max and n >= self._max:
                    return


class IteratorSource(Source):
    """Wrap any row iterator/callable (streaming-bridge hook)."""

    def __init__(self, it: Callable[[], Iterator[Row]],
                 schema: Optional[RowSchema] = None):
        self._it = it
        self.schema = schema or ARTICLE_INPUT_SCHEMA

    def rows(self) -> Iterator[Row]:
        return self._it()


class KafkaSource(Source):
    """Kafka topic consumer (App.java:134-143). Optional dependency: raises
    a clear error at use time when kafka-python is unavailable.

    ``idle_timeout`` (seconds, 0 = wait forever — Kafka's default,
    because a quiet topic is normal) bounds how long the consumer may
    sit with no messages: an unbounded stream that idles past it raises
    ``StreamIdleError`` (same contract as SocketSource) so a dead
    broker/partition is a typed, retryable event — wrap in
    ``ResilientSource`` for reconnect-with-backoff.
    """

    def __init__(self, topic: str, bootstrap_servers: str = "localhost:9092",
                 group_id: str = "summarization", max_count: int = 0,
                 schema: Optional[RowSchema] = None,
                 idle_timeout: float = 0.0):
        self.topic = topic
        self.bootstrap_servers = bootstrap_servers
        self.group_id = group_id
        self._max = max_count
        self._idle_timeout = idle_timeout
        self._faults = faultinject.plan()
        self.schema = schema or ARTICLE_INPUT_SCHEMA

    def rows(self) -> Iterator[Row]:
        try:
            from kafka import KafkaConsumer  # type: ignore
        except ImportError as e:  # pragma: no cover - env without kafka
            raise RuntimeError(
                "KafkaSource needs the kafka-python package; use "
                "CollectionSource/SocketSource or install kafka-python") from e
        if self._faults.fire("io.connect"):
            raise ConnectionRefusedError(
                f"injected io.connect fault for {self.bootstrap_servers}")
        kwargs = {}
        if self._idle_timeout:
            # kafka-python ends iteration (no exception) on this timeout;
            # the tail check below turns that into the typed idle error
            kwargs["consumer_timeout_ms"] = int(self._idle_timeout * 1000)
        consumer = KafkaConsumer(
            self.topic, bootstrap_servers=self.bootstrap_servers,
            group_id=self.group_id, value_deserializer=lambda b: b, **kwargs)
        n = 0
        try:
            for msg in consumer:  # pragma: no cover - needs a broker
                if self._faults.fire("io.read"):
                    raise ConnectionResetError(
                        f"injected io.read fault after {n} row(s)")
                try:
                    row = Message.from_json(
                        msg.value.decode("utf-8")).to_row()
                except (ValueError, TypeError):
                    obs.counter("pipeline/codec_errors_total").inc()
                    log.warning("dropping malformed kafka message")
                    continue
                _count_source_row()
                yield row
                n += 1
                if self._max and n >= self._max:
                    return
            if self._idle_timeout and not (self._max and n >= self._max):
                # iteration ended on consumer_timeout_ms, not on the
                # bound: the stream went idle
                raise StreamIdleError(
                    f"no kafka messages on {self.topic!r} in "
                    f"{self._idle_timeout:.0f}s (dead broker/partition?)")
        finally:
            # an abandoned consumer lingers in its group until the
            # session times out, forcing a rebalance per reconnect —
            # leave the group promptly on ANY exit path
            consumer.close()


class ResilientSource(Source):
    """Reconnect-with-backoff + exactly-once wrapper for any source.

    ``factory`` builds a fresh inner source per (re)connection attempt
    (construction must be cheap and side-effect free, which holds for
    every source here: sockets/consumers open inside ``rows()``).  On a
    connection-class failure — ``OSError`` (covers ConnectionError and
    socket errors), ``StreamIdleError``, or (when kafka-python is
    installed) ``KafkaError``, which subclasses RuntimeError rather than
    OSError — the stream reconnects with decorrelated-jitter backoff up
    to ``max_reconnects`` times, then surfaces ``RetriesExhaustedError``
    with the last cause chained.

    Exactly-once: a reconnected peer typically replays from its own
    notion of the start (a socket server re-streams; a Kafka consumer
    re-polls uncommitted offsets), so rows are deduped by their first
    column (the Message uuid) before reaching the consumer; replayed
    duplicates are counted in ``resilience/io_dup_rows_total``, and
    reconnects in ``resilience/io_reconnects_total``.  Pass
    ``dedup=False`` for schemas whose first column is not a unique key.
    The dedup memory is BOUNDED: only the ``dedup_window``
    least-recently-SEEN keys are held (LRU — a replayed key refreshes
    its recency, so a peer that replays the same prefix on every
    reconnect cannot age the live keys out; default 65536, evictions
    counted in ``pipeline/dedup_evictions_total``).  The window only
    needs to cover replay depth since the last reconnect, and an
    unbounded set would leak on exactly the long-running streams this
    wrapper is for; ``dedup_window=0`` keeps every key (short bounded
    streams).

    ``seed``/``sleep`` pin the backoff for deterministic chaos tests.
    """

    def __init__(self, factory: Callable[[], Source],
                 max_reconnects: int = 8, base_delay: float = 0.05,
                 max_delay: float = 5.0, seed: Optional[int] = None,
                 dedup: bool = True, dedup_window: int = 65536,
                 schema: Optional[RowSchema] = None,
                 sleep: Callable[[float], None] = time.sleep):
        self._factory = factory
        self._max_reconnects = max_reconnects
        self._base_delay = base_delay
        self._max_delay = max_delay
        self._seed = seed
        self._dedup = dedup
        self._dedup_window = dedup_window
        self._sleep = sleep
        self._c_reconnects = obs.counter("resilience/io_reconnects_total")
        self._c_dups = obs.counter("resilience/io_dup_rows_total")
        self._c_dedup_evicted = obs.counter("pipeline/dedup_evictions_total")
        self.schema = schema or factory().schema

    def rows(self) -> Iterator[Row]:
        policy = RetryPolicy(
            max_attempts=self._max_reconnects + 1,
            base_delay=self._base_delay, max_delay=self._max_delay,
            seed=self._seed, name="io.source", sleep=self._sleep)
        seen: "collections.OrderedDict[Any, None]" = collections.OrderedDict()
        while True:
            src = self._factory()
            try:
                for row in src.rows():
                    if self._dedup:
                        key = row[0] if row else None
                        if key in seen:
                            # LRU refresh: a replayed key is evidence it
                            # is still live replay depth — keep it young
                            seen.move_to_end(key)
                            self._c_dups.inc()
                            continue
                        seen[key] = None
                        if self._dedup_window and len(seen) > self._dedup_window:
                            seen.popitem(last=False)  # oldest-seen out
                            self._c_dedup_evicted.inc()
                    yield row
                return  # clean end of stream
            except _RECONNECT_ERRORS as e:
                policy.note_failure(e)  # raises when the budget is spent
                self._c_reconnects.inc()
                delay = policy.next_delay()
                log.warning("stream source failed (%s); reconnecting in "
                            "%.2fs", e, delay)
                self._sleep(delay)


# --------------------------------------------------------------------------
# Sinks (all flush per record — the Issue-6 fix)
# --------------------------------------------------------------------------

class Sink:
    def write(self, row: Row) -> None:
        raise NotImplementedError

    def close(self) -> None:
        pass


class CollectionSink(Sink):
    def __init__(self) -> None:
        self.rows: List[Row] = []
        self._lock = threading.Lock()

    def write(self, row: Row) -> None:
        with self._lock:
            self.rows.append(row)
        _count_sink_row()


class PrintSink(Sink):
    """print().setParallelism(1) parity (App.java:100,121,129)."""

    def write(self, row: Row) -> None:
        print(row, flush=True)
        _count_sink_row()


class SocketSink(Sink):
    def __init__(self, host: str, port: int, timeout: float = 30.0):
        self._sock = socket_lib.create_connection((host, port), timeout=timeout)

    def write(self, row: Row) -> None:
        data = (Message.from_row(row).to_json() + "\n").encode("utf-8")
        self._sock.sendall(data)  # immediate flush
        _count_sink_row()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


class KafkaSink(Sink):
    """Kafka topic producer (App.java:145-152); optional dependency."""

    def __init__(self, topic: str, bootstrap_servers: str = "localhost:9092"):
        self.topic = topic
        self.bootstrap_servers = bootstrap_servers
        self._producer = None

    def _ensure(self):
        if self._producer is None:
            try:
                from kafka import KafkaProducer  # type: ignore
            except ImportError as e:  # pragma: no cover
                raise RuntimeError(
                    "KafkaSink needs the kafka-python package") from e
            self._producer = KafkaProducer(
                bootstrap_servers=self.bootstrap_servers)
        return self._producer

    def write(self, row: Row) -> None:  # pragma: no cover - needs a broker
        p = self._ensure()
        p.send(self.topic, Message.from_row(row).to_json().encode("utf-8"))
        p.flush()  # immediate flush
        _count_sink_row()

    def close(self) -> None:  # pragma: no cover
        if self._producer is not None:
            self._producer.close()


class BreakerSink(Sink):
    """Circuit-breaker wrapper: a failing sink SHEDS rows instead of
    blocking (or repeatedly stalling) the whole pipeline job.

    Semantics (RESILIENCE.md "graceful degradation"): while the breaker
    is closed, writes flow and failures are counted against it; after
    ``threshold`` consecutive failures it opens and rows are dropped
    immediately (``resilience/sink_shed_total``) for ``reset_secs``,
    then a half-open probe write decides recovery.  Shedding loses data
    BY DESIGN — a streaming job that blocks on a dead broker loses all
    of it — and every loss is counted (``resilience/sink_errors_total``,
    ``resilience/sink_shed_total``).  ``raise_on_error=True`` restores
    fail-stop for pipelines that prefer crashing to shedding.

    Injection point ``io.write`` fires inside the protected write.
    """

    def __init__(self, inner: Sink, breaker: Optional[CircuitBreaker] = None,
                 raise_on_error: bool = False):
        self._inner = inner
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            threshold=5, reset_secs=30.0, name="io.sink")
        self._raise = raise_on_error
        self._faults = faultinject.plan()
        self._c_shed = obs.counter("resilience/sink_shed_total")
        self._c_errors = obs.counter("resilience/sink_errors_total")

    @property
    def breaker(self) -> CircuitBreaker:
        return self._breaker

    def write(self, row: Row) -> None:
        if not self._breaker.allow():
            self._c_shed.inc()
            return
        try:
            if self._faults.fire("io.write"):
                raise ConnectionResetError("injected io.write fault")
            self._inner.write(row)
        except (OSError, RuntimeError) as e:
            self._breaker.record_failure()
            self._c_errors.inc()
            self._c_shed.inc()
            log.warning("sink write failed (%s); row shed "
                        "(breaker %s)", e, self._breaker.state)
            if self._raise:
                raise
        else:
            self._breaker.record_success()

    def close(self) -> None:
        self._inner.close()


class QueueSink(Sink):
    """Push rows into a thread-safe queue (bridge glue)."""

    def __init__(self, q: Optional["queue.Queue[Row]"] = None):
        self.queue: "queue.Queue[Row]" = q if q is not None else queue.Queue()

    def write(self, row: Row) -> None:
        self.queue.put(row)
        _count_sink_row()
