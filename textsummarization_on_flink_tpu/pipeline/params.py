"""Typed pipeline-parameter system (the WithParams/ParamInfo layer).

Rebuilds the reference's Flink-ML param mixins
(/root/reference/src/main/java/org/apache/flink/table/ml/lib/tensorflow/
param/*.java) in Python: typed `ParamInfo` declarations with
required/optional semantics and defaults, a `Params` store with JSON
round-trip (the reference persists models as params-JSON only,
TFModel via toJson/loadJson, TensorFlowTest.java:142-168), and the same
eight mixin groups with train/inference deliberately duplicated so an
estimator and its model can diverge (doc/Flink-AI-Extended Integration
Report.md:30).

Name mapping from the reference (TPU-native meanings):
  * zookeeper_connect_str -> coordinator_address: the reference rendezvous
    store is ZooKeeper (HasClusterConfig.java:15-19); ours is the
    jax.distributed coordination service (parallel/distributed.py).
  * worker_num / ps_num keep their names; ps_num exists for surface parity
    and must be 0 — there is no parameter server on TPU
    (HasClusterConfig.java:20-29; ps busy-loop run_summarization.py:412-415).
  * *_scripts -> the entry is in-process (no python-subprocess launch), so
    scripts hold importable entry names instead of file paths.
  * *_hyper_params: the reference's space-joined argv strings
    (TFEstimator.java:52); parsed by HParams.from_string.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Dict, Generic, List, Optional, Type, TypeVar

T = TypeVar("T")


class ParamValidators:
    @staticmethod
    def always_true() -> Callable[[Any], bool]:
        return lambda v: True

    @staticmethod
    def gt_eq(bound: float) -> Callable[[Any], bool]:
        return lambda v: v is not None and v >= bound

    @staticmethod
    def non_empty() -> Callable[[Any], bool]:
        return lambda v: v is not None and len(v) > 0


@dataclasses.dataclass(frozen=True)
class ParamInfo(Generic[T]):
    """A typed parameter declaration (ParamInfoFactory parity)."""

    name: str
    description: str
    type_: Type
    required: bool = False
    has_default: bool = False
    default: Optional[T] = None
    validator: Callable[[Any], bool] = lambda v: True

    def __hash__(self) -> int:
        return hash(self.name)


class Params:
    """The param store (org.apache.flink.ml.api.misc.param.Params parity):
    get falls back to defaults, raises on missing required params; JSON
    round-trip for model persistence."""

    def __init__(self) -> None:
        self._map: Dict[str, Any] = {}

    def set(self, info: ParamInfo, value: Any) -> "Params":
        if value is not None and not isinstance(value, info.type_) and not (
                info.type_ is float and isinstance(value, int)):
            raise TypeError(
                f"param {info.name} expects {info.type_.__name__}, "
                f"got {type(value).__name__}")
        if not info.validator(value):
            raise ValueError(f"invalid value for param {info.name}: {value!r}")
        self._map[info.name] = value
        return self

    def get(self, info: ParamInfo) -> Any:
        if info.name in self._map:
            return self._map[info.name]
        if info.has_default:
            return info.default
        if info.required:
            raise KeyError(f"required param {info.name} is not set")
        return None

    def contains(self, info: ParamInfo) -> bool:
        return info.name in self._map

    def remove(self, info: ParamInfo) -> None:
        self._map.pop(info.name, None)

    def size(self) -> int:
        return len(self._map)

    # -- persistence (config-only model JSON, TFModel.toJson parity) --
    def to_json(self) -> str:
        return json.dumps(self._map, sort_keys=True)

    def load_json(self, s: str) -> "Params":
        self._map.update(json.loads(s))
        return self

    @classmethod
    def from_json(cls, s: str) -> "Params":
        return cls().load_json(s)


class WithParams:
    """Base mixin: everything stores into self.params (WithParams parity)."""

    def __init__(self) -> None:
        self._params = Params()

    @property
    def params(self) -> Params:
        return self._params

    def _get(self, info: ParamInfo) -> Any:
        return self._params.get(info)

    def _set(self, info: ParamInfo, v: Any) -> "WithParams":
        self._params.set(info, v)
        return self

    @classmethod
    def param_infos(cls) -> Dict[str, ParamInfo]:
        """All ParamInfo declarations visible on this class (over the MRO)."""
        out: Dict[str, ParamInfo] = {}
        for klass in cls.__mro__:
            for v in vars(klass).values():
                if isinstance(v, ParamInfo):
                    out.setdefault(v.name, v)
        return out

    def load_params_json(self, s: str) -> "WithParams":
        """JSON -> params, re-validating every DECLARED param through the
        typed set() path (bare Params.load_json skips type checks; model
        JSON arrives from untrusted files, TensorFlowTest.java:152-163)."""
        loaded = json.loads(s)
        infos = self.param_infos()
        for name, value in loaded.items():
            if name in infos:
                self._params.set(infos[name], value)
            else:
                self._params._map[name] = value  # unknown: keep, like Flink
        return self


# --------------------------------------------------------------------------
# The eight param groups (§2.1 of SURVEY.md)
# --------------------------------------------------------------------------

class HasClusterConfig(WithParams):
    """HasClusterConfig.java:15-29 (defaults preserved)."""

    COORDINATOR_ADDRESS = ParamInfo(
        "coordinator_address",
        "distributed coordination service address (the reference's "
        "zookeeper_connect_str; here the jax.distributed coordinator)",
        str, has_default=True, default="127.0.0.1:2181")
    WORKER_NUM = ParamInfo(
        "worker_num", "number of training hosts", int,
        has_default=True, default=1, validator=ParamValidators.gt_eq(1))
    PS_NUM = ParamInfo(
        "ps_num", "parameter servers (surface parity only; must be 0 — "
        "SPMD has no PS role)", int,
        has_default=True, default=0, validator=ParamValidators.gt_eq(0))

    def set_coordinator_address(self, v: str): return self._set(self.COORDINATOR_ADDRESS, v)
    def get_coordinator_address(self) -> str: return self._get(self.COORDINATOR_ADDRESS)
    def set_worker_num(self, v: int): return self._set(self.WORKER_NUM, v)
    def get_worker_num(self) -> int: return self._get(self.WORKER_NUM)
    def set_ps_num(self, v: int): return self._set(self.PS_NUM, v)
    def get_ps_num(self) -> int: return self._get(self.PS_NUM)
    # reference-name aliases
    set_zookeeper_connect_str = set_coordinator_address
    get_zookeeper_connect_str = get_coordinator_address


class HasTrainPythonConfig(WithParams):
    """HasTrainPythonConfig.java (scripts/map-func/hyperparams/env)."""

    TRAIN_SCRIPTS = ParamInfo(
        "train_scripts", "training entry names", list,
        has_default=True, default=None)
    TRAIN_MAP_FUNC = ParamInfo(
        "train_map_func", "training entry function", str,
        has_default=True, default="main_on_flink")
    TRAIN_HYPER_PARAMS_KEY = ParamInfo(
        "train_hyper_params_key", "property key the hyperparams travel "
        "under (reference: TF_Hyperparameter)", str,
        has_default=True, default="TF_Hyperparameter")
    TRAIN_HYPER_PARAMS = ParamInfo(
        "train_hyper_params", "training hyperparameter argv strings", list,
        has_default=True, default=None)
    TRAIN_ENV_PATH = ParamInfo(
        "train_env_path", "virtualenv path (unused in-process)", str,
        has_default=True, default=None)

    def set_train_scripts(self, v: List[str]): return self._set(self.TRAIN_SCRIPTS, v)
    def get_train_scripts(self) -> Optional[List[str]]: return self._get(self.TRAIN_SCRIPTS)
    def set_train_map_func(self, v: str): return self._set(self.TRAIN_MAP_FUNC, v)
    def get_train_map_func(self) -> str: return self._get(self.TRAIN_MAP_FUNC)
    def set_train_hyper_params_key(self, v: str): return self._set(self.TRAIN_HYPER_PARAMS_KEY, v)
    def get_train_hyper_params_key(self) -> str: return self._get(self.TRAIN_HYPER_PARAMS_KEY)
    def set_train_hyper_params(self, v: List[str]): return self._set(self.TRAIN_HYPER_PARAMS, v)
    def get_train_hyper_params(self) -> Optional[List[str]]: return self._get(self.TRAIN_HYPER_PARAMS)
    def set_train_env_path(self, v: str): return self._set(self.TRAIN_ENV_PATH, v)
    def get_train_env_path(self) -> Optional[str]: return self._get(self.TRAIN_ENV_PATH)


class HasInferencePythonConfig(WithParams):
    """HasInferencePythonConfig.java — duplicated, not shared, with the
    train group, so estimator and model can diverge (Integration Report:30)."""

    INFERENCE_SCRIPTS = ParamInfo(
        "inference_scripts", "inference entry names", list,
        has_default=True, default=None)
    INFERENCE_MAP_FUNC = ParamInfo(
        "inference_map_func", "inference entry function", str,
        has_default=True, default="main_on_flink")
    INFERENCE_HYPER_PARAMS_KEY = ParamInfo(
        "inference_hyper_params_key", "property key the hyperparams travel "
        "under (reference: TF_Hyperparameter)", str,
        has_default=True, default="TF_Hyperparameter")
    INFERENCE_HYPER_PARAMS = ParamInfo(
        "inference_hyper_params", "inference hyperparameter argv strings",
        list, has_default=True, default=None)
    INFERENCE_ENV_PATH = ParamInfo(
        "inference_env_path", "virtualenv path (unused in-process)", str,
        has_default=True, default=None)

    def set_inference_scripts(self, v: List[str]): return self._set(self.INFERENCE_SCRIPTS, v)
    def get_inference_scripts(self) -> Optional[List[str]]: return self._get(self.INFERENCE_SCRIPTS)
    def set_inference_map_func(self, v: str): return self._set(self.INFERENCE_MAP_FUNC, v)
    def get_inference_map_func(self) -> str: return self._get(self.INFERENCE_MAP_FUNC)
    def set_inference_hyper_params_key(self, v: str): return self._set(self.INFERENCE_HYPER_PARAMS_KEY, v)
    def get_inference_hyper_params_key(self) -> str: return self._get(self.INFERENCE_HYPER_PARAMS_KEY)
    def set_inference_hyper_params(self, v: List[str]): return self._set(self.INFERENCE_HYPER_PARAMS, v)
    def get_inference_hyper_params(self) -> Optional[List[str]]: return self._get(self.INFERENCE_HYPER_PARAMS)
    def set_inference_env_path(self, v: str): return self._set(self.INFERENCE_ENV_PATH, v)
    def get_inference_env_path(self) -> Optional[str]: return self._get(self.INFERENCE_ENV_PATH)


class HasTrainSelectedCols(WithParams):
    TRAIN_SELECTED_COLS = ParamInfo(
        "train_selected_cols", "input columns selected for training", list,
        required=True, validator=ParamValidators.non_empty())

    def set_train_selected_cols(self, v: List[str]): return self._set(self.TRAIN_SELECTED_COLS, v)
    def get_train_selected_cols(self) -> List[str]: return self._get(self.TRAIN_SELECTED_COLS)


class HasTrainOutputCols(WithParams):
    TRAIN_OUTPUT_COLS = ParamInfo(
        "train_output_cols", "output columns of the training stage", list,
        has_default=True, default=None)

    def set_train_output_cols(self, v: List[str]): return self._set(self.TRAIN_OUTPUT_COLS, v)
    def get_train_output_cols(self) -> Optional[List[str]]: return self._get(self.TRAIN_OUTPUT_COLS)


class HasTrainOutputTypes(WithParams):
    TRAIN_OUTPUT_TYPES = ParamInfo(
        "train_output_types", "output column wire types (DataTypes names)",
        list, has_default=True, default=None)

    def set_train_output_types(self, v: List[str]): return self._set(self.TRAIN_OUTPUT_TYPES, v)
    def get_train_output_types(self) -> Optional[List[str]]: return self._get(self.TRAIN_OUTPUT_TYPES)


class HasInferenceSelectedCols(WithParams):
    INFERENCE_SELECTED_COLS = ParamInfo(
        "inference_selected_cols", "input columns selected for inference",
        list, required=True, validator=ParamValidators.non_empty())

    def set_inference_selected_cols(self, v: List[str]): return self._set(self.INFERENCE_SELECTED_COLS, v)
    def get_inference_selected_cols(self) -> List[str]: return self._get(self.INFERENCE_SELECTED_COLS)


class HasInferenceOutputCols(WithParams):
    INFERENCE_OUTPUT_COLS = ParamInfo(
        "inference_output_cols", "output columns of the inference stage",
        list, required=True, validator=ParamValidators.non_empty())

    def set_inference_output_cols(self, v: List[str]): return self._set(self.INFERENCE_OUTPUT_COLS, v)
    def get_inference_output_cols(self) -> List[str]: return self._get(self.INFERENCE_OUTPUT_COLS)


class HasInferenceOutputTypes(WithParams):
    INFERENCE_OUTPUT_TYPES = ParamInfo(
        "inference_output_types", "output column wire types (DataTypes names)",
        list, required=True, validator=ParamValidators.non_empty())

    def set_inference_output_types(self, v: List[str]): return self._set(self.INFERENCE_OUTPUT_TYPES, v)
    def get_inference_output_types(self) -> List[str]: return self._get(self.INFERENCE_OUTPUT_TYPES)
