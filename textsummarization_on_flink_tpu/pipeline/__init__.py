from textsummarization_on_flink_tpu.pipeline import bridge  # noqa: F401
from textsummarization_on_flink_tpu.pipeline import codec  # noqa: F401
from textsummarization_on_flink_tpu.pipeline import estimator  # noqa: F401
from textsummarization_on_flink_tpu.pipeline import io  # noqa: F401
from textsummarization_on_flink_tpu.pipeline import params  # noqa: F401
from textsummarization_on_flink_tpu.pipeline import app  # noqa: F401
