"""Host-side streaming bridge: the driver <-> worker record queues.

This replaces Flink-AI-Extended's Java<->Python data-exchange queues (the
`MLMapFunction` read/write queue pair described in
/root/reference/doc/Flink-AI-Extended Integration Report.md:887-941): a
bounded byte-record queue in each direction between the pipeline driver
(which owns sources/sinks) and the worker (which owns the model loop).

Design requirements carried over from the reference's observed failure
modes:
  * results must flush IMMEDIATELY — the reference's bridge only surfaced a
    result when the NEXT record arrived (Issue 6, report:879-897); here a
    put wakes the consumer before returning.
  * clean end-of-stream — `close()` makes drained `get`s return None
    instead of blocking forever.

Two interchangeable implementations:
  * `NativeRecordQueue`: C++ ring buffer (native/bridge.cpp) loaded via
    ctypes — mirrors the reference's native data plane (AI-Extended's
    queues + TF runtime are C++); used automatically when the shared
    library is built.
  * `PyRecordQueue`: pure-Python fallback with identical semantics.

`make_record_queue()` picks native when available.  Both are safe for one
producer + one consumer thread (the bridge topology; matches the
reference's per-task queue pair).
"""

from __future__ import annotations

import collections
import ctypes
import logging
import os
import threading
from typing import Optional

_deque = collections.deque

log = logging.getLogger(__name__)

_NATIVE_LIB_NAMES = ("libtsbridge.so", "tsbridge.so")


class RecordQueue:
    """Interface: a bounded queue of byte records with end-of-stream."""

    def put(self, data: bytes, timeout: Optional[float] = None) -> bool:
        """Enqueue; False on timeout or if closed."""
        raise NotImplementedError

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        """Dequeue; None when closed-and-drained (end of stream) or timeout.
        Use `closed` to distinguish timeout from end-of-stream."""
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    @property
    def closed(self) -> bool:
        raise NotImplementedError

    def __len__(self) -> int:
        raise NotImplementedError


class PyRecordQueue(RecordQueue):
    """Condition-variable deque mirroring the C++ implementation exactly —
    including close() waking a producer parked in a full-queue put()."""

    def __init__(self, capacity: int = 1024):
        self._items: "collections.deque[bytes]" = _deque()
        self._capacity = max(capacity, 1)
        self._cond = threading.Condition()
        self._closed = False

    def put(self, data: bytes, timeout: Optional[float] = None) -> bool:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._closed or len(self._items) < self._capacity,
                timeout=timeout)
            if not ok or self._closed:
                return False
            self._items.append(bytes(data))
            self._cond.notify_all()  # immediate flush
            return True

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self._closed or len(self._items) > 0,
                timeout=timeout)
            if not ok or not self._items:
                return None  # timeout, or closed-and-drained
            rec = self._items.popleft()
            self._cond.notify_all()
            return rec

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()  # wakes parked producers AND consumers

    @property
    def closed(self) -> bool:
        with self._cond:
            return self._closed

    def __len__(self) -> int:
        with self._cond:
            return len(self._items)


class NativeRecordQueue(RecordQueue):
    """ctypes wrapper over the C++ ring buffer (native/bridge.cpp)."""

    _lib = None
    _lib_path: Optional[str] = None

    @classmethod
    def load_library(cls) -> Optional[ctypes.CDLL]:
        if cls._lib is not None:
            return cls._lib
        here = os.path.dirname(os.path.abspath(__file__))
        candidates = [os.path.join(here, "..", "native", n)
                      for n in _NATIVE_LIB_NAMES]
        env = os.environ.get("TS_BRIDGE_LIB")
        if env:
            candidates.insert(0, env)
        for path in candidates:
            path = os.path.abspath(path)
            if os.path.exists(path):
                try:
                    lib = ctypes.CDLL(path)
                except OSError as e:
                    log.warning("failed to load bridge library %s: %s", path, e)
                    continue
                lib.tsb_queue_new.restype = ctypes.c_void_p
                lib.tsb_queue_new.argtypes = [ctypes.c_size_t]
                lib.tsb_queue_free.argtypes = [ctypes.c_void_p]
                lib.tsb_queue_put.restype = ctypes.c_int
                lib.tsb_queue_put.argtypes = [
                    ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t,
                    ctypes.c_double]
                lib.tsb_queue_get.restype = ctypes.c_ssize_t
                lib.tsb_queue_get.argtypes = [
                    ctypes.c_void_p, ctypes.POINTER(ctypes.c_void_p),
                    ctypes.c_double]
                lib.tsb_record_free.argtypes = [ctypes.c_void_p]
                lib.tsb_queue_close.argtypes = [ctypes.c_void_p]
                lib.tsb_queue_closed.restype = ctypes.c_int
                lib.tsb_queue_closed.argtypes = [ctypes.c_void_p]
                lib.tsb_queue_size.restype = ctypes.c_size_t
                lib.tsb_queue_size.argtypes = [ctypes.c_void_p]
                cls._lib = lib
                cls._lib_path = path
                log.info("loaded native bridge library %s", path)
                return lib
        return None

    def __init__(self, capacity: int = 1024):
        lib = self.load_library()
        if lib is None:
            raise RuntimeError("native bridge library not built "
                               "(python native/build.py)")
        self._handle = ctypes.c_void_p(lib.tsb_queue_new(capacity))
        self._local_closed = False

    def put(self, data: bytes, timeout: Optional[float] = None) -> bool:
        t = -1.0 if timeout is None else float(timeout)
        r = self._lib.tsb_queue_put(self._handle, data, len(data), t)
        return r == 0

    def get(self, timeout: Optional[float] = None) -> Optional[bytes]:
        t = -1.0 if timeout is None else float(timeout)
        ptr = ctypes.c_void_p()
        n = self._lib.tsb_queue_get(self._handle, ctypes.byref(ptr), t)
        if n < 0:
            return None
        try:
            if n == 0:
                return b""
            return ctypes.string_at(ptr, n)
        finally:
            if ptr.value:
                self._lib.tsb_record_free(ptr)

    def close(self) -> None:
        self._lib.tsb_queue_close(self._handle)

    @property
    def closed(self) -> bool:
        return bool(self._lib.tsb_queue_closed(self._handle))

    def __len__(self) -> int:
        return int(self._lib.tsb_queue_size(self._handle))

    def __del__(self) -> None:
        try:
            if getattr(self, "_handle", None):
                self._lib.tsb_queue_free(self._handle)
                self._handle = None
        except Exception:  # pragma: no cover - tslint: disable=TS005 — __del__ during interpreter teardown
            pass


def native_available() -> bool:
    return NativeRecordQueue.load_library() is not None


def make_record_queue(capacity: int = 1024,
                      prefer_native: bool = True) -> RecordQueue:
    if prefer_native and native_available():
        return NativeRecordQueue(capacity)
    return PyRecordQueue(capacity)
