"""Checkpoint save/restore with the reference's full lifecycle semantics.

Rebuilds the reference checkpoint story (SURVEY.md §5.4) without TF:

  * 3-checkpoint retention + index file — `Saver(max_to_keep=3)` +
    the `checkpoint` latest-file protocol
    (/root/reference/src/main/python/pointer-generator/run_summarization.py:192,
    train.py:68).
  * best-model track with its own `checkpoint_best` index
    (run_summarization.py:250-292).
  * `load_ckpt` retry loop — decoders wait for trainers to produce a first
    checkpoint (util.py:29-41: infinite 10s retries).
  * checkpoint surgery: `convert_to_coverage_model`
    (run_summarization.py:157-178) and `restore_best_model`
    (run_summarization.py:132-154, which drops Adagrad accumulators).

Format: one ``.npz`` per checkpoint holding every leaf of the TrainState
pytree under its slash-joined key path (``params/decoder/attention/W_h``,
``opt_state/accumulators/...``, ``step``), plus a small JSON sidecar of
hparams for provenance.  Arrays are gathered to host before writing
(multi-host callers save on the chief only, parallel/distributed.is_chief).

Mesh story (ISSUE 8): a sharded TrainState saves through the same path —
the host-local gather in ``state_to_arrays`` assembles full arrays from
whatever layout the sharding registry (parallel/sharding.py) placed them
in, so checkpoints are mesh-shape-agnostic; ``restore_sharded`` places a
restored state onto ANY mesh against the registry specs (save at
dp4 x tp2, resume at dp2 x tp2, bit-identical after gather).
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import re
import time
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.resilience.errors import (
    CheckpointCorruptError,
)
from textsummarization_on_flink_tpu.train import optim
from textsummarization_on_flink_tpu.train.trainer import TrainState

log = logging.getLogger(__name__)

PyTree = Any

CKPT_PREFIX = "model.ckpt"
INDEX_FILE = "checkpoint"  # latest-pointer file, tf.train.Saver protocol
BEST_INDEX_FILE = "checkpoint_best"
MANIFEST_SUFFIX = ".sum"  # checksum manifest sidecar (RESILIENCE.md)


# --------------------------------------------------------------------------
# Pytree <-> flat dict
# --------------------------------------------------------------------------

def _flatten(tree: PyTree, prefix: str = "") -> Dict[str, np.ndarray]:
    """Flatten nested dicts/NamedTuples to slash-joined keys."""
    out: Dict[str, np.ndarray] = {}
    tree = jax.device_get(tree)  # one batched D2H transfer, not per-leaf

    def rec(node: Any, path: str) -> None:
        if isinstance(node, dict):
            for k, v in node.items():
                rec(v, f"{path}/{k}" if path else str(k))
        elif hasattr(node, "_fields"):  # NamedTuple
            for k in node._fields:
                rec(getattr(node, k), f"{path}/{k}" if path else str(k))
        elif isinstance(node, (list, tuple)):  # e.g. transformer layer lists
            for i, v in enumerate(node):
                rec(v, f"{path}/{i}" if path else str(i))
        else:
            arr = np.asarray(node)
            if arr.dtype == jnp.bfloat16:
                # npz silently degrades ml_dtypes bf16 to a raw void
                # dtype on round trip; widen losslessly to f32 here and
                # let trainer.cast_opt_state re-narrow on resume
                arr = arr.astype(np.float32)
            out[path] = arr

    rec(tree, prefix)
    return out


def _listify(node: Any) -> Any:
    """Turn {'0': .., '1': ..} dicts (flattened lists) back into lists."""
    if isinstance(node, dict):
        node = {k: _listify(v) for k, v in node.items()}
        if node and all(k.isdigit() for k in node) \
                and sorted(int(k) for k in node) == list(range(len(node))):
            return [node[str(i)] for i in range(len(node))]
    return node


def _unflatten_dicts(flat: Dict[str, np.ndarray]) -> Dict[str, Any]:
    """Rebuild a nested tree from slash-joined keys (lists restored from
    their integer-key segments)."""
    root: Dict[str, Any] = {}
    for key, val in flat.items():
        parts = key.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return _listify(root)


def state_to_arrays(state: TrainState) -> Dict[str, np.ndarray]:
    if jax.process_count() > 1:
        # tp/sp shards may live on other hosts' devices; a bare device_get
        # raises on non-addressable arrays. All-gather the full values
        # first (every host participates; only the chief writes).
        from jax.experimental import multihost_utils

        state = multihost_utils.process_allgather(state, tiled=True)
    return _flatten(state)


def arrays_to_state(flat: Dict[str, np.ndarray]) -> TrainState:
    tree = _unflatten_dicts(flat)
    step = tree.get("step", np.zeros((), np.int32))
    params = tree["params"]
    acc = tree.get("opt_state", {}).get("accumulators")
    if acc is None:
        acc = jax.tree_util.tree_map(lambda p: np.zeros_like(p), params)
    return TrainState(params=params,
                      opt_state=optim.AdagradState(accumulators=acc),
                      step=np.asarray(step, np.int32))


# --------------------------------------------------------------------------
# Raw file IO
# --------------------------------------------------------------------------

def content_fingerprint(tree: PyTree) -> str:
    """Content fingerprint of one params pytree: sha256 over every
    leaf's bytes in deterministic (flattened-name) order, truncated to
    16 hex chars.  Two trees collide only if they are byte-identical.

    The ONE fingerprint scheme (ISSUE 12/14): the distillation
    teacher sidecar (train/distill.teacher_fingerprint) and the serve
    layer's summary-cache key (decode/decoder.params_fingerprint,
    SERVING.md "Front door") both resolve through here, so the two can
    never drift — a draft checkpoint verified against a teacher and a
    cache entry keyed on a snapshot mean the same bytes."""
    flat = _flatten(tree)
    h = hashlib.sha256()
    for name in sorted(flat):
        h.update(name.encode("utf-8"))
        h.update(np.ascontiguousarray(flat[name]).tobytes())
    return h.hexdigest()[:16]


def _file_sha256(path: str) -> Tuple[str, int]:
    h = hashlib.sha256()
    size = 0
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
            size += len(block)
    return h.hexdigest(), size


def save_arrays(path: str, flat: Dict[str, np.ndarray]) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
    # checksum manifest (RESILIENCE.md): hashed from the tmp file BEFORE
    # publish, so a manifest can never describe a file it didn't see;
    # published after the npz so readers either find a verifiable pair
    # or (crash window) a checkpoint without a manifest — never a
    # manifest for a missing/partial checkpoint
    digest, size = _file_sha256(tmp)
    try:
        # an overwrite (e.g. training re-reaching a step after a NaN
        # rollback) must not leave the OLD manifest describing the NEW
        # bytes during the publish window — drop it first so readers see
        # manifest-less (loadable unverified), never mismatched
        os.remove(path + MANIFEST_SUFFIX)
    except OSError:
        pass
    os.replace(tmp, path)  # atomic publish; readers never see partial files
    mtmp = path + MANIFEST_SUFFIX + ".tmp"
    with open(mtmp, "w", encoding="utf-8") as f:
        json.dump({"algo": "sha256", "hexdigest": digest, "bytes": size,
                   "file": os.path.basename(path)}, f)
    os.replace(mtmp, path + MANIFEST_SUFFIX)


def load_arrays(path: str) -> Dict[str, np.ndarray]:
    with np.load(path, allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def verify_manifest(path: str) -> bool:
    """Check `path` against its checksum manifest.

    Returns True when the manifest exists and matches, False when there
    is no manifest (pre-manifest checkpoint: nothing to verify against).
    Raises CheckpointCorruptError on a mismatch or unreadable manifest.
    """
    mpath = path + MANIFEST_SUFFIX
    if not os.path.exists(mpath):
        return False
    try:
        with open(mpath, "r", encoding="utf-8") as f:
            manifest = json.load(f)
        want = manifest["hexdigest"]
        want_bytes = int(manifest.get("bytes", -1))
    except (OSError, ValueError, KeyError) as e:
        raise CheckpointCorruptError(
            f"unreadable checksum manifest {mpath}") from e
    got, size = _file_sha256(path)
    if got != want or (want_bytes >= 0 and size != want_bytes):
        raise CheckpointCorruptError(
            f"checkpoint {path} failed checksum verification "
            f"(manifest {want[:12]}.../{want_bytes}B, "
            f"file {got[:12]}.../{size}B)")
    return True


def load_arrays_verified(path: str,
                         faults: Optional[Any] = None,
                         ) -> Dict[str, np.ndarray]:
    """Checksum-verify (when a manifest exists) then load.  A zip/npz
    decode failure is normalized to CheckpointCorruptError so every
    corruption class routes through the same fallback."""
    plan = faults if faults is not None else faultinject.plan()
    if plan.fire("ckpt.load"):
        raise CheckpointCorruptError(f"injected ckpt.load fault for {path}")
    verify_manifest(path)
    try:
        return load_arrays(path)
    except (ValueError, OSError, KeyError) as e:
        # manifest matched (or was absent) but the payload won't decode
        raise CheckpointCorruptError(
            f"checkpoint {path} failed to decode: {e}") from e


def _write_index(directory: str, ckpt_path: str, index_file: str) -> None:
    tmp = os.path.join(directory, index_file + ".tmp")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"model_checkpoint_path": os.path.basename(ckpt_path)}, f)
    os.replace(tmp, os.path.join(directory, index_file))


def latest_checkpoint(directory: str, index_file: str = INDEX_FILE,
                      ) -> Optional[str]:
    """Resolve the newest checkpoint path via the index file (falling back
    to a directory scan, like tf.train.latest_checkpoint)."""
    idx = os.path.join(directory, index_file)
    if os.path.exists(idx):
        try:
            with open(idx, "r", encoding="utf-8") as f:
                name = json.load(f)["model_checkpoint_path"]
            path = name if os.path.isabs(name) else os.path.join(directory, name)
            if os.path.exists(path):
                return path
        except (json.JSONDecodeError, KeyError, OSError):
            log.warning("unreadable checkpoint index %s; rescanning", idx)
    prefix = "bestmodel" if index_file == BEST_INDEX_FILE else CKPT_PREFIX
    pattern = os.path.join(directory, f"{prefix}-*.npz")
    found = sorted(glob.glob(pattern), key=_ckpt_step)
    return found[-1] if found else None


def _ckpt_step(path: str) -> Tuple[int, int]:
    """Sort key: (step, is_surgery).  Surgery outputs
    (`-<N>_cov_init.npz`, `-<N>_restored.npz`) carry their source step and
    sort *after* the plain checkpoint of the same step (they are newer)."""
    m = re.search(r"-(\d+)(_[a-z_]+)?\.npz$", path)
    if not m:
        return (-1, 0)
    return (int(m.group(1)), 1 if m.group(2) else 0)


def checkpoint_candidates(directory: str, index_file: str = INDEX_FILE,
                          ) -> List[str]:
    """Checkpoint paths newest-first: the index-resolved latest, then
    every on-disk sibling in descending step order (the corruption
    fallback chain, RESILIENCE.md)."""
    prefix = "bestmodel" if index_file == BEST_INDEX_FILE else CKPT_PREFIX
    pattern = os.path.join(directory, f"{prefix}-*.npz")
    found = sorted(glob.glob(pattern), key=_ckpt_step, reverse=True)
    latest = latest_checkpoint(directory, index_file)
    if latest is not None and latest in found:
        found.remove(latest)
        found.insert(0, latest)
    elif latest is not None:
        found.insert(0, latest)
    return found


def load_ckpt(directory: str, index_file: str = INDEX_FILE,
              max_retries: Optional[int] = None, retry_secs: float = 10.0,
              faults: Optional[Any] = None,
              ) -> Tuple[str, Dict[str, np.ndarray]]:
    """Load the newest loadable checkpoint, retrying until one appears
    (util.py:29-41: infinite 10s retry by default).

    Resilience (ISSUE 2): each attempt walks the candidate chain newest
    to oldest, checksum-verifying via the manifest — a corrupted latest
    checkpoint falls back to the next-older one instead of crashing
    (``resilience/ckpt_fallbacks_total``).  The wait loop itself is
    observable: ``ckpt/load_retries_total`` counts sleeps and
    ``ckpt/load_wait_seconds`` gauges the cumulative wait, so a decoder
    stuck waiting on a trainer is visible rather than silent.
    """
    attempt = 0
    waited = 0.0
    c_retries = obs.counter("ckpt/load_retries_total")
    c_fallbacks = obs.counter("resilience/ckpt_fallbacks_total")
    g_wait = obs.gauge("ckpt/load_wait_seconds")
    while True:
        for i, path in enumerate(checkpoint_candidates(directory, index_file)):
            try:
                flat = load_arrays_verified(path, faults=faults)
            except CheckpointCorruptError as e:
                c_fallbacks.inc()
                log.warning("checkpoint %s unusable (%s); falling back to "
                            "the next-older checkpoint", path, e)
                continue
            except OSError as e:  # raced with retention cleanup
                log.info("Failed to load checkpoint from %s: %s", path, e)
                continue
            if i > 0:
                log.warning("loaded fallback checkpoint %s (newer "
                            "candidates were corrupt)", path)
            return path, flat
        attempt += 1
        if max_retries is not None and attempt > max_retries:
            raise FileNotFoundError(
                f"no loadable checkpoint in {directory} after "
                f"{max_retries} retries")
        log.info("Failed to load checkpoint from %s. Sleeping %.0f secs...",
                 directory, retry_secs)
        c_retries.inc()
        time.sleep(retry_secs)
        waited += retry_secs
        g_wait.set(waited)


# --------------------------------------------------------------------------
# Checkpointer / BestModelSaver
# --------------------------------------------------------------------------

class Checkpointer:
    """Rolling-retention trainer checkpoints (Saver(max_to_keep=3) parity)."""

    def __init__(self, directory: str, max_to_keep: int = 3,
                 hps: Optional[HParams] = None):
        self.directory = directory
        self.max_to_keep = max_to_keep
        self.hps = hps
        # a per-job fault plan (hps.faults) is resolved ONCE so its RNG
        # streams and fire budgets persist across restore() calls — a
        # "fails exactly N times then heals" spec must not reset per
        # call.  The process default stays dynamic (resolved per use) so
        # TS_FAULTS / use_plan() contexts keep routing.
        self._job_faults = (
            faultinject.plan_for(hps)
            if hps is not None and getattr(hps, "faults", "") else None)
        os.makedirs(directory, exist_ok=True)
        # the provenance sidecar is written on the first save(), not here:
        # consulting is_chief() would force JAX backend init inside a
        # filesystem-only constructor (it can hang on a down TPU tunnel,
        # and before jax.distributed.initialize every host believes it is
        # process 0) — ADVICE r3
        self._sidecar_pending = hps is not None

    def _write_sidecar(self) -> None:
        # written once, atomically — chief-only (every host constructs a
        # Checkpointer on a shared dir; a shared tmp name would race),
        # pid-suffixed as defense
        tmp = os.path.join(self.directory, f"hparams.json.tmp{os.getpid()}")
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.hps.to_json())
        os.replace(tmp, os.path.join(self.directory, "hparams.json"))
        self._sidecar_pending = False

    def save(self, state: TrainState) -> str:
        """Multi-host: EVERY host must call this (the shard gather inside
        state_to_arrays is collective); only the chief touches the
        filesystem."""
        from textsummarization_on_flink_tpu.parallel import distributed

        reg = obs.registry_for(self.hps)
        t0 = time.perf_counter()
        with obs.spans.span(reg, "checkpoint/save"):
            flat = state_to_arrays(state)  # collective on multi-host
            step = int(np.asarray(flat.get("step", 0)))
            path = os.path.join(self.directory, f"{CKPT_PREFIX}-{step}.npz")
            if not distributed.is_chief():
                return path
            if self._sidecar_pending:
                self._write_sidecar()
            save_arrays(path, flat)
            _write_index(self.directory, path, INDEX_FILE)
            self._retain()
        reg.histogram("checkpoint/save_seconds").observe(
            time.perf_counter() - t0)
        reg.counter("checkpoint/saves_total").inc()
        try:
            reg.counter("checkpoint/save_bytes_total").inc(
                os.path.getsize(path))
        except OSError:  # pragma: no cover - raced with retention/cleanup
            pass
        log.info("saved checkpoint %s", path)
        return path

    def _retain(self) -> None:
        ckpts = sorted(
            glob.glob(os.path.join(self.directory, f"{CKPT_PREFIX}-*.npz")),
            key=_ckpt_step)
        for old in ckpts[: max(0, len(ckpts) - self.max_to_keep)]:
            try:
                os.remove(old)
                log.info("removed old checkpoint %s", old)
            except OSError:
                pass
            try:
                os.remove(old + MANIFEST_SUFFIX)
            except OSError:
                pass

    def _load_with_fallback(
            self, reg: obs.Registry,
    ) -> Tuple[Optional[str], Optional[Dict[str, np.ndarray]]]:
        """(path, arrays) of the newest loadable checkpoint, checksum-
        verified, falling back over corrupt candidates (RESILIENCE.md);
        (None, None) when the directory holds no loadable checkpoint."""
        faults = (self._job_faults if self._job_faults is not None
                  else faultinject.plan())
        for path in checkpoint_candidates(self.directory):
            try:
                return path, load_arrays_verified(path, faults=faults)
            except (CheckpointCorruptError, OSError) as e:
                reg.counter("resilience/ckpt_fallbacks_total").inc()
                log.warning("checkpoint %s unusable (%s); falling back to "
                            "the next-older checkpoint", path, e)
        return None, None

    def restore(self, path: Optional[str] = None) -> Optional[TrainState]:
        reg = obs.registry_for(self.hps)
        if path is None:
            path, flat = self._load_with_fallback(reg)
            if flat is None:
                return None
        else:
            # explicit path: verification failure surfaces to the caller
            # (they asked for THIS checkpoint, silently substituting
            # another would be wrong)
            flat = load_arrays_verified(
                path,
                faults=(self._job_faults if self._job_faults is not None
                        else faultinject.plan()))
        t0 = time.perf_counter()
        with obs.spans.span(reg, "checkpoint/restore"):
            state = arrays_to_state(flat)
        reg.histogram("checkpoint/restore_seconds").observe(
            time.perf_counter() - t0)
        reg.counter("checkpoint/restores_total").inc()
        try:
            reg.counter("checkpoint/restore_bytes_total").inc(
                os.path.getsize(path))
        except OSError:  # pragma: no cover
            pass
        return state

    def restore_sharded(self, plan: Any,
                        path: Optional[str] = None,
                        ) -> Optional[TrainState]:
        """Restore and place onto `plan`'s mesh against the sharding
        registry's specs (ISSUE 8: one mesh story).

        Checkpoints are mesh-shape-agnostic: save() gathers shards to
        full host arrays (state_to_arrays), so a state saved from a
        dp4 x tp2 mesh restores onto dp2 x tp2 — or any other shape the
        registry can lay it out on — with bit-identical values after a
        gather.  When the registry's hps store the Adagrad accumulators
        in bf16, they are re-narrowed BEFORE placement (npz cannot hold
        bf16, so save() widened them losslessly to f32) — the same
        widen/narrow round trip the Trainer applies on resume.
        """
        state = self.restore(path)
        if state is None:
            return None
        from textsummarization_on_flink_tpu.train import (
            trainer as trainer_lib,
        )

        registry = plan.registry
        state = trainer_lib.cast_opt_state(registry.hps, state)
        return registry.shard_state(state)


class BestModelSaver:
    """Eval-side best-model track (run_summarization.py:250-292): keeps ONE
    `bestmodel-<step>.npz` under eval_dir, indexed by `checkpoint_best`."""

    def __init__(self, eval_dir: str):
        self.eval_dir = eval_dir
        os.makedirs(eval_dir, exist_ok=True)

    def __call__(self, params: PyTree, running_avg_loss: float, step: int,
                 ) -> str:
        path = os.path.join(self.eval_dir, f"bestmodel-{step}.npz")
        old = glob.glob(os.path.join(self.eval_dir, "bestmodel-*.npz"))
        save_arrays(path, _flatten(params, "params"))
        _write_index(self.eval_dir, path, BEST_INDEX_FILE)
        for o in old:
            if o != path:
                try:
                    os.remove(o)
                except OSError:
                    pass
                try:
                    os.remove(o + MANIFEST_SUFFIX)
                except OSError:
                    pass
        log.info("saved best model (loss %.4f) to %s", running_avg_loss, path)
        return path


# --------------------------------------------------------------------------
# Checkpoint surgery
# --------------------------------------------------------------------------

def convert_to_coverage_model(train_dir: str, hps: HParams,
                              seed: int = 0, force: bool = False) -> str:
    """Add fresh coverage params to the latest non-coverage checkpoint and
    save it as `<ckpt>_cov_init` (run_summarization.py:157-178 semantics:
    restore non-coverage vars, init the new coverage vars, save-and-exit).

    Refuses to re-convert a checkpoint that is itself a coverage conversion
    (double invocation would overwrite trained coverage params with fresh
    noise); pass force=True to override.
    """
    from textsummarization_on_flink_tpu.models import pointer_generator as pg

    path = latest_checkpoint(train_dir)
    if path is None:
        raise FileNotFoundError(f"no checkpoint in {train_dir}")
    if "_cov_init" in os.path.basename(path) and not force:
        raise RuntimeError(
            f"latest checkpoint {path} is already a coverage conversion; "
            "re-converting would destroy trained coverage params "
            "(pass force=True to override)")
    state = arrays_to_state(load_arrays(path))
    if "attention" not in (state.params.get("decoder") or {}):
        raise ValueError(
            "coverage conversion applies to the pointer_generator family "
            "only — the transformer's coverage penalty has no parameters "
            "to add, set --coverage directly")
    new_params = pg.add_coverage_params(state.params,
                                        jax.random.PRNGKey(seed))
    # fresh accumulator only for the new variable (others keep history)
    new_acc = jax.tree_util.tree_map(lambda x: x, state.opt_state.accumulators)
    new_acc["decoder"]["attention"]["w_c"] = np.full_like(
        np.asarray(new_params["decoder"]["attention"]["w_c"]),
        hps.adagrad_init_acc)
    new_state = TrainState(params=new_params,
                           opt_state=optim.AdagradState(accumulators=new_acc),
                           step=state.step)
    out = path[: -len(".npz")] + "_cov_init.npz"
    save_arrays(out, state_to_arrays(new_state))
    _write_index(train_dir, out, INDEX_FILE)
    log.info("saved coverage-converted checkpoint %s", out)
    return out


def restore_best_model(eval_dir: str, train_dir: str, hps: HParams) -> str:
    """Copy the eval best model into the train dir with FRESH Adagrad
    accumulators (run_summarization.py:132-154 restores only non-Adagrad
    variables), saved as `model.ckpt-<step>_restored.npz`."""
    path = latest_checkpoint(eval_dir, BEST_INDEX_FILE)
    if path is None:
        raise FileNotFoundError(f"no best model in {eval_dir}")
    flat = load_arrays(path)
    params = _unflatten_dicts(flat)["params"]
    acc = jax.tree_util.tree_map(
        lambda p: np.full_like(p, hps.adagrad_init_acc), params)
    m = re.search(r"-(\d+)\.npz$", path)
    step = int(m.group(1)) if m else 0
    state = TrainState(params=params,
                       opt_state=optim.AdagradState(accumulators=acc),
                       step=np.asarray(step, np.int32))
    out = os.path.join(train_dir, f"{CKPT_PREFIX}-{step}_restored.npz")
    save_arrays(out, state_to_arrays(state))
    _write_index(train_dir, out, INDEX_FILE)
    log.info("restored best model %s -> %s", path, out)
    return out
