"""Checkpoint NaN/Inf inspector CLI.

Behavior parity with /root/reference/src/main/python/pointer-generator/
inspect_checkpoint.py:11-45: scan every tensor in a checkpoint, report
which are finite / contain some non-finite / are entirely non-finite.

Usage: python -m textsummarization_on_flink_tpu.checkpoint.inspect <file.npz>
"""

from __future__ import annotations

import sys
from typing import Dict, List

import numpy as np

from textsummarization_on_flink_tpu.checkpoint.checkpointer import load_arrays


def inspect_arrays(flat: Dict[str, np.ndarray]) -> Dict[str, List[str]]:
    finite, some_bad, all_bad = [], [], []
    for name in sorted(flat):
        v = np.asarray(flat[name])
        if not np.issubdtype(v.dtype, np.floating) and \
                not np.issubdtype(v.dtype, np.complexfloating):
            finite.append(name)
            continue
        bad = ~np.isfinite(v)
        if not bad.any():
            finite.append(name)
        elif bad.all():
            all_bad.append(name)
        else:
            some_bad.append(name)
    return {"finite": finite, "some_infnan": some_bad, "all_infnan": all_bad}


def main(argv: List[str]) -> int:
    if len(argv) != 1:
        print("USAGE: python -m textsummarization_on_flink_tpu.checkpoint."
              "inspect <checkpoint.npz>", file=sys.stderr)
        return 2
    flat = load_arrays(argv[0])
    report = inspect_arrays(flat)
    print(f"{len(flat)} tensors in {argv[0]}")
    for name in report["finite"]:
        print(f"  ok      {name}")
    for name in report["some_infnan"]:
        print(f"  SOMEBAD {name}  (contains some inf/nan)")
    for name in report["all_infnan"]:
        print(f"  ALLBAD  {name}  (entirely inf/nan)")
    if not report["some_infnan"] and not report["all_infnan"]:
        print("CHECK PASSED: checkpoint contains no inf/NaN values")
        return 0
    print("CHECK FAILED: checkpoint contains inf/NaN values")
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
