from textsummarization_on_flink_tpu.checkpoint.checkpointer import (  # noqa: F401
    BestModelSaver,
    Checkpointer,
    convert_to_coverage_model,
    latest_checkpoint,
    load_ckpt,
    restore_best_model,
)
