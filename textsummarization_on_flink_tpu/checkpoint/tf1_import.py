"""TF1 pretrained-checkpoint importer (pretrained_model_tf1.2.1 layout).

Maps the reference graph's TF1 variable names onto our parameter pytree so
the published pointer-generator checkpoint can be served without
retraining (SURVEY.md §7.4 item 3).  Names verified against the variable
scopes in /root/reference/src/main/python/pointer-generator/model.py
(seq2seq/embedding:210, encoder:89, reduce_final_st:108,
output_projection:228) and attention_decoder.py (W_h:66, v:70,
coverage/w_c:73, Attention/Linear:91+219, calculate_pgen:165,
AttnOutputProjection:172); LSTM cells use the TF>=1.2 `lstm_cell/kernel`
fused naming (noted in the pointer-generator README).

Two entry points:
  * `import_tf1_arrays(name->ndarray)`: pure numpy, no TF needed — feed it
    from any tool that can read a TF bundle (including
    `tf.train.load_checkpoint` on a machine that has TF).
  * `import_tf1_checkpoint(path)`: convenience wrapper that uses
    tensorflow if importable, else raises with instructions.

Conv-shaped attention tensors are squeezed: W_h [1,1,2H,D] -> [2H,D],
w_c [1,1,1,D] -> [D].
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

PyTree = Any

_DEC = "seq2seq/decoder/attention_decoder"

# TF1 variable name -> (tree path tuple, squeeze)
TF1_NAME_MAP: Dict[str, Any] = {
    "seq2seq/embedding/embedding": (("embedding",), False),
    "seq2seq/encoder/bidirectional_rnn/fw/lstm_cell/kernel":
        (("encoder", "fw", "kernel"), False),
    "seq2seq/encoder/bidirectional_rnn/fw/lstm_cell/bias":
        (("encoder", "fw", "bias"), False),
    "seq2seq/encoder/bidirectional_rnn/bw/lstm_cell/kernel":
        (("encoder", "bw", "kernel"), False),
    "seq2seq/encoder/bidirectional_rnn/bw/lstm_cell/bias":
        (("encoder", "bw", "bias"), False),
    "seq2seq/reduce_final_st/w_reduce_c": (("reduce", "w_reduce_c"), False),
    "seq2seq/reduce_final_st/w_reduce_h": (("reduce", "w_reduce_h"), False),
    "seq2seq/reduce_final_st/bias_reduce_c":
        (("reduce", "bias_reduce_c"), False),
    "seq2seq/reduce_final_st/bias_reduce_h":
        (("reduce", "bias_reduce_h"), False),
    f"{_DEC}/W_h": (("decoder", "attention", "W_h"), True),
    f"{_DEC}/v": (("decoder", "attention", "v"), False),
    f"{_DEC}/coverage/w_c": (("decoder", "attention", "w_c"), True),
    f"{_DEC}/Attention/Linear/Matrix":
        (("decoder", "attention", "linear_kernel"), False),
    f"{_DEC}/Attention/Linear/Bias":
        (("decoder", "attention", "linear_bias"), False),
    f"{_DEC}/Linear/Matrix": (("decoder", "input_linear", "kernel"), False),
    f"{_DEC}/Linear/Bias": (("decoder", "input_linear", "bias"), False),
    f"{_DEC}/lstm_cell/kernel": (("decoder", "cell", "kernel"), False),
    f"{_DEC}/lstm_cell/bias": (("decoder", "cell", "bias"), False),
    f"{_DEC}/calculate_pgen/Linear/Matrix":
        (("decoder", "pgen_linear", "kernel"), False),
    f"{_DEC}/calculate_pgen/Linear/Bias":
        (("decoder", "pgen_linear", "bias"), False),
    f"{_DEC}/AttnOutputProjection/Linear/Matrix":
        (("decoder", "output_linear", "kernel"), False),
    f"{_DEC}/AttnOutputProjection/Linear/Bias":
        (("decoder", "output_linear", "bias"), False),
    "seq2seq/output_projection/w": (("output_projection", "w"), False),
    "seq2seq/output_projection/v": (("output_projection", "v"), False),
}

# Variables we deliberately skip: optimizer slots + bookkeeping.
_SKIP_SUFFIXES = ("/Adagrad",)
_SKIP_NAMES = ("global_step", "train_step/last_loss")


def import_tf1_arrays(tf1_vars: Dict[str, np.ndarray],
                      strict: bool = True) -> PyTree:
    """Build our params pytree from a {tf1_name: ndarray} dict.

    Missing `coverage/w_c` is tolerated (non-coverage checkpoints); use
    models.pointer_generator.add_coverage_params afterwards if needed.
    """
    params: Dict[str, Any] = {}
    seen = set()
    for name, value in tf1_vars.items():
        if name in _SKIP_NAMES or any(name.endswith(s) for s in _SKIP_SUFFIXES):
            continue
        if name not in TF1_NAME_MAP:
            if strict:
                raise KeyError(f"unmapped TF1 variable: {name!r} "
                               f"shape {np.shape(value)}")
            continue
        path, squeeze = TF1_NAME_MAP[name]
        v = np.asarray(value, dtype=np.float32)
        if squeeze:
            v = np.squeeze(v)
        node = params
        for p in path[:-1]:
            node = node.setdefault(p, {})
        node[path[-1]] = v
        seen.add(name)
    required = set(TF1_NAME_MAP) - {f"{_DEC}/coverage/w_c"}
    missing = required - seen
    if missing:
        raise KeyError(f"TF1 checkpoint missing variables: {sorted(missing)}")
    return params


def import_tf1_checkpoint(path: str, strict: bool = True) -> PyTree:
    """Read a TF checkpoint bundle directly (requires tensorflow)."""
    try:
        from tensorflow.python.training import py_checkpoint_reader
        reader = py_checkpoint_reader.NewCheckpointReader(path)
    except ImportError as e:
        raise ImportError(
            "tensorflow is not available in this environment; dump the "
            "checkpoint to {name: ndarray} with any TF installation and "
            "call import_tf1_arrays instead") from e
    shapes = reader.get_variable_to_shape_map()
    return import_tf1_arrays({n: reader.get_tensor(n) for n in shapes},
                             strict=strict)


def infer_hps_from_params(params: PyTree, base: Optional[Any] = None):
    """Derive the model dims the checkpoint was trained with: embedding
    [V, E], encoder fused kernel [(E+H), 4H], so every dim is determined.
    `base` supplies the non-architectural fields (paths, decode lengths)."""
    from textsummarization_on_flink_tpu.config import HParams

    base = base if base is not None else HParams()
    vsize, emb = params["embedding"].shape
    hidden = params["encoder"]["fw"]["kernel"].shape[1] // 4
    has_cov = "w_c" in params["decoder"]["attention"]
    return base.replace(vocab_size=int(vsize), emb_dim=int(emb),
                        hidden_dim=int(hidden),
                        coverage=bool(base.coverage or has_cov))


def import_to_train_dir(bundle_path: str, train_dir: str,
                        hps: Optional[Any] = None, strict: bool = True,
                        seed: int = 0) -> str:
    """End-to-end: TF1 bundle -> servable checkpoint in `train_dir`.

    Adagrad accumulators are re-initialized (the reference's
    restore_best_model drops them too, run_summarization.py:132-154);
    a non-coverage checkpoint under coverage hps gets fresh coverage
    params (convert_to_coverage_model semantics, :157-178).
    Returns the saved checkpoint path.
    """
    import jax

    from textsummarization_on_flink_tpu.checkpoint import (
        checkpointer as ckpt_lib,
    )
    from textsummarization_on_flink_tpu.models import pointer_generator as pg
    from textsummarization_on_flink_tpu.train import trainer as trainer_lib

    params = import_tf1_checkpoint(bundle_path, strict=strict)
    hps = infer_hps_from_params(params, base=hps)
    if hps.coverage and "w_c" not in params["decoder"]["attention"]:
        params = pg.add_coverage_params(params, jax.random.PRNGKey(seed))
    state = trainer_lib.init_train_state(hps, hps.vocab_size, params=params)
    return ckpt_lib.Checkpointer(train_dir, hps=hps).save(state)


def _main(argv=None) -> int:
    import argparse

    from textsummarization_on_flink_tpu.config import HParams

    ap = argparse.ArgumentParser(
        description="Import a TF1 pointer-generator checkpoint bundle "
                    "(pretrained_model_tf1.2.1) into a servable train dir.")
    ap.add_argument("bundle", help="TF1 checkpoint prefix (path minus "
                                   ".index/.data-* suffix)")
    ap.add_argument("train_dir", help="output directory (the --log_root/"
                                      "--exp_name/train the decoder reads)")
    ap.add_argument("--coverage", action="store_true",
                    help="add fresh coverage params if the bundle lacks "
                         "them (convert_to_coverage_model semantics)")
    ap.add_argument("--lenient", action="store_true",
                    help="ignore unmapped variables instead of failing")
    args = ap.parse_args(argv)
    path = import_to_train_dir(
        args.bundle, args.train_dir,
        hps=HParams(coverage=args.coverage), strict=not args.lenient)
    print(f"imported -> {path}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(_main())
