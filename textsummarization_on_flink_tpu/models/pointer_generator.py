"""Pointer-generator seq2seq model (See et al. 2017), TPU-native.

Functional JAX re-design of the reference SummarizationModel
(/root/reference/src/main/python/pointer-generator/model.py) and
attention_decoder (attention_decoder.py).  Differences from the reference
are architectural, not semantic:

  * the 100-step Python-unrolled decoder graph (model.py:214,
    attention_decoder.py:141-174) is a single `lax.scan`;
  * training never materializes the extended-vocab final distribution
    (model.py:162-183); the gold-token probability is computed directly
    (see ops/losses.gold_mixture_prob);
  * the in-article OOV budget is static (`hps.max_oov_buckets`) instead of
    the dynamic per-batch `max_art_oovs` placeholder (model.py:45);
  * decode-time single-step semantics (initial_state_attention=True,
    attention_decoder.py:138-160) are preserved exactly, including the
    quirk that the previous step's attention is recomputed to update
    coverage while the current step's attention does not update it.

Parameter tree field names mirror the TF1 variable layout so checkpoint
import is a pure renaming exercise (checkpoint/tf1_import.py).

All public functions are pure and jittable; `hps` is static.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from textsummarization_on_flink_tpu import config as config_lib
from textsummarization_on_flink_tpu import models as models_lib
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.ops import attention as attn_ops
from textsummarization_on_flink_tpu.ops import losses as loss_ops
from textsummarization_on_flink_tpu.ops import lstm as lstm_ops

Array = jax.Array
Params = Dict[str, Any]


class EncoderOutput(NamedTuple):
    enc_states: Array  # [B, T_enc, 2H]
    enc_features: Array  # precomputed W_h h_i, [B, T_enc, 2H]
    dec_in_state: Tuple[Array, Array]  # (c, h) each [B, H]


class DecodeStepOutput(NamedTuple):
    topk_ids: Array  # [B, 2*beam]
    topk_log_probs: Array  # [B, 2*beam]
    state: Tuple[Array, Array]  # new (c, h)
    attn_dist: Array  # [B, T_enc]
    p_gen: Array  # [B]
    coverage: Array  # [B, T_enc] updated coverage (zeros if coverage off)


class TrainOutput(NamedTuple):
    loss: Array  # NLL (the reference's self._loss)
    coverage_loss: Array  # 0.0 when coverage off
    total_loss: Array  # loss + cov_loss_wt * coverage_loss
    attn_dists: Array  # [B, T_dec, T_enc] (for inspection/attn-vis)
    p_gens: Array  # [B, T_dec]


# --------------------------------------------------------------------------
# Initialization (model.py:204-231 initializer choices)
# --------------------------------------------------------------------------

def _trunc_normal(key: Array, shape, std: float) -> Array:
    return std * jax.random.truncated_normal(key, -2.0, 2.0, shape, jnp.float32)


def _uniform(key: Array, shape, mag: float) -> Array:
    return jax.random.uniform(key, shape, jnp.float32, -mag, mag)


def _glorot(key: Array, shape) -> Array:
    if len(shape) == 1:
        fan_in = fan_out = shape[0]
    else:
        fan_in, fan_out = shape[-2], shape[-1]
    limit = (6.0 / (fan_in + fan_out)) ** 0.5
    return jax.random.uniform(key, shape, jnp.float32, -limit, limit)


def init_params(hps: HParams, vsize: int, key: Array) -> Params:
    """Build the parameter pytree. Names map 1:1 onto the TF1 checkpoint
    variable layout (see checkpoint/tf1_import.py for the exact mapping)."""
    H, E = hps.hidden_dim, hps.emb_dim
    D = 2 * H  # attention vector size == encoder state size (attention_decoder.py:63)
    keys = iter(jax.random.split(key, 24))
    tn = hps.trunc_norm_init_std
    mag = hps.rand_unif_init_mag

    params: Params = {
        "embedding": _trunc_normal(next(keys), (vsize, E), tn),
        "encoder": {
            "fw": {"kernel": _uniform(next(keys), (E + H, 4 * H), mag),
                   "bias": jnp.zeros((4 * H,), jnp.float32)},
            "bw": {"kernel": _uniform(next(keys), (E + H, 4 * H), mag),
                   "bias": jnp.zeros((4 * H,), jnp.float32)},
        },
        "reduce": {
            "w_reduce_c": _trunc_normal(next(keys), (2 * H, H), tn),
            "w_reduce_h": _trunc_normal(next(keys), (2 * H, H), tn),
            "bias_reduce_c": _trunc_normal(next(keys), (H,), tn),
            "bias_reduce_h": _trunc_normal(next(keys), (H,), tn),
        },
        "decoder": {
            "cell": {"kernel": _uniform(next(keys), (E + H, 4 * H), mag),
                     "bias": jnp.zeros((4 * H,), jnp.float32)},
            "attention": {
                "W_h": _glorot(next(keys), (D, D)),
                "v": _glorot(next(keys), (D,)),
                "w_c": _glorot(next(keys), (D,)),
                "linear_kernel": _glorot(next(keys), (2 * H, D)),
                "linear_bias": jnp.zeros((D,), jnp.float32),
            },
            "input_linear": {"kernel": _glorot(next(keys), (E + D, E)),
                             "bias": jnp.zeros((E,), jnp.float32)},
            "pgen_linear": {"kernel": _glorot(next(keys), (D + H + H + E, 1)),
                            "bias": jnp.zeros((1,), jnp.float32)},
            "output_linear": {"kernel": _glorot(next(keys), (H + D, H)),
                              "bias": jnp.zeros((H,), jnp.float32)},
        },
        "output_projection": {
            "w": _trunc_normal(next(keys), (H, vsize), tn),
            "v": _trunc_normal(next(keys), (vsize,), tn),
        },
    }
    return params


def add_coverage_params(params: Params, key: Array) -> Params:
    """Fresh w_c for coverage conversion (run_summarization.py:157-178)."""
    new = jax.tree_util.tree_map(lambda x: x, params)  # shallow-ish copy
    D = new["decoder"]["attention"]["W_h"].shape[0]
    new["decoder"]["attention"]["w_c"] = _glorot(key, (D,))
    return new


# --------------------------------------------------------------------------
# Forward pieces
# --------------------------------------------------------------------------

def _linear(p: Dict[str, Array], *args: Array) -> Array:
    """attention_decoder.py:184-228 `linear`: concat args, matmul, bias."""
    x = jnp.concatenate(args, axis=-1) if len(args) > 1 else args[0]
    return x @ p["kernel"] + p["bias"]


def _cast(hps: HParams, x: Array) -> Array:
    return x.astype(jnp.bfloat16) if hps.compute_dtype == "bfloat16" else x


def _proj(hps: HParams, x: Array, w: Array) -> Array:
    """x @ w with bf16 operands + f32 accumulation in bfloat16 mode — the
    [H, vocab] output projection is the FLOP-dominant matmul (SURVEY §7.2
    step 7 note).  Delegates to the ONE dtype-aware vocab matmul
    (ops/losses.project_scores) so the streaming chunked loss projects
    identically."""
    return loss_ops.project_scores(x, w, hps.compute_dtype)


def encode(params: Params, hps: HParams, enc_batch: Array, enc_lens: Array,
           enc_padding_mask: Array) -> EncoderOutput:
    """Embed + biLSTM + state reduction (model.py:210-221)."""
    emb = params["embedding"][enc_batch]  # [B, T, E]
    emb = _cast(hps, emb)
    enc_states, fw_st, bw_st = lstm_ops.bidirectional_encoder(
        params["encoder"]["fw"], params["encoder"]["bw"], emb, enc_lens,
        enc_padding_mask, unroll=hps.scan_unroll)
    # The decoder attention re-streams enc_states AND enc_feats from HBM
    # on EVERY decode step (T_dec x 2 x [B, T, D] — the step's dominant
    # bandwidth consumer), so in bf16 mode keep both in bf16: half the
    # bytes.  The attention energies/softmax still run in f32 — the op's
    # f32 dec_feats promote the arithmetic, so only the HBM
    # representation narrows, not the softmax math.
    if hps.compute_dtype != "bfloat16":
        enc_states = enc_states.astype(jnp.float32)
    # _reduce_states (model.py:97-121): ReLU linear from fw||bw to H
    r = params["reduce"]
    old_c = jnp.concatenate([fw_st[0], bw_st[0]], axis=-1)
    old_h = jnp.concatenate([fw_st[1], bw_st[1]], axis=-1)
    new_c = jax.nn.relu(old_c @ r["w_reduce_c"] + r["bias_reduce_c"])
    new_h = jax.nn.relu(old_h @ r["w_reduce_h"] + r["bias_reduce_h"])
    enc_feats = attn_ops.encoder_features(
        params["decoder"]["attention"], enc_states)
    return EncoderOutput(enc_states, enc_feats, (new_c, new_h))


def _decoder_core(params: Params, hps: HParams, enc: EncoderOutput,
                  enc_padding_mask: Array, state: Tuple[Array, Array],
                  context: Array, coverage: Array, x: Array,
                  ) -> Dict[str, Array]:
    """One train-mode decoder step (attention_decoder.py:141-174):
    merged input+context `x` -> cell -> attention (updates coverage) ->
    p_gen -> output projection input.  coverage always flows; with
    coverage off it is simply unused by the attention energies.

    `x` is the input_linear output; forward_train hoists its embedding
    half out of the scan (one [B, T, E] @ [E, E] matmul) and adds the
    context half per step."""
    dp = params["decoder"]
    cell_out, new_state = lstm_ops.lstm_cell(dp["cell"], x, state)
    new_context, attn_dist, new_cov = attn_ops.attend(
        dp["attention"], enc.enc_states, enc.enc_features, enc_padding_mask,
        new_state, coverage if hps.coverage else None, hps.coverage)
    if new_cov is None:
        new_cov = coverage
    p_gen = jax.nn.sigmoid(
        _linear(dp["pgen_linear"], new_context, new_state[0], new_state[1], x)
    )[:, 0]
    output = _linear(dp["output_linear"], cell_out, new_context)
    return dict(x=x, state=new_state, context=new_context, attn_dist=attn_dist,
                coverage=new_cov, p_gen=p_gen, output=output)


def forward_train(params: Params, hps: HParams, arrays: Dict[str, Array],
                  ) -> TrainOutput:
    """Full training/eval forward pass (model.py:199-277 semantics).

    The decoder scan carries only the recurrent state; everything batched
    over steps is hoisted out of it:
      * the embedding half of input_linear runs as one [B, T, E] matmul
        before the scan;
      * the FLOP-dominant [H, V] output projection, its softmax, and the
        NLL run AFTER the scan as one [T_dec, B, H] @ [H, V] matmul —
        per-step projection feeds the MXU M=B rows per 128-row tile
        (~12% fill at the reference batch); hoisted it is M=T_dec*B;
      * the coverage loss is the closed-form exclusive prefix sum of the
        stacked attention outputs (loss_ops.coverage_loss).
    Memory note: the hoisted scores tensor is [T_dec, B, V] f32 (~320 MB
    at reference scale), the price of the MXU-shaped matmul.
    """
    B = arrays["enc_batch"].shape[0]
    T_enc = arrays["enc_batch"].shape[1]
    enc = encode(params, hps, arrays["enc_batch"], arrays["enc_lens"],
                 arrays["enc_padding_mask"])
    emb_dec = params["embedding"][arrays["dec_batch"]]  # [B, T_dec, E]
    w = params["output_projection"]["w"]
    v = params["output_projection"]["v"]
    ip = params["decoder"]["input_linear"]
    E = emb_dec.shape[-1]
    emb_proj = emb_dec @ ip["kernel"][:E] + ip["bias"]  # [B, T_dec, E]
    k_ctx = ip["kernel"][E:]

    def step(carry, emb_proj_t):
        state, context, coverage = carry
        x = emb_proj_t + context @ k_ctx
        res = _decoder_core(params, hps, enc, arrays["enc_padding_mask"],
                            state, context, coverage, x)
        return ((res["state"], res["context"], res["coverage"]),
                (res["output"], res["attn_dist"], res["p_gen"]))

    D = enc.enc_states.shape[-1]
    init = (enc.dec_in_state, jnp.zeros((B, D), jnp.float32),
            jnp.zeros((B, T_enc), jnp.float32))
    _, (outputs, attn_dists, p_gens) = jax.lax.scan(
        step, init, jnp.swapaxes(emb_proj, 0, 1),
        unroll=max(hps.scan_unroll, 1))

    # hoisted projection + loss over all steps at once.  Memory note:
    # the [T_dec, B, V] f32 scores tensor (~320 MB at reference scale)
    # is also held as an autodiff residual (logsumexp/take_along_axis
    # grads need it), so training peak HBM grows by roughly 2x its size;
    # --remat recomputes it in backward instead (trade ~one extra
    # projection matmul for the residual) for larger batches/vocabs, and
    # --loss_chunk streams the projection+loss in T_dec chunks so the
    # full scores tensor never materializes in EITHER pass (the byte
    # diet, PERF.md) — token-exact vs the materialized path.
    dec_mask = arrays["dec_padding_mask"]
    targets_t = jnp.swapaxes(arrays["target_batch"], 0, 1)  # [T_dec, B]

    if hps.loss_chunk > 0:
        if hps.pointer_gen:
            gold = loss_ops.streaming_gold_probs(
                outputs, attn_dists, p_gens, targets_t,
                arrays["enc_batch_extend_vocab"], w, v,
                chunk=hps.loss_chunk, compute_dtype=hps.compute_dtype)
            loss = loss_ops.pointer_nll(jnp.swapaxes(gold, 0, 1), dec_mask)
        else:
            loss = loss_ops.streaming_softmax_cross_entropy(
                outputs, targets_t, jnp.swapaxes(dec_mask, 0, 1), w, v,
                chunk=hps.loss_chunk, compute_dtype=hps.compute_dtype)
    else:
        def scores_loss(outputs, attn_dists, p_gens):
            scores = _proj(hps, outputs, w) + v  # [T_dec, B, V]
            if hps.pointer_gen:
                gold = loss_ops.gold_mixture_prob_from_scores(
                    scores, attn_dists, p_gens, targets_t,
                    arrays["enc_batch_extend_vocab"])
                return loss_ops.pointer_nll(jnp.swapaxes(gold, 0, 1),
                                            dec_mask)
            return loss_ops.softmax_cross_entropy_baseline(
                jnp.swapaxes(scores, 0, 1), arrays["target_batch"], dec_mask)

        if hps.remat:
            scores_loss = jax.checkpoint(scores_loss)
        loss = scores_loss(outputs, attn_dists, p_gens)
    attn_b = jnp.swapaxes(attn_dists, 0, 1)  # [B, T_dec, T_enc]
    if hps.coverage:
        cov_loss = loss_ops.coverage_loss(attn_b, dec_mask)
    else:
        cov_loss = jnp.zeros(())
    total = loss + hps.cov_loss_wt * cov_loss
    return TrainOutput(loss=loss, coverage_loss=cov_loss, total_loss=total,
                       attn_dists=attn_b,
                       p_gens=jnp.swapaxes(p_gens, 0, 1))


# --------------------------------------------------------------------------
# Decode mode (beam search building blocks)
# --------------------------------------------------------------------------

def run_encoder(params: Params, hps: HParams, arrays: Dict[str, Array],
                ) -> EncoderOutput:
    """Beam-search encoder pass (model.py:347-364)."""
    return encode(params, hps, arrays["enc_batch"], arrays["enc_lens"],
                  arrays["enc_padding_mask"])


def final_distribution(hps: HParams, vocab_dist: Array, attn_dist: Array,
                       p_gen: Array, enc_batch_extend_vocab: Array) -> Array:
    """Extended-vocab mixture distribution [B, V + max_oov_buckets]
    (model.py:146-183), with the static OOV budget replacing the dynamic
    max_art_oovs.  Used at decode time only."""
    B, V = vocab_dist.shape
    ext_V = V + hps.max_oov_buckets
    weighted_vocab = p_gen[:, None] * vocab_dist
    weighted_attn = (1.0 - p_gen)[:, None] * attn_dist  # [B, T_enc]
    base = jnp.zeros((B, ext_V), vocab_dist.dtype)
    base = base.at[:, :V].set(weighted_vocab)
    b_idx = jnp.arange(B)[:, None].repeat(attn_dist.shape[1], axis=1)
    return base.at[b_idx, enc_batch_extend_vocab].add(weighted_attn)


def decode_onestep(params: Params, hps: HParams, enc: EncoderOutput,
                   enc_padding_mask: Array, enc_batch_extend_vocab: Array,
                   latest_tokens: Array, state: Tuple[Array, Array],
                   prev_coverage: Array) -> DecodeStepOutput:
    """One beam-search decoder step with the reference's decode-mode
    (initial_state_attention=True) semantics, attention_decoder.py:138-160:

      1. re-run attention at the PREVIOUS state to rebuild the previous
         context vector and update coverage (this is the only place
         coverage advances in decode mode);
      2. merge input+context, step the cell;
      3. attention at the new state WITHOUT updating coverage;
      4. p_gen, output projection, pointer mixture, top-2*beam.

    latest_tokens: [B] fixed-vocab ids (caller maps OOV->UNK,
    beam_search.py:112); state: (c, h) [B, H]; prev_coverage: [B, T_enc].
    """
    dp = params["decoder"]
    use_cov = hps.coverage
    ctx_prev, _, cov = attn_ops.attend(
        dp["attention"], enc.enc_states, enc.enc_features, enc_padding_mask,
        state, prev_coverage if use_cov else None, use_cov)
    if cov is None:
        cov = prev_coverage
    inp_emb = params["embedding"][latest_tokens]
    x = _linear(dp["input_linear"], inp_emb, ctx_prev)
    cell_out, new_state = lstm_ops.lstm_cell(dp["cell"], x, state)
    context, attn_dist, _ = attn_ops.attend(
        dp["attention"], enc.enc_states, enc.enc_features, enc_padding_mask,
        new_state, cov if use_cov else None, use_cov)
    p_gen = jax.nn.sigmoid(
        _linear(dp["pgen_linear"], context, new_state[0], new_state[1], x))[:, 0]
    output = _linear(dp["output_linear"], cell_out, context)
    vocab_scores = _proj(hps, output, params["output_projection"]["w"]) + \
        params["output_projection"]["v"]
    vocab_dist = jax.nn.softmax(vocab_scores, axis=-1)
    if hps.pointer_gen:
        final_dist = final_distribution(hps, vocab_dist, attn_dist, p_gen,
                                        enc_batch_extend_vocab)
    else:
        final_dist = vocab_dist
    k = 2 * hps.beam_size  # model.py:284 (batch_size==beam_size there)
    topk_probs, topk_ids = jax.lax.top_k(final_dist, k)
    return DecodeStepOutput(topk_ids=topk_ids,
                            topk_log_probs=jnp.log(topk_probs),
                            state=new_state, attn_dist=attn_dist, p_gen=p_gen,
                            coverage=cov)


def decode_onestep_shared(params: Params, hps: HParams, enc_one: EncoderOutput,
                          enc_mask: Array, ext_ids: Array,
                          latest_tokens: Array, state: Tuple[Array, Array],
                          prev_coverage: Array,
                          nb: Optional[Array] = None) -> DecodeStepOutput:
    """decode_onestep with the PER-ARTICLE encoder view shared across
    the K beam hypotheses (decode byte diet, ISSUE 7): enc_one leaves
    are [T_enc, ...] with no hypothesis axis, enc_mask/ext_ids [T_enc].
    The two attention queries broadcast against one encoder copy
    (ops/attention.attend_shared) instead of the K-fold
    `jnp.broadcast_to` the adapter used to materialize per step; only
    genuinely per-hypothesis tensors (cell state, coverage, the
    extended-vocab mixture) carry K.  Same decode-mode semantics
    (initial_state_attention=True) step for step.

    ``nb`` (length-masked slot decode, ISSUE 11): traced active-block
    count routing both attends through the blocked conditional chain
    (ops/attention._attend_shared_blocked) so per-step encoder traffic
    scales with the longest active resident's true length."""
    dp = params["decoder"]
    use_cov = hps.coverage
    block = config_lib.resolve_enc_block(hps) if nb is not None else 0
    ctx_prev, _, cov = attn_ops.attend_shared(
        dp["attention"], enc_one.enc_states, enc_one.enc_features, enc_mask,
        state, prev_coverage if use_cov else None, use_cov,
        nb=nb, block=block)
    if cov is None:
        cov = prev_coverage
    inp_emb = params["embedding"][latest_tokens]
    x = _linear(dp["input_linear"], inp_emb, ctx_prev)
    cell_out, new_state = lstm_ops.lstm_cell(dp["cell"], x, state)
    context, attn_dist, _ = attn_ops.attend_shared(
        dp["attention"], enc_one.enc_states, enc_one.enc_features, enc_mask,
        new_state, cov if use_cov else None, use_cov,
        nb=nb, block=block)
    p_gen = jax.nn.sigmoid(
        _linear(dp["pgen_linear"], context, new_state[0], new_state[1], x))[:, 0]
    output = _linear(dp["output_linear"], cell_out, context)
    vocab_scores = _proj(hps, output, params["output_projection"]["w"]) + \
        params["output_projection"]["v"]
    vocab_dist = jax.nn.softmax(vocab_scores, axis=-1)
    K = latest_tokens.shape[0]
    if hps.pointer_gen:
        # the mixture scatter is genuinely per-hypothesis; the broadcast
        # ext ids are an int32 index operand, not a streamed tensor
        ext_k = jnp.broadcast_to(ext_ids[None], (K,) + ext_ids.shape)
        final_dist = final_distribution(hps, vocab_dist, attn_dist, p_gen,
                                        ext_k)
    else:
        final_dist = vocab_dist
    topk_probs, topk_ids = jax.lax.top_k(final_dist, 2 * hps.beam_size)
    return DecodeStepOutput(topk_ids=topk_ids,
                            topk_log_probs=jnp.log(topk_probs),
                            state=new_state, attn_dist=attn_dist, p_gen=p_gen,
                            coverage=cov)


# --------------------------------------------------------------------------
# Beam-search adapter protocol (shared by all model families)
# --------------------------------------------------------------------------

class BeamStepOut(NamedTuple):
    """Model-agnostic one-step beam output (decode/beam_search.py).
    ``state`` is an opaque pytree whose every leaf has leading beam axis K,
    so the search can gather surviving hypotheses with one tree_map."""

    topk_ids: Array  # [K, 2*beam]
    topk_log_probs: Array  # [K, 2*beam]
    attn_dist: Array  # [K, T_enc]
    p_gen: Array  # [K]
    state: Any


def beam_encode(params: Params, hps: HParams, arrays: Dict[str, Array],
                ) -> EncoderOutput:
    """Batched encoder view for beam search (leaves lead with B; the
    search vmaps per article)."""
    return run_encoder(params, hps, arrays)


def beam_adapter(hps: HParams):
    """(init_state, step) closures implementing the beam protocol for the
    LSTM pointer-generator.  State = decoder cell (c, h) + coverage."""
    K = hps.beam_size

    def init_state(params: Params, enc_one: EncoderOutput):
        del params
        H = enc_one.dec_in_state[0].shape[-1]
        T_enc = enc_one.enc_states.shape[0]
        return {
            "cell_c": jnp.broadcast_to(enc_one.dec_in_state[0][None], (K, H)),
            "cell_h": jnp.broadcast_to(enc_one.dec_in_state[1][None], (K, H)),
            "coverage": jnp.zeros((K, T_enc), jnp.float32),
        }

    def step(params: Params, enc_one: EncoderOutput, enc_mask: Array,
             ext_ids: Array, t: Array, latest: Array, state,
             nb=None) -> BeamStepOut:
        del t  # the LSTM state carries all positional context
        # per-article encoder view handed through UN-broadcast (decode
        # byte diet): only cell state + coverage carry the K axis
        out = decode_onestep_shared(params, hps, enc_one, enc_mask, ext_ids,
                                    latest,
                                    (state["cell_c"], state["cell_h"]),
                                    state["coverage"], nb=nb)
        return BeamStepOut(
            topk_ids=out.topk_ids, topk_log_probs=out.topk_log_probs,
            attn_dist=out.attn_dist, p_gen=out.p_gen,
            state={"cell_c": out.state[0], "cell_h": out.state[1],
                   "coverage": out.coverage})

    return init_state, step


#: the length-masked slot-decode adapter (ISSUE 11): the shared
#: protocol wrapper threads the traced block count into this family's
#: step, where it scales the two encoder attends with true length
beam_adapter_masked = models_lib.masked_adapter(beam_adapter)


def pad_enc_view(enc_view: EncoderOutput, t_target: int) -> EncoderOutput:
    """Zero-pad a bucket-width encoder view's time axis to ``t_target``
    (the prefill -> pack hand-off, decode/beam_search.prefill_jit).
    The biLSTM encoder is pad-invariant by construction (masked
    carry-through + length-aware reverse, ops/lstm.py), so a
    bucket-width encode equals the valid prefix of a full-width one and
    zeros are exactly what full-width encoding writes past the valid
    length; dec_in_state carries no time axis."""
    def pad(x):
        if x.shape[1] >= t_target:
            return x
        widths = [(0, 0)] * x.ndim
        widths[1] = (0, t_target - x.shape[1])
        return jnp.pad(x, widths)

    return EncoderOutput(enc_states=pad(enc_view.enc_states),
                         enc_features=pad(enc_view.enc_features),
                         dec_in_state=enc_view.dec_in_state)
