"""Average-attention (AAN) draft decoder family — the speculative tier's
cheap proposer (ISSUE 10; ROADMAP item 4).

*Accelerating Neural Transformer via an Average Attention Network*
(PAPERS.md): replace decoder self-attention with a CUMULATIVE-AVERAGE
layer — position t summarizes its prefix as the running mean of the
layer inputs, passed through a small FFN and merged with the current
input through a learned forget/input gate.  The decode step then carries
ONE running sum per layer instead of a growing KV cache, so per-token
cost and resident state are O(1) in history — the property that makes
this family the draft tier under continuous serving (a draft slot is
``L*H`` floats, vs the transformer's ``2*L*T*nh*hd`` cache).

Everything around the decoder self-attention is the transformer family
verbatim — the SAME encoder stack (``transformer._encoder_stack``), the
same per-layer cross-attention/copy mechanism, the same tied-embedding
loss head (``transformer.train_output_tail``), the same
``TransformerEncView`` encoder view — so the family plugs into beam
search, serving, checkpointing, and the sharding registry with zero new
plumbing (param leaf names match the transformer's where shared).

Two init modes:

  * ``init_params`` — fresh (training a draft from scratch / tests);
  * ``init_from_transformer`` — the distilled greedy-draft bootstrap: a
    tf1_import-style declarative mapping copies every shared leaf from a
    full-model checkpoint (embedding, positions, the WHOLE encoder, an
    evenly-strided subset of decoder layers' cross-attention/LN/FFN, the
    loss head) and fresh-initializes only the AAN average-FFN and gate,
    which have no full-model counterpart.  The mapped draft starts out
    proposing from the full model's own representations — acceptance is
    non-trivial from step zero, no distillation run required.

The NARROW variant (ISSUE 12; PERF.md "Distilled narrow draft"):
``draft_hidden`` < H runs the decoder blocks at width H_d while the
embedding, positions, and the WHOLE encoder stay H-wide (copied verbatim
from the full model under ``spec_draft="map"``), bridged by learned
boundary projections — an [H, H_d] ``emb_proj`` on decoder inputs and
[H, H_d] cross-attention K/V maps on the shared encoder output — and a
FACTORED vocab head (``draft_vocab_rank``): scores = (h @ [H_d, r]) @
[r, V] + out_bias, so the projection term scales with r*V instead of
H*V.  That projection is what made the equal-width draft lose on FLOPs
(BYTE_BUDGET.json spec kill condition); the narrow decoder has no
full-model counterpart and is trained by sequence-level distillation
(train/distill.DistillTrainer) through the SAME
``transformer.train_output_tail`` loss head.  Both variants keep the
beam-adapter contract, so every loop kind and ``spec_verify`` work
unmodified.

Numerics note: ``forward_train`` computes the prefix mean with
``jnp.cumsum`` (one parallel pass over T_dec) while the decode step adds
to a running f32 sum — different summation trees, so train/decode parity
is tight-tolerance, not bitwise (pinned by test).  Beam-loop parity
(while/scan/chunked/slot) IS exact: every loop kind drives the same
jitted step.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.models import pointer_generator as pg
from textsummarization_on_flink_tpu import models as models_lib
from textsummarization_on_flink_tpu.models import transformer as tf

Array = jax.Array
Params = Dict[str, Any]

TrainOutput = pg.TrainOutput
BeamStepOut = pg.BeamStepOut
TransformerEncView = tf.TransformerEncView


# --------------------------------------------------------------------------
# Init
# --------------------------------------------------------------------------

def _decoder_hps(hps: HParams) -> HParams:
    """HParams view for the DECODER-side blocks: hidden_dim is the
    draft width H_d (config.resolve_draft_hidden) and ffn_width follows
    it (4*H_d when ffn_dim is auto), while the caller keeps the
    original hps for the H-wide embedding/encoder side.  At equal
    width this is the identity, so the legacy draft's shapes (and the
    family used as a FULL model) are untouched."""
    from textsummarization_on_flink_tpu.config import resolve_draft_hidden

    Hd = resolve_draft_hidden(hps)
    if Hd == hps.hidden_dim:
        return hps
    return hps.replace(hidden_dim=Hd, ffn_dim=hps.ffn_dim or 4 * Hd)


def _init_cross_attn(key: Array, H_in: int, H_d: int) -> Dict[str, Array]:
    """Cross-attention parameters whose K/V maps consume the H_in-wide
    shared encoder output and emit H_d-wide heads — the encoder-view
    boundary projection of the narrow draft (square at equal width,
    where it matches ``tf._init_attn``'s shapes)."""
    ks = jax.random.split(key, 4)
    return {
        "wq": pg._glorot(ks[0], (H_d, H_d)),
        "wk": pg._glorot(ks[1], (H_in, H_d)),
        "wv": pg._glorot(ks[2], (H_in, H_d)),
        "wo": pg._glorot(ks[3], (H_d, H_d)),
    }


def _init_aan_layer(key: Array, H: int, F: int) -> Dict[str, Any]:
    k_ffn, k_gate = jax.random.split(key)
    return {
        "ln1": tf._init_ln(H),
        # the average branch: FFN over the prefix mean, then a 2H->2H
        # input/forget gate over [x_t, ffn(avg_t)] (AAN §3.2)
        "aan_ffn": tf._init_ffn(k_ffn, H, F),
        "aan_gate": {"kernel": pg._glorot(k_gate, (2 * H, 2 * H)),
                     "bias": jnp.zeros((2 * H,), jnp.float32)},
        "ln_cross": tf._init_ln(H),
        "cross_attn": None,  # filled by caller (needs its own key)
        "ln2": tf._init_ln(H),
        "ffn": None,  # filled by caller
    }


def init_params(hps: HParams, vsize: int, key: Array) -> Params:
    """Fresh parameter pytree.  Shared leaves carry the transformer
    family's names/layout (embedding, pos_enc/pos_dec, encoder,
    decoder.layers[i].{ln1,ln_cross,cross_attn,ln2,ffn}, pgen_linear,
    out_bias) so sharding rules and the checkpoint mapping apply
    unchanged; aan_ffn/aan_gate are family-specific, and the narrow
    variant adds emb_proj (the [H, H_d] decoder-input adapter) and
    vocab_head (the factored [H_d, r]·[r, V] projection)."""
    H, F = hps.hidden_dim, hps.ffn_width
    dhps = _decoder_hps(hps)
    Hd, Fd = dhps.hidden_dim, dhps.ffn_width
    rank = hps.draft_vocab_rank
    n_keys = 3 + 2 * hps.enc_layers + 4 * hps.dec_layers + 3
    keys = iter(jax.random.split(key, n_keys))

    enc_layers = []
    for _ in range(hps.enc_layers):
        enc_layers.append({
            "ln1": tf._init_ln(H), "self_attn": tf._init_attn(next(keys), H),
            "ln2": tf._init_ln(H), "ffn": tf._init_ffn(next(keys), H, F),
        })
    dec_layers = []
    for _ in range(hps.dec_layers):
        layer = _init_aan_layer(next(keys), Hd, Fd)
        layer["cross_attn"] = _init_cross_attn(next(keys), H, Hd)
        layer["ffn"] = tf._init_ffn(next(keys), Hd, Fd)
        dec_layers.append(layer)
    params = {
        "embedding": pg._trunc_normal(next(keys), (vsize, H), 0.02),
        "pos_enc": pg._trunc_normal(next(keys), (hps.max_enc_steps, H), 0.02),
        "pos_dec": pg._trunc_normal(next(keys), (hps.max_dec_steps + 1, H),
                                    0.02),
        "encoder": {"layers": enc_layers, "ln_out": tf._init_ln(H)},
        "decoder": {"layers": dec_layers, "ln_out": tf._init_ln(Hd)},
        "pgen_linear": {"kernel": pg._glorot(next(keys), (2 * Hd, 1)),
                        "bias": jnp.zeros((1,), jnp.float32)},
        "out_bias": jnp.zeros((vsize,), jnp.float32),
    }
    if Hd != H:
        params["emb_proj"] = {"kernel": pg._glorot(next(keys), (H, Hd))}
    if rank:
        k1, k2 = jax.random.split(next(keys))
        params["vocab_head"] = {"w1": pg._glorot(k1, (Hd, rank)),
                                "w2": pg._glorot(k2, (rank, vsize))}
    return params


#: decoder-layer leaves copied 1:1 from the mapped full-model layer
#: (tf1_import-style declarative map — the strict check below guarantees
#: every draft leaf is either on this list or in _FRESH_KEYS)
_MAPPED_LAYER_KEYS = ("ln1", "ln_cross", "cross_attn", "ln2", "ffn")
#: family-specific leaves with no full-model counterpart — fresh init
_FRESH_KEYS = ("aan_ffn", "aan_gate")


def draft_layer_indices(full_layers: int, draft_layers: int) -> List[int]:
    """Evenly-strided subset of the full model's decoder layers the
    mapped draft keeps (first and last always included when
    draft_layers >= 2): the standard layer-skip draft recipe."""
    if draft_layers >= full_layers:
        return list(range(full_layers))
    if draft_layers == 1:
        return [full_layers - 1]  # the layer feeding the loss head
    step = (full_layers - 1) / (draft_layers - 1)
    return sorted({round(i * step) for i in range(draft_layers)})


def init_from_transformer(full_params: Params, full_hps: HParams,
                          draft_hps: HParams, key: Array) -> Params:
    """The distilled greedy-draft bootstrap: build AAN draft params from
    a FULL transformer checkpoint (checkpoint/tf1_import.py-style
    declarative mapping — copy shared leaves, fresh-init the rest,
    strict-check that nothing falls through).

    Copied: embedding/pos_enc/pos_dec, the whole encoder, out_bias —
    and at EQUAL width additionally pgen_linear, decoder ln_out, and,
    for each of the ``draft_hps.dec_layers`` evenly-strided kept layers,
    ln1/ln_cross/cross_attn/ln2/ffn.  Fresh: aan_ffn + aan_gate (no
    counterpart; the cumulative-average branch replaces self-attention).

    The NARROW variant (draft_hidden < hidden_dim) keeps the shared
    H-wide leaves (embedding, positions, encoder, out_bias) and
    fresh-initializes the ENTIRE H_d-wide decoder side — boundary
    projections, AAN blocks, cross-attention maps, pgen, the factored
    vocab head — because no full-model leaf has the right shape.  An
    undistilled narrow map therefore starts near zero acceptance
    (exactness still holds); train it with train/distill.DistillTrainer.
    A factored head at equal width (draft_vocab_rank > 0,
    draft_hidden = 0) keeps the mapped decoder layers and
    fresh-initializes only the head.
    """
    if full_hps.model_family != "transformer":
        raise ValueError(
            f"init_from_transformer maps transformer checkpoints only, "
            f"got model_family={full_hps.model_family!r} (use fresh init "
            f"or a separately trained draft for other families)")
    if draft_hps.hidden_dim != full_hps.hidden_dim:
        raise ValueError(
            f"mapped draft must share hidden_dim with the full model "
            f"(draft {draft_hps.hidden_dim} vs full {full_hps.hidden_dim})")
    H = draft_hps.hidden_dim
    dhps = _decoder_hps(draft_hps)
    Hd, Fd = dhps.hidden_dim, dhps.ffn_width
    rank = draft_hps.draft_vocab_rank
    cp = lambda x: jnp.asarray(x)  # noqa: E731 — copy-by-reference is fine
    keep = draft_layer_indices(full_hps.dec_layers, draft_hps.dec_layers)
    keys = iter(jax.random.split(key, len(keep) + 3))
    dec_layers = []
    for src_idx in keep:
        src = full_params["decoder"]["layers"][src_idx]
        k_layer = next(keys)
        layer = _init_aan_layer(k_layer, Hd, Fd)
        if Hd == H:
            for k in _MAPPED_LAYER_KEYS:
                layer[k] = jax.tree_util.tree_map(cp, src[k])
        else:
            # no H_d-shaped counterpart exists: the boundary projection
            # and blocks stay fresh (fold_in re-keys off the layer key)
            layer["cross_attn"] = _init_cross_attn(
                jax.random.fold_in(k_layer, 1), H, Hd)
            layer["ffn"] = tf._init_ffn(jax.random.fold_in(k_layer, 2),
                                        Hd, Fd)
        dec_layers.append(layer)
        # strict check (tf1_import discipline): every key accounted for
        unknown = set(layer) - set(_MAPPED_LAYER_KEYS) - set(_FRESH_KEYS)
        if unknown:
            raise KeyError(f"unmapped draft layer keys: {sorted(unknown)}")
    params = {
        "embedding": cp(full_params["embedding"]),
        "pos_enc": cp(full_params["pos_enc"]),
        "pos_dec": cp(full_params["pos_dec"]),
        "encoder": jax.tree_util.tree_map(cp, full_params["encoder"]),
        "out_bias": cp(full_params["out_bias"]),
    }
    k_tail = next(keys)
    if Hd == H:
        params["decoder"] = {
            "layers": dec_layers,
            "ln_out": jax.tree_util.tree_map(
                cp, full_params["decoder"]["ln_out"])}
        params["pgen_linear"] = jax.tree_util.tree_map(
            cp, full_params["pgen_linear"])
    else:
        params["decoder"] = {"layers": dec_layers, "ln_out": tf._init_ln(Hd)}
        params["pgen_linear"] = {
            "kernel": pg._glorot(jax.random.fold_in(k_tail, 0), (2 * Hd, 1)),
            "bias": jnp.zeros((1,), jnp.float32)}
        params["emb_proj"] = {
            "kernel": pg._glorot(jax.random.fold_in(k_tail, 1), (H, Hd))}
    if rank:
        vsize = full_params["out_bias"].shape[0]
        params["vocab_head"] = {
            "w1": pg._glorot(jax.random.fold_in(k_tail, 2), (Hd, rank)),
            "w2": pg._glorot(jax.random.fold_in(k_tail, 3), (rank, vsize))}
    return params


def make_draft_params(hps: HParams, full_params: Params,
                      seed: int = 0) -> Params:
    """Resolve ``hps.spec_draft`` to draft parameters: 'map' = the
    transformer->AAN checkpoint mapping above, 'fresh' = random init
    (tests/smokes; near-zero acceptance but exactness still holds).
    The ONE resolver — decode/decoder.py and scripts build drafts only
    through here."""
    from textsummarization_on_flink_tpu.config import derive_draft_hps

    dhps = derive_draft_hps(hps)
    if hps.spec_draft == "map":
        return init_from_transformer(full_params, hps, dhps,
                                     jax.random.PRNGKey(seed))
    if hps.spec_draft == "fresh":
        return init_params(dhps, hps.vocab_size, jax.random.PRNGKey(seed))
    raise ValueError(
        f"make_draft_params needs spec_draft='map'|'fresh', got "
        f"{hps.spec_draft!r}")


# --------------------------------------------------------------------------
# The cumulative-average block
# --------------------------------------------------------------------------

def _aan_gate(layer: Dict[str, Any], x_norm: Array, g: Array) -> Array:
    """Input/forget gating of the current input against the averaged
    branch (AAN §3.2): ``i, f = sigmoid(W [x; g])``, out = i*x + f*g."""
    dt = x_norm.dtype
    H = x_norm.shape[-1]
    gates = jax.nn.sigmoid(
        jnp.concatenate([x_norm, g], axis=-1)
        @ layer["aan_gate"]["kernel"].astype(dt)
        + layer["aan_gate"]["bias"].astype(dt))
    return gates[..., :H] * x_norm + gates[..., H:] * g


def _aan_block_train(layer: Dict[str, Any], x_norm: Array) -> Array:
    """Teacher-forced cumulative-average branch over the time axis
    (axis -2): prefix mean via one parallel cumsum (f32 accumulate),
    FFN, gate.  The decode step computes the same quantity from a
    running sum — see the module docstring's numerics note."""
    T = x_norm.shape[-2]
    csum = jnp.cumsum(x_norm.astype(jnp.float32), axis=-2)
    denom = (jnp.arange(T, dtype=jnp.float32) + 1.0)[:, None]
    avg = (csum / denom).astype(x_norm.dtype)
    g = tf._ffn_block(layer["aan_ffn"], avg)
    return _aan_gate(layer, x_norm, g)


# --------------------------------------------------------------------------
# Training forward (fully parallel over decode steps, like the transformer)
# --------------------------------------------------------------------------

def _embed_dec_draft(params: Params, hps: HParams, tokens: Array,
                     positions: Array) -> Array:
    """Decoder-input embedding: the shared H-wide embedding + positions,
    down-projected through the learned [H, H_d] ``emb_proj`` adapter
    when the narrow variant carries one (the embedding-boundary
    projection; identity at equal width)."""
    y = tf._embed_dec(params, hps, tokens, positions)
    ep = params.get("emb_proj")
    if ep is not None:
        y = y @ ep["kernel"].astype(y.dtype)
    return y


def forward_train(params: Params, hps: HParams, arrays: Dict[str, Array],
                  ) -> TrainOutput:
    """Teacher-forced forward -> TrainOutput through the SHARED loss head
    (transformer.train_output_tail): same pointer mixture, same
    --loss_chunk streaming, same coverage penalty.  The narrow variant
    runs the decoder blocks at H_d (``_decoder_hps``) against the
    H-wide encoder output — ``tf._mha`` is width-agnostic, the
    rectangular K/V kernels are the boundary."""
    enc_mask = arrays["enc_padding_mask"]
    T_dec = arrays["dec_batch"].shape[1]
    dhps = _decoder_hps(hps)

    x = tf._embed_enc(params, hps, arrays["enc_batch"])
    enc_out = tf._encoder_stack(params, hps, x, enc_mask)
    enc_out_c = pg._cast(hps, enc_out)

    y = _embed_dec_draft(params, hps, arrays["dec_batch"], jnp.arange(T_dec))
    cross_mask = enc_mask[:, None, :]

    def layer_fn(layer, y, enc_out_c, cross_mask):
        a = _aan_block_train(layer, tf._ln(layer["ln1"], y))
        y = y + a
        c, probs = tf._mha(dhps, layer["cross_attn"],
                           tf._ln(layer["ln_cross"], y), enc_out_c,
                           cross_mask)
        y = y + c
        y = y + tf._ffn_block(layer["ffn"], tf._ln(layer["ln2"], y))
        return y, c, probs

    if hps.remat:
        layer_fn = jax.checkpoint(layer_fn)
    attn_dist = None
    for layer in params["decoder"]["layers"]:
        y, c, probs = layer_fn(layer, y, enc_out_c, cross_mask)
        attn_dist = probs
        cross_ctx = c
    h = tf._ln(params["decoder"]["ln_out"], y).astype(jnp.float32)
    return tf.train_output_tail(params, hps, arrays, h, cross_ctx, attn_dist)


# --------------------------------------------------------------------------
# Decoding (O(1)-in-history step + beam adapter)
# --------------------------------------------------------------------------

def beam_encode(params: Params, hps: HParams, arrays: Dict[str, Array],
                ) -> TransformerEncView:
    """The transformer family's encoder-view precompute, ONE body
    (tf.beam_encode): per-layer cross-attention K/V from the shared
    H-wide encoder output, with the head split at the DRAFT width —
    the narrow variant's rectangular [H, H_d] K/V kernels make this
    the encoder-view boundary projection; identity at equal width."""
    return tf.beam_encode(params, hps, arrays, head_hps=_decoder_hps(hps))


def decode_onestep(params: Params, hps: HParams,
                   enc_one: TransformerEncView, enc_mask: Array,
                   ext_ids: Array, t: Array, latest: Array,
                   aan_sum: Array, nb=None) -> Tuple[Array, Array, Array,
                                                     Array, Array]:
    """One AAN decode step for K hypotheses: O(1) in history — the only
    carried decode state is the [K, L, H] running sum (f32), updated by
    one add; no cache gather, no attention over past positions.

    Returns (final_dist [K, V_ext], attn_dist [K, T_enc], p_gen [K],
    h [K, H_d], new_sum [K, L, H_d]).
    """
    dhps = _decoder_hps(hps)
    y = _embed_dec_draft(params, hps, latest, t)  # [K, H_d]
    dt = y.dtype
    new_sums = []
    attn_dist = None
    for li, layer in enumerate(params["decoder"]["layers"]):
        x_norm = tf._ln(layer["ln1"], y)
        s = aan_sum[:, li] + x_norm.astype(jnp.float32)  # running sum
        new_sums.append(s)
        avg = (s / (t.astype(jnp.float32) + 1.0)).astype(dt)
        g = tf._ffn_block(layer["aan_ffn"], avg)
        y = y + _aan_gate(layer, x_norm, g)
        # cross attention + output head are the transformer family's
        # shared decode blocks — one numerics source for all three
        # decode paths (beam step / spec verify / this); dhps carries
        # the draft width so head splits/scales follow H_d
        cross_out, attn_dist = tf.cross_attend_layer(
            dhps, layer, y, enc_one.cross_k[li], enc_one.cross_v[li],
            enc_mask, nb=nb)
        y = y + cross_out
        y = y + tf._ffn_block(layer["ffn"], tf._ln(layer["ln2"], y))
        cross_ctx = cross_out
    final_dist, p_gen, h = tf.decode_output_tail(params, dhps, y,
                                                 cross_ctx, attn_dist,
                                                 ext_ids)
    new_sum = jnp.stack(new_sums, axis=1)  # [K, L, H_d]
    return final_dist, attn_dist, p_gen, h, new_sum


def beam_adapter(hps: HParams):
    """Beam protocol (init_state, step): the decode state is ONE
    [K, L, H_d] running-sum tensor — every loop kind (while/scan/
    chunked/slot) works unmodified, and a resident draft slot costs
    L*H_d floats instead of a KV cache (narrower still for the narrow
    draft)."""
    K = hps.beam_size
    L = hps.dec_layers
    H = _decoder_hps(hps).hidden_dim

    def init_state(params: Params, enc_one: TransformerEncView):
        del params, enc_one
        return {"aan_sum": jnp.zeros((K, L, H), jnp.float32)}

    def step(params: Params, enc_one: TransformerEncView, enc_mask: Array,
             ext_ids: Array, t: Array, latest: Array, state,
             nb=None) -> BeamStepOut:
        final_dist, attn_dist, p_gen, _, new_sum = decode_onestep(
            params, hps, enc_one, enc_mask, ext_ids, t, latest,
            state["aan_sum"], nb=nb)
        topk_probs, topk_ids = jax.lax.top_k(final_dist, 2 * hps.beam_size)
        return BeamStepOut(topk_ids=topk_ids,
                           topk_log_probs=jnp.log(topk_probs + 1e-10),
                           attn_dist=attn_dist, p_gen=p_gen,
                           state={"aan_sum": new_sum})

    return init_state, step


#: the length-masked slot-decode adapter (ISSUE 11) — the shared
#: protocol wrapper; nb reaches the transformer cross-attention block
beam_adapter_masked = models_lib.masked_adapter(beam_adapter)


#: the AAN encoder view IS the transformer's (same K/V precompute), so
#: the prefill pad hand-off is the transformer's too
pad_enc_view = tf.pad_enc_view
