"""Transformer (BART-class) summarization model family, TPU-native.

The reference repository's only model is the LSTM pointer-generator
(/root/reference/src/main/python/pointer-generator/model.py); this module
is the framework's second model family — the BASELINE.md stretch row
("BART-base behind the same Estimator/Model API") — sharing every
surrounding subsystem: the same ``HParams``, the same ``Batch`` arrays,
the same ``TrainOutput`` contract consumed by the Trainer/Evaluator, the
same on-device beam search (via the beam-adapter protocol in
decode/beam_search.py), the same checkpointing and serving stack.

Architecture (TPU-first choices, not a port of any torch code):

  * pre-LN encoder-decoder with learned positional embeddings and a tied
    input/output embedding ([V, H] — the single biggest matrix, sharded
    over the tp mesh axis exactly like the pointer-generator's
    output_projection);
  * teacher-forced training is fully parallel over decode steps (one
    batched matmul chain — no scan), which is the transformer's
    structural advantage over the reference's 100-step unrolled LSTM
    graph (model.py:214);
  * the pointer/copy mechanism is preserved: the FINAL decoder layer's
    cross-attention (averaged over heads) is the copy distribution,
    ``p_gen = sigmoid(linear([h, cross_ctx]))`` mixes it with the vocab
    softmax, and training computes the gold mixture probability from raw
    logits (same math as ops/losses.gold_mixture_prob, deliberately
    inlined in log space so neither the [B, T, V] softmax nor the
    extended-vocab distribution is ever materialized);
  * coverage (``hps.coverage``) penalizes repeated cross-attention via
    the closed-form exclusive-cumsum coverage loss
    (ops/losses.coverage_loss).  Unlike the LSTM family, coverage does
    NOT feed back into attention energies — that mechanism is specific
    to the reference's additive attention (attention_decoder.py:113-123);
    here coverage is purely the training penalty;
  * incremental decoding uses a static-shape KV cache ([K, L, T, nh, hd]
    with a position mask) so the whole beam search stays inside one
    jitted while_loop;
  * attention logits, softmax, and layernorm run in f32; matmuls follow
    ``hps.compute_dtype`` (bf16 on the MXU).

No dropout: the reference trains without regularization
(run_summarization.py:62-74 has no dropout flag) and determinism keeps
step-parity tests exact.
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from textsummarization_on_flink_tpu import config as config_lib
from textsummarization_on_flink_tpu import models as models_lib
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.models import pointer_generator as pg
from textsummarization_on_flink_tpu.ops import losses as loss_ops

Array = jax.Array
Params = Dict[str, Any]

TrainOutput = pg.TrainOutput  # same contract for Trainer/Evaluator


# --------------------------------------------------------------------------
# Shapes / init
# --------------------------------------------------------------------------

def _head_dim(hps: HParams) -> int:
    return hps.hidden_dim // hps.num_heads


def _init_attn(key: Array, H: int) -> Dict[str, Array]:
    ks = jax.random.split(key, 4)
    return {
        "wq": pg._glorot(ks[0], (H, H)),
        "wk": pg._glorot(ks[1], (H, H)),
        "wv": pg._glorot(ks[2], (H, H)),
        "wo": pg._glorot(ks[3], (H, H)),
    }


def _init_ln(H: int) -> Dict[str, Array]:
    return {"scale": jnp.ones((H,), jnp.float32),
            "bias": jnp.zeros((H,), jnp.float32)}


def _init_ffn(key: Array, H: int, F: int) -> Dict[str, Array]:
    k1, k2 = jax.random.split(key)
    return {"w1": pg._glorot(k1, (H, F)), "b1": jnp.zeros((F,), jnp.float32),
            "w2": pg._glorot(k2, (F, H)), "b2": jnp.zeros((H,), jnp.float32)}


def init_params(hps: HParams, vsize: int, key: Array) -> Params:
    """Parameter pytree.  Top-level ``embedding`` is [V, H] (same name and
    vocab-leading layout as the pointer-generator so mesh tp-sharding and
    divisibility validation apply unchanged)."""
    H, F = hps.hidden_dim, hps.ffn_width
    n_keys = 3 + 2 * hps.enc_layers + 3 * hps.dec_layers + 1
    keys = iter(jax.random.split(key, n_keys))

    enc_layers = []
    for _ in range(hps.enc_layers):
        enc_layers.append({
            "ln1": _init_ln(H), "self_attn": _init_attn(next(keys), H),
            "ln2": _init_ln(H), "ffn": _init_ffn(next(keys), H, F),
        })
    dec_layers = []
    for _ in range(hps.dec_layers):
        dec_layers.append({
            "ln1": _init_ln(H), "self_attn": _init_attn(next(keys), H),
            "ln_cross": _init_ln(H), "cross_attn": _init_attn(next(keys), H),
            "ln2": _init_ln(H), "ffn": _init_ffn(next(keys), H, F),
        })
    return {
        "embedding": pg._trunc_normal(next(keys), (vsize, H), 0.02),
        "pos_enc": pg._trunc_normal(next(keys), (hps.max_enc_steps, H), 0.02),
        "pos_dec": pg._trunc_normal(next(keys), (hps.max_dec_steps + 1, H),
                                    0.02),
        "encoder": {"layers": enc_layers, "ln_out": _init_ln(H)},
        "decoder": {"layers": dec_layers, "ln_out": _init_ln(H)},
        "pgen_linear": {"kernel": pg._glorot(next(keys), (2 * H, 1)),
                        "bias": jnp.zeros((1,), jnp.float32)},
        "out_bias": jnp.zeros((vsize,), jnp.float32),
    }


# --------------------------------------------------------------------------
# Core blocks
# --------------------------------------------------------------------------

def _ln(p: Dict[str, Array], x: Array) -> Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    return ((x32 - mu) * jax.lax.rsqrt(var + 1e-6)
            * p["scale"] + p["bias"]).astype(x.dtype)


def _split_heads(hps: HParams, x: Array) -> Array:
    """[..., H] -> [..., nh, hd]"""
    return x.reshape(x.shape[:-1] + (hps.num_heads, _head_dim(hps)))


def _merge_heads(x: Array) -> Array:
    return x.reshape(x.shape[:-2] + (x.shape[-2] * x.shape[-1],))


def _mha(hps: HParams, p: Dict[str, Array], q_in: Array, kv_in: Array,
         mask: Array) -> Tuple[Array, Array]:
    """Multi-head attention.

    q_in: [..., Tq, H]; kv_in: [..., Tk, H]; mask: broadcastable to
    [..., Tq, Tk] (1 = attend).  Returns (output [..., Tq, H],
    head-averaged probabilities [..., Tq, Tk] in f32).
    """
    # compute in the activation dtype: master params are f32, cast per
    # use (bf16 activations @ f32 weights would silently PROMOTE the
    # matmul back to f32 — half the MXU's bf16 rate); accumulation stays
    # f32 via preferred_element_type
    dt = q_in.dtype
    q = _split_heads(hps, q_in @ p["wq"].astype(dt))  # [..., Tq, nh, hd]
    k = _split_heads(hps, kv_in @ p["wk"].astype(dt))
    v = _split_heads(hps, kv_in @ p["wv"].astype(dt))
    scale = _head_dim(hps) ** -0.5
    logits = jnp.einsum("...qnd,...knd->...nqk", q, k,
                        preferred_element_type=jnp.float32)
    logits = logits * scale
    neg = jnp.asarray(-1e30, jnp.float32)
    logits = jnp.where(mask[..., None, :, :] > 0, logits, neg)
    probs = jax.nn.softmax(logits, axis=-1)
    # a fully-masked query row gives a uniform softmax over -1e30 logits;
    # zero it so padding queries emit exact zeros (matches the clamped
    # masked_softmax semantics in ops/attention.py)
    any_key = jnp.sum(mask[..., None, :, :], axis=-1, keepdims=True) > 0
    probs = jnp.where(any_key, probs, 0.0)
    ctx = jnp.einsum("...nqk,...knd->...qnd", probs.astype(dt), v,
                     preferred_element_type=jnp.float32).astype(dt)
    out = _merge_heads(ctx) @ p["wo"].astype(dt)
    return out, jnp.mean(probs, axis=-3)  # head-avg [..., Tq, Tk]


def _ffn_block(p: Dict[str, Array], x: Array) -> Array:
    dt = x.dtype  # see _mha: keep the matmuls in the activation dtype
    h = jax.nn.gelu(x @ p["w1"].astype(dt) + p["b1"].astype(dt))
    return h @ p["w2"].astype(dt) + p["b2"].astype(dt)


def _use_flash(hps: HParams, T: int) -> bool:
    """Route self-attention through the Pallas TPU flash kernel when it
    pays off: long sequences at head widths the kernel tiles natively
    (the [B, nh, T, T] score tensor never hits HBM).  TS_FLASH=on forces
    it on ANY shape — unaligned T/head_dim are zero-padded to the 128
    grid by the caller (exact numerics; extra FLOPs), which is the
    roofline-motivated A/B for the bandwidth-bound reference scale
    (T=400, hd=32 — BASELINE.md: the einsum path's materialized f32
    score tensors dominate the transformer step's bytes).  =off
    disables; auto (the FROZEN default) keeps the conservative
    natively-aligned T>=1024 rule.  Either way the kernel is TPU-only
    (its Mosaic lowering has no CPU/GPU path), so a non-TPU backend
    always falls through to the einsum formula.  Cross-attention never
    uses it — its probabilities ARE the copy distribution and must be
    materialized anyway."""
    from textsummarization_on_flink_tpu.config import flash_mode_from_env

    mode = flash_mode_from_env()
    if mode == "off":
        return False
    hd = _head_dim(hps)
    aligned = T % 128 == 0 and hd % 128 == 0
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover - tslint: disable=TS005 — backend probe: any init failure means "not TPU"
        on_tpu = False
    if mode == "on":
        return on_tpu
    return on_tpu and aligned and T >= 1024


def _self_attention(hps: HParams, p: Dict[str, Array], x_norm: Array,
                    pad_mask: Optional[Array], causal: bool) -> Array:
    """Self-attention block used by the encoder (padding mask) and the
    training decoder (causal).  Dispatch order: sequence-parallel
    attention when --sp_attention=ring|ulysses under an sp>1 mesh, then
    the Pallas flash kernel on eligible shapes, then the einsum formula."""
    T = x_norm.shape[-2]
    sp_mesh = None
    if hps.sp_attention and not causal and pad_mask is not None:
        from textsummarization_on_flink_tpu.parallel import (
            ring_attention as ra,
        )

        mesh = ra.current_mesh()
        if mesh is not None and mesh.shape.get("sp", 1) > 1:
            sp_mesh = mesh
    use_flash = sp_mesh is None and _use_flash(hps, T)
    if sp_mesh is not None or use_flash:
        # shared head projection for both kernel paths — one site to
        # change if the projection ever grows biases or dtype casts;
        # params cast to the activation dtype like _mha
        dt = x_norm.dtype
        q = _split_heads(hps, x_norm @ p["wq"].astype(dt))  # [B, T, nh, hd]
        k = _split_heads(hps, x_norm @ p["wk"].astype(dt))
        v = _split_heads(hps, x_norm @ p["wv"].astype(dt))
        sm_scale = _head_dim(hps) ** -0.5
    if sp_mesh is not None:
        # the ring/ulysses kernels accumulate logits and context in the
        # input dtype (ring_attention.py) — hand them f32 q/k/v so the
        # module invariant 'attention logits, softmax run in f32' holds
        # on the sp path too; the projections above still ran at bf16
        fn = ra.make_sp_attention(sp_mesh, hps.sp_attention, "sp")
        ctx = _merge_heads(fn(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), pad_mask, sm_scale))
        # downcast the f32-accumulated context before the wo matmul, like
        # _mha — else the projection runs at the MXU's f32 rate
        return ctx.astype(dt) @ p["wo"].astype(dt)
    if use_flash:
        from jax.experimental.pallas.ops.tpu import flash_attention as fa

        q, k, v = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))  # [B,nh,T,hd]
        hd = q.shape[-1]
        t_pad, hd_pad = -T % 128, -hd % 128
        if t_pad or hd_pad:
            # zero-pad to the kernel's 128-lane grid (TS_FLASH=on at
            # unaligned shapes, e.g. reference scale T=400 hd=32).
            # Exact numerics: zero head-dim columns change no dot
            # product and their output columns are sliced away; zero
            # key rows are excluded from real queries by the padding
            # segment (non-causal) or live strictly in the future
            # (causal); padded-tail query rows are sliced away.
            widths = [(0, 0), (0, 0), (0, t_pad), (0, hd_pad)]
            q, k, v = (jnp.pad(t, widths) for t in (q, k, v))
        seg = None
        if not causal:
            # padding keys (article padding AND the alignment tail) live
            # in a different segment than real tokens, so real queries
            # never attend them (padding queries produce garbage rows
            # that downstream masks discard)
            pm = pad_mask if pad_mask is not None \
                else jnp.ones((q.shape[0], T), q.dtype)
            if t_pad:
                pm = jnp.pad(pm, [(0, 0), (0, t_pad)])
            ids = (pm <= 0).astype(jnp.int32)  # [B, T+t_pad]
            seg = fa.SegmentIds(q=ids, kv=ids)
        out = fa.flash_attention(q, k, v, segment_ids=seg, causal=causal,
                                 sm_scale=sm_scale)
        if t_pad or hd_pad:
            out = out[:, :, :T, :hd]
        ctx = _merge_heads(jnp.swapaxes(out, 1, 2))
        return ctx @ p["wo"].astype(ctx.dtype)
    if causal:
        mask = jnp.tril(jnp.ones((T, T), jnp.float32))[None]
    else:
        mask = pad_mask[:, None, :]
    out, _ = _mha(hps, p, x_norm, x_norm, mask)
    return out


def _encoder_stack(params: Params, hps: HParams, x: Array,
                   enc_mask: Array) -> Array:
    """x: [B, T_enc, H]; enc_mask: [B, T_enc] -> [B, T_enc, H] (f32)."""

    def layer_fn(layer, x, enc_mask):
        a = _self_attention(hps, layer["self_attn"], _ln(layer["ln1"], x),
                            enc_mask, causal=False)
        x = x + a
        return x + _ffn_block(layer["ffn"], _ln(layer["ln2"], x))

    if hps.remat:  # recompute layer activations in backward (HBM <- FLOPs)
        layer_fn = jax.checkpoint(layer_fn)
    for layer in params["encoder"]["layers"]:
        x = layer_fn(layer, x, enc_mask)
    return _ln(params["encoder"]["ln_out"], x).astype(jnp.float32)


# --------------------------------------------------------------------------
# Training forward (fully parallel over decode steps)
# --------------------------------------------------------------------------

class TransformerEncView(NamedTuple):
    """Per-batch encoder view for decoding: the per-layer cross-attention
    K/V, precomputed once per article (the raw encoder states are fully
    consumed by this projection — no other decode-time reader)."""

    cross_k: Array  # [B, L, T_enc, nh, hd]
    cross_v: Array  # [B, L, T_enc, nh, hd]


def _embed_enc(params: Params, hps: HParams, enc_batch: Array) -> Array:
    T = enc_batch.shape[-1]
    x = params["embedding"][enc_batch] + params["pos_enc"][:T]
    return pg._cast(hps, x)


def _embed_dec(params: Params, hps: HParams, tokens: Array,
               positions: Array) -> Array:
    x = params["embedding"][tokens] + params["pos_dec"][positions]
    return pg._cast(hps, x)


def forward_train(params: Params, hps: HParams, arrays: Dict[str, Array],
                  ) -> TrainOutput:
    """Teacher-forced training/eval forward pass -> TrainOutput.

    Same loss semantics as the pointer-generator family: masked-average
    pointer NLL + optional coverage penalty on the copy attention.  The
    gold mixture probability is computed from raw logits (the same math
    as ops/losses.gold_mixture_prob, inlined in log space so the
    [B, T, V] softmax is never materialized)."""
    enc_mask = arrays["enc_padding_mask"]  # [B, T_enc]
    T_dec = arrays["dec_batch"].shape[1]

    x = _embed_enc(params, hps, arrays["enc_batch"])
    enc_out = _encoder_stack(params, hps, x, enc_mask)
    enc_out_c = pg._cast(hps, enc_out)

    y = _embed_dec(params, hps, arrays["dec_batch"], jnp.arange(T_dec))
    cross_mask = enc_mask[:, None, :]  # [B, 1, T_enc]

    def layer_fn(layer, y, enc_out_c, cross_mask):
        a = _self_attention(hps, layer["self_attn"], _ln(layer["ln1"], y),
                            None, causal=True)
        y = y + a
        c, probs = _mha(hps, layer["cross_attn"], _ln(layer["ln_cross"], y),
                        enc_out_c, cross_mask)
        y = y + c
        y = y + _ffn_block(layer["ffn"], _ln(layer["ln2"], y))
        return y, c, probs

    if hps.remat:
        layer_fn = jax.checkpoint(layer_fn)
    attn_dist = None
    for layer in params["decoder"]["layers"]:
        y, c, probs = layer_fn(layer, y, enc_out_c, cross_mask)
        attn_dist = probs  # final layer's head-averaged copy distribution
        cross_ctx = c
    h = _ln(params["decoder"]["ln_out"], y).astype(jnp.float32)
    return train_output_tail(params, hps, arrays, h, cross_ctx, attn_dist)


def vocab_scores_of(params: Params, hps: HParams, h: Array) -> Array:
    """Raw vocabulary scores for final-LN decoder states ``h``
    [..., H_dec]: the tied-embedding projection, or — when the family
    carries a factored low-rank head (the distilled narrow draft,
    ISSUE 12) — ``(h @ w1) @ w2`` with w1 [H_d, r], w2 [r, V], never
    materializing the [H_d, V] product.  ONE source: the train loss
    head and every decode output tail route the projection through
    here, so the two heads cannot drift.  Both factored matmuls route
    through the ONE dtype-aware projection (ops/losses.project_scores,
    bf16 operands + f32 accumulation under compute_dtype=bfloat16) —
    same kernel as the tied branch and the streaming chunk bodies."""
    vh = params.get("vocab_head")
    if vh is not None:
        hr = loss_ops.project_scores(h, vh["w1"], hps.compute_dtype)
        return loss_ops.project_scores(hr, vh["w2"], hps.compute_dtype) \
            + params["out_bias"]
    return pg._proj(hps, h, params["embedding"].T) + params["out_bias"]


def vocab_proj_weight(params: Params) -> Array:
    """[H_dec, V] dense projection matrix for the STREAMING loss
    kernels (ops/losses), which consume one weight matrix: the tied
    embedding transpose, or the materialized w1 @ w2 of the factored
    head (parameter-sized — r*V*H_d FLOPs once per step, amortized
    over B*T_dec positions).  Factored-head caveat: the streaming path
    projects h @ (w1 @ w2) while ``vocab_scores_of`` computes
    (h @ w1) @ w2, so loss_chunk on/off agree to matmul-association
    tolerance for factored heads, not bitwise (the tied head stays
    exact — identical W, identical kernel)."""
    vh = params.get("vocab_head")
    if vh is not None:
        return vh["w1"] @ vh["w2"]
    return params["embedding"].T


def train_output_tail(params: Params, hps: HParams, arrays: Dict[str, Array],
                      h: Array, cross_ctx: Array, attn_dist: Array,
                      ) -> TrainOutput:
    """The loss head shared by every transformer-shaped decoder family
    (transformer, avg_attention — including the factored-head narrow
    draft): p_gen from [h, cross_ctx], vocab projection via
    ``vocab_scores_of`` (streamed when --loss_chunk, materialized
    otherwise), pointer mixture or baseline CE, coverage penalty.  ONE
    source for the mixture math keeps the families' losses from
    drifting.

    h: [B, T_dec, H_dec] final-LN decoder states (f32); cross_ctx:
    final layer's cross-attention output; attn_dist: its head-averaged
    copy distribution [B, T_dec, T_enc].
    """
    dec_mask = arrays["dec_padding_mask"]  # [B, T_dec]

    p_gens = jax.nn.sigmoid(
        jnp.concatenate([h, cross_ctx.astype(jnp.float32)], axis=-1)
        @ params["pgen_linear"]["kernel"]
        + params["pgen_linear"]["bias"])[..., 0]  # [B, T_dec]

    targets = arrays["target_batch"]
    if hps.loss_chunk > 0:
        # streaming chunked loss (PERF.md byte diet): the [B, T_dec, V]
        # tied-projection logits never materialize — ops/losses streams
        # [chunk, B, V] blocks with a backward that recomputes them.
        # Step-major views for the shared streaming kernels.
        h_t = jnp.swapaxes(h, 0, 1)  # [T_dec, B, H]
        targets_t = jnp.swapaxes(targets, 0, 1)
        if hps.pointer_gen:
            gold_t = loss_ops.streaming_gold_probs(
                h_t, jnp.swapaxes(attn_dist, 0, 1),
                jnp.swapaxes(p_gens, 0, 1), targets_t,
                arrays["enc_batch_extend_vocab"],
                vocab_proj_weight(params), params["out_bias"],
                chunk=hps.loss_chunk, compute_dtype=hps.compute_dtype)
            gold = jnp.swapaxes(gold_t, 0, 1)
            loss = loss_ops.mask_and_avg(-jnp.log(gold + 1e-10), dec_mask)
        else:
            loss = loss_ops.streaming_softmax_cross_entropy(
                h_t, targets_t, jnp.swapaxes(dec_mask, 0, 1),
                vocab_proj_weight(params), params["out_bias"],
                chunk=hps.loss_chunk, compute_dtype=hps.compute_dtype)
    else:
        logits = vocab_scores_of(params, hps, h)  # [B, T_dec, V]
        if hps.pointer_gen:
            # gold prob without materializing the [B, T, V] softmax —
            # the SAME mixture math as the pg family and the streaming
            # path (one source of truth), on step-major views
            gold = jnp.swapaxes(loss_ops.gold_mixture_prob_from_scores(
                jnp.swapaxes(logits, 0, 1), jnp.swapaxes(attn_dist, 0, 1),
                jnp.swapaxes(p_gens, 0, 1), jnp.swapaxes(targets, 0, 1),
                arrays["enc_batch_extend_vocab"]), 0, 1)
            loss = loss_ops.mask_and_avg(-jnp.log(gold + 1e-10), dec_mask)
        else:
            loss = loss_ops.softmax_cross_entropy_baseline(
                logits, targets, dec_mask)
    if hps.coverage:
        cov_loss = loss_ops.coverage_loss(attn_dist, dec_mask)
    else:
        cov_loss = jnp.zeros(())
    total = loss + hps.cov_loss_wt * cov_loss
    return TrainOutput(loss=loss, coverage_loss=cov_loss, total_loss=total,
                       attn_dists=attn_dist, p_gens=p_gens)


# --------------------------------------------------------------------------
# Decoding (KV-cache incremental step + beam adapter)
# --------------------------------------------------------------------------

def beam_encode(params: Params, hps: HParams, arrays: Dict[str, Array],
                head_hps: Optional[HParams] = None) -> TransformerEncView:
    """Encode a batch once and precompute per-layer cross-attention K/V
    (leaves have a leading batch axis; vmapped per-article downstream).

    ``head_hps`` carries the DECODER-side width for the head split (the
    narrow AAN draft's H_d — its rectangular [H, H_d] K/V kernels make
    this precompute the encoder-view boundary projection, ISSUE 12);
    None = hps (the transformer itself).  ONE body for both families —
    a numerics change here reaches every encoder view."""
    head_hps = head_hps if head_hps is not None else hps
    x = _embed_enc(params, hps, arrays["enc_batch"])
    enc_out = _encoder_stack(params, hps, x, arrays["enc_padding_mask"])
    enc_c = pg._cast(hps, enc_out)
    dt = enc_c.dtype  # keep the K/V precompute matmuls in the cast dtype
    ks, vs = [], []
    for layer in params["decoder"]["layers"]:
        p = layer["cross_attn"]
        ks.append(_split_heads(head_hps, enc_c @ p["wk"].astype(dt)))
        vs.append(_split_heads(head_hps, enc_c @ p["wv"].astype(dt)))
    return TransformerEncView(cross_k=jnp.stack(ks, axis=1),
                              cross_v=jnp.stack(vs, axis=1))


BeamStepOut = pg.BeamStepOut  # shared beam protocol output type


def cross_attend_layer(hps: HParams, layer: Dict[str, Any], y: Array,
                       ck: Array, cv: Array, enc_mask: Array,
                       nb: Optional[Array] = None,
                       ) -> Tuple[Array, Array]:
    """One decoder layer's cross-attention against its precomputed
    per-article K/V (``TransformerEncView`` slices) for a stack of R
    query rows — beam hypotheses, verify positions, or the AAN draft's
    rows all share this ONE block (the decode-side analogue of
    ``train_output_tail``'s factoring: a numerics fix lands once).

    y: [R, H]; ck/cv: [T_enc, nh, hd]; enc_mask: [T_enc].  Returns
    (cross_out [R, H] — NOT yet residual-added — and the head-averaged
    probabilities [R, T_enc], f32).

    ``nb`` (length-masked slot decode, ISSUE 11): traced active-block
    count — the logits/context einsums run as a statically-unrolled
    chain of ``resolve_enc_block(hps)``-position key blocks, each gated
    by a real XLA conditional on ``b < nb``, so the K/V bytes streamed
    per step scale with the longest active resident's TRUE article
    length.  Uncovered blocks sit at the masked-logit floor (exactly
    where enc_mask=0 keys sit in the dense path), so softmax weights
    there are 0 and skipped context blocks contribute exactly nothing;
    the result differs from dense only by block-wise partial-sum
    association.  nb=None keeps the dense einsums."""
    hd = _head_dim(hps)
    dt = y.dtype
    cp = layer["cross_attn"]
    qc = _split_heads(hps, _ln(layer["ln_cross"], y) @ cp["wq"].astype(dt))
    q32 = qc.astype(jnp.float32)
    if nb is None:
        clogits = jnp.einsum("knd,tnd->knt", q32,
                             ck.astype(jnp.float32)) * (hd ** -0.5)
        clogits = jnp.where(enc_mask[None, None, :] > 0, clogits, -1e30)
    else:
        T = enc_mask.shape[0]
        block = config_lib.resolve_enc_block(hps)
        nblocks = -(-T // block)
        clogits = jnp.full(q32.shape[:2] + (T,), -1e30, jnp.float32)
        for b in range(nblocks):
            lo, hi = b * block, min((b + 1) * block, T)

            def write_block(cl, lo=lo, hi=hi):
                lb = jnp.einsum("knd,tnd->knt", q32,
                                ck[lo:hi].astype(jnp.float32)) * (hd ** -0.5)
                lb = jnp.where(enc_mask[lo:hi][None, None, :] > 0, lb, -1e30)
                return cl.at[:, :, lo:hi].set(lb)

            clogits = jax.lax.cond(b < nb, write_block, lambda cl: cl,
                                   clogits)
    cprobs = jax.nn.softmax(clogits, axis=-1)
    any_key = jnp.sum(enc_mask) > 0
    cprobs = jnp.where(any_key, cprobs, 0.0)
    if nb is None:
        cctx = jnp.einsum("knt,tnd->knd", cprobs, cv.astype(jnp.float32))
    else:
        cctx = jnp.zeros(q32.shape, jnp.float32)
        for b in range(nblocks):
            lo, hi = b * block, min((b + 1) * block, T)

            def add_block(cc, lo=lo, hi=hi):
                return cc + jnp.einsum("knt,tnd->knd", cprobs[:, :, lo:hi],
                                       cv[lo:hi].astype(jnp.float32))

            cctx = jax.lax.cond(b < nb, add_block, lambda cc: cc, cctx)
    cross_out = _merge_heads(cctx).astype(dt) @ cp["wo"].astype(dt)
    return cross_out, jnp.mean(cprobs, axis=1)


def decode_output_tail(params: Params, hps: HParams, y: Array,
                       cross_ctx: Array, attn_dist: Array, ext_ids: Array,
                       ) -> Tuple[Array, Array, Array]:
    """Decoder output head shared by every transformer-shaped decode
    path (beam adapter step, ``spec_verify``, the AAN step): final LN,
    vocab projection via ``vocab_scores_of`` (tied, or the narrow
    draft's factored head), p_gen, pointer mixture.  Returns
    (final_dist [R, V_ext], p_gen [R], h [R, H_dec] f32)."""
    h = _ln(params["decoder"]["ln_out"], y).astype(jnp.float32)
    vocab_scores = vocab_scores_of(params, hps, h)
    vocab_dist = jax.nn.softmax(vocab_scores, axis=-1)
    p_gen = jax.nn.sigmoid(
        jnp.concatenate([h, cross_ctx.astype(jnp.float32)], axis=-1)
        @ params["pgen_linear"]["kernel"]
        + params["pgen_linear"]["bias"])[:, 0]
    if hps.pointer_gen:
        R = y.shape[0]
        ext_r = jnp.broadcast_to(ext_ids[None], (R,) + ext_ids.shape)
        final_dist = pg.final_distribution(hps, vocab_dist, attn_dist,
                                           p_gen, ext_r)
    else:
        final_dist = vocab_dist
    return final_dist, p_gen, h


def beam_adapter(hps: HParams):
    """Beam-search protocol: (init_state, step) closures over params.

    State leaves all carry a leading beam axis K so the search can gather
    surviving hypotheses with one tree_map.  The KV cache is static-shape
    [K, L, T_dec+1, nh, hd]; position validity comes from the step index.
    """
    K = hps.beam_size
    L = hps.dec_layers
    nh, hd = hps.num_heads, _head_dim(hps)
    T = hps.max_dec_steps + 1
    # --decode_cache_dtype=bfloat16 (decode byte diet, ISSUE 7): the
    # cache is the dominant per-hypothesis resident tensor; bf16 storage
    # halves it and its per-step traffic.  The einsums below widen to
    # f32 before the logits/softmax, so the attention MATH is unchanged
    # — only the HBM representation narrows (drift envelope pinned).
    cache_dtype = (jnp.bfloat16 if hps.decode_cache_dtype == "bfloat16"
                   else jnp.float32)

    def init_state(params: Params, enc_one: TransformerEncView):
        del params, enc_one
        return {
            "cache_k": jnp.zeros((K, L, T, nh, hd), cache_dtype),
            "cache_v": jnp.zeros((K, L, T, nh, hd), cache_dtype),
        }

    def step(params: Params, enc_one: TransformerEncView, enc_mask: Array,
             ext_ids: Array, t: Array, latest: Array, state, nb=None):
        """enc_one leaves are per-article (no batch axis); latest: [K].
        nb: traced active-block count for the length-masked slot path
        (None = dense cross-attention, the batch-search default)."""
        y = _embed_dec(params, hps, latest, t)  # [K, H]
        pos_ok = (jnp.arange(T) <= t).astype(jnp.float32)  # [T]
        cache_k, cache_v = state["cache_k"], state["cache_v"]
        attn_dist = None
        dt = y.dtype  # projections in the activation dtype (see _mha);
        # the cache and softmaxes below deliberately stay f32
        for li, layer in enumerate(params["decoder"]["layers"]):
            p = layer["self_attn"]
            h_norm = _ln(layer["ln1"], y)
            q = _split_heads(hps, h_norm @ p["wq"].astype(dt))  # [K, nh, hd]
            k_new = _split_heads(hps, h_norm @ p["wk"].astype(dt))
            v_new = _split_heads(hps, h_norm @ p["wv"].astype(dt))
            cache_k = cache_k.at[:, li, t].set(k_new.astype(cache_dtype))
            cache_v = cache_v.at[:, li, t].set(v_new.astype(cache_dtype))
            # widen the (possibly bf16) cache at the point of use: the
            # logits einsum and softmax stay f32 whatever the storage
            kk = cache_k[:, li].astype(jnp.float32)  # [K, T, nh, hd]
            vv = cache_v[:, li].astype(jnp.float32)
            logits = jnp.einsum("knd,ktnd->knt", q.astype(jnp.float32), kk)
            logits = logits * (hd ** -0.5)
            logits = jnp.where(pos_ok[None, None, :] > 0, logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1)
            ctx = jnp.einsum("knt,ktnd->knd", probs, vv)
            y = y + _merge_heads(ctx).astype(dt) @ p["wo"].astype(dt)
            # cross attention against the precomputed per-layer K/V
            cross_out, attn_dist = cross_attend_layer(
                hps, layer, y, enc_one.cross_k[li], enc_one.cross_v[li],
                enc_mask, nb=nb)
            y = y + cross_out
            y = y + _ffn_block(layer["ffn"], _ln(layer["ln2"], y))
            cross_ctx = cross_out
        final_dist, p_gen, _ = decode_output_tail(params, hps, y,
                                                  cross_ctx, attn_dist,
                                                  ext_ids)
        topk_probs, topk_ids = jax.lax.top_k(final_dist, 2 * hps.beam_size)
        return BeamStepOut(topk_ids=topk_ids,
                           topk_log_probs=jnp.log(topk_probs + 1e-10),
                           attn_dist=attn_dist, p_gen=p_gen,
                           state={"cache_k": cache_k, "cache_v": cache_v})

    return init_state, step


#: the length-masked slot-decode adapter (ISSUE 11): the shared
#: protocol wrapper threads the traced block count into this family's
#: step, where it bounds the per-layer cross-attention block chain
beam_adapter_masked = models_lib.masked_adapter(beam_adapter)


def pad_enc_view(enc_view: TransformerEncView, t_target: int,
                 ) -> TransformerEncView:
    """Zero-pad a bucket-width encoder view's key axis to ``t_target``
    (the prefill -> pack hand-off, decode/beam_search.prefill_jit): the
    padded K/V positions sit behind the valid-length mask, so they are
    never attended — zeros keep the 0-weight context products exact."""
    def pad(x):
        if x.shape[2] >= t_target:
            return x
        widths = [(0, 0)] * x.ndim
        widths[2] = (0, t_target - x.shape[2])
        return jnp.pad(x, widths)

    return TransformerEncView(cross_k=pad(enc_view.cross_k),
                              cross_v=pad(enc_view.cross_v))


# --------------------------------------------------------------------------
# Speculative verify (parallel multi-position teacher-forced scoring)
# --------------------------------------------------------------------------

def spec_init_state(hps: HParams, spec_k: int) -> Dict[str, Array]:
    """Single-hypothesis KV cache for the speculative verifier
    (decode/speculative.py): [L, W, nh, hd] with W = max_dec_steps +
    spec_k + 1, wide enough that a verify block starting at the last
    in-horizon step (t = T-1) writes its k+1 entries without clamping.
    Position validity comes from the committed step counter, exactly
    like the incremental adapter's cache — rejected draft positions are
    simply never attended and the next block overwrites them."""
    L = hps.dec_layers
    nh, hd = hps.num_heads, _head_dim(hps)
    W = hps.max_dec_steps + spec_k + 1
    cache_dtype = (jnp.bfloat16 if hps.decode_cache_dtype == "bfloat16"
                   else jnp.float32)
    return {
        "cache_k": jnp.zeros((L, W, nh, hd), cache_dtype),
        "cache_v": jnp.zeros((L, W, nh, hd), cache_dtype),
    }


def spec_verify(params: Params, hps: HParams, enc_one: TransformerEncView,
                enc_mask: Array, ext_ids: Array, t0: Array, tokens: Array,
                state: Dict[str, Array]):
    """Score S = spec_k + 1 teacher-forced positions in ONE parallel
    decoder pass — the speculative fast path's "one fat step" for the
    full model (decode/speculative.py; ISSUE 10).

    ``tokens`` [S] are the inputs consumed at steps t0 .. t0+S-1 (the
    last committed token followed by the draft's proposals, already
    OOV→UNK mapped by the caller).  Each position's Q attends the cache
    entries at positions <= its own step — the SAME masked-softmax the
    incremental ``beam_adapter`` step computes, just batched over the S
    query rows (extra masked columns contribute exact zeros, so the
    per-position numerics match the K=1 incremental step; the spec
    exactness tests pin this).  Returns per-position
    ``(topk_ids [S, 2], topk_log_probs [S, 2], attn_dist [S, T_enc],
    p_gen [S], state')`` where state' holds all S cache entries —
    append-only: acceptance never rolls the cache back, the committed
    step counter does.
    """
    S = tokens.shape[0]
    hd = _head_dim(hps)
    W = state["cache_k"].shape[1]
    cache_dtype = state["cache_k"].dtype
    pos = t0 + jnp.arange(S)  # [S] absolute decode steps
    y = _embed_dec(params, hps, tokens, pos)  # [S, H]
    dt = y.dtype
    cache_k, cache_v = state["cache_k"], state["cache_v"]
    pos_ok = jnp.arange(W)[None, :] <= pos[:, None]  # [S, W]
    attn_dist = None
    for li, layer in enumerate(params["decoder"]["layers"]):
        p = layer["self_attn"]
        h_norm = _ln(layer["ln1"], y)
        q = _split_heads(hps, h_norm @ p["wq"].astype(dt))  # [S, nh, hd]
        k_new = _split_heads(hps, h_norm @ p["wk"].astype(dt))
        v_new = _split_heads(hps, h_norm @ p["wv"].astype(dt))
        cache_k = cache_k.at[li, pos].set(k_new.astype(cache_dtype))
        cache_v = cache_v.at[li, pos].set(v_new.astype(cache_dtype))
        kk = cache_k[li].astype(jnp.float32)  # [W, nh, hd]
        vv = cache_v[li].astype(jnp.float32)
        logits = jnp.einsum("snd,tnd->snt", q.astype(jnp.float32), kk)
        logits = logits * (hd ** -0.5)
        logits = jnp.where(pos_ok[:, None, :], logits, -1e30)
        probs = jax.nn.softmax(logits, axis=-1)
        ctx = jnp.einsum("snt,tnd->snd", probs, vv)
        y = y + _merge_heads(ctx).astype(dt) @ p["wo"].astype(dt)
        cross_out, attn_dist = cross_attend_layer(
            hps, layer, y, enc_one.cross_k[li], enc_one.cross_v[li],
            enc_mask)
        y = y + cross_out
        y = y + _ffn_block(layer["ffn"], _ln(layer["ln2"], y))
        cross_ctx = cross_out
    final_dist, p_gen, _ = decode_output_tail(params, hps, y, cross_ctx,
                                              attn_dist, ext_ids)
    topk_probs, topk_ids = jax.lax.top_k(final_dist, 2)
    return (topk_ids, jnp.log(topk_probs + 1e-10), attn_dist, p_gen,
            {"cache_k": cache_k, "cache_v": cache_v})
