"""Model families: pointer-generator (LSTM seq2seq) and transformer."""
