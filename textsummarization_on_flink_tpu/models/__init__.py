"""Model families: pointer-generator (LSTM seq2seq) and transformer.

Every family is a module exposing the same functional surface, so the
Trainer/Evaluator, beam search, checkpointing, and serving stack are
family-agnostic:

  init_params(hps, vsize, key) -> Params
  forward_train(params, hps, arrays) -> TrainOutput
  beam_encode(params, hps, arrays) -> per-batch encoder view (pytree)
  beam_adapter(hps) -> (init_state, step) beam-search closures

Select with ``hps.model_family`` (the reference has a single hardcoded
model, run_summarization.py:376; the family seam is a rebuild addition
that the BASELINE.md stretch config requires).

The third family, ``avg_attention``, is the speculative tier's draft
(O(1)-in-history decode state); it honors two extra HParams the other
families ignore — ``draft_hidden`` (narrow decoder behind boundary
projections) and ``draft_vocab_rank`` (factored vocab head) — while
keeping this exact functional surface, so every consumer listed above
works on the narrow variant unmodified (ISSUE 12).
"""

from __future__ import annotations

from types import ModuleType

FAMILIES = ("pointer_generator", "transformer", "avg_attention")


def masked_adapter(beam_adapter_fn):
    """Derive a family's ``beam_adapter_masked`` from its
    ``beam_adapter`` (the length-masked slot-decode protocol, ISSUE 11):
    the same step with an explicit leading ``nb`` (traced active-block
    count) argument, which step_slots_jit binds from the residents'
    valid lengths.  ONE wrapper — the calling convention lives here, so
    a future change to the masked-step signature lands in one place for
    every family."""

    def beam_adapter_masked(hps):
        init_state, step = beam_adapter_fn(hps)

        def step_masked(params, enc_one, enc_mask, ext_ids, nb, t, latest,
                        state):
            return step(params, enc_one, enc_mask, ext_ids, t, latest,
                        state, nb=nb)

        return init_state, step_masked

    return beam_adapter_masked


def get_family(name: str) -> ModuleType:
    """Resolve a model-family name to its module (lazy imports keep
    startup light and avoid cycles)."""
    if name == "pointer_generator":
        from textsummarization_on_flink_tpu.models import pointer_generator
        return pointer_generator
    if name == "transformer":
        from textsummarization_on_flink_tpu.models import transformer
        return transformer
    if name == "avg_attention":
        from textsummarization_on_flink_tpu.models import avg_attention
        return avg_attention
    raise ValueError(
        f"unknown model_family {name!r}; expected one of {FAMILIES}")
