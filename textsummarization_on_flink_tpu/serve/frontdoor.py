"""The serving front door: request coalescing, a content-hash summary
cache, and per-tenant token-bucket admission (ISSUE 14; SERVING.md
"Front door").

At millions of users traffic is heavy-tailed — the same trending
article arrives thousands of times — yet every ``submit()`` used to run
a full decode.  FastSeq's core lesson is that serving throughput comes
from never doing redundant work (PAPERS.md), and the pointer-
generator's deterministic tiers make summaries exactly reusable: for a
fixed (article bytes, tier, params fingerprint) the decode is
reproducible, so a cache hit is exact, not approximate.  This module
sits between ``submit`` and the RequestQueue in BOTH the single-server
and fleet paths (``ServingServer``/``FleetRouter``), three layers deep:

  * **Per-tenant token-bucket admission** (``serve_tenant_rate`` /
    ``serve_tenant_burst``): a submit finding its tenant's bucket empty
    is shed with the typed ``TenantThrottledError`` BEFORE the queue or
    the admission breaker — one tenant's burst spends its own bucket,
    not the shared queue (the weighted-fair pickup side lives in
    serve/queue.py).  Sheds emit ``tenant_shed`` trace events and count
    in ``serve/tenant_shed_total``.
  * **Bounded LRU summary cache** (``serve_cache_entries`` /
    ``serve_cache_bytes``), keyed ``(content_hash, tier,
    params_fingerprint)``: a hit resolves the future synchronously at
    submit — byte-identical to a fresh decode of the same key — without
    touching the queue.  INSERTS key on the fingerprint stamped on the
    ``DecodedResult`` at decode time and LOOKUPS on the decoder's
    current fingerprint, so a checkpoint hot-swap invalidates by
    construction: swapped params report a new fingerprint and the old
    entries simply stop matching (no flush walk, no stale window).
  * **In-flight coalescing** (``serve_coalesce``): submits whose
    ``(content_hash, tier)`` matches a resident computation attach to
    that ONE leader — every attached future resolves exactly once from
    the leader's result, re-stamped with the follower's own
    uuid/article/reference (identical decoded words).  A leader
    FAILURE fails all attached futures with the leader's typed cause —
    never hangs, never double-decodes; in the fleet path the leader is
    the router-level future, so replica kill/requeue and hedging
    resolve the followers transparently (a hedged twin is a replica
    attempt UNDER the leader, so it can neither defeat coalescing nor
    double-fill the cache — the fill hangs off the exactly-once
    caller-visible future).

Content hashing is normalized through ONE helper, ``article_key``:
bytes-level sha256 over the whitespace-split word stream TRUNCATED to
``max_enc_steps`` — the exact visible window ``SummaryExample.build``
tokenizes — so two articles identical in the visible window coalesce,
and a SocketSource-ingested article hashes identically to the same
article submitted directly (both paths funnel the decoded ``article``
string here).

Failure posture: the cache layer degrades to MISS-AND-DECODE — an
internal cache error (or the armed ``serve.cache_fault`` injection
point) turns lookups into misses and skips inserts, counted in
``serve/cache_errors_total``; it can never produce a wrong summary or
a hung future.

Import-light by design: no jax/numpy — follower/hit results are
shallow copies of the leader's ``DecodedResult`` (class-agnostic, so
stub decoders and the virtual-time SLO gate ride the same code).
"""

from __future__ import annotations

import copy
import hashlib
import logging
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import resolve_tenant_burst
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs import locksan
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.serve.errors import (
    TenantThrottledError,
)
from textsummarization_on_flink_tpu.serve.queue import ServeFuture

log = logging.getLogger(__name__)

#: LRU bound on per-tenant token buckets: caller-supplied tenant names
#: must not grow the admission map without bound (a cold tenant's
#: bucket is just "full burst", which is exactly what re-creating it
#: yields, so eviction loses nothing but the partial-refill state)
MAX_TENANT_BUCKETS = 4096


def article_key(article: str, max_enc_steps: int) -> str:
    """The canonical content hash of one article's VISIBLE window.

    Bytes-level: sha256 over the utf-8 encoding of the whitespace-split
    word stream truncated to ``max_enc_steps`` words — exactly the
    window ``SummaryExample.build`` tokenizes (batching.py truncates
    BEFORE vocab mapping), so two articles that differ only past the
    window produce the same key and coalesce/cache together, and
    whitespace differences a transport may introduce (trailing newline
    from a socket line codec, double spaces) cannot split the key.
    The ONE helper: every submit path — direct, pipeline-driven,
    fleet-routed — hashes through here.
    """
    words = article.split()
    if len(words) > max_enc_steps:
        words = words[:max_enc_steps]
    h = hashlib.sha256(" ".join(words).encode("utf-8"))
    return h.hexdigest()[:16]


def _result_bytes(res: Any) -> int:
    """Approximate resident bytes of one cached DecodedResult: the
    decoded word payload plus any attention/p_gen arrays riding it
    (``nbytes`` duck-typed so this module never imports numpy)."""
    n = 64  # object overhead floor
    for w in getattr(res, "decoded_words", ()):
        n += len(w) + 1
    n += int(getattr(getattr(res, "attn_dists", None), "nbytes", 0) or 0)
    n += int(getattr(getattr(res, "p_gens", None), "nbytes", 0) or 0)
    return n


def _snapshot(res: Any) -> Any:
    """A defensive copy of `res` with its OWN decoded-word list: the
    cache must hold (and hand out) payloads no caller-side in-place
    mutation can reach — a consumer editing result.decoded_words must
    never edit the resident cache entry, or every later hit would
    serve the mutated, no-longer-byte-identical summary.  The attention
    arrays stay shared (large, and treated as immutable throughout the
    serve layer)."""
    out = copy.copy(res)
    out.decoded_words = list(getattr(res, "decoded_words", ()) or ())
    return out


def _restamp(res: Any, uuid: str, article: str, reference: str) -> Any:
    """A defensive copy of `res` carrying the FOLLOWER's identity
    columns (uuid/article/reference) over the leader's decoded payload
    — the class-agnostic synthesis both the coalescing and cache paths
    use, so a follower's row differs from the leader's only in the
    columns that are the follower's own (word list copied, see
    ``_snapshot``)."""
    out = _snapshot(res)
    out.uuid = uuid
    out.article = article
    out.reference = reference
    return out


class _TokenBucket:
    """One tenant's admission bucket: ``rate`` tokens/sec, capped at
    ``burst``; clock-injectable (the virtual-time SLO gate refills on
    virtual seconds).  Mutated only under the FrontDoor lock."""

    __slots__ = ("rate", "burst", "tokens", "t_last")

    def __init__(self, rate: float, burst: float, now: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst  # a fresh tenant starts with a full burst
        self.t_last = now

    def take(self, now: float) -> bool:
        elapsed = max(0.0, now - self.t_last)
        self.t_last = now
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        if self.tokens < 1.0:
            return False
        self.tokens -= 1.0
        return True


class _CacheEntry:
    __slots__ = ("result", "nbytes", "t_insert")

    def __init__(self, result: Any, nbytes: int, t_insert: float):
        self.result = result
        self.nbytes = nbytes
        self.t_insert = t_insert


class SummaryCache:
    """Bounded LRU of DecodedResults keyed (content_hash, tier,
    params_fingerprint): ``max_entries`` entries and (optionally)
    ``max_bytes`` approximate payload bytes, LRU-evicted (counted in
    ``serve/cache_evictions_total``).  Thread-safe; get/put are O(1)
    OrderedDict moves.  Entry age at hit rides the
    ``serve/cache_entry_age_seconds`` histogram — a low hit age under a
    fast hot-swap cadence means the cache is churning, not serving."""

    def __init__(self, max_entries: int, max_bytes: int = 0,
                 registry: Optional[obs.Registry] = None,
                 clock: Callable[[], float] = time.monotonic):
        if max_entries < 1:
            raise ValueError(f"max_entries must be >= 1, got {max_entries}")
        self.max_entries = max_entries
        self.max_bytes = max_bytes
        self._clock = clock
        self._lock = locksan.make_lock("SummaryCache._lock")
        self._entries: "OrderedDict[Tuple[str, str, str], _CacheEntry]" = \
            OrderedDict()
        self._bytes = 0
        reg = registry if registry is not None else obs.registry()
        self._c_evictions = reg.counter("serve/cache_evictions_total")
        self._h_age = reg.histogram("serve/cache_entry_age_seconds")
        self._g_entries = reg.gauge("serve/cache_entries")
        self._g_bytes = reg.gauge("serve/cache_bytes")

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def nbytes(self) -> int:
        with self._lock:
            return self._bytes

    def get(self, key: Tuple[str, str, str]) -> Optional[Any]:
        """The cached DecodedResult for `key` (LRU-touched), or None.
        The caller restamps identity columns; the returned object is the
        resident one — treat it as immutable."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            self._entries.move_to_end(key)
            self._h_age.observe(max(0.0, self._clock() - entry.t_insert))
            return entry.result

    def put(self, key: Tuple[str, str, str], result: Any) -> None:
        nbytes = _result_bytes(result)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = _CacheEntry(result, nbytes, self._clock())
            self._bytes += nbytes
            while len(self._entries) > self.max_entries or (
                    self.max_bytes and self._bytes > self.max_bytes
                    and len(self._entries) > 1):
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
                self._c_evictions.inc()
            self._g_entries.set(len(self._entries))
            self._g_bytes.set(self._bytes)


class _Flight:
    """One in-flight coalesced computation: the (key, tier) it owns,
    the leader's future once committed, and the follower futures
    attached while it was resident.  All mutation happens under the
    owning FrontDoor's lock; the leader-done callback snapshots the
    follower list under that lock before resolving outside it."""

    __slots__ = ("key", "tier", "leader_uuid", "followers", "settled")

    def __init__(self, key: str, tier: str, leader_uuid: str):
        self.key = key
        self.tier = tier
        self.leader_uuid = leader_uuid
        #: [(uuid, article, reference, future)] attached so far
        self.followers: List[Tuple[str, str, str, ServeFuture]] = []
        self.settled = False


class FrontDoor:
    """The admission-side front door one serving ingress owns (a
    ``ServingServer`` or the ``FleetRouter`` — each builds its own, so
    the fleet path coalesces ACROSS replicas while a bare server
    coalesces its own traffic).

    ``fingerprint`` is a zero-arg callable returning the ACTIVE params
    fingerprint for cache lookups ("" when the decoder has none — stub
    decoders and the virtual-time gate cache consistently under "").
    Returning None skips the lookup entirely (the FleetRouter reports
    None mid-rolling-swap, when replicas disagree — a mixed fleet must
    not serve one snapshot's summary under another's key).

    Protocol (the submit path):

        door.admit_tenant(tenant, uuid)          # may raise typed shed
        kind, val = door.open(article, tier, uuid, reference)
        if kind in ("hit", "follower"): return val          # a future
        # kind == "leader" (or "pass" when nothing is armed)
        ... normal queue submit -> leader_future ...
        door.commit(val, leader_future)   # or door.abort(val, error)
    """

    def __init__(self, hps: Any, registry: Optional[obs.Registry] = None,
                 fingerprint: Optional[Callable[[], Optional[str]]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 faults: Optional[Any] = None):
        self._hps = hps
        self._reg = registry if registry is not None \
            else obs.registry_for(hps)
        self._fingerprint = fingerprint if fingerprint is not None \
            else (lambda: "")
        self._clock = clock
        self._faults = faults if faults is not None \
            else faultinject.plan_for(hps)
        self._max_enc = int(getattr(hps, "max_enc_steps", 400))
        self._coalesce = bool(getattr(hps, "serve_coalesce", False))
        cache_entries = int(getattr(hps, "serve_cache_entries", 0))
        self._cache: Optional[SummaryCache] = None
        if cache_entries > 0:
            self._cache = SummaryCache(
                cache_entries, int(getattr(hps, "serve_cache_bytes", 0)),
                registry=self._reg, clock=clock)
        self._rate = float(getattr(hps, "serve_tenant_rate", 0.0))
        self._burst = float(resolve_tenant_burst(hps)) if self._rate > 0 \
            else 0.0
        self._lock = locksan.make_lock("FrontDoor._lock")
        self._flights: Dict[Tuple[str, str], _Flight] = {}
        self._tenants: "OrderedDict[str, _TokenBucket]" = OrderedDict()
        # the submit hot path tests ONE bool when nothing is armed
        self.armed = bool(self._coalesce or self._cache is not None
                          or self._rate > 0)
        self._c_hits = self._reg.counter("serve/cache_hits_total")
        self._c_misses = self._reg.counter("serve/cache_misses_total")
        self._c_coalesced = self._reg.counter("serve/coalesced_total")
        self._c_tenant_shed = self._reg.counter("serve/tenant_shed_total")
        self._c_cache_errors = self._reg.counter("serve/cache_errors_total")
        # per-tenant cost accounting (ISSUE 15): decode tokens a tenant
        # did NOT pay for because the cache answered — the savings side
        # of serve/tenant_tokens_total, so the fairness story is
        # auditable per tenant on /fleet/snapshot
        self._c_tokens_saved = self._reg.counter(
            "serve/tenant_tokens_saved_total")

    # -- tenant admission --
    def admit_tenant(self, tenant: str, uuid: str = "") -> None:
        """Spend one token from `tenant`'s bucket or shed typed.  A
        no-op when no per-tenant rate is configured (today's behavior);
        the default "" tenant is a tenant like any other — a job that
        names no tenants runs one shared bucket, which at rate 0 means
        no bucket at all."""
        if self._rate <= 0:
            return
        now = self._clock()
        with self._lock:
            bucket = self._tenants.get(tenant)
            if bucket is None:
                bucket = _TokenBucket(self._rate, self._burst, now)
                self._tenants[tenant] = bucket
                # the tenant map is BOUNDED: LRU-evict cold buckets so
                # caller-supplied tenant strings cannot grow memory
                # without bound.  (An evicted-then-returning tenant
                # restarts with a full burst — which is also what a
                # brand-new tenant gets, so rotating tenant NAMES
                # already buys a full burst per name regardless; rate
                # limiting is only as strong as tenant authentication,
                # SERVING.md "Front door".)
                while len(self._tenants) > MAX_TENANT_BUCKETS:
                    self._tenants.popitem(last=False)
            else:
                self._tenants.move_to_end(tenant)
            ok = bucket.take(now)
        if not ok:
            # labeled child rolls up into the unlabeled total: WHO is
            # being throttled is the per-tenant fairness evidence
            self._c_tenant_shed.labels(tenant=tenant or "default").inc()
            obs.spans.request_event(self._reg, "tenant_shed", None, uuid,
                                    tenant=tenant)
            raise TenantThrottledError(
                f"tenant {tenant!r} over its admission rate "
                f"({self._rate:g} req/s, burst {self._burst:g}); request "
                f"{uuid!r} shed")

    # -- cache + coalescing --
    def _cache_get(self, key: Tuple[str, str, str]) -> Optional[Any]:
        """A lookup that can only ever degrade to a MISS: internal cache
        errors (and the armed ``serve.cache_fault`` injection point)
        are swallowed and counted — never a wrong summary, never a hung
        future."""
        if self._faults.fire("serve.cache_fault"):
            self._c_cache_errors.inc()
            return None
        try:
            return self._cache.get(key)  # type: ignore[union-attr]
        except Exception:
            self._c_cache_errors.inc()
            log.exception("summary-cache lookup failed; degrading to miss")
            return None

    def _cache_put(self, key: Tuple[str, str, str], result: Any) -> None:
        if self._faults.fire("serve.cache_fault"):
            self._c_cache_errors.inc()
            return
        try:
            self._cache.put(key, result)  # type: ignore[union-attr]
            flightrec.record(
                self._reg, "front_door", entries=len(self._cache),
                bytes=self._cache.nbytes,
                hits=int(self._c_hits.value),
                misses=int(self._c_misses.value))
        except Exception:
            self._c_cache_errors.inc()
            log.exception("summary-cache insert failed; entry dropped")

    def open(self, article: str, tier: str, uuid: str, reference: str,
             trace: Optional[Any] = None,
             tenant: str = "") -> Tuple[str, Any]:
        """Route one submit through the front door.  `trace` is the
        caller's externally-minted TraceContext, if any — a hit's or
        follower's events must land under the SAME trace the caller's
        route events use, not a fresh one.  `tenant` labels the
        hit/coalesce accounting (ISSUE 15: whose decode cost was
        avoided), never the cache key — summaries are shared across
        tenants by design.  Returns one of

          * ``("pass", None)`` — nothing armed; submit normally;
          * ``("hit", future)`` — summary cache hit: the future is
            already resolved (synchronously, queue untouched);
          * ``("follower", future)`` — attached to an in-flight leader;
            resolves when the leader does;
          * ``("leader", flight)`` — this submit leads a new
            computation: enqueue it, then ``commit(flight, future)``
            (or ``abort(flight, error)`` if admission raised).
        """
        if not self._coalesce and self._cache is None:
            return "pass", None
        key = article_key(article, self._max_enc)
        if self._cache is not None:
            fp = self._fingerprint()
            if fp is not None:
                cached = self._cache_get((key, tier, fp))
                if cached is not None:
                    self._c_hits.labels(tenant=tenant or "default").inc()
                    self._c_tokens_saved.labels(
                        tenant=tenant or "default").inc(
                        len(getattr(cached, "decoded_words", ()) or ()))
                    fut = self._make_future(uuid, trace)
                    obs.spans.request_event(
                        self._reg, "cache_hit", fut.trace, uuid,
                        key=key, tier=tier)
                    fut._resolve(_restamp(cached, uuid, article, reference))
                    return "hit", fut
                # counted only when a lookup actually ran: a None
                # fingerprint (mixed fleet mid-swap) means the cache
                # was deliberately dark, and counting those as misses
                # would read as the cache failing to serve
                self._c_misses.inc()
        if not self._coalesce:
            # cache without coalescing: the submit still leads a
            # fill-only flight (UNREGISTERED — concurrent identical
            # submits each decode, exactly today's behavior) so its
            # resolution can file the cache entry
            return "leader", _Flight(key, tier, uuid)
        with self._lock:
            flight = self._flights.get((key, tier))
            if flight is None:
                flight = _Flight(key, tier, uuid)
                self._flights[(key, tier)] = flight
                return "leader", flight
            fut = self._make_future(uuid, trace)
            # root event BEFORE the attach, and under the lock: the
            # instant the follower joins the flight it may resolve on
            # the dispatch thread, and its resolve must never precede
            # its root in the stream (queue.py's enqueue-before-put
            # rule, follower edition — _leader_done's snapshot takes
            # this same lock, so resolution cannot interleave; emit is
            # a non-blocking queue put, cheap under the lock)
            obs.spans.request_event(
                self._reg, "coalesced", fut.trace, uuid,
                leader=flight.leader_uuid, key=key, tier=tier)
            flight.followers.append((uuid, article, reference, fut))
        self._c_coalesced.labels(tenant=tenant or "default").inc()
        return "follower", fut

    def _make_future(self, uuid: str,
                     trace: Optional[Any] = None) -> ServeFuture:
        fut = ServeFuture(uuid, registry=self._reg)
        if trace is not None:
            fut.trace = trace  # the caller's context wins (ISSUE 13)
        elif self._reg.enabled:
            fut.trace = obs.TraceContext.new()
        return fut

    def disarm(self) -> None:
        """Turn the door off and RELEASE its cache (FleetRouter
        construction: replicas behind a router serve what they are
        routed, so N-1 resident caches would be dead weight).  In-flight
        flights keep settling — only new submits bypass."""
        self.armed = False
        self._cache = None

    def commit(self, flight: _Flight, leader_future: ServeFuture) -> None:
        """The leader was admitted: wire its future so resolution fills
        the cache and settles every attached follower exactly once
        (the callback runs on whichever thread resolves the leader —
        dispatch, evictor, drain, or the fleet's requeue path)."""
        leader_future.add_done_callback(
            lambda fut: self._leader_done(flight, fut))

    def abort(self, flight: _Flight, error: BaseException) -> None:
        """The leader's admission RAISED (queue full, closed): the
        flight never existed as far as the queue is concerned — drop it
        and fail any already-attached follower with the same typed
        cause (they asked for exactly the computation that was just
        refused)."""
        followers = self._close(flight)
        for _, _, _, fut in followers:
            fut._reject(error)

    def _close(self, flight: _Flight,
               ) -> List[Tuple[str, str, str, ServeFuture]]:
        """Retire `flight` from the in-flight map and snapshot its
        followers (under the lock, so a late attach either lands in the
        snapshot or finds no flight and becomes a new leader/hit)."""
        with self._lock:
            if flight.settled:
                return []
            flight.settled = True
            cur = self._flights.get((flight.key, flight.tier))
            if cur is flight:
                del self._flights[(flight.key, flight.tier)]
            followers, flight.followers = flight.followers, []
        return followers

    def _leader_done(self, flight: _Flight, fut: ServeFuture) -> None:
        followers = self._close(flight)
        err = fut.error
        if err is not None:
            # leader failure fails every attached future with the
            # leader's own typed cause — exactly once each, never a
            # hang.  (In the fleet path requeue/hedging already
            # happened UNDER this future, so a surviving replica's
            # result arrives here as a success.)
            for _, _, _, ffut in followers:
                ffut._reject(err)
            return
        res = fut._result
        if self._cache is not None and not getattr(res, "degraded", False):
            # keyed on the fingerprint stamped AT DECODE TIME (the
            # decoder's _make_result), not at submit: a hot-swap
            # landing between admit and dispatch must file the entry
            # under the params that actually produced it.  DEGRADED
            # results never cache: a beam request that fell to greedy
            # under deadline pressure is not byte-identical to a fresh
            # beam decode, and filing it under the beam key would
            # poison every later hit (followers still resolve from it
            # below — they SHARED the degraded computation, which is
            # the coalescing contract, not the cache's).  The entry is
            # a _snapshot: the leader's caller holds the live result
            # object, and its in-place edits must not reach the cache.
            self._cache_put(
                (flight.key, flight.tier,
                 str(getattr(res, "params_fingerprint", "") or "")),
                _snapshot(res))
        for uuid, article, reference, ffut in followers:
            ffut._resolve(_restamp(res, uuid, article, reference))

    # -- introspection --
    def inflight(self) -> int:
        with self._lock:
            return len(self._flights)

    @property
    def cache(self) -> Optional[SummaryCache]:
        return self._cache


__all__ = ["FrontDoor", "SummaryCache", "article_key"]
