"""Concurrent request serving: dynamic micro-batching, shape buckets,
admission control (ISSUE 4 tentpole; SERVING.md).

The decode path dispatches one compiled program per batch; this package
turns that into a *service*: a thread-safe admission-controlled request
queue (``serve.queue``), a time/size micro-batcher that coalesces
independent requests into bucket-padded device batches
(``serve.batcher``), and a ``ServingServer`` (``serve.server``) whose
single dispatch thread runs them through ``BeamSearchDecoder`` — each
request resolving its own ``ServeFuture`` exactly once.

Layer map:
  * ``errors``  — ``ServeOverloadError`` / ``ServeClosedError`` (typed,
    under the resilience taxonomy).
  * ``queue``   — ``ServeFuture`` / ``ServeRequest`` / ``RequestQueue``
    (bounded depth + admission circuit breaker; jax-free).
  * ``batcher`` — ``MicroBatcher`` + ``resolve_buckets`` (coalescing
    window ``serve_max_wait_ms``, size cap ``serve_max_batch``,
    encoder-length buckets ``serve_buckets``; jax-free) and
    ``ContinuousBatcher`` (``serve_mode=continuous``: persistent slotted
    decode with in-flight refill at chunk boundaries — no
    dispatch-window barrier; jax-free, engine injected).
  * ``server``  — ``ServingServer``: submit()/serve() fronting the
    decoder, per-request quality tiers (``submit(tier=...)`` —
    beam/greedy/spec/draft, SERVING.md "Quality tiers") with
    per-request deadline re-tiering, between-batch checkpoint
    hot-swap, full obs instrumentation.
  * ``frontdoor`` — the production front door (ISSUE 14, SERVING.md
    "Front door"): ``article_key`` content hashing, in-flight
    coalescing, the bounded (content_hash, tier, params_fingerprint)
    ``SummaryCache``, and per-tenant token-bucket admission —
    ``FrontDoor`` sits between submit and the queue in BOTH the
    single-server and fleet paths (jax-free).
  * ``router``/``fleet`` — the elastic fleet (ISSUE 13, SERVING.md
    "Elastic fleet"): ``ReplicaHandle`` rotation state + least-loaded
    ``pick_replica`` (``router``), and the ``FleetRouter`` fronting N
    replicas — health-aware routing, request hedging, rolling hot-swap,
    chaos-tested replica failover with typed requeue (``fleet``;
    jax-free).

``serve.queue``/``serve.batcher`` never import jax; ``serve.server``
defers the decoder import until it actually builds one, so admission
and batching logic stay testable (and chaos-drivable) without a device.
"""

from __future__ import annotations

from textsummarization_on_flink_tpu.serve.errors import (
    ReplicaKilledError,
    ServeClosedError,
    ServeError,
    ServeOverloadError,
    TenantThrottledError,
)
from textsummarization_on_flink_tpu.serve.queue import (
    RequestQueue,
    ServeFuture,
    ServeRequest,
)
from textsummarization_on_flink_tpu.serve.batcher import (
    ContinuousBatcher,
    MicroBatcher,
    resolve_buckets,
)
from textsummarization_on_flink_tpu.serve.frontdoor import (
    FrontDoor,
    SummaryCache,
    article_key,
)

__all__ = [
    "ContinuousBatcher", "FleetRouter", "FrontDoor", "MicroBatcher",
    "ReplicaKilledError", "RequestQueue", "ServeClosedError", "ServeError",
    "ServeFuture", "ServeOverloadError", "ServeRequest", "ServingServer",
    "SummaryCache", "TenantThrottledError", "article_key",
    "resolve_buckets",
]


def __getattr__(name: str):
    # ServingServer/FleetRouter lazily: serve.server imports pipeline.io
    # (sockets, breakers) which light importers of this package don't
    # need, and serve.fleet imports serve.server's error surface
    if name == "ServingServer":
        from textsummarization_on_flink_tpu.serve.server import ServingServer

        return ServingServer
    if name == "FleetRouter":
        from textsummarization_on_flink_tpu.serve.fleet import FleetRouter

        return FleetRouter
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
