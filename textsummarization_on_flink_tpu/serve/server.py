"""ServingServer: concurrent request serving over the decode path.

The reference's whole point is *streaming* summarization (Kafka rows
through Flink into TF and back out, App.java inference job), but the
repo's decode loop was synchronous — one caller, one batch at a time
(decode/decoder.py ``decode()``).  This module turns the decoder into a
shared service:

    server = ServingServer(hps, vocab, train_dir=...)   # or params=
    with server:
        fut = server.submit("some article text .", uuid="u1")
        result = fut.result(timeout=30)                 # DecodedResult

Many callers submit concurrently; ONE dispatch thread consumes the
admission-controlled queue (serve/queue.py) through the engine
``hps.serve_mode`` selects:

  * ``microbatch`` (default/fallback) — coalesce into micro-batches
    (serve/batcher.MicroBatcher) and run each through
    ``BeamSearchDecoder.decode_batch``: independent requests share
    device dispatches (batch-fill > 1 under load), jit cache bounded by
    the shape buckets;
  * ``continuous`` — a persistent slotted decode loop
    (serve/batcher.ContinuousBatcher over decode/decoder.
    SlotDecodeEngine): queued requests run a bucketed PREFILL stage
    (encoder + cross-attention cache at the article's serve bucket,
    ISSUE 11) into a small ready queue, free slots refill from it at
    chunk boundaries, and resident decode is length-masked — per-chunk
    cost follows the longest active article's true length, not the
    padded shape; each future resolves the moment ITS sequence
    finishes — no dispatch-window straggler barrier (SERVING.md
    "Continuous batching" / "Prefill/decode disaggregation").

Contracts (both modes):
  * every admitted request resolves EXACTLY ONCE — with a
    ``DecodedResult`` or with the typed error that killed its batch
    (microbatch) / its residency (continuous);
  * per-request ``Deadline`` measured from enqueue, and a request whose
    budget died waiting in the queue is evicted with the typed
    ``DeadlineExceededError`` (counted in
    ``serve/deadline_evictions_total``) instead of burning a dispatch.
    Beyond that the modes differ: micro-batch requests carry a quality
    TIER (``submit(tier=...)`` — beam|greedy|spec|draft, SERVING.md
    "Quality tiers") and each group member whose budget cannot cover
    the full-beam estimate is re-tiered ALONE
    (beam->``serve_degrade_tier``, spec->draft; counted per request in
    ``serve/degraded_total`` and per requested tier) — the group then
    dispatches once per effective tier under each sub-group's tightest
    deadline; continuous mode never degrades (the slot state is
    fixed-beam, non-beam tiers are rejected at submit) — an expired
    RESIDENT is evicted typed at the next chunk boundary;
  * checkpoint hot-swap happens BETWEEN dispatches via the decoder's
    lock-guarded ``maybe_reload_checkpoint`` — between batches
    (microbatch) or ticks (continuous, where new params land at the
    next chunk boundary, so a resident article may finish under
    refreshed weights);
  * ``serve(source, sink)`` drives any pipeline/io.py Source/Sink pair
    through the queue with blocking-submit backpressure — the
    concurrency upgrade for ``pipeline/app.py:start_inference``.

Speculative tier (SERVING.md "Quality tiers"): spec-tier sub-batches
dispatch through the decoder's draft-then-verify engine; with
``hps.spec_k_adaptive`` the decoder's ONE SpecKController adapts the
draft length between cycles inside each dispatch and carries its
learned acceptance estimate across requests — this dispatch loop is
single-threaded, which is what makes the controller's unlocked
mutation safe (decode/speculative.py; the current pick is on the
``decode/spec_k_current`` gauge).

Observability (SERVING.md): serve/queue_depth, serve/time_in_queue_
seconds, serve/batch_fill, serve/e2e_latency_seconds, serve/shed_total,
serve/degraded_total, serve/errors_total, and the per-tier family
(serve/tier_*_total).  Chaos: injection point ``serve.dispatch`` fails
whole (sub-)batches deterministically.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, List, Optional, Sequence

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs import http as obs_http
from textsummarization_on_flink_tpu.obs import profile as profile_lib
from textsummarization_on_flink_tpu.obs import slo as slo_lib
from textsummarization_on_flink_tpu.config import (
    SERVE_TIERS,
    HParams,
    parse_fair_weights,
    resolve_refill_chunk,
    resolve_serve_slots,
)
from textsummarization_on_flink_tpu.data.batching import SummaryExample
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.pipeline.io import (
    CollectionSink,
    SchemaProjectionError,
    Sink,
    Source,
)
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.resilience.errors import (
    DeadlineExceededError,
)
from textsummarization_on_flink_tpu.resilience.policy import Deadline
from textsummarization_on_flink_tpu.serve.batcher import (
    ContinuousBatcher,
    MicroBatcher,
)
from textsummarization_on_flink_tpu.serve.errors import (
    ReplicaKilledError,
    ServeClosedError,
    ServeOverloadError,
)
from textsummarization_on_flink_tpu.serve.frontdoor import FrontDoor
from textsummarization_on_flink_tpu.serve.queue import (
    RequestQueue,
    ServeFuture,
    ServeRequest,
    track_rejection,
    track_request,
)

log = logging.getLogger(__name__)

#: columns the serving path consumes from a pipeline row (the
#: inference_selected_cols default, App.java:100)
SERVE_COLS = ("uuid", "article", "reference")


class ServingServer:
    """Thread-safe concurrent serving front-end for one decoder.

    Construct with ``params=`` (static weights) or ``train_dir=``
    (checkpoint dir: continuous mode hot-swaps the newest checkpoint
    between batches), or inject a prebuilt ``decoder=`` (tests, custom
    wiring).  ``start()`` launches the dispatch thread; ``stop()``
    drains the queue and joins (context-manager sugar does both).
    """

    def __init__(self, hps: HParams, vocab: Vocab,
                 params: Optional[Any] = None,
                 train_dir: Optional[str] = None,
                 decoder: Optional[Any] = None,
                 decode_root: Optional[str] = None,
                 engine: Optional[Any] = None,
                 registry: Optional[obs.Registry] = None,
                 clock: Any = time.monotonic):
        self._hps = hps
        self._vocab = vocab
        self._clock = clock
        self._reg = registry if registry is not None else obs.registry_for(hps)
        # the performance attribution plane (obs/profile.py, ISSUE 16):
        # installed before the batcher/decoder wirings so every phase
        # timer and compile-ledger site shares THIS server's clock
        # (virtual in the deterministic gates); first install on the
        # registry wins, like the SLO engine below
        profile_lib.install_profiler(
            self._reg, clock=clock,
            divergence_factor=float(getattr(
                hps, "profile_divergence_factor", 5.0)))
        if decoder is None:
            # deferred: decoder pulls in beam_search -> jax; a server
            # built around an injected stub must not pay that import
            from textsummarization_on_flink_tpu.decode.decoder import (
                BeamSearchDecoder,
            )

            decoder = BeamSearchDecoder(
                hps.replace(single_pass=False), vocab, batcher=None,
                params=params, train_dir=train_dir, decode_root=decode_root)
        self._decoder = decoder
        self._queue = RequestQueue(
            hps.serve_max_queue, registry=self._reg,
            fair_weights=parse_fair_weights(
                getattr(hps, "serve_fair_weights", "")))
        self._faults = faultinject.plan_for(hps)
        # the serving front door (ISSUE 14; SERVING.md "Front door"):
        # per-tenant token-bucket admission, the (content_hash, tier,
        # params_fingerprint) summary cache, and in-flight coalescing —
        # all between submit and the queue.  `clock` is injectable so
        # the virtual-time SLO gate refills tenant buckets on virtual
        # seconds.  Lookups key on THIS server's live fingerprint.
        self._door = FrontDoor(hps, registry=self._reg,
                               fingerprint=lambda: self.params_fingerprint,
                               clock=clock, faults=self._faults)
        self._mode = getattr(hps, "serve_mode", "microbatch")
        self._batcher: Optional[MicroBatcher] = None
        self._cont: Optional[ContinuousBatcher] = None
        if self._mode == "continuous":
            # engine= injects a stub (tests, SLO gate); the real one
            # drives the decoder's persistent slot kernels
            if engine is None:
                engine = self._decoder.slot_engine(
                    slots=resolve_serve_slots(hps),
                    chunk=resolve_refill_chunk(hps))
            self._cont = ContinuousBatcher(hps, self._queue, engine,
                                           registry=self._reg,
                                           faults=self._faults)
        else:
            self._batcher = MicroBatcher(hps, vocab, self._queue,
                                         registry=self._reg)
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._killed = False  # abrupt death (kill()): no drain, no refill
        # micro-batch groups currently inside decode_batch (0 or 1 —
        # single dispatch thread): the router's drain detection must
        # not call a server idle while a group is mid-dispatch
        self._dispatching = 0
        # deterministic-driver clock for tick_once (the fleet SLO gate
        # drives rounds without a dispatch thread)
        self._tick_last = time.monotonic()
        # failure flight recorder (OBSERVABILITY.md "Flight recorder"):
        # per-tick/per-dispatch frames ring in memory; the serve-side
        # triggers (dispatch failure, breaker open, eviction storm) dump
        # them next to the decode output.  Needs a directory to land in:
        # the decoder's decode dir when it has one, else the job's log
        # root; stub wirings with neither run without a recorder.
        if self._reg.enabled and getattr(hps, "flight_frames", 0) > 0:
            flight_dir = getattr(decoder, "_decode_dir", None)
            if flight_dir is None and hps.log_root:
                flight_dir = os.path.join(hps.log_root,
                                          hps.exp_name or "exp")
            if flight_dir:
                flightrec.install_flight_recorder(
                    self._reg, flight_dir, capacity=hps.flight_frames)
        # live exposition plane (/metrics, /healthz, /snapshot, /spans):
        # off unless TS_OBS_HTTP / HParams(obs_http_port) says otherwise
        obs_http.maybe_serve(self._reg, hps)
        # the router's routing inputs ride /healthz (ISSUE 13): the
        # effective serve_mode joins the queue-depth/slots-free gauges
        # in the JSON body, so an external router scrapes the same
        # facts the in-process FleetRouter reads off stats().  The
        # ACTIVE params fingerprint rides along (ISSUE 14): an external
        # cache tier keys on exactly what the in-process summary cache
        # keys on, and a hot-swap is observable as the value changing.
        # the eager sha (one D2H + full-tree hash) is only worth paying
        # when something will read it: an enabled registry's /healthz,
        # or an armed door's cache lookups (which memoize through the
        # decoder anyway).  A dark job skips it entirely.
        self._published_fp = (self.params_fingerprint
                              if self._reg.enabled else "")
        obs_http.set_health_info(self._reg, serve_mode=self._mode,
                                 params_fingerprint=self._published_fp)
        self._h_queue_time = self._reg.histogram(
            "serve/time_in_queue_seconds")
        self._h_e2e = self._reg.histogram("serve/e2e_latency_seconds")
        self._c_done = self._reg.counter("serve/completed_total")
        self._c_degraded = self._reg.counter("serve/degraded_total")
        self._c_errors = self._reg.counter("serve/errors_total")
        self._c_rows_out = self._reg.counter("serve/sink_rows_total")
        self._c_evictions = self._reg.counter(
            "serve/deadline_evictions_total")
        # per-tier telemetry (SERVING.md "Quality tiers"): completions
        # by EFFECTIVE tier, and degradations by the tier the request
        # ASKED for — literal metric names (the obs doc-drift gate scans
        # for literals), looked up through these dicts
        self._c_tier_done = {
            "beam": self._reg.counter("serve/tier_beam_total"),
            "greedy": self._reg.counter("serve/tier_greedy_total"),
            "spec": self._reg.counter("serve/tier_spec_total"),
            "draft": self._reg.counter("serve/tier_draft_total"),
        }
        self._c_tier_degraded = {
            "beam": self._reg.counter("serve/tier_degraded_beam_total"),
            "spec": self._reg.counter("serve/tier_degraded_spec_total"),
        }
        # per-tenant cost accounting: decoded tokens charged to the
        # tenant that asked for them (the front door's savings
        # counterpart lives in serve/frontdoor.py)
        self._c_tenant_tokens = self._reg.counter(
            "serve/tenant_tokens_total")
        # the SLO burn-rate engine (obs/slo.py; SLO_POLICY.json at the
        # repo root): first install on this registry wins, the clock is
        # THIS server's (virtual in the committed gate) — request
        # resolutions feed it via queue.track_request, dispatch rounds
        # evaluate it.  _ingress_track gates the whole feed: a replica
        # BEHIND a FleetRouter must not double-count what the router
        # already tracks (the router-level future is the caller-visible
        # request; replica attempts are implementation detail)
        self._ingress_track = True
        self._c_requests = self._reg.counter("serve/requests_total")
        slo_lib.install_slo_engine(self._reg, clock=clock)

    # -- lifecycle --
    def start(self) -> "ServingServer":
        if self._killed:
            raise ServeClosedError("cannot start a killed replica")
        if self._thread is not None:
            return self
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="serve-dispatch")
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 60.0) -> None:
        """Refuse new submits, drain everything already admitted, join.

        Every admitted request still resolves (the exactly-once
        contract survives shutdown); only if the dispatcher fails to
        drain within `timeout` are leftovers rejected with the typed
        ``ServeClosedError``."""
        self._queue.close()
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():  # pragma: no cover - defensive
                log.warning("serve dispatch thread still draining after "
                            "%.0fs; rejecting leftovers", timeout or 0)
        n = self._queue.drain_reject(
            ServeClosedError("server stopped before this request ran"))
        if n:
            self._c_errors.inc(n)
        if self._cont is not None:
            # shutdown backstop for the prefill queue (ISSUE 11): a
            # dispatch thread that died past its join timeout may leave
            # prefilled-but-unslotted requests behind; their futures
            # must resolve (fail_pending counts its own errors)
            self._cont.fail_pending(
                ServeClosedError("server stopped before this request ran"))
        self._thread = None
        # a stopped server's silence is not a failure: retire the beat
        # so /healthz reflects the components still running
        obs_http.retire_heartbeat(self._reg, "serve/dispatch")

    def kill(self, error: Optional[BaseException] = None) -> int:
        """Simulate (or surface) abrupt replica death: refuse new
        submits, abandon the dispatch thread WITHOUT draining, and
        reject every admitted request — residents and prefill-queue
        entries through the typed ``fail_resident``/``fail_pending``
        path, queued requests via ``drain_reject`` — with
        ``ReplicaKilledError`` (or `error`).  Returns the number of
        requests rejected.

        The exactly-once contract survives death: every rejected future
        resolves exactly once with the typed cause, which is what lets
        the FleetRouter requeue them on surviving replicas (SERVING.md
        "Elastic fleet").  Idempotent; a clean ``stop()`` is the
        graceful sibling."""
        if self._killed:
            return 0
        err = error if error is not None else ReplicaKilledError(
            "serving replica killed mid-decode")
        self._killed = True
        self._queue.close()
        self._stop.set()
        t = self._thread
        if t is not None:
            # the dispatch thread exits at its next loop-top _killed
            # check; join BEFORE failing residents so the kill path
            # never races a live tick over the engine state
            t.join(timeout=30.0)
            if t.is_alive():  # pragma: no cover - defensive
                log.warning("killed serve dispatch thread still inside a "
                            "dispatch; residents will fail under it")
            self._thread = None
        n = 0
        if self._cont is not None:
            n += self._cont.fail_resident(err)
            n += self._cont.fail_pending(err)
        drained = self._queue.drain_reject(err)
        if drained:
            self._c_errors.inc(drained)
        n += drained
        obs_http.retire_heartbeat(self._reg, "serve/dispatch")
        if n:
            log.warning("replica killed: %d admitted request(s) rejected "
                        "%s for requeue", n, type(err).__name__)
        return n

    @property
    def killed(self) -> bool:
        return self._killed

    def hot_swap(self) -> bool:
        """Router-orchestrated FORCED checkpoint swap (SERVING.md
        "Elastic fleet"): reload the newest checkpoint NOW — no 60s
        self-gate — between dispatches, while the router holds this
        replica drained.  Same failure tolerance as the between-batch
        path: a failed reload keeps the replica serving its CURRENT
        snapshot (counted in ``serve/ckpt_reload_errors_total``) and
        returns False; the router keeps it in rotation either way."""
        try:
            # -inf forces the cadence check; the decoder's params lock
            # still makes the (params, ckpt, draft) swap atomic
            self._decoder.maybe_reload_checkpoint(float("-inf"))
            self._publish_fingerprint()
            return True
        except Exception:
            self._reg.counter("serve/ckpt_reload_errors_total").inc()
            log.exception("router-orchestrated hot-swap failed; serving "
                          "on the current snapshot")
            return False

    def disable_front_door(self) -> None:
        """Disarm THIS server's front door (FleetRouter construction):
        behind a router, coalescing/caching must dedup ACROSS replicas
        and tenant tokens must be charged exactly once — so the router
        runs the one front door and replicas serve what they are
        routed.  (A hedged twin or a requeue would otherwise coalesce
        against its own primary, or double-spend a tenant's bucket.)
        Also releases this replica's now-dead cache."""
        self._door.disarm()

    @property
    def params_fingerprint(self) -> str:
        """The ACTIVE params fingerprint (the decoder's cached sha over
        its current ``_params_snapshot``; "" for decoders without the
        surface — stubs, the SLO gate's sims — which therefore cache
        consistently under the empty fingerprint).  The summary cache's
        lookup key (SERVING.md "Front door")."""
        fp = getattr(self._decoder, "params_fingerprint", "")
        return fp if isinstance(fp, str) else ""

    def _publish_fingerprint(self) -> None:
        """Refresh the /healthz fingerprint after a (possible) swap.
        Called once per dispatch loop / tick but gated on the CHANGE:
        the decoder's sha is memoized per params object, and the
        health-info dict update only runs when the value moved (at
        most once per actual reload, not per tick).  Dark registries
        skip even the memoized read — nothing would serve the value."""
        if not self._reg.enabled:
            return
        fp = self.params_fingerprint
        if fp != self._published_fp:
            self._published_fp = fp  # tslint: disable=TS009 — written only by whichever single loop (dispatch or tick_once) owns this server; roots never coexist
            obs_http.set_health_info(self._reg, params_fingerprint=fp)

    def idle(self) -> bool:
        """True when the server holds NO admitted work: queue empty, no
        group coalescing or mid-dispatch (the micro-batcher pops
        requests off the queue up to ``serve_max_wait_ms`` before the
        dispatch starts — those are admitted work the queue no longer
        shows), no residents, no prefilled entries — the router's
        drained predicate for rolling hot-swap."""
        if not self._queue.empty() or self._dispatching:
            return False
        if self._batcher is not None and self._batcher.in_flight:
            return False
        if self._cont is not None and (self._cont.busy()
                                       or self._cont.pending()):
            return False
        return True

    def stats(self) -> dict:
        """Live routing inputs (the in-process mirror of the /healthz
        body's ``serve`` section): queue depth, resident/free slots
        (continuous), prefilled count, effective serve_mode, and the
        LIVE admission-breaker state (the ``breaker_state`` gauge only
        refreshes on allow(), so a scraped OPEN may already be past its
        reset window — the state property re-evaluates)."""
        out = {
            "queue_depth": self._queue.qsize(),
            "serve_mode": self._mode,
            "admission": self._queue.breaker.state,
        }
        if self._cont is not None:
            active = self._cont.active()
            out["slots_active"] = active
            out["slots_free"] = self._cont.slots - active
            out["prefilled"] = self._cont.prefilled()
        return out

    def load(self) -> int:
        """Admitted-but-unresolved work count — the FleetRouter's
        least-loaded routing key (queued + coalescing/dispatching +
        resident + prefilled)."""
        n = self._queue.qsize()
        if self._batcher is not None:
            n += self._batcher.in_flight
        if self._cont is not None:
            n += self._cont.active() + self._cont.prefilled()
        return n

    @property
    def registry(self) -> obs.Registry:
        """This replica's obs registry — the router reads its /healthz
        payload (heartbeat staleness, breaker states) through it."""
        return self._reg

    @property
    def serve_mode(self) -> str:
        return self._mode

    def __enter__(self) -> "ServingServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    def _track_request(self, fut: "ServeFuture", tenant: str,
                       tier: str) -> "ServeFuture":
        """Ingress accounting for one admitted future — the shared
        ``queue.track_request`` helper (labeled requests_total + SLO
        feed), gated off entirely behind a FleetRouter."""
        if self._ingress_track:
            track_request(self._reg, self._clock, fut, tenant, tier,
                          counter=self._c_requests)
        return fut

    def disable_ingress_tracking(self) -> None:
        """Stop counting this server's submits as caller-visible
        requests (FleetRouter construction, alongside
        ``disable_front_door``): the router tracks the one
        caller-visible future per request — a replica also counting
        each routed/hedged/requeued attempt would double-count
        ``serve/requests_total`` and the SLO burn windows."""
        self._ingress_track = False

    # -- request API --
    def submit(self, article: str, uuid: str = "", reference: str = "",
               block: bool = False, timeout: Optional[float] = None,
               tier: str = "",
               trace: Optional[obs.TraceContext] = None,
               tenant: str = "") -> ServeFuture:
        """Admit one request; returns its future.

        Non-blocking (default): full queue / open admission breaker
        raises ``ServeOverloadError`` immediately — the caller sheds or
        retries with backoff.  ``block=True`` waits up to `timeout` for
        queue space instead (pipeline backpressure mode).

        ``tier`` picks the request's quality tier
        (beam|greedy|spec|draft, SERVING.md "Quality tiers"; "" = the
        job's ``serve_default_tier``).  Tier problems are caller errors
        and fail HERE, synchronously: an unknown tier, a spec/draft ask
        against a decoder with no draft model, or a non-beam tier on a
        continuous-mode server (the persistent slot state is fixed-beam
        by construction).

        ``tenant`` names the request's fairness/admission tenant
        (SERVING.md "Front door"; "" = the default tenant, today's
        behavior).  With ``serve_tenant_rate`` armed, an over-rate
        tenant's submit sheds HERE with the typed
        ``TenantThrottledError``; with fair weights configured, pickup
        interleaves tenants by weight.

        Front door (ISSUE 14): with the summary cache armed a hit
        resolves the returned future SYNCHRONOUSLY (byte-identical to
        a fresh decode of the same (article, tier, fingerprint), queue
        untouched); with coalescing armed a duplicate of an in-flight
        (content_hash, tier) attaches to that one computation and
        resolves from its result.

        The per-request Deadline starts NOW (enqueue), so queue wait
        spends the ``decode_deadline_secs`` budget and an aged request
        degrades to greedy exactly like a slow one (RESILIENCE.md).

        ``trace`` injects an externally-minted TraceContext (the
        FleetRouter threads ONE context through every replica attempt
        of a routed request, SERVING.md "Elastic fleet"); None mints a
        fresh per-request root, the pre-fleet behavior."""
        tier = tier or getattr(self._hps, "serve_default_tier", "beam")
        if tier not in SERVE_TIERS:
            raise ValueError(
                f"tier must be one of {SERVE_TIERS}, got {tier!r}")
        if self._mode == "continuous" and tier != "beam":
            raise ValueError(
                f"continuous serving decodes at the beam tier only (the "
                f"resident slot state is fixed-beam); got tier={tier!r} "
                f"— use serve_mode=microbatch for tiered requests")
        if tier != "beam" and getattr(self._decoder, "sharded", False):
            raise ValueError(
                f"sharded (mesh) serving decodes at the beam tier only "
                f"(the search is jit-built once for the mesh plan); got "
                f"tier={tier!r}")
        if tier in ("spec", "draft") and not getattr(
                self._decoder, "has_draft", False):
            raise ValueError(
                f"tier={tier!r} needs a draft model: set hps.spec_draft "
                f"('map'/'fresh') or construct the decoder with "
                f"draft_params=")
        flight = None
        try:
            if self._door.armed:
                # a stopped/killed server refuses new submits — checked
                # BEFORE the door, or a cached article would keep
                # "succeeding" against a dead server while uncached ones
                # raise typed (the shutdown contract must not depend on
                # what happens to be cached)
                if self._queue.closed:
                    raise ServeClosedError("serving queue is closed")
                # tenant bucket FIRST (a throttled tenant must not probe
                # the cache), then cache/coalescing — both before the
                # queue, so a hit or a follower never spends queue depth
                self._door.admit_tenant(tenant, uuid)
                kind, val = self._door.open(article, tier, uuid, reference,
                                            trace=trace, tenant=tenant)
                if kind in ("hit", "follower"):
                    return self._track_request(val, tenant, tier)
                if kind == "leader":
                    flight = val
            try:
                example = SummaryExample.build(
                    article, [], self._vocab, self._hps,
                    uuid=uuid, reference=reference)
                req = ServeRequest(
                    uuid, article, reference, example,
                    deadline=Deadline.after(
                        getattr(self._hps, "decode_deadline_secs", 0.0)),
                    registry=self._reg, tier=tier, trace=trace,
                    tenant=tenant)
                self._queue.submit(req, block=block, timeout=timeout)
            except BaseException as e:
                if flight is not None:
                    # the leader died before admission completed —
                    # tokenization error, queue full, closed: any
                    # follower that attached in the window fails with
                    # the same typed cause (it asked for exactly this
                    # computation), and the flight is retired so later
                    # duplicates lead fresh
                    self._door.abort(flight, e)
                raise
        except ServeOverloadError:
            # a caller-visible shed (tenant throttle, open breaker,
            # full queue) is a BAD event for the SLO burn windows:
            # without this, total admission failure — the exact outage
            # the engine pages on — reads as a healthy SLO because only
            # admitted futures reach track_request's done-callback
            if self._ingress_track:
                track_rejection(self._reg, tenant, tier)
            raise
        if flight is not None:
            self._door.commit(flight, req.future)
        return self._track_request(req.future, tenant, tier)

    def pending(self) -> int:
        return self._queue.qsize()

    # -- pipeline driving --
    def serve(self, source: Source, sink: Optional[Sink] = None,
              cols: Sequence[str] = SERVE_COLS, max_count: int = 0,
              result_timeout: Optional[float] = 600.0) -> Sink:
        """Drive a pipeline Source through the queue into a Sink.

        Rows are projected to `cols` (uuid, article, reference) via the
        source's schema, submitted with BLOCKING backpressure (a full
        queue slows the feed instead of shedding pipeline rows), and
        each result row (uuid, article, summary, reference) is written
        to the sink the moment its future resolves — per-record
        immediacy, the Issue-6 contract, but now out-of-order under
        concurrency (rows are uuid-keyed by design).  Returns the sink
        after every submitted row resolved; the first request failure
        re-raises after the drain."""
        out = sink if sink is not None else CollectionSink()
        cols = list(cols)
        try:
            source.schema.select(cols)
        except ValueError as e:
            self._reg.counter("pipeline/feeder_errors_total").inc()
            raise SchemaProjectionError(
                f"source schema {source.schema!r} cannot provide serving "
                f"columns {cols}") from e

        def write_row(fut: ServeFuture) -> None:
            if fut.error is None:
                out.write(fut.result().as_row())
                self._c_rows_out.inc()

        futures: List[ServeFuture] = []
        n = 0
        for row in source.rows():
            try:
                uuid, article, reference = source.schema.project_row(
                    row, cols)
            except (IndexError, ValueError) as e:
                self._reg.counter("pipeline/feeder_errors_total").inc()
                raise SchemaProjectionError(
                    f"row {row!r} does not match schema "
                    f"{source.schema!r}") from e
            fut = self.submit(str(article), uuid=str(uuid),
                              reference=str(reference), block=True)
            fut.add_done_callback(write_row)
            futures.append(fut)
            n += 1
            if max_count and n >= max_count:
                break
        first_error: Optional[BaseException] = None
        for fut in futures:
            try:
                fut.result(timeout=result_timeout)
            except Exception as e:  # noqa: PERF203  # tslint: disable=TS005 — deferred re-raise: the first failure is raised after ALL futures drain; counting here would double serve/errors_total (the rejection site already counted)
                if first_error is None:
                    first_error = e
        if first_error is not None:
            raise first_error
        return out

    # -- dispatch loop --
    def _tightest_deadline(self, group: List[ServeRequest]) -> Deadline:
        """The batch runs under the most urgent member's budget: one
        dispatch serves them all, so the least headroom decides whether
        the whole batch degrades to greedy."""
        bounded = [r.deadline for r in group if r.deadline.bounded]
        if not bounded:
            return Deadline.never()
        return min(bounded, key=lambda d: d.remaining())

    def _beat(self) -> None:
        # one beat per dispatch-loop iteration; the shared
        # LOOP_HEARTBEAT_PERIOD carries the jit-compile-tolerance
        # rationale (obs/http.py) and keeps the trainer's and this
        # loop's /healthz semantics from drifting
        obs_http.heartbeat(self._reg, "serve/dispatch",
                           period=obs_http.LOOP_HEARTBEAT_PERIOD)

    def _run(self) -> None:
        if self._mode == "continuous":
            self._run_continuous()
            return
        t_last = time.monotonic()
        while True:
            if self._killed:
                return  # abrupt death: no drain (kill() rejects leftovers)
            self._beat()
            group = self._batcher.next_group()
            if group is None:
                if self._stop.is_set() and self._queue.empty():
                    return
                continue
            self._dispatching += 1
            try:
                self._dispatch(group)
            finally:
                self._dispatching -= 1
                # the group's futures are all settled: the coalesce/
                # dispatch in-flight window (opened inside next_group)
                # closes — idle()/load() stop counting it
                self._batcher.end_group()
            # burn-rate refresh once per dispatch round: the group's
            # resolutions just landed in the SLO windows, so alert
            # transitions (and the slo_burn flight dump) fire on the
            # dispatch thread, deterministically per round
            slo_lib.evaluate(self._reg)
            if self._stop.is_set() and self._queue.empty():
                return
            try:
                # hot-swap strictly BETWEEN batches; the decoder's param
                # lock makes the (params, ckpt_name) swap atomic even
                # against out-of-band decode_batch callers
                t_last = self._decoder.maybe_reload_checkpoint(t_last)
                self._publish_fingerprint()
            except Exception:
                # a failed reload must not kill the dispatch thread —
                # that would hang every queued and future request; the
                # decoder keeps serving its current params and the next
                # reload window retries
                self._reg.counter("serve/ckpt_reload_errors_total").inc()
                log.exception("between-batch checkpoint reload failed; "
                              "continuing on current params")
                t_last = time.monotonic()

    def _continuous_round(self, t_last: float, poll: float = 0.05) -> float:
        """ONE continuous-mode scheduler round (beat -> tick -> between-
        chunk hot-swap), shared by the dispatch thread's loop and the
        deterministic ``tick_once`` driver so the two can never drift.
        A failed tick — injected serve.dispatch fault, engine error —
        fails the RESIDENT requests only (each resolves exactly once
        with the typed cause) and the round returns normally, mirroring
        the micro-batch 'a failed dispatch fails its batch only'
        contract at slot granularity."""
        self._beat()
        try:
            self._cont.tick(poll)
        except Exception as e:  # tslint: disable=TS005 — every resident future is rejected with the typed cause and counted in serve/errors_total by fail_resident; the loop must outlive any one tick
            flightrec.trigger(self._reg, "serve_dispatch",
                              error=type(e).__name__)
            n = self._cont.fail_resident(e)
            log.exception("continuous dispatch tick failed; rejected "
                          "%d resident request(s)", n)
        # burn-rate refresh once per scheduler round (same rationale as
        # the micro-batch loop's per-dispatch evaluate)
        slo_lib.evaluate(self._reg)
        try:
            # same hot-swap cadence as the micro-batch loop (the
            # decoder self-gates at 60s); a resident article picks
            # up new params at its next chunk boundary (SERVING.md)
            t_next = self._decoder.maybe_reload_checkpoint(t_last)
            self._publish_fingerprint()
            return t_next
        except Exception:
            self._reg.counter("serve/ckpt_reload_errors_total").inc()
            log.exception("between-chunk checkpoint reload failed; "
                          "continuing on current params")
            return time.monotonic()

    def _run_continuous(self) -> None:
        """The continuous-mode dispatch loop: drive scheduler rounds
        until stopped AND drained (or killed — abrupt death skips the
        drain; kill() resolves the leftovers typed)."""
        t_last = time.monotonic()
        while True:
            if self._killed:
                return
            t_last = self._continuous_round(t_last)
            # drain condition: queue empty AND no residents AND no
            # prefilled-but-unslotted requests (a tick can harvest every
            # resident right after the prefill stage drained the
            # queue's tail — those entries must pack on the next tick,
            # not be rejected by stop()'s backstop)
            if (self._stop.is_set() and self._queue.empty()
                    and not self._cont.busy()
                    and not self._cont.pending()):
                return

    def tick_once(self, poll: float = 0.0) -> None:
        """One continuous-mode scheduler round on the CALLER's thread.

        The deterministic-driver hook (SERVING.md "Elastic fleet"): the
        fleet virtual-time SLO gate and single-threaded harnesses drive
        the REAL dispatch path — the exact code the dispatch thread
        runs, including the tick-failure blast radius and the
        between-chunk hot-swap — one round at a time, with no threads
        and no sleeps.  Never call concurrently with a started
        dispatch thread (single consumer, like the thread itself)."""
        if self._cont is None:
            raise ValueError(
                "tick_once drives the continuous engine; this server is "
                f"serve_mode={self._mode!r} — start() it instead")
        self._tick_last = self._continuous_round(self._tick_last, poll)

    #: deadline-pressure re-tiering per REQUESTED tier: beam falls to
    #: the configured target, spec falls to its verify-free draft;
    #: greedy/draft are already the floor of their branch
    def _degrade_target(self, tier: str) -> Optional[str]:
        if tier == "beam":
            return self._hps.serve_degrade_tier
        if tier == "spec":
            return "draft"
        return None

    def _effective_tier(self, r: ServeRequest) -> tuple:
        """(effective tier, degraded?) for one request — the ISSUE-10
        satellite fix: degradation is decided PER REQUEST against its
        own deadline, not once for the whole micro-batch, so one
        tight-deadline member no longer drags its batchmates down to
        greedy with it."""
        tier = r.tier or getattr(self._hps, "serve_default_tier", "beam")
        target = self._degrade_target(tier)
        if target is None or not self._decoder.should_degrade(r.deadline):
            return tier, False
        if target in ("spec", "draft") and not getattr(
                self._decoder, "has_draft", False):
            target = "greedy"  # draftless jobs keep the legacy ladder
        return target, True

    def _dispatch(self, group: List[ServeRequest]) -> None:
        now = time.monotonic()
        # decoders without the tier surface (should_degrade — legacy
        # stubs, custom wirings) keep the pre-tier contract: one
        # whole-group dispatch, degradation decided inside decode_batch
        legacy = not hasattr(self._decoder, "should_degrade")
        #: effective tier -> [(request, degraded?)] — a mixed group
        #: dispatches once per tier (a dispatch runs ONE compiled
        #: program, so tiers cannot share a device batch)
        by_tier: dict = {}
        for r in group:
            queue_s = now - r.enqueue_t
            self._h_queue_time.observe(
                queue_s,
                trace_id=r.trace.trace_id if r.trace is not None else None)
            if r.deadline.expired():
                # the ISSUE-6 bugfix, micro-batch side: a request whose
                # budget died in the queue is resolved typed instead of
                # burning a dispatch on an answer nobody is waiting for
                self._c_evictions.inc()
                obs.spans.request_event(self._reg, "evict", r.trace,
                                        r.uuid, where="queue")
                r.future._reject(DeadlineExceededError(
                    f"request {r.uuid!r} deadline expired while queued"))
                continue
            tattr = {"tenant": r.tenant} if r.tenant else {}
            if legacy:
                obs.spans.request_event(
                    self._reg, "admit", r.trace, r.uuid,
                    queue_ms=round(queue_s * 1e3, 3), **tattr)
                by_tier.setdefault(None, []).append((r, False))
                continue
            tier, degraded = self._effective_tier(r)
            obs.spans.request_event(
                self._reg, "admit", r.trace, r.uuid,
                queue_ms=round(queue_s * 1e3, 3), tier=tier, **tattr)
            by_tier.setdefault(tier, []).append((r, degraded))
        for tier, members in by_tier.items():
            self._dispatch_tier(tier, members)

    def _dispatch_tier(self, tier: Optional[str],
                       members: List[tuple]) -> None:
        """One device dispatch for one tier's sub-group (tier=None is
        the legacy whole-group path for tier-less decoders — the
        decoder decides its own degradation from the deadline)."""
        group = [r for r, _ in members]
        degraded_map = {id(r): d for r, d in members}
        # micro-batch flight frame (the per-dispatch analogue of the
        # continuous per-tick frame), recorded before the dispatch so a
        # failing batch leaves its own pre-failure frame behind
        flightrec.record(self._reg, "serve_dispatch", fill=len(group),
                         queue_depth=self._queue.qsize(),
                         tier=tier or "legacy")
        # per-tier micro-batch dispatch phase (obs/profile.py, ISSUE
        # 16): one labeled phase sample per device dispatch, keyed by
        # the effective tier so the /profile phase table splits beam
        # from greedy from spec wall time
        prof = profile_lib.profiler_for(self._reg)
        t0 = prof.start()
        try:
            with obs.spans.span(self._reg, "serve/dispatch",
                                fill=len(group), tier=tier or "legacy"):
                if self._faults.fire("serve.dispatch"):
                    raise RuntimeError("injected serve.dispatch fault")
                batch = self._batcher.build(group)
                deadline = self._tightest_deadline(group)
                if tier is None:
                    results = self._decoder.decode_batch(
                        batch, deadline=deadline)
                else:
                    results = self._decoder.decode_batch(
                        batch, deadline=deadline, tier=tier)
            dt = prof.end("serve/dispatch", t0)
            prof.observe_dispatch(
                "serve/dispatch", f"tier_{tier or 'legacy'}", dt)
            if len(results) != len(group):
                raise RuntimeError(
                    f"decoder returned {len(results)} results for "
                    f"{len(group)} real rows (real_mask drift?)")
        except Exception as e:
            # a failed dispatch fails ITS tier sub-batch only — each
            # member resolves exactly once with the typed cause; the
            # server lives on to serve the next group
            flightrec.trigger(self._reg, "serve_dispatch",
                              error=type(e).__name__, tier=tier)
            self._c_errors.inc(len(group))
            log.exception("serve dispatch failed; rejecting %d request(s)",
                          len(group))
            for r in group:
                r.future._reject(e)
            return
        done_t = time.monotonic()
        for r, res in zip(group, results):
            degraded = degraded_map.get(id(r), False)
            res.degraded = bool(degraded or getattr(res, "degraded",
                                                    False))
            if tier is None:
                if res.degraded:  # legacy path: the decoder decided
                    self._c_degraded.inc()
            elif degraded:
                # counted HERE, on successful completion, so a failed
                # sub-dispatch can never report more degraded results
                # than completions (same semantics as the legacy path)
                asked = r.tier or getattr(self._hps, "serve_default_tier",
                                          "beam")
                self._c_degraded.inc()  # per REQUEST, not per batch
                if asked in self._c_tier_degraded:
                    self._c_tier_degraded[asked].inc()
            if tier in self._c_tier_done:
                self._c_tier_done[tier].inc()
            # the landing bucket's exemplar is THIS request's trace_id
            # (ISSUE 15): a fat p99 bucket on /metrics names a concrete
            # uuid to chase through trace_summary.py --request
            self._h_e2e.observe(
                done_t - r.enqueue_t,
                trace_id=r.trace.trace_id if r.trace is not None else None)
            self._c_tenant_tokens.labels(
                tenant=r.tenant or "default").inc(
                len(getattr(res, "decoded_words", ()) or ()))
            self._c_done.inc()
            obs.spans.request_event(
                self._reg, "finish", r.trace, r.uuid,
                tier=tier or "legacy", degraded=bool(res.degraded))
            r.future._resolve(res)


__all__ = ["ServingServer", "ServeFuture", "ServeOverloadError",
           "ServeClosedError", "SERVE_COLS"]
