"""Thread-safe request queue + admission control for concurrent serving.

The unit of work is a ``ServeRequest``: one summarization request
(uuid, article, reference) already tokenized into a ``SummaryExample``,
carrying the ``ServeFuture`` its caller blocks on and a ``Deadline``
measured from *enqueue* (not batch start — time spent queued counts
against the request's budget, RESILIENCE.md degradation contract).

Admission control (``RequestQueue``): the queue depth is BOUNDED
(``serve_max_queue``).  A non-blocking submit against a full queue is
rejected with the typed ``ServeOverloadError`` — never silently dropped,
never parked unbounded — and every rejection is a *failure* recorded
against an admission ``CircuitBreaker``: under sustained overload the
breaker opens and requests are shed immediately without touching the
queue (the ``BreakerSink`` load-shedding semantics from pipeline/io.py,
applied to the ingress side), then a half-open probe admission decides
recovery.  Blocking submits (the pipeline-driving path) exert
backpressure instead: they wait for space and bypass the breaker.

Multi-tenant fairness (ISSUE 14; SERVING.md "Front door"): each
``ServeRequest`` carries a ``tenant`` ("" = the default tenant), the
queue keeps one FIFO per tenant under the shared depth bound, and the
CONSUMER side (``get``/``get_nowait``) picks across the non-empty
tenants by smooth weighted round-robin (``serve_fair_weights``) — so
one tenant's deep backlog cannot starve another's pickup, while a
single-tenant queue degenerates to exactly the historical global FIFO
(same tenant => strict arrival order).  The admission-rate side (the
per-tenant token bucket) lives in serve/frontdoor.py.

Import-light: no jax; numpy only transitively via data.batching.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Deque, Dict, List, Optional

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs import locksan
from textsummarization_on_flink_tpu.resilience.policy import (
    CircuitBreaker,
    Deadline,
)
from textsummarization_on_flink_tpu.serve.errors import (
    ServeClosedError,
    ServeOverloadError,
)

log = logging.getLogger(__name__)

#: bound on the fair-pickup credit map: caller-supplied tenant names
#: must not grow it without end — once past the bound, credits of
#: tenants with NO queued work are pruned (their fairness debt is at
#: most one round's weight, so the reset is noise)
MAX_TENANT_CREDITS = 4096


def track_request(registry: "obs.Registry", clock: Callable[[], float],
                  fut: "ServeFuture", tenant: str, tier: str,
                  counter: Optional["obs.registry.Counter"] = None) -> None:
    """Ingress-side accounting for ONE admitted future (ISSUE 15): the
    labeled ``serve/requests_total{tenant,tier}`` child (rolls up into
    the unlabeled total), and — when an SLO engine is installed on
    `registry` — a done-callback classifying (tenant, tier, latency,
    error) into the burn-rate windows on the future's exactly-once
    resolution.  Latency runs on the CALLER's clock (virtual in the
    committed gate).  The one helper both ingresses share
    (``ServingServer.submit`` and ``FleetRouter.submit``), so router
    and replica classification can never silently diverge — and each
    request is tracked exactly once, at the ingress that owns it (a
    replica behind a router has its tracking disabled).

    `counter` takes the ingress's construction-time
    ``serve/requests_total`` parent (the cached-sibling idiom of every
    other hot-path counter here), skipping the per-submit registry-lock
    name lookup; None resolves it per call."""
    tenant = tenant or "default"
    c = counter if counter is not None \
        else registry.counter("serve/requests_total")
    c.labels(tenant=tenant, tier=tier).inc()
    eng = registry.slo
    if eng is not None:
        t0 = clock()
        fut.add_done_callback(lambda f: eng.record(
            tenant, tier, clock() - t0, error=f.error is not None))


def track_rejection(registry: "obs.Registry", tenant: str,
                    tier: str) -> None:
    """Ingress-side SLO accounting for ONE caller-visible REJECTED
    submit (tenant throttle, open admission breaker, full queue): a
    shed request is a bad event under every objective, or total
    admission failure — the exact outage the burn-rate engine pages on
    — would read as a healthy SLO because only admitted futures ever
    reach ``track_request``'s done-callback.  Cold path (rejections);
    no-op without an installed engine."""
    eng = registry.slo
    if eng is not None:
        eng.record(tenant or "default", tier, 0.0, error=True)


class ServeFuture:
    """A per-request completion handle that resolves EXACTLY ONCE.

    ``result(timeout)`` blocks for the ``DecodedResult`` (re-raising the
    failure that rejected the request); ``add_done_callback`` runs the
    callback on the resolving thread (or immediately when already done).
    A second ``_resolve``/``_reject`` is a programming error and raises
    — the exactly-once contract is load-bearing for the acceptance test
    and for sinks that must see one row per request.
    """

    __slots__ = ("uuid", "trace", "scope", "_event", "_result", "_error",
                 "_lock", "_callbacks", "_registry")

    def __init__(self, uuid: str = "",
                 registry: Optional[obs.Registry] = None):
        self.uuid = uuid
        # resolve-event scope tag (ISSUE 13): "" for replica-level
        # futures; the FleetRouter stamps its caller-visible future
        # "fleet" so a hedged/requeued uuid's TERMINAL resolve is
        # distinguishable from its replica attempts' resolves in the
        # event stream (scripts/trace_summary.py --request keys the
        # total_ms phase on it)
        self.scope = ""
        # the request's TraceContext (set by ServeRequest): resolution
        # is the terminal lifecycle event of a trace, and it can happen
        # on any thread — the dispatcher, an evictor, drain_reject —
        # so the ids ride the future itself
        self.trace: Optional[obs.TraceContext] = None
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._lock = locksan.make_lock("ServeFuture._lock")
        self._callbacks: List[Callable[["ServeFuture"], None]] = []
        self._registry = registry if registry is not None else obs.registry()

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        """The rejection cause once done (None while pending / on
        success) — lets callbacks route without a try/except."""
        return self._error

    def result(self, timeout: Optional[float] = None) -> Any:
        """The DecodedResult, blocking up to `timeout` seconds.  Raises
        the rejection error verbatim, or TimeoutError on expiry."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"serve request {self.uuid!r} not resolved in {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def add_done_callback(self,
                          fn: Callable[["ServeFuture"], None]) -> None:
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(fn)
                return
        self._run_callback(fn)

    def _run_callback(self, fn: Callable[["ServeFuture"], None]) -> None:
        try:
            fn(self)
        except Exception:  # a sink callback must never kill the dispatcher
            self._registry.counter("serve/callback_errors_total").inc()
            log.exception("serve future callback failed (uuid=%s)", self.uuid)

    def _finish(self, result: Any, error: Optional[BaseException]) -> None:
        with self._lock:
            if self._event.is_set():
                raise AssertionError(
                    f"ServeFuture {self.uuid!r} resolved twice")
            self._result = result
            self._error = error
            # the trace's terminal event: EVERY resolution path
            # (success, dispatch failure, eviction, drain) funnels
            # through _finish, so the enqueue->resolve timeline closes
            # exactly once per request.  Emitted BEFORE the event sets:
            # a waiter unblocked by result() must find the resolve
            # record already in the stream (emit is a non-blocking
            # queue put — cheap under the lock).
            attrs: dict = ({"error": type(error).__name__}
                           if error is not None else {})
            if self.scope:
                attrs["scope"] = self.scope
            obs.spans.request_event(self._registry, "resolve", self.trace,
                                    self.uuid, **attrs)
            self._event.set()
            callbacks, self._callbacks = self._callbacks, []
        for fn in callbacks:
            self._run_callback(fn)

    def _resolve(self, result: Any) -> None:
        self._finish(result, None)

    def _reject(self, error: BaseException) -> None:
        self._finish(None, error)


class ServeRequest:
    """One admitted (or about-to-be-admitted) summarization request."""

    __slots__ = ("uuid", "article", "reference", "example", "future",
                 "deadline", "enqueue_t", "trace", "tier", "tenant")

    def __init__(self, uuid: str, article: str, reference: str,
                 example: Any, deadline: Optional[Deadline] = None,
                 registry: Optional[obs.Registry] = None,
                 tier: str = "", trace: Optional[obs.TraceContext] = None,
                 tenant: str = ""):
        self.uuid = uuid
        self.article = article
        self.reference = reference
        self.example = example  # data.batching.SummaryExample
        # the tenant whose fairness bucket this request rides ("" = the
        # default tenant — a job that never names tenants keeps ONE
        # bucket and therefore the historical global-FIFO pickup)
        self.tenant = tenant
        # requested quality tier (SERVING.md "Quality tiers"): one of
        # config.SERVE_TIERS, or "" = the server's default.  The
        # EFFECTIVE tier may be lower — per-request deadline-pressure
        # degradation happens at dispatch, not here.
        self.tier = tier
        self.future = ServeFuture(uuid, registry=registry)
        # request-scoped trace root (ISSUE 9): minted at the request's
        # birth on the SUBMIT thread and carried on the object, so the
        # dispatch thread and slot engine stamp the same trace_id —
        # the thread-local span stack could never link them.  A dark
        # job (obs=False / TS_OBS=0) skips the mint: every consumer
        # (request_event, span parent) discards the ids anyway, so the
        # submit hot path shouldn't pay the urandom read for them.
        # An EXPLICIT ``trace`` wins over the mint (ISSUE 13): the
        # FleetRouter mints ONE context per routed request and threads
        # it through every replica attempt (primary, hedge, requeue),
        # so a request's cross-replica lifecycle shares one trace_id.
        reg = registry if registry is not None else obs.registry()
        if trace is not None:
            self.trace = trace
        else:
            self.trace = obs.TraceContext.new() if reg.enabled else None
        self.future.trace = self.trace
        # the budget runs from ENQUEUE: queue wait spends it, so a
        # request that aged in a deep queue reaches the decoder with
        # less room and degrades (or at worst expires) honestly
        self.deadline = deadline if deadline is not None else Deadline.never()
        self.enqueue_t = time.monotonic()


class RequestQueue:
    """Bounded FIFO of ServeRequests with breaker-backed admission.

    Non-blocking ``submit``: breaker-gated; a full queue raises
    ``ServeOverloadError`` and counts a breaker failure (consecutive
    failures trip it open — subsequent submits shed immediately for
    ``reset_secs`` without touching the queue).  Blocking ``submit``:
    waits up to `timeout` for space (backpressure; no breaker
    involvement) and raises ``ServeOverloadError`` only on timeout.

    Weighted-fair pickup (ISSUE 14): internally one FIFO per tenant
    under the shared ``max_depth`` bound; ``get``/``get_nowait`` pick
    the next tenant by smooth weighted round-robin over the NON-EMPTY
    tenants (``fair_weights``, unlisted tenants weigh 1.0) and pop that
    tenant's head — per-tenant order stays FIFO, cross-tenant pickup
    interleaves by weight, and the single-tenant case is byte-for-byte
    the historical global FIFO.

    Metrics (serve/ namespace, SERVING.md): ``serve/queue_depth`` gauge,
    ``serve/submitted_total`` / ``serve/shed_total`` counters, and the
    admission breaker's ``resilience/serve.admission/*`` family.
    """

    def __init__(self, max_depth: int,
                 breaker: Optional[CircuitBreaker] = None,
                 registry: Optional[obs.Registry] = None,
                 fair_weights: Optional[Dict[str, float]] = None):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = max_depth
        # per-tenant FIFOs + TWO conditions over one lock (the stdlib
        # Queue discipline): producers blocked on space wait on
        # not_full, consumers on not_empty, and each side wakes exactly
        # ONE waiter per transition — notify_all here would cost
        # O(waiters) context switches per request under the
        # high-concurrency load the serve bench measures
        self._lock = locksan.make_lock("RequestQueue._lock")
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._buckets: "OrderedDict[str, Deque[ServeRequest]]" = \
            OrderedDict()
        self._size = 0
        self._weights: Dict[str, float] = dict(fair_weights or {})
        #: smooth-WRR credits, persistent across pickups so a tenant's
        #: fairness debt survives its bucket draining and refilling
        self._credits: Dict[str, float] = {}
        reg = registry if registry is not None else obs.registry()
        self._reg = reg
        # under sustained overload there is no point probing the queue
        # per request; a short reset window keeps shedding responsive
        # to recovery while bounding the lock traffic of hot rejection
        self._breaker = breaker if breaker is not None else CircuitBreaker(
            threshold=2 * max_depth, reset_secs=0.25,
            name="serve.admission", registry=reg)
        self._closed = False
        self._g_depth = reg.gauge("serve/queue_depth")
        self._c_submitted = reg.counter("serve/submitted_total")
        self._c_shed = reg.counter("serve/shed_total")

    @property
    def breaker(self) -> CircuitBreaker:
        return self._breaker

    def close(self) -> None:
        """Refuse all further submits (pending requests stay queued for
        the drain; ``drain_reject`` empties them with typed errors)."""
        self._closed = True

    @property
    def closed(self) -> bool:
        return self._closed

    def submit(self, req: ServeRequest, block: bool = False,
               timeout: Optional[float] = None) -> None:
        """Admit `req` or raise ``ServeOverloadError``/``ServeClosedError``.

        The request's queue clock restarts here: admission time is when
        the deadline-from-enqueue semantics begin for queue-wait
        accounting."""
        if self._closed:
            raise ServeClosedError("serving queue is closed")
        if not block and not self._breaker.allow():
            # labeled child rolls up into the unlabeled total, so the
            # per-tenant split (ISSUE 15 cost accounting) is free and
            # the aggregate dashboards keep their historical meaning
            self._c_shed.labels(tenant=req.tenant or "default").inc()
            obs.spans.request_event(self._reg, "shed", req.trace, req.uuid,
                                    cause="breaker_open")
            raise ServeOverloadError(
                "request shed: admission breaker open (sustained overload)")
        req.enqueue_t = time.monotonic()
        # lifecycle root event BEFORE the queue put: the instant the
        # request becomes visible to the dispatch thread it may emit
        # admit/slot/resolve, and those must never precede enqueue in
        # the stream (a full-queue bounce turns the trace into
        # enqueue -> shed — an honest timeline for a request that
        # reached the queue and bounced)
        obs.spans.request_event(self._reg, "enqueue", req.trace, req.uuid,
                                depth=self._size)
        if not self._put(req, block, timeout):
            if not block:
                self._breaker.record_failure()
            self._c_shed.labels(tenant=req.tenant or "default").inc()
            obs.spans.request_event(self._reg, "shed", req.trace, req.uuid,
                                    cause="queue_full")
            raise ServeOverloadError(
                f"serve queue full (depth {self.max_depth}); request "
                f"{req.uuid!r} rejected") from None
        if not block:
            self._breaker.record_success()
        self._c_submitted.inc()
        self._g_depth.set(self._size)

    def _put(self, req: ServeRequest, block: bool,
             timeout: Optional[float]) -> bool:
        """Append `req` to its tenant's FIFO; False when full (after
        waiting up to `timeout` in blocking mode)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while self._size >= self.max_depth:
                if not block:
                    return False
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return False
                # loop on the PREDICATE, never on wait()'s verdict: a
                # wake that races the timeout consumes the notify, and
                # shedding here would bounce a request against a queue
                # that just freed a slot (the stdlib Queue.put
                # discipline — the next iteration's remaining<=0 check
                # is what enforces the deadline)
                self._not_full.wait(remaining)
            self._buckets.setdefault(req.tenant or "",
                                     deque()).append(req)
            self._size += 1
            self._not_empty.notify()
        return True

    def _pick_tenant(self) -> str:
        """Smooth weighted round-robin over the NON-EMPTY tenant FIFOs
        (caller holds the condition lock, size > 0): every candidate
        earns its weight in credit, the richest one pays the round's
        total back and is picked — over time each tenant's pickup share
        converges to weight/sum(weights) regardless of backlog depth.
        Deterministic: insertion order breaks ties (strict >), so the
        virtual-time SLO gate replays exactly."""
        total = 0.0
        best: Optional[str] = None
        for tenant, bucket in self._buckets.items():
            if not bucket:
                continue
            w = self._weights.get(tenant, 1.0)
            total += w
            credit = self._credits.get(tenant, 0.0) + w
            self._credits[tenant] = credit
            if best is None or credit > self._credits[best]:
                best = tenant
        assert best is not None  # caller guarantees size > 0
        self._credits[best] -= total
        return best

    def _pop(self) -> Optional[ServeRequest]:
        """Pop the next request by fair pickup (caller holds the lock);
        None when empty."""
        if self._size == 0:
            return None
        tenant = self._pick_tenant()
        bucket = self._buckets[tenant]
        req = bucket.popleft()
        if not bucket:
            # drop the empty FIFO so the pickup scan stays proportional
            # to the ACTIVE tenant count (credits persist separately —
            # but bounded: past MAX_TENANT_CREDITS, idle tenants'
            # residual debt is pruned rather than leaked)
            del self._buckets[tenant]
            if len(self._credits) > MAX_TENANT_CREDITS:
                for t in [t for t in self._credits
                          if t not in self._buckets]:
                    if len(self._credits) <= MAX_TENANT_CREDITS:
                        break
                    del self._credits[t]
        self._size -= 1
        self._not_full.notify()
        return req

    def get(self, timeout: float = 0.05) -> Optional[ServeRequest]:
        """Next request by weighted-fair pickup, or None after
        `timeout` seconds idle."""
        deadline = time.monotonic() + timeout
        with self._lock:
            while self._size == 0:
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._not_empty.wait(remaining):
                    if self._size == 0:
                        return None
            req = self._pop()
        self._g_depth.set(self._size)
        return req

    def get_nowait(self) -> Optional[ServeRequest]:
        with self._lock:
            req = self._pop()
        if req is None:
            return None
        self._g_depth.set(self._size)
        return req

    def qsize(self) -> int:
        return self._size

    def empty(self) -> bool:
        return self._size == 0

    def drain_reject(self, error: BaseException) -> int:
        """Reject every still-queued request with `error` (hard stop);
        returns the number rejected."""
        n = 0
        while True:
            req = self.get_nowait()
            if req is None:
                return n
            req.future._reject(error)
            n += 1
