"""Supervised OS-process replicas + socket transport (ISSUE 17 tentpole;
SERVING.md "Process fleet", RESILIENCE.md "Process-grain failover").

The in-process fleet (serve/fleet.py) shares one address space: a
replica "kill" is a cooperative method call, and a wedged replica can
still take the whole process down with it.  This module breaks the
process boundary — each replica runs as its OWN supervised OS child
(``cli.py serve-replica``), so the failure unit the chaos suite
SIGKILLs is a real pid and the blast radius of a crash is one process:

  * ``ReplicaProcess``   — the supervisor for ONE child: spawn (HParams
    over the ``TS_HPS_JSON`` env), readiness handshake (the child
    publishes its bound ports through an atomically-renamed portfile,
    then must answer ``/healthz`` with status "ok" AND its own pid —
    a stale portfile left by a previous incarnation can never pass),
    restart-on-death under ``RetryPolicy`` decorrelated-jitter backoff,
    and crash-loop containment: a child that dies ``threshold``
    consecutive times without a stable run trips a ``CircuitBreaker``
    — held out of rotation for the reset window, flight-dumped
    (``flight_replica_crashloop.<rid>.jsonl``), surfaced on ``/alerts``
    as an incident, and thereafter restarted only at the breaker's
    half-open probe cadence, never spun forever.
  * ``RemoteReplica``    — the wire-side ``ServingServer`` surface the
    router drives: submits travel one persistent ingress socket as
    newline-delimited ``pipeline.io.Message`` JSON frames; results
    stream back over a reply socket read through ``ResilientSource``
    (reconnect + bounded-LRU dedup on ``(uuid, seq)`` — the child
    replays its retained reply ring on every reconnect, so replays are
    expected and deduped, while a RE-submitted uuid carries a fresh
    seq and passes).  A child death fails every in-flight future with
    the typed ``ReplicaKilledError`` the router's requeue path already
    understands — reconstructed purely from the supervisor's view
    (socket EOF + process exit), because a SIGKILLed child writes
    nothing on its way out.
  * ``RemoteReplicaHandle`` — the rotation view: ``healthy()`` scrapes
    the child's real ``/healthz`` (timeout-bounded, interval-cached so
    a wedged child costs ONE timeout per cache window, never a frozen
    router tick) and enforces pid incarnation.
  * ``ProcFleet``        — assembles N (supervisor, remote, handle)
    triples under one ``FleetRouter`` plus a supervision thread that
    ticks restarts and fires the ``serve.proc_kill`` chaos point
    (SIGKILL the most-loaded live child, never the last one standing).
  * ``replica_child_main`` — the child entry point behind
    ``python -m textsummarization_on_flink_tpu.cli serve-replica``.

Exactly-once over flaky transport, end to end: the child's reply hub
assigns every outcome frame a monotonic ``seq`` and retains a bounded
ring; the supervisor's reader dedups ``(uuid, seq)``; the router-level
``_Routed`` future settles first-wins.  At-least-once delivery + dedup
+ single-settle = exactly-once, the same ledger the in-process fleet
proves, now across a process boundary.

The in-process fleet stays the default fast path and test substrate
(``serve_fleet_transport=inproc``); ``proc`` opts into real processes.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.obs import export as obs_export
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs import http as obs_http
from textsummarization_on_flink_tpu.obs import locksan
from textsummarization_on_flink_tpu.pipeline.io import Message, ResilientSource
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.resilience.policy import (
    CircuitBreaker,
    RetryPolicy,
)
from textsummarization_on_flink_tpu.serve.errors import (
    ReplicaKilledError,
    ServeClosedError,
    ServeError,
    ServeOverloadError,
    TenantThrottledError,
)
from textsummarization_on_flink_tpu.serve.queue import ServeFuture
from textsummarization_on_flink_tpu.serve.router import ReplicaHandle

log = logging.getLogger(__name__)

LOOPBACK = "127.0.0.1"

# env contract between supervisor and child (all strings)
ENV_HPS = "TS_HPS_JSON"          # HParams.to_json() — the child's config
ENV_REPLICA_ID = "TS_REPLICA_ID"  # stamps events/flight dumps (ISSUE 15)
ENV_PORTFILE = "TS_PORTFILE"     # where the child publishes bound ports
ENV_IN_FLEET = "TS_REPLICA_IN_FLEET"  # "1": disarm door + ingress count
ENV_STUB = "TS_REPLICA_STUB"     # "1": stub engine (process-machinery tests)
ENV_STUB_STEP_MS = "TS_REPLICA_STUB_STEP_MS"  # stub per-chunk wall cost

# the reply wire row: dedup key first (ResilientSource dedups row[0])
_REPLY_SCHEMA = ("dedup_key", "message")

# wire error name -> typed exception the router's requeue/shed logic
# already dispatches on; anything else arrives as plain ServeError
_WIRE_ERRORS: Dict[str, type] = {
    "ReplicaKilledError": ReplicaKilledError,
    "ServeClosedError": ServeClosedError,
    "ServeOverloadError": ServeOverloadError,
    "TenantThrottledError": TenantThrottledError,
    "ValueError": ValueError,
}


def _error_from_wire(wire: str) -> Exception:
    """``"ExcType: message"`` -> a typed exception (ServeError default)."""
    name, _, detail = wire.partition(":")
    cls = _WIRE_ERRORS.get(name.strip(), ServeError)
    return cls(detail.strip() or wire)


def _http_healthz(port: int, timeout_s: float) -> Optional[Dict[str, Any]]:
    """One timeout-bounded ``/healthz`` scrape -> payload dict or None.

    A 503 still carries the full payload (the "degraded" body), so it
    parses rather than erroring; only transport/parse failures are None.
    """
    url = f"http://{LOOPBACK}:{port}/healthz"
    try:
        try:
            with urllib.request.urlopen(url, timeout=timeout_s) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            body = e.read()
        payload = json.loads(body.decode("utf-8"))
        return payload if isinstance(payload, dict) else None
    except (OSError, ValueError):
        return None


# --------------------------------------------------------------------------
# Supervisor: one child process
# --------------------------------------------------------------------------

class ReplicaProcess:
    """Lifecycle supervisor for ONE replica child process.

    State machine (all transitions inside ``tick()``, driven by the
    fleet's supervision thread against an injectable clock):

        idle -> starting -> ready -> backoff -> starting -> ...
                                  \\-> stopped (graceful or halt)

    * starting: spawned, waiting for the portfile + a pid-matching
      ``/healthz`` "ok" within ``ready_timeout`` (miss = SIGKILL, death).
    * ready: serving; a poll() that returns is a death.
    * backoff: dead, next spawn gated by the RetryPolicy delay AND the
      crash-loop breaker — OPEN holds the replica out entirely;
      HALF_OPEN admits exactly one probe spawn, whose readiness (not
      mere survival) records the success that re-closes.
    * stopped: terminal; ``stop()`` walks the SIGTERM -> wait(term_grace)
      -> SIGKILL escalation ladder, ``halt()`` goes straight to SIGKILL.

    Crash-loop containment: ``threshold`` consecutive deaths without a
    ``crashloop_window``-long stable run trip the breaker; the first
    trip flight-dumps ``replica_crashloop`` and files an ``/alerts``
    incident.  A stable run records one success first, so the
    consecutive-death count measures a LOOP, not lifetime bad luck.
    """

    IDLE, STARTING, READY, BACKOFF, STOPPED = (
        "idle", "starting", "ready", "backoff", "stopped")

    def __init__(self, rid: str, cmd: List[str], env: Dict[str, str],
                 state_dir: str,
                 registry: Optional[obs.Registry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 ready_timeout: float = 60.0,
                 term_grace: float = 5.0,
                 restart_base_delay: float = 0.05,
                 restart_max_delay: float = 2.0,
                 seed: int = 0,
                 crashloop_threshold: int = 3,
                 crashloop_window: float = 30.0,
                 scrape_timeout: float = 0.25,
                 on_death: Optional[Callable[[Optional[int]], None]] = None,
                 on_ready: Optional[Callable[["ReplicaProcess"], None]] = None):
        self.rid = rid
        self.cmd = list(cmd)
        self.portfile = os.path.join(state_dir, f"replica-{rid}.ports.json")
        self._env = dict(env)
        self._env[ENV_REPLICA_ID] = rid
        self._env[ENV_PORTFILE] = self.portfile
        self._reg = registry if registry is not None else obs.registry()
        self._clock = clock
        self.ready_timeout = ready_timeout
        self.term_grace = term_grace
        self.crashloop_window = crashloop_window
        self._scrape_timeout = scrape_timeout
        self.on_death = on_death
        self.on_ready = on_ready
        # the crash-loop breaker IS the containment policy: consecutive
        # deaths trip it, reset_secs is the hold-out window, half-open
        # admits the single probe spawn
        self.breaker = CircuitBreaker(
            threshold=crashloop_threshold, reset_secs=crashloop_window,
            name=f"serve.replica.{rid}.crashloop", clock=clock,
            registry=self._reg)
        self._policy = RetryPolicy(
            base_delay=restart_base_delay, max_delay=restart_max_delay,
            seed=seed, name=f"serve.replica.{rid}.restart",
            registry=self._reg)
        self._c_deaths = self._reg.counter(
            "serve/replica_deaths_total").labels(replica=rid)
        self._c_restarts = self._reg.counter(
            "serve/replica_restarts_total").labels(replica=rid)
        self._c_crashloops = self._reg.counter(
            "serve/replica_crashloops_total").labels(replica=rid)
        self._lock = locksan.make_rlock("ReplicaProcess._lock")
        self.state = self.IDLE
        self.proc: Optional[subprocess.Popen] = None
        self.incarnation = 0
        self.deaths = 0
        self.last_exit_code: Optional[int] = None
        self._ports: Optional[Dict[str, Any]] = None
        self._ready_deadline = 0.0
        self._ready_at: Optional[float] = None
        self._next_restart_t = 0.0
        self._contained = False

    # -- queries --

    def ready(self) -> bool:
        with self._lock:
            return (self.state == self.READY and self.proc is not None
                    and self.proc.poll() is None)

    def pid(self) -> int:
        with self._lock:
            return self.proc.pid if self.proc is not None else -1

    def ports(self) -> Optional[Dict[str, Any]]:
        """The child's published port map, or None until the CURRENT
        incarnation has written it.  The portfile's own pid field is the
        staleness defense: a file left by a previous (or foreign)
        incarnation never resolves."""
        with self._lock:
            if self._ports is not None:
                return self._ports
            if self.proc is None:
                return None
            pid = self.proc.pid
        try:
            with open(self.portfile, "r", encoding="utf-8") as f:
                d = json.load(f)
        except (OSError, ValueError):
            return None
        if not isinstance(d, dict) or d.get("pid") != pid:
            return None  # stale incarnation — not OUR child's ports
        with self._lock:
            if self.proc is not None and self.proc.pid == pid:
                self._ports = d
        return d

    # -- lifecycle --

    def start(self) -> None:
        """Spawn the first incarnation (idempotent; terminal after
        stop/halt)."""
        with self._lock:
            if self.state == self.STOPPED:
                raise ServeClosedError(f"replica {self.rid} is stopped")
            if self.state == self.IDLE:
                self._spawn()

    def tick(self) -> None:
        """One supervision step: readiness probe, death detection,
        backoff-gated restart.  Never blocks past one scrape timeout."""
        with self._lock:
            state = self.state
            proc = self.proc
        if state == self.STARTING:
            assert proc is not None
            code = proc.poll()
            if code is not None:
                self._on_exit(code)
                return
            if self._check_ready():
                self._mark_ready()
                return
            if self._clock() >= self._ready_deadline:
                # wedged before ever answering /healthz: a hung child is
                # a dead child with worse manners — SIGKILL and account
                # it as a death (feeds the crash-loop breaker too)
                log.error("replica %s: not ready after %.1fs; killing",
                          self.rid, self.ready_timeout)
                self._signal(signal.SIGKILL)
                try:
                    proc.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    pass
                self._on_exit(proc.poll())
            return
        if state == self.READY:
            assert proc is not None
            code = proc.poll()
            if code is not None:
                self._on_exit(code)
            return
        if state == self.BACKOFF:
            if self._clock() < self._next_restart_t:
                return
            # the containment gate: OPEN sheds the restart entirely;
            # HALF_OPEN hands out the single probe spawn
            if not self.breaker.allow():
                return
            with self._lock:
                if self.state == self.BACKOFF:
                    self._spawn()

    def kill_now(self) -> bool:
        """SIGKILL the live child (the ``serve.proc_kill`` chaos action
        and the smoke's mid-decode kill).  Supervision continues — the
        next tick detects the death and schedules the restart."""
        with self._lock:
            proc = self.proc
        if proc is not None and proc.poll() is None:
            self._signal(signal.SIGKILL)
            return True
        return False

    def halt(self) -> None:
        """Permanent SIGKILL-now stop (router ``kill_replica``
        semantics: the replica never rejoins)."""
        with self._lock:
            self.state = self.STOPPED
            proc = self.proc
        if proc is not None and proc.poll() is None:
            self._signal(signal.SIGKILL)
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass

    def stop(self) -> None:
        """Graceful stop: SIGTERM -> wait(term_grace) -> SIGKILL ->
        wait.  Terminal."""
        with self._lock:
            self.state = self.STOPPED
            proc = self.proc
        if proc is None or proc.poll() is not None:
            return
        self._signal(signal.SIGTERM)
        try:
            proc.wait(timeout=self.term_grace)
        except subprocess.TimeoutExpired:
            log.warning("replica %s: SIGTERM grace %.1fs expired; "
                        "escalating to SIGKILL", self.rid, self.term_grace)
            self._signal(signal.SIGKILL)
            try:
                proc.wait(timeout=5.0)
            except subprocess.TimeoutExpired:
                pass

    def restart_for_swap(self) -> None:
        """Rolling-swap restart: graceful ladder down, immediate fresh
        spawn (no backoff — this death was ASKED for, it must not feed
        the crash-loop count either)."""
        self.stop()
        with self._lock:
            self.state = self.IDLE
            self._spawn()

    # -- internals --

    def _spawn(self) -> None:
        # caller holds the lock
        try:
            os.unlink(self.portfile)
        except OSError:
            pass
        self._ports = None
        self._ready_at = None
        self.incarnation += 1
        if self.incarnation > 1:
            self._c_restarts.inc()
        self.proc = subprocess.Popen(self.cmd, env=self._env)
        self.state = self.STARTING
        self._ready_deadline = self._clock() + self.ready_timeout
        log.info("replica %s: spawned incarnation %d (pid %d)",
                 self.rid, self.incarnation, self.proc.pid)

    def _check_ready(self) -> bool:
        ports = self.ports()
        if ports is None:
            return False
        payload = _http_healthz(int(ports["obs_port"]), self._scrape_timeout)
        if payload is None:
            return False
        # incarnation identity: the scraped process must be the child we
        # spawned, not a survivor of a previous run squatting the port
        if payload.get("pid") != self.pid():
            return False
        return payload.get("status") == "ok"

    def _mark_ready(self) -> None:
        with self._lock:
            if self.breaker.state == CircuitBreaker.HALF_OPEN:
                # the probe spawn reached readiness: the loop is broken
                self.breaker.record_success()
                self._contained = False
            self.state = self.READY
            self._ready_at = self._clock()
        log.info("replica %s: ready (incarnation %d)",
                 self.rid, self.incarnation)
        if self.on_ready is not None:
            self.on_ready(self)

    def _on_exit(self, code: Optional[int]) -> None:
        now = self._clock()
        with self._lock:
            self.deaths += 1
            self.last_exit_code = code
            self._c_deaths.inc()
            # a crashloop_window-long stable run resets the CONSECUTIVE
            # death count — the breaker measures a loop, not a lifetime
            if (self._ready_at is not None
                    and now - self._ready_at >= self.crashloop_window):
                self.breaker.record_success()
            self.breaker.record_failure()
            tripped = (self.breaker.state == CircuitBreaker.OPEN
                       and not self._contained)
            if tripped:
                self._contained = True
            self._ports = None
            self._next_restart_t = now + self._policy.next_delay()
            if self.state != self.STOPPED:
                self.state = self.BACKOFF
        log.warning("replica %s: child died (exit %s, death %d)",
                    self.rid, code, self.deaths)
        if tripped:
            self._contain(code)
        if self.on_death is not None:
            self.on_death(code)

    def _contain(self, code: Optional[int]) -> None:
        """First breaker trip: count, flight-dump, file the incident.
        Restarts from here on happen only at half-open probe cadence."""
        self._c_crashloops.inc()
        log.error("replica %s: crash loop contained after %d deaths "
                  "(window %.1fs); held out of rotation",
                  self.rid, self.deaths, self.crashloop_window)
        flightrec.trigger(self._reg, "replica_crashloop",
                          replica=self.rid, exit_code=code,
                          deaths=self.deaths,
                          window_s=self.crashloop_window)
        obs_http.add_incident(self._reg, "replica_crashloop",
                              replica=self.rid, exit_code=code,
                              deaths=self.deaths,
                              window_s=self.crashloop_window)

    def _signal(self, sig: int) -> None:
        with self._lock:
            proc = self.proc
        if proc is None:
            return
        try:
            os.kill(proc.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            pass


# --------------------------------------------------------------------------
# Supervisor: the wire-side server surface
# --------------------------------------------------------------------------

class _ReaderStopped(Exception):
    """Raised inside the reply factory to end the reader thread; NOT an
    OSError, so ResilientSource surfaces it instead of reconnecting."""


class _RemoteResult:
    """The resolved value of one remote decode: the DecodedResult
    surface downstream consumers read (summary/tier/fingerprint for the
    router's cache insert, ``as_row`` for sinks) rebuilt from the reply
    frame plus the submit-time registration."""

    __slots__ = ("uuid", "article", "summary", "reference", "tier",
                 "degraded", "params_fingerprint", "decoded_words")

    def __init__(self, uuid: str, article: str, summary: str,
                 reference: str, tier: str, params_fingerprint: str = ""):
        self.uuid = uuid
        self.article = article
        self.summary = summary
        self.reference = reference
        self.tier = tier
        self.degraded = False
        self.params_fingerprint = params_fingerprint
        self.decoded_words = summary.split()

    def as_row(self) -> Tuple[str, str, str, str]:
        return (self.uuid, self.article, self.summary, self.reference)


class _ReplySource:
    """pipeline.io Source over the child's reply socket.

    Yields ``((uuid, seq), Message)`` rows — the composite dedup key is
    what makes ring REPLAY (same uuid, same seq) collapse under
    ResilientSource's LRU while a router RE-submit of the same uuid
    (fresh seq) passes.  Port resolution happens inside ``rows()``: the
    wrapping ResilientSource constructs sources outside its retry
    window, so every fallible step must live in the iterator.

    EOF is NOT a clean end here: the child closing the stream means it
    died or restarted, so ``rows()`` raises ConnectionResetError to
    force the reconnect path (ResilientSource treats a clean return as
    stream-complete and would end supervision of a live fleet).
    """

    schema = _REPLY_SCHEMA

    def __init__(self, ports_fn: Callable[[], Optional[Dict[str, Any]]],
                 connect_timeout: float,
                 on_socket: Callable[[Optional[socket.socket]], None],
                 malformed_counter: Any):
        self._ports_fn = ports_fn
        self._timeout = connect_timeout
        self._on_socket = on_socket
        self._c_malformed = malformed_counter

    def rows(self):
        ports = self._ports_fn()  # raises _ReaderStopped on shutdown
        if ports is None:
            raise ConnectionRefusedError("reply port not published yet")
        sock = socket.create_connection(
            (LOOPBACK, int(ports["reply_port"])), timeout=self._timeout)
        self._on_socket(sock)
        try:
            sock.settimeout(None)  # stream reads block until EOF/close
            with sock.makefile("r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        d = json.loads(line)
                        seq = int(d.get("seq", -1))
                        msg = Message(uuid=d.get("uuid", ""),
                                      article=d.get("article", ""),
                                      summary=d.get("summary", ""),
                                      reference=d.get("reference", ""),
                                      tier=d.get("tier", ""),
                                      error=d.get("error", ""))
                    except (ValueError, TypeError, AttributeError):
                        self._c_malformed.inc()
                        log.warning("dropping malformed reply frame: %.120r",
                                    line)
                        continue
                    yield ((msg.uuid, seq), msg)
        finally:
            self._on_socket(None)
            try:
                sock.close()
            except OSError:
                pass
        raise ConnectionResetError(
            "reply stream EOF (child died or restarted)")


class RemoteReplica:
    """The ``ServingServer`` surface of one CHILD PROCESS, as the
    FleetRouter drives it: ``submit`` frames the request onto the
    ingress socket and returns a local ServeFuture; the reply-reader
    thread settles it from the child's outcome frame; a child death
    fails everything in flight with ``ReplicaKilledError`` so the
    router's existing requeue path replays orphans on survivors."""

    def __init__(self, rid: str, proc: ReplicaProcess, hps: Any,
                 registry: Optional[obs.Registry] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.rid = rid
        self._proc = proc
        self._hps = hps
        self._router_reg = registry if registry is not None else obs.registry()
        # the identity registry the FleetRouter stamps (flight dumps,
        # /fleet source map); supervisor-side, so near-empty — the
        # child's real telemetry lives in ITS process
        self.registry = obs.Registry()
        self._clock = clock
        #: back-reference to the rotation handle (set by ProcFleet) so a
        #: detected death removes the replica from rotation immediately
        self.handle: Optional[ReplicaHandle] = None
        timeout_ms = getattr(hps, "serve_scrape_timeout_ms", 250.0)
        self._timeout_s = max(0.001, timeout_ms / 1000.0)
        interval_ms = getattr(hps, "serve_scrape_interval_ms", 50.0)
        self._scrape_interval_s = max(0.0, interval_ms / 1000.0)
        self._capacity = (int(getattr(hps, "serve_max_queue", 64))
                          + max(int(getattr(hps, "serve_slots", 0)),
                                int(getattr(hps, "serve_max_batch", 1)), 1))
        self._c_scrape_errors = self._router_reg.counter(
            "serve/replica_scrape_errors_total").labels(replica=rid)
        self._c_malformed = self._router_reg.counter(
            "serve/replica_reply_malformed_total").labels(replica=rid)
        self._lock = locksan.make_lock("RemoteReplica._lock")
        self._pending: Dict[str, List[Tuple[ServeFuture, str, str, str]]] = {}
        self._killed = False
        self._closed = False
        self._ingress_lock = locksan.make_lock("RemoteReplica._ingress_lock")
        self._ingress_sock: Optional[socket.socket] = None
        # guards the scrape cache + fingerprint (written by the router
        # thread AND the supervisor callbacks); the HTTP scrape itself
        # runs OUTSIDE it — a wedged child must not stall cache readers
        self._scrape_lock = locksan.make_lock("RemoteReplica._scrape_lock")
        self._reader: Optional[threading.Thread] = None
        self._reader_stop = threading.Event()
        self._reply_sock: Optional[socket.socket] = None
        self._scrape_cache: Optional[Dict[str, Any]] = None
        self._scrape_cache_t = -1.0
        self._fingerprint = ""

    # -- ServingServer surface --

    @property
    def killed(self) -> bool:
        return self._killed

    @property
    def params_fingerprint(self) -> str:
        """The child's last-scraped active fingerprint (rolling-swap
        bookkeeping; "" until a successful scrape reports one)."""
        return self._fingerprint

    def submit(self, article: str, uuid: str = "", reference: str = "",
               block: bool = False, timeout: Optional[float] = None,
               tier: str = "", trace: Optional[Any] = None,
               tenant: str = "") -> ServeFuture:
        """Frame one request onto the child's ingress socket.

        Typed shed semantics match the in-process server: closed/killed
        raises ``ServeClosedError``; a not-ready child, a full pending
        window, or a transport failure raise ``ServeOverloadError`` (a
        router-visible failure that trips the rotation breaker without
        burning the request)."""
        if self._killed or self._closed:
            raise ServeClosedError(f"replica {self.rid} is closed")
        if not self._proc.ready():
            raise ServeOverloadError(
                f"replica {self.rid} process is not ready")
        fut = ServeFuture(uuid, registry=self._router_reg)
        fut.trace = trace
        fut.scope = "replica"
        with self._lock:
            n = sum(len(v) for v in self._pending.values())
            if n >= self._capacity:
                raise ServeOverloadError(
                    f"replica {self.rid} pending window full "
                    f"({n}/{self._capacity})")
            # register BEFORE the send: the reply can race the return
            self._pending.setdefault(uuid, []).append(
                (fut, article, reference, tier))
        line = Message(uuid=uuid, article=article, reference=reference,
                       tier=tier).to_json()
        try:
            self._send_ingress(line)
        except OSError as e:
            with self._lock:
                entries = self._pending.get(uuid)
                if entries:
                    entries[:] = [t for t in entries if t[0] is not fut]
                    if not entries:
                        del self._pending[uuid]
            raise ServeOverloadError(
                f"replica {self.rid} ingress send failed: {e}") from e
        if block:
            fut.result(timeout)
        return fut

    def load(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._pending.values())

    def stats(self) -> Dict[str, Any]:
        """The router-facing stats view, off the scrape cache (the
        child's admission breaker arrives via /healthz's breakers map —
        a remote can only ever see the scraped state)."""
        payload = self.scrape_healthz()
        breakers = (payload or {}).get("breakers", {})
        return {
            "queue_depth": self.load(),
            "serve_mode": getattr(self._hps, "serve_mode", ""),
            "admission": breakers.get("serve.admission",
                                      CircuitBreaker.CLOSED),
        }

    def start(self) -> None:
        self._proc.start()
        if self._reader is None or not self._reader.is_alive():
            self._reader_stop.clear()
            self._reader = threading.Thread(
                target=self._reader_main,
                name=f"ts-reply-reader-{self.rid}", daemon=True)
            self._reader.start()

    def idle(self) -> bool:
        """Drained: nothing pending HERE and the child reports an empty
        queue (rolling-swap gate)."""
        if self.load() > 0:
            return False
        payload = self.scrape_healthz()
        if payload is None:
            return False
        serve = payload.get("serve", {})
        return not serve.get("queue_depth", 0)

    def hot_swap(self) -> bool:
        """Rolling swap at process grain: restart the child, which
        reloads the newest checkpoint on boot.  Readmission happens via
        the rotation breaker's half-open probe once the fresh
        incarnation scrapes healthy."""
        try:
            self._proc.restart_for_swap()
            return True
        except Exception:  # tslint: disable=TS005 — logged and reported as a failed swap; the router counts it in serve/swaps_failed_total and keeps the old incarnation serving
            log.exception("replica %s: swap restart failed", self.rid)
            return False

    def kill(self, error: Optional[BaseException] = None) -> int:
        """Permanent kill (router ``kill_replica``): SIGKILL the child,
        stop supervising it, fail everything in flight."""
        err = error if error is not None else ReplicaKilledError(
            f"replica {self.rid} killed")
        self._killed = True
        self._proc.halt()
        n = self._fail_pending(err)
        self._close_ingress()
        return n

    def stop(self, timeout: float = 60.0) -> None:
        """Graceful stop: drain in-flight replies, walk the child down
        the SIGTERM escalation ladder, fail any leftovers typed."""
        self._closed = True
        deadline = time.monotonic() + max(0.0, timeout)
        while self.load() > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        self._proc.stop()
        self._stop_reader()
        self._fail_pending(ServeClosedError(
            f"replica {self.rid} stopped with requests in flight"))
        self._close_ingress()

    def disable_ingress_tracking(self) -> None:
        pass  # the CHILD disarms its own counting (TS_REPLICA_IN_FLEET)

    def disable_front_door(self) -> None:
        pass  # likewise — router-level door is the only armed one

    # -- scrape path (RemoteReplicaHandle.healthy reads through this) --

    def scrape_healthz(self) -> Optional[Dict[str, Any]]:
        """Timeout-bounded, interval-cached ``/healthz`` scrape.

        The cache holds FAILURES too: a wedged child costs one
        ``serve_scrape_timeout_ms`` wait per ``serve_scrape_interval_ms``
        window, never a timeout per router tick."""
        now = self._clock()
        with self._scrape_lock:
            if (self._scrape_cache_t >= 0.0
                    and now - self._scrape_cache_t < self._scrape_interval_s):
                return self._scrape_cache
        # cache miss: scrape with NO lock held (two racing misses cost
        # one duplicate probe, last-write-wins — cheaper than every
        # reader waiting out a wedged child's timeout behind the lock)
        payload = None
        ports = self._proc.ports()
        if ports is not None:
            payload = _http_healthz(int(ports["obs_port"]), self._timeout_s)
        if payload is None:
            self._c_scrape_errors.inc()
        with self._scrape_lock:
            if payload is not None:
                fp = payload.get("serve", {}).get("params_fingerprint", "")
                if fp:
                    self._fingerprint = fp
            self._scrape_cache = payload
            self._scrape_cache_t = now
        return payload

    @property
    def pid(self) -> int:
        return self._proc.pid()

    # -- death / transport internals --

    def on_child_ready(self, proc: ReplicaProcess) -> None:
        """Supervisor readiness notification: drop the (negative) scrape
        cache so the router's next health probe sees the fresh
        incarnation instead of waiting out the cache window."""
        with self._scrape_lock:
            self._scrape_cache = None
            self._scrape_cache_t = -1.0

    def on_child_death(self, exit_code: Optional[int]) -> None:
        """Supervisor death notification: every in-flight future fails
        with the typed ``ReplicaKilledError`` the router requeues on —
        reconstructed purely from the supervisor's view (process exit +
        reply-socket EOF); a SIGKILLed child wrote nothing."""
        n = self._fail_pending(ReplicaKilledError(
            f"replica {self.rid} process died (exit {exit_code}) "
            f"with the request in flight"))
        if n:
            log.warning("replica %s: failed %d in-flight request(s) on "
                        "child death", self.rid, n)
        self._close_ingress()
        with self._scrape_lock:
            self._scrape_cache = None
            self._scrape_cache_t = -1.0  # next health read scrapes fresh
        h = self.handle
        if (h is not None and not h.killed
                and h.breaker.state == CircuitBreaker.CLOSED):
            # out of rotation NOW — don't wait for the next failed scrape
            h.breaker.record_failure()

    def _fail_pending(self, err: BaseException) -> int:
        with self._lock:
            pending = self._pending
            self._pending = {}
        n = 0
        for entries in pending.values():
            for fut, _, _, _ in entries:
                try:
                    fut._reject(err)
                    n += 1
                except Exception:  # tslint: disable=TS005 — a poisoned callback on one future must not strand its siblings unsettled; the rejection itself is the typed failure path
                    log.exception("replica %s: failed settling a future",
                                  self.rid)
        return n

    def _send_ingress(self, line: str) -> None:
        data = (line + "\n").encode("utf-8")
        for attempt in (0, 1):
            try:
                with self._ingress_lock:
                    sock = self._ingress_sock
                if sock is None:
                    # connect with NO lock held: a slow or refusing
                    # child costs the connecting thread one timeout,
                    # not every sender queued behind the lock (TS008)
                    ports = self._proc.ports()
                    if ports is None:
                        raise ConnectionRefusedError(
                            "ingress port not published")
                    fresh = socket.create_connection(
                        (LOOPBACK, int(ports["ingress_port"])),
                        timeout=self._timeout_s)
                    fresh.settimeout(self._timeout_s)
                    with self._ingress_lock:
                        if self._ingress_sock is None:
                            self._ingress_sock = fresh
                        else:
                            fresh.close()  # racing connector won
                with self._ingress_lock:
                    sock = self._ingress_sock
                    if sock is None:
                        raise OSError("ingress socket closed mid-send")
                    sock.sendall(data)  # tslint: disable=TS008 — one socket, interleaving-free framing REQUIRES serializing writers; bounded by settimeout(_timeout_s) above
                return
            except OSError:
                with self._ingress_lock:
                    self._close_ingress_locked()
                if attempt:
                    raise

    def _close_ingress(self) -> None:
        with self._ingress_lock:
            self._close_ingress_locked()

    def _close_ingress_locked(self) -> None:
        if self._ingress_sock is not None:
            try:
                self._ingress_sock.close()
            except OSError:
                pass
            self._ingress_sock = None

    def _stop_reader(self) -> None:
        self._reader_stop.set()
        sock = self._reply_sock
        if sock is not None:
            try:
                sock.close()  # unblocks the stream read with an OSError
            except OSError:
                pass
        reader = self._reader
        if reader is not None and reader.is_alive():
            reader.join(timeout=5.0)

    def _register_reply_sock(self, sock: Optional[socket.socket]) -> None:
        self._reply_sock = sock

    def _reply_factory(self) -> _ReplySource:
        return _ReplySource(self._reader_ports, self._timeout_s,
                            self._register_reply_sock, self._c_malformed)

    def _reader_ports(self) -> Optional[Dict[str, Any]]:
        if self._reader_stop.is_set():
            raise _ReaderStopped()
        return self._proc.ports()

    def _reader_sleep(self, delay: float) -> None:
        # interruptible backoff: shutdown never waits out a full delay
        if self._reader_stop.wait(delay):
            raise _ReaderStopped()

    def _reader_main(self) -> None:
        # ResilientSource IS the exactly-once reply transport: reconnect
        # with backoff across child restarts, LRU-dedup on (uuid, seq)
        # so ring replay collapses while re-submitted uuids pass
        src = ResilientSource(
            self._reply_factory, max_reconnects=1_000_000,
            base_delay=0.02, max_delay=0.5, seed=0,
            dedup=True, dedup_window=65536, schema=_REPLY_SCHEMA,
            sleep=self._reader_sleep)
        try:
            for _, msg in src.rows():
                self._on_reply(msg)
        except _ReaderStopped:
            pass
        except Exception:  # tslint: disable=TS005 — terminal reader failure: logged loudly; in-flight futures still fail typed via the death path, never silently hang
            if not self._reader_stop.is_set():
                log.exception("replica %s: reply reader died", self.rid)

    def _on_reply(self, msg: Message) -> None:
        with self._lock:
            entries = self._pending.get(msg.uuid)
            if not entries:
                # orphan frame: the future already settled (death path
                # beat the reply, or a replay outran the dedup window).
                # Dropping it is what keeps resolution exactly-once.
                return
            fut, article, reference, tier = entries.pop(0)
            if not entries:
                del self._pending[msg.uuid]
        try:
            if msg.error:
                fut._reject(_error_from_wire(msg.error))
            else:
                fut._resolve(_RemoteResult(
                    uuid=msg.uuid, article=article, summary=msg.summary,
                    reference=reference, tier=msg.tier or tier,
                    params_fingerprint=self._fingerprint))
        except Exception:  # tslint: disable=TS005 — a poisoned done-callback must not kill the reader thread that settles every OTHER reply
            log.exception("replica %s: failed settling reply %s",
                          self.rid, msg.uuid)


class RemoteReplicaHandle(ReplicaHandle):
    """Rotation state for a process replica: health comes from a REAL
    ``/healthz`` scrape of the child (timeout-bounded + interval-cached
    in RemoteReplica), gated on pid incarnation — a handle can never
    call a previous incarnation healthy."""

    def __init__(self, rid: str, remote: RemoteReplica,
                 registry: Optional[obs.Registry],
                 clock: Callable[[], float] = time.monotonic,
                 reset_secs: float = 1.0):
        super().__init__(rid, remote, registry=registry, clock=clock,
                         reset_secs=reset_secs)
        self.remote = remote

    def healthy(self) -> bool:
        payload = self.remote.scrape_healthz()
        if payload is None:
            return False  # unreachable/timed out/not started == unhealthy
        if payload.get("pid") != self.remote.pid:
            return False  # stale incarnation answering on a reused port
        return payload.get("status") == "ok"

    def load(self) -> int:
        return self.remote.load()


# --------------------------------------------------------------------------
# The assembled process fleet
# --------------------------------------------------------------------------

class ProcFleet:
    """N supervised child replicas behind one FleetRouter.

        fleet = ProcFleet(hps, registry=reg)
        fleet.start()
        fleet.wait_ready(timeout=60)
        fut = fleet.router.submit(article, uuid="u1")
        ...
        fleet.stop()

    The supervision thread ticks every child's restart state machine
    (~20 Hz) and fires the ``serve.proc_kill`` chaos point: SIGKILL the
    most-loaded live child, never the last one standing.  The router is
    the stock serve/fleet.py one — it adopts the pre-built
    RemoteReplicaHandles, so routing, requeue, hedging, and rolling
    swap are EXACTLY the in-process code paths over the wire surface.
    """

    SUPERVISE_PERIOD_S = 0.05

    def __init__(self, hps: Any,
                 registry: Optional[obs.Registry] = None,
                 state_dir: Optional[str] = None,
                 child_argv: Optional[List[str]] = None,
                 child_env: Optional[Dict[str, str]] = None,
                 clock: Callable[[], float] = time.monotonic,
                 stub: bool = False,
                 replicas: Optional[int] = None,
                 ready_timeout: float = 60.0,
                 term_grace: float = 5.0,
                 crashloop_threshold: int = 3,
                 crashloop_window: float = 30.0,
                 restart_base_delay: float = 0.05,
                 restart_max_delay: float = 2.0,
                 replica_reset_secs: float = 1.0,
                 faults: Optional[Any] = None):
        n = replicas if replicas is not None \
            else int(getattr(hps, "serve_replicas", 1))
        if n < 1:
            raise ValueError(f"a process fleet needs >= 1 replica, got {n}")
        self._hps = hps
        self._reg = registry if registry is not None \
            else obs.registry_for(hps)
        self.state_dir = state_dir or tempfile.mkdtemp(prefix="ts-procfleet-")
        argv = list(child_argv) if child_argv is not None else [
            sys.executable, "-m", "textsummarization_on_flink_tpu.cli",
            "serve-replica"]
        base_env = dict(os.environ if child_env is None else child_env)
        base_env[ENV_HPS] = hps.to_json()
        base_env[ENV_IN_FLEET] = "1"
        if stub:
            base_env[ENV_STUB] = "1"
        scrape_timeout_s = max(
            0.001, getattr(hps, "serve_scrape_timeout_ms", 250.0) / 1000.0)
        self.procs: List[ReplicaProcess] = []
        self.remotes: List[RemoteReplica] = []
        self.handles: List[RemoteReplicaHandle] = []
        handle_map: Dict[str, RemoteReplicaHandle] = {}
        for i in range(n):
            rid = f"p{i}"
            proc = ReplicaProcess(
                rid, argv, dict(base_env), self.state_dir,
                registry=self._reg, clock=clock,
                ready_timeout=ready_timeout, term_grace=term_grace,
                restart_base_delay=restart_base_delay,
                restart_max_delay=restart_max_delay, seed=i,
                crashloop_threshold=crashloop_threshold,
                crashloop_window=crashloop_window,
                scrape_timeout=scrape_timeout_s)
            remote = RemoteReplica(rid, proc, hps, registry=self._reg,
                                   clock=clock)
            handle = RemoteReplicaHandle(rid, remote, registry=self._reg,
                                         clock=clock,
                                         reset_secs=replica_reset_secs)
            remote.handle = handle
            proc.on_death = remote.on_child_death
            proc.on_ready = remote.on_child_ready
            self.procs.append(proc)
            self.remotes.append(remote)
            self.handles.append(handle)
            handle_map[rid] = handle
        self._faults = faults if faults is not None \
            else faultinject.plan_for(hps)
        # import here, not at module top: fleet.py imports router/obs
        # back and the lazy serve/__init__ hook keeps the cycle shallow
        from textsummarization_on_flink_tpu.serve.fleet import FleetRouter

        self.router = FleetRouter(handle_map, hps, registry=self._reg,
                                  clock=clock, faults=self._faults)
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ProcFleet":
        """Spawn every child + reader, start routing + supervision."""
        self.router.start()  # calls RemoteReplica.start() per replica
        if self._thread is None or not self._thread.is_alive():
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._supervise_loop, name="ts-procfleet-supervise",
                daemon=True)
            self._thread.start()
        return self

    def wait_ready(self, timeout: float = 60.0) -> bool:
        """Block until every non-stopped child is ready AND its handle
        is back in routing rotation (True), or the deadline passes
        (False).  Rotation matters: a requeue can only land on an
        IN-ROTATION survivor, so callers that start killing before the
        rotation warmed up would see typed failures instead of
        failover."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            live = [p for p in self.procs if p.state != ReplicaProcess.STOPPED]
            if (live and all(p.ready() for p in live)
                    and all(h.in_rotation() for h in self.handles
                            if not h.killed)):
                return True
            time.sleep(0.02)
        return False

    def supervise_once(self) -> None:
        """One supervision pass (the thread's body; tests drive it
        directly for determinism)."""
        self._maybe_chaos_kill()
        for p in self.procs:
            try:
                p.tick()
            except Exception:  # tslint: disable=TS005 — one replica's broken state machine must not stop supervision of the others; the failure is logged every tick until fixed
                log.exception("supervision tick failed for replica %s",
                              p.rid)

    def stop(self, timeout: float = 60.0) -> None:
        """Supervision down FIRST (no restarts racing the shutdown),
        then the router's drain-and-stop walks each child down the
        SIGTERM escalation ladder."""
        self._stop_evt.set()
        if self._thread is not None and self._thread.is_alive():
            self._thread.join(timeout=5.0)
        self.router.stop(timeout=timeout)

    # -- internals --

    def _supervise_loop(self) -> None:
        while not self._stop_evt.wait(self.SUPERVISE_PERIOD_S):
            self.supervise_once()

    def _maybe_chaos_kill(self) -> None:
        if not self._faults.armed("serve.proc_kill"):
            return
        live = [p for p in self.procs if p.ready()]
        if len(live) < 2:
            return  # never orphan the whole fleet
        if not any(r.load() for r in self.remotes):
            return  # save the fire budget for a mid-decode kill
        if not self._faults.fire("serve.proc_kill"):
            return
        victim = max(live, key=lambda p: self._load_of(p.rid))
        log.warning("chaos: SIGKILLing replica %s (pid %d) mid-decode",
                    victim.rid, victim.pid())
        victim.kill_now()

    def _load_of(self, rid: str) -> int:
        for r in self.remotes:
            if r.rid == rid:
                return r.load()
        return 0


# --------------------------------------------------------------------------
# The child process
# --------------------------------------------------------------------------

class _ReplyHub:
    """The child's outcome-frame ledger: every settled request becomes
    one JSON frame stamped with a monotonic ``seq``, retained in a
    bounded ring.  Each reply connection REPLAYS the retained ring from
    the start before streaming new frames — at-least-once delivery; the
    supervisor's (uuid, seq) dedup makes it exactly-once."""

    def __init__(self, capacity: int = 65536):
        self._capacity = capacity
        self._cv = locksan.make_condition("_ReplyHub._cv")
        self._frames: List[str] = []
        self._base = 0  # absolute seq of _frames[0]
        self._next_seq = 0
        self._closed = False

    @property
    def capacity(self) -> int:
        """Ring size — must dominate one replica's in-flight capacity
        (SERVE_SLO.json process_fleet pins this) or a reconnect could
        replay past live work."""
        return self._capacity

    def publish(self, msg: Message) -> None:
        d = json.loads(msg.to_json())
        with self._cv:
            d["seq"] = self._next_seq
            self._next_seq += 1
            self._frames.append(json.dumps(d, sort_keys=True))
            overflow = len(self._frames) - self._capacity
            if overflow > 0:
                del self._frames[:overflow]
                self._base += overflow
            self._cv.notify_all()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def stream(self, start: int = 0):
        """Yield frames from absolute seq `start` (oldest retained if
        the ring already dropped it), blocking for new ones until
        close()."""
        idx = start
        while True:
            with self._cv:
                if idx < self._base:
                    idx = self._base
                while (not self._closed
                       and idx >= self._base + len(self._frames)):
                    self._cv.wait(0.5)
                if idx < self._base + len(self._frames):
                    frame = self._frames[idx - self._base]
                    idx += 1
                else:
                    return  # closed and drained
            yield frame


class _StubDecoder:
    """Continuous mode drives the engine; only the between-chunk
    hot-swap hook is ever consulted."""

    params_fingerprint = "stub"

    def maybe_reload_checkpoint(self, last: float) -> float:
        return last


class _StubEngine:
    """SlotDecodeEngine over wall-clock sleeps: each request occupies a
    slot for a couple of chunks so a SIGKILL mid-decode really orphans
    in-flight work.  Process-machinery tests only (TS_REPLICA_STUB) —
    no params, no jax dispatch, deterministic output."""

    CHUNKS_PER_REQUEST = 2

    def __init__(self, hps: Any, step_ms: float = 5.0):
        self.slots = int(getattr(hps, "serve_slots", 2))
        self.chunk = max(1, int(getattr(hps, "serve_refill_chunk", 1)))
        self._step_s = max(0.0, step_ms) / 1000.0
        self._remaining = [0] * self.slots
        self._active = [False] * self.slots

    def pack(self, idx: int, example: Any) -> None:
        self._active[idx] = True
        self._remaining[idx] = self.CHUNKS_PER_REQUEST

    def step(self) -> List[int]:
        time.sleep(self._step_s)
        fin = []
        for i in range(self.slots):
            if self._active[i]:
                self._remaining[i] -= 1
                if self._remaining[i] <= 0:
                    fin.append(i)
        return fin

    def unpack(self, idx: int, example: Any):
        from textsummarization_on_flink_tpu.decode.decoder import DecodedResult

        self._active[idx] = False
        return DecodedResult(
            uuid=example.uuid, article=example.original_article,
            decoded_words=["ok", "."], reference=example.reference,
            abstract_sents=[])

    def release(self, idx: int) -> None:
        self._active[idx] = False


def _build_child_server(hps: HParams, reg: obs.Registry, rid: str):
    """The child's ServingServer: stub engine for process-machinery
    tests, otherwise the real decoder over seed-deterministic params (or
    the newest checkpoint when a train_dir exists)."""
    from textsummarization_on_flink_tpu.data.vocab import Vocab
    from textsummarization_on_flink_tpu.serve.server import ServingServer

    if hps.vocab_path:
        vocab = Vocab(hps.vocab_path, hps.vocab_size)
    else:
        vocab = Vocab(words=[f"w{i}" for i in range(32)])
    decode_root = tempfile.mkdtemp(prefix=f"ts-replica-{rid}-decode-")
    if os.environ.get(ENV_STUB):
        step_ms = float(os.environ.get(ENV_STUB_STEP_MS, "5"))
        return ServingServer(hps, vocab, decoder=_StubDecoder(),
                             engine=_StubEngine(hps, step_ms=step_ms),
                             registry=reg, decode_root=decode_root)
    train_dir = os.path.join(hps.log_root or ".", hps.exp_name or "exp",
                             "train")
    if hps.log_root and os.path.isdir(train_dir):
        return ServingServer(hps, vocab, train_dir=train_dir,
                             registry=reg, decode_root=decode_root)
    from textsummarization_on_flink_tpu.train import trainer as trainer_lib

    # seed-deterministic init: every replica (and the parent's solo
    # baseline) materializes the SAME params from the same seed
    params = trainer_lib.init_train_state(
        hps, vocab.size(), seed=hps.seed).params
    return ServingServer(hps, vocab, params=params, registry=reg,
                         decode_root=decode_root)


def _child_submit(server: Any, hub: _ReplyHub, msg: Message) -> None:
    """Admit one ingress frame; every outcome (sync shed included)
    becomes exactly one reply frame."""
    try:
        fut = server.submit(msg.article, uuid=msg.uuid,
                            reference=msg.reference, tier=msg.tier,
                            block=False)
    except Exception as e:  # tslint: disable=TS005 — the catch IS the wire error path: the type+message cross back as an error frame and re-raise typed in the supervisor
        hub.publish(Message(uuid=msg.uuid, reference=msg.reference,
                            tier=msg.tier,
                            error=f"{type(e).__name__}: {e}"))
        return
    ref, tier = msg.reference, msg.tier

    def _done(f: Any) -> None:
        err = f.error
        if err is not None:
            hub.publish(Message(uuid=msg.uuid, reference=ref, tier=tier,
                                error=f"{type(err).__name__}: {err}"))
            return
        res = f.result()
        hub.publish(Message(uuid=msg.uuid, summary=res.summary,
                            reference=ref,
                            tier=getattr(res, "tier", tier) or tier))

    fut.add_done_callback(_done)


def _ingress_conn(conn: socket.socket, server: Any, hub: _ReplyHub) -> None:
    try:
        with conn, conn.makefile("r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = Message.from_json(line)
                except (ValueError, TypeError, KeyError):
                    log.warning("dropping malformed ingress frame: %.120r",
                                line)
                    continue
                _child_submit(server, hub, msg)
    except OSError:
        pass


def _reply_conn(conn: socket.socket, hub: _ReplyHub) -> None:
    try:
        with conn:
            # replay-from-start of the retained ring: at-least-once; the
            # supervisor's (uuid, seq) dedup collapses the replays
            for frame in hub.stream(0):
                conn.sendall((frame + "\n").encode("utf-8"))
    except OSError:
        pass


def _accept_loop(listener: socket.socket, stop_evt: threading.Event,
                 handler: Callable[[socket.socket], None],
                 name: str) -> None:
    while not stop_evt.is_set():
        try:
            conn, _ = listener.accept()
        except OSError:
            return  # listener closed at shutdown
        t = threading.Thread(target=handler, args=(conn,),
                             name=name, daemon=True)
        t.start()


def replica_child_main(argv: Optional[List[str]] = None) -> int:
    """The ``cli.py serve-replica`` entry point: build the ServingServer
    from TS_HPS_JSON, bind obs-HTTP + ingress + reply sockets on
    ephemeral ports, publish them through the portfile handshake, serve
    until SIGTERM."""
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    argv = list(argv or [])
    rid = os.environ.get(ENV_REPLICA_ID, "p0")
    hps_json = os.environ.get(ENV_HPS, "")
    hps = HParams.from_json(hps_json) if hps_json \
        else HParams.from_argv(argv)
    hps.validate()
    reg = obs.Registry()
    flightrec.set_replica_id(reg, rid)  # before any frame is recorded
    if hps.log_root:
        child_dir = os.path.join(hps.log_root, hps.exp_name or "exp",
                                 f"replica-{rid}")
        os.makedirs(child_dir, exist_ok=True)
        obs_export.install_event_sink(reg, child_dir)
        flightrec.install_flight_recorder(reg, child_dir)
    server = _build_child_server(hps, reg, rid)
    if os.environ.get(ENV_IN_FLEET):
        # behind a router the ROUTER owns the caller-visible request
        # count and the front door; mirror serve/fleet.py's disarm
        server.disable_ingress_tracking()
        server.disable_front_door()
    stop_evt = threading.Event()
    signal.signal(signal.SIGTERM, lambda s, f: stop_evt.set())
    signal.signal(signal.SIGINT, lambda s, f: stop_evt.set())

    obs_srv = obs_http.ObsHttpServer(reg, port=0).start()
    hub = _ReplyHub()
    ingress = socket.create_server((LOOPBACK, 0))
    reply = socket.create_server((LOOPBACK, 0))
    server.start()
    threading.Thread(
        target=_accept_loop,
        args=(ingress, stop_evt,
              lambda c: _ingress_conn(c, server, hub), "ts-ingress"),
        name="ts-ingress-accept", daemon=True).start()
    threading.Thread(
        target=_accept_loop,
        args=(reply, stop_evt, lambda c: _reply_conn(c, hub), "ts-reply"),
        name="ts-reply-accept", daemon=True).start()

    # the readiness handshake: ports land in the portfile ATOMICALLY
    # (tmp + rename — the supervisor never reads a torn write) once the
    # server is actually accepting; pid stamps the incarnation
    ports = {
        "pid": os.getpid(),
        "start_time": time.time(),
        "replica_id": rid,
        "obs_port": obs_srv.port,
        "ingress_port": ingress.getsockname()[1],
        "reply_port": reply.getsockname()[1],
    }
    portfile = os.environ.get(ENV_PORTFILE, "")
    if portfile:
        tmp = portfile + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(ports, f, sort_keys=True)
        os.replace(tmp, portfile)
    print(json.dumps(ports, sort_keys=True), flush=True)
    log.info("replica %s serving (pid %d, obs=%d ingress=%d reply=%d)",
             rid, ports["pid"], ports["obs_port"], ports["ingress_port"],
             ports["reply_port"])

    while not stop_evt.wait(0.2):
        pass
    log.info("replica %s: SIGTERM — draining and stopping", rid)
    try:
        server.stop(timeout=10.0)
    finally:
        sink = reg.event_sink
        if sink is not None:
            # a SIGTERM'd survivor is the chaos gate's WITNESS: its
            # events.jsonl must carry every buffered lifecycle record
            try:
                sink.close()
            except Exception:  # tslint: disable=TS005 — best-effort flush on the shutdown path; a sink failure must not block the child's exit ladder
                log.exception("event sink close failed")
        hub.close()
        for s in (ingress, reply):
            try:
                s.close()
            except OSError:
                pass
        obs_srv.close()
    return 0


__all__ = [
    "ProcFleet", "RemoteReplica", "RemoteReplicaHandle", "ReplicaProcess",
    "replica_child_main",
]
