"""Typed failure vocabulary for the serving subsystem (ISSUE 4).

Both errors subclass ``resilience.errors.ResilienceError`` so generic
resilience handlers (and the pre-existing RuntimeError handlers above
them) keep working:

  * ``ServeOverloadError`` — the admission controller rejected the
    request: the bounded queue is full, or the admission circuit
    breaker is open and the request was shed before touching the queue
    (the BreakerSink load-shedding semantics, RESILIENCE.md).  The
    request was NEVER enqueued; the caller may retry with backoff.
  * ``ServeClosedError`` — the server is stopping/stopped; submissions
    are refused and any request still queued at hard-stop is rejected
    with this.
  * ``TenantThrottledError`` — the request's tenant is over its
    token-bucket admission rate (the front door's per-tenant shed,
    SERVING.md "Front door"); a subclass of ``ServeOverloadError`` so
    overload handlers keep working.
  * ``ReplicaKilledError`` — the replica serving this request died
    mid-decode (chaos ``serve.replica_kill``, or a real crash surfaced
    through ``ServingServer.kill``).  The FleetRouter routes on exactly
    this type: a killed replica's residents and queued requests
    re-enqueue on survivors (SERVING.md "Elastic fleet"), so a caller
    only ever sees it when the whole fleet is gone.

Import-light by design (no jax/numpy): callers catch these in
admission paths that must stay cheap.
"""

from __future__ import annotations

from textsummarization_on_flink_tpu.resilience.errors import ResilienceError


class ServeError(ResilienceError):
    """Base class for serving-subsystem failures."""


class ServeOverloadError(ServeError):
    """Admission control rejected the request (queue full / breaker
    open); it was never enqueued.  Retry with backoff, or shed."""


class ServeClosedError(ServeError):
    """The serving server is stopped (or stopping); no new requests."""


class TenantThrottledError(ServeOverloadError):
    """The request's TENANT is over its token-bucket admission rate
    (serve_tenant_rate, SERVING.md "Front door"); the request was shed
    before touching the queue or the admission breaker — one tenant's
    burst spends its own bucket, never the shared queue.  Subclasses
    ``ServeOverloadError`` so existing overload handlers (retry with
    backoff, shed) keep working unchanged."""


class ReplicaKilledError(ServeError):
    """The replica holding this request died mid-decode; the request is
    requeue-eligible (the FleetRouter re-enqueues it on a survivor)."""


class HierPartialFailureError(ServeError):
    """A hierarchical document request (serve/hiersum.py) could not
    complete its map-reduce: one or more chunk sub-requests — or the
    reduce pass — failed with a typed cause.  Raised on the PARENT
    future exactly once, and only after every outstanding chunk future
    has resolved (no orphaned sub-requests); the per-chunk verdicts
    ride ``failed`` keyed by chunk index (or the string "reduce").

    Partial output is never fabricated: a document summary missing a
    chunk would be a silently-wrong answer, which is worse than a typed
    failure the caller can retry (SERVING.md "Hierarchical
    summarization")."""

    def __init__(self, uuid: str, failed: dict, chunks: int):
        self.uuid = uuid
        #: {chunk index | "reduce": the sub-request's typed error}
        self.failed = failed
        #: total chunk fan-out width of the document
        self.chunks = chunks
        parts = ", ".join(
            f"{k}: {type(v).__name__}" for k, v in sorted(
                failed.items(), key=lambda kv: str(kv[0])))
        super().__init__(
            f"hierarchical request {uuid!r}: {len(failed)} of "
            f"{chunks} chunk sub-request(s) failed ({parts})")
