"""FleetRouter: an elastic serving fleet over N ServingServer replicas
(ISSUE 13 tentpole; SERVING.md "Elastic fleet").

One ``ServingServer`` is one process; millions of users need N replicas
that can be drained, upgraded, and LOST mid-decode without losing a
single admitted request — the Flink job-topology story (PAPER.md: one
App, workers come and go under a coordinator) rebuilt on this repo's
own substrate.  The router fronts in-process replicas first (the
pipeline/io socket path is the named follow-on); each replica keeps its
own registry, queue, breaker, and dispatch loop — the router only ever
talks to the same surfaces an external router would scrape
(/healthz-shaped health, queue-depth/slots-free load, typed submit
errors).

Four capabilities, each fleet-level exactly-once:

  * **Least-loaded routing** (serve/router.py): submits go to the
    least-loaded IN-ROTATION replica; a replica with a stale heartbeat
    or an open admission breaker is removed from rotation (per-replica
    ``resilience/serve.replica.<id>/*`` breaker) and readmitted through
    that breaker's single-in-flight half-open probe.
  * **Request hedging**: once a routed request has been outstanding
    longer than ``serve_hedge_ms``, the router duplicates it to a
    second replica; the FIRST resolution wins through the router-level
    ``ServeFuture`` (the loser's result is discarded — never
    double-resolved).  A hedge is a PURCHASED duplicate (FastSeq:
    throughput comes from never doing redundant work), so wins and
    waste are both counted (``serve/hedges_total``,
    ``serve/hedge_wins_total``, ``serve/hedge_suppressed_total``) and
    spend is capped at ``serve_hedge_max_ratio`` of admissions.
  * **Rolling checkpoint hot-swap**: ``start_rolling_swap()`` walks the
    fleet replica-at-a-time — drain (stop routing to it, let its
    backlog finish) -> ``ServingServer.hot_swap()`` (the existing
    between-batch atomic reload, forced) -> readmit — so at most one
    replica is ever out for upgrade and no replica ever serves from a
    half-swapped (full, draft) pair (the per-replica params lock
    guarantees pair atomicity; the router guarantees one-at-a-time).
  * **Chaos-tested failover**: the ``serve.replica_kill`` fault point
    (or ``kill_replica()``) kills a replica mid-decode; its residents
    and prefill-queue entries reject typed through the server's
    ``fail_resident``/``fail_pending`` path and the router REQUEUES
    them on survivors (tagged with a ``requeued`` trace event), so
    every admitted request still resolves exactly once.  Replica death
    triggers a flight-recorder dump (``flight_replica_kill.jsonl``).

Exactly-once is held at the ROUTER future: every replica attempt
(primary, hedge, requeue) is an ordinary replica-level request whose
own future resolves exactly once; the router's ``_Routed`` bookkeeping
settles the caller-visible future on the first success (or the last
outstanding failure) and discards everything after.

Determinism hook: the router needs no thread — ``tick()`` advances
health refresh, the swap state machine, chaos, and the hedge scan one
round at a time, and replicas expose ``tick_once()`` — so the
virtual-time SLO gate (tests/test_serve_slo.py "fleet") drives the REAL
router + batchers single-threaded with an injected clock, no sleeps.
``start()`` runs the same ``tick()`` on a background thread for
production use.  Import-light: no jax.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs import http as obs_http
from textsummarization_on_flink_tpu.obs import locksan
from textsummarization_on_flink_tpu.obs import slo as slo_lib
from textsummarization_on_flink_tpu.resilience import faultinject
from textsummarization_on_flink_tpu.serve.errors import (
    ReplicaKilledError,
    ServeClosedError,
    ServeOverloadError,
)
from textsummarization_on_flink_tpu.serve.frontdoor import FrontDoor
from textsummarization_on_flink_tpu.serve.queue import (
    ServeFuture,
    track_rejection,
    track_request,
)
from textsummarization_on_flink_tpu.serve.router import (
    ReplicaHandle,
    fleet_fingerprint,
    pick_replica,
    refresh_rotation,
)

log = logging.getLogger(__name__)


class _Routed:
    """One router-level request: the caller-visible future plus the
    attempt bookkeeping that makes first-wins exactly-once.

    ``_outstanding`` counts replica attempts whose futures have not yet
    reported back; a SUCCESS settles immediately (first wins), an ERROR
    settles only when it is the last attempt standing (a hedge twin or
    a requeued copy may still win)."""

    __slots__ = ("uuid", "article", "reference", "tier", "tenant",
                 "future", "ctx",
                 "submit_t", "hedged", "requeues", "tried", "_outstanding",
                 "_settled", "_last_error", "_lock")

    def __init__(self, uuid: str, article: str, reference: str, tier: str,
                 future: ServeFuture, ctx: Optional[obs.TraceContext],
                 submit_t: float, tenant: str = ""):
        self.uuid = uuid
        self.article = article
        self.reference = reference
        self.tier = tier
        self.tenant = tenant
        self.future = future
        self.ctx = ctx
        self.submit_t = submit_t
        self.hedged = False
        self.requeues = 0
        self.tried: set = set()  # replica ids this request ever ran on
        self._outstanding = 0
        self._settled = False
        self._last_error: Optional[BaseException] = None
        self._lock = locksan.make_lock("_Routed._lock")

    def add_outstanding(self) -> None:
        with self._lock:
            self._outstanding += 1

    def drop_outstanding(self) -> None:
        """Retire an attempt that was REPLACED (requeue).  Normally the
        replacement is still outstanding and this only decrements — but
        if the replacement ALREADY reported a deferred error in the
        window between its registration and this drop, the phantom
        slot being retired is what kept offer_error from settling, so
        settle here (otherwise the caller's future would hang)."""
        error: Optional[BaseException] = None
        with self._lock:
            self._outstanding -= 1
            if (self._outstanding <= 0 and not self._settled
                    and self._last_error is not None):
                self._settled = True
                error = self._last_error
        if error is not None:
            self.future._reject(error)

    def offer_result(self, result: Any) -> bool:
        """First success wins; later offers are discarded (False)."""
        with self._lock:
            self._outstanding -= 1
            if self._settled:
                return False
            self._settled = True
        self.future._resolve(result)
        return True

    def offer_error(self, error: BaseException) -> bool:
        """An attempt failed terminally.  Rejects the caller's future
        only when NO other attempt is still outstanding (a surviving
        hedge/requeue twin may yet win); returns True when it did."""
        with self._lock:
            self._outstanding -= 1
            if self._settled:
                return False
            self._last_error = error
            if self._outstanding > 0:
                return False
            self._settled = True
            error = self._last_error
        self.future._reject(error)
        return True

    def force(self, error: BaseException) -> bool:
        """Shutdown backstop: settle an unresolved future typed."""
        with self._lock:
            if self._settled:
                return False
            self._settled = True
        self.future._reject(error)
        return True


class _SwapState:
    """Rolling hot-swap progress: replica order + cursor (advanced one
    phase per router tick — drain, then swap+readmit)."""

    __slots__ = ("order", "idx")

    def __init__(self, order: List[str]):
        self.order = order
        self.idx = 0


class FleetRouter:
    """Health-aware router over N in-process ServingServer replicas.

        servers = [ServingServer(hps, vocab, ..., registry=Registry())
                   for _ in range(3)]
        router = FleetRouter(servers, hps)
        with router:                     # starts replicas + router tick
            fut = router.submit("article text .", uuid="u1")
            result = fut.result(timeout=30)
            router.rolling_swap()        # replica-at-a-time upgrade

    Replicas should be constructed with their OWN registries (each
    carries per-replica gauges — two replicas sharing one registry
    fight over ``serve/queue_depth``); the router shares its event sink
    into replica registries that lack one, so one ``events.jsonl``
    carries every request's full cross-replica lifecycle.  `clock` is
    injectable (virtual-time gates); `registry` defaults through
    ``obs.registry_for(hps)`` like every other component.
    """

    def __init__(self, replicas, hps: Any,
                 registry: Optional[obs.Registry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 tick_secs: float = 0.005,
                 replica_reset_secs: float = 1.0,
                 faults: Optional[Any] = None):
        self._hps = hps
        self._clock = clock
        self._tick_secs = tick_secs
        self._reg = registry if registry is not None \
            else obs.registry_for(hps)
        if isinstance(replicas, Mapping):
            items = list(replicas.items())
        else:
            items = [(f"r{i}", s) for i, s in enumerate(replicas)]
        if not items:
            raise ValueError("a fleet needs at least one replica")
        self._handles: Dict[str, ReplicaHandle] = {}
        self._handle_list: List[ReplicaHandle] = []
        for rid, server in items:
            if isinstance(server, ReplicaHandle):
                # pre-built handle (ISSUE 17: the proc transport's
                # RemoteReplicaHandle carries its own scrape-cached
                # health/load reads): adopt it as-is — its id wins over
                # the positional default
                h = server
                rid = h.rid
                server = h.server
            else:
                h = ReplicaHandle(rid, server, registry=self._reg,
                                  clock=clock,
                                  reset_secs=replica_reset_secs)
            self._handles[rid] = h
            self._handle_list.append(h)
            # fleet identity (ISSUE 15 satellite): stamp the replica id
            # on each replica's registry so its request events and
            # flight-recorder frames/dump FILENAMES carry it — replica
            # 2's flight_serve_dispatch dump can never clobber or
            # shadow replica 0's in a shared log directory
            rreg = server.registry
            if rreg.enabled and rreg is not self._reg:
                flightrec.set_replica_id(rreg, rid)
            if hasattr(server, "disable_ingress_tracking"):
                # behind a router the ROUTER future is the one
                # caller-visible request: a replica also tracking each
                # routed/hedged/requeued attempt would double-count
                # serve/requests_total and the SLO burn windows
                # (directly on shared-registry wiring, or through the
                # /fleet/* merge on per-replica registries)
                server.disable_ingress_tracking()
        # hedging knobs, precomputed (the scan is a hot loop)
        self._hedge_s = max(0.0, float(
            getattr(hps, "serve_hedge_ms", 0.0))) / 1000.0
        self._hedge_ratio = float(
            getattr(hps, "serve_hedge_max_ratio", 0.1))
        self._max_requeues = max(1, len(items) - 1)
        self._faults = faults if faults is not None \
            else faultinject.plan_for(hps)
        # the fleet-level front door (ISSUE 14; SERVING.md "Front
        # door"): coalescing/caching dedup ACROSS replicas and tenant
        # tokens are charged once, here — so each replica's own door is
        # disarmed below.  Cache lookups key on the fleet's COMMON
        # fingerprint; mid-rolling-swap (replicas disagreeing) the
        # lookup side goes dark rather than serve one snapshot's
        # summary under another's key (inserts still file under the
        # decode-time fingerprint riding each result).
        self._door = FrontDoor(hps, registry=self._reg,
                               fingerprint=self._fleet_fingerprint,
                               clock=clock, faults=self._faults)
        for h in self._handle_list:
            if hasattr(h.server, "disable_front_door"):
                h.server.disable_front_door()
        self._lock = locksan.make_lock("FleetRouter._lock")
        self._inflight: List[_Routed] = []
        self._n_submitted = 0
        self._n_hedges = 0
        self._swap: Optional[_SwapState] = None
        self._closed = False
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # fleet telemetry (OBSERVABILITY.md; rotation breakers ride the
        # resilience/* wildcard family)
        self._c_submitted = self._reg.counter("serve/fleet_submitted_total")
        self._c_requests = self._reg.counter("serve/requests_total")
        self._c_hedges = self._reg.counter("serve/hedges_total")
        self._c_hedge_wins = self._reg.counter("serve/hedge_wins_total")
        self._c_hedge_suppressed = self._reg.counter(
            "serve/hedge_suppressed_total")
        self._c_requeued = self._reg.counter("serve/requeued_total")
        self._c_kills = self._reg.counter("serve/replica_kills_total")
        self._c_swaps = self._reg.counter("serve/fleet_swaps_total")
        self._g_rotation = self._reg.gauge("serve/replicas_in_rotation")
        self._g_rotation.set(len(self._handle_list))
        # failure flight recorder: replica death must leave the ticks
        # preceding it behind (same wiring rationale as ServingServer)
        if (self._reg.enabled and getattr(hps, "flight_frames", 0) > 0
                and getattr(hps, "log_root", "")):
            flightrec.install_flight_recorder(
                self._reg, os.path.join(hps.log_root,
                                        hps.exp_name or "exp"),
                capacity=hps.flight_frames)
        # one events.jsonl for the whole fleet: share the router's sink
        # into replica registries that have none, so a request's
        # replica-side lifecycle (enqueue/admit/slot/...) lands in the
        # same stream as the router's route/hedge/requeued events
        sink = self._reg.event_sink
        if sink is not None:
            for h in self._handle_list:
                rreg = h.server.registry
                if rreg.enabled and rreg.event_sink is None:
                    rreg.event_sink = sink
        # the fleet aggregation plane (ISSUE 15 tentpole, piece 3):
        # /fleet/metrics and /fleet/snapshot merge over this ordered
        # {replica_id: Registry} map — wired onto the router's registry
        # AND every replica's, so whichever registry happens to own the
        # process exposition port (obs_http.maybe_serve is first-caller
        # -wins and replicas construct before the router) can answer
        if self._reg.enabled:
            self._reg.fleet_sources = self._fleet_registries
            for h in self._handle_list:
                rreg = h.server.registry
                if rreg.enabled and rreg.fleet_sources is None:
                    rreg.fleet_sources = self._fleet_registries
            obs_http.maybe_serve(self._reg, hps)
        # per-tenant/per-tier SLO burn-rate engine at the FLEET ingress
        # (obs/slo.py): the router-level future is the caller-visible
        # exactly-once resolution, so latency/error classification
        # happens here, over the router's (possibly virtual) clock
        slo_lib.install_slo_engine(self._reg, clock=clock)

    # -- lifecycle --
    def start(self) -> "FleetRouter":
        if self._thread is not None:
            return self
        for h in self._handle_list:
            if not h.killed:
                h.server.start()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="fleet-router")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.tick()
            self._stop.wait(self._tick_secs)

    def stop(self, timeout: Optional[float] = 60.0) -> None:
        """Refuse new submits, stop the tick thread, drain every live
        replica (their stop() preserves exactly-once), then settle any
        future the drain somehow left behind — typed, never hung."""
        with self._lock:
            self._closed = True
        if self._thread is not None:
            self._stop.set()
            self._thread.join(timeout=10.0)
            self._thread = None
        for h in self._handle_list:
            if not h.killed:
                h.server.stop(timeout=timeout)
        leftovers = 0
        with self._lock:
            routed, self._inflight = list(self._inflight), []
        for r in routed:
            if r.force(ServeClosedError(
                    "fleet stopped before this request resolved")):
                leftovers += 1
        if leftovers:  # pragma: no cover - defensive backstop
            log.warning("fleet stop settled %d unresolved request(s) "
                        "typed", leftovers)
        # retire the /fleet/* source map everywhere it was wired: a
        # stopped fleet must not pin its replicas (and their decoders)
        # in memory through a long-lived registry, nor keep answering
        # scrapes with a dead fleet's registries
        for reg in (self._reg, *(h.server.registry
                                 for h in self._handle_list)):
            # == not `is`: a bound method is minted per attribute
            # access, but compares equal on (func, self) — which is
            # exactly "wired by THIS router"
            if getattr(reg, "fleet_sources", None) == \
                    self._fleet_registries:
                reg.fleet_sources = None

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, exc_type, exc, tb) -> None:
        self.stop()

    def _fleet_fingerprint(self) -> Optional[str]:
        """The fleet's cache-lookup fingerprint — the routing-policy
        helper ``router.fleet_fingerprint`` over this fleet's handles
        (None while live replicas disagree mid-swap: lookups go dark)."""
        return fleet_fingerprint(self._handle_list)

    def _fleet_registries(self) -> Dict[str, obs.Registry]:
        """The ordered {id: Registry} map the /fleet/* merge runs over
        (obs/registry.py merge_fleet_series).  The router's own
        registry rides first under ``router`` — in fleet mode the front
        door (and therefore the per-tenant hit/shed/hedge cost
        accounting) is router-owned, and a /fleet/snapshot audit that
        showed tenant spend but never tenant savings would lie.  Dead
        replicas stay listed: their counters are history the fleet view
        must keep summing, and their gauges stop updating honestly.
        Registries are deduplicated by IDENTITY: under shared-registry
        wiring (bench --serve-replicas shares ONE process registry
        across router and replicas) the merge must count each series
        once, not once per replica id."""
        out: Dict[str, obs.Registry] = {}
        seen = set()
        if self._reg.enabled:
            out["router"] = self._reg
            seen.add(id(self._reg))
        for h in self._handle_list:
            reg = h.server.registry
            if id(reg) in seen:
                continue
            seen.add(id(reg))
            out[h.rid] = reg
        return out

    def _track_request(self, fut: ServeFuture, tenant: str,
                       tier: str) -> ServeFuture:
        """Fleet-ingress accounting for one caller-visible future — the
        shared ``queue.track_request`` helper over the ROUTER future,
        so hedges/requeues resolve into one recorded outcome (replica
        ingress tracking is disabled at construction)."""
        track_request(self._reg, self._clock, fut, tenant, tier,
                      counter=self._c_requests)
        return fut

    # -- request API --
    def submit(self, article: str, uuid: str = "", reference: str = "",
               block: bool = False, timeout: Optional[float] = None,
               tier: str = "",
               trace: Optional[obs.TraceContext] = None,
               tenant: str = "") -> ServeFuture:
        """Route one request to the least-loaded in-rotation replica;
        returns the ROUTER-level future (resolves exactly once, from
        whichever replica attempt wins).  Raises the typed
        ``ServeOverloadError`` when no replica will take it (or
        ``TenantThrottledError`` when `tenant` is over its admission
        rate — charged once, here, never again per attempt).

        Front door (ISSUE 14): with caching/coalescing armed this is
        the fleet's ONE dedup point — a duplicate of an in-flight
        (content_hash, tier) attaches to the routed leader's
        exactly-once future, so hedging and kill-requeue happen UNDER
        the leader and every attached future resolves from whichever
        replica attempt finally wins; a cache hit resolves here without
        touching any replica.

        One TraceContext is minted here and threaded through every
        replica attempt, so the uuid's cross-replica lifecycle
        (enqueue -> route -> [kill -> requeued -> route] -> resolve)
        reconstructs from one events.jsonl (OBSERVABILITY.md).  An
        EXPLICIT ``trace`` wins over the mint (ISSUE 19): the
        hierarchical summarizer threads one PARENT context's children
        through every chunk sub-request, so a document's whole fan-out
        shares one trace_id across router and replicas alike."""
        with self._lock:
            if self._closed:
                raise ServeClosedError("fleet router is stopped")
        # normalize the tier BEFORE the door, exactly like
        # ServingServer.submit: "" and the explicit default must key
        # the same flight and the same cache entry, or identical
        # requests would split into separate decodes purely on how the
        # caller spelled the default
        tier = tier or getattr(self._hps, "serve_default_tier", "beam")
        flight = None
        if self._door.armed:
            try:
                self._door.admit_tenant(tenant, uuid)
            except ServeOverloadError:
                # a fleet-ingress shed is a BAD event for the SLO burn
                # windows, exactly like the standalone server's: the
                # router owns ingress tracking (replica tracking is
                # disabled), so without this a tenant-throttle outage
                # at the fleet front door reads as a healthy SLO
                track_rejection(self._reg, tenant, tier)
                raise
            kind, val = self._door.open(article, tier, uuid, reference,
                                        trace=trace, tenant=tenant)
            if kind in ("hit", "follower"):
                # hits and followers ARE fleet admissions (the counter's
                # documented meaning, and the hedge waste cap's
                # denominator — undercounting would suppress hedges far
                # below the committed ratio of real admitted traffic)
                with self._lock:
                    self._n_submitted += 1
                self._c_submitted.inc()
                return self._track_request(val, tenant, tier)
            if kind == "leader":
                flight = val
        ctx = trace if trace is not None else (
            obs.TraceContext.new() if self._reg.enabled else None)
        future = ServeFuture(uuid, registry=self._reg)
        future.trace = ctx
        future.scope = "fleet"  # the TERMINAL resolve in the trace
        routed = _Routed(uuid, article, reference, tier, future, ctx,
                         submit_t=self._clock(), tenant=tenant)
        try:
            last_error: Optional[BaseException] = None
            while True:
                with self._lock:
                    handle = pick_replica(self._handle_list,
                                          exclude=routed.tried)
                if handle is None:
                    if last_error is None:
                        last_error = ServeOverloadError(
                            f"no serving replica in rotation for request "
                            f"{uuid!r} ({len(self._handle_list)} "
                            f"configured)")
                    # surface the replicas' own typed verdict: a caller
                    # must be able to tell retryable overload from a
                    # terminal ServeClosedError (stopped replicas)
                    raise last_error
                err = self._attempt(routed, handle, block=block,
                                    timeout=timeout)
                if err is None:
                    break
                last_error = err
        except BaseException as e:
            # the leader never got routed — typed overload, or a
            # replica's own synchronous verdict (e.g. a tier the
            # replica cannot serve, which _attempt does NOT swallow):
            # the flight must die with it or every later duplicate
            # would attach to a leader that never existed and hang
            if flight is not None:
                self._door.abort(flight, e)
            if isinstance(e, ServeOverloadError):
                # every replica full (or a typed overload verdict): a
                # caller-visible shed, fed to the burn windows at the
                # ingress that owns this request's tracking
                track_rejection(self._reg, tenant, tier)
            raise
        with self._lock:
            self._inflight.append(routed)
            self._n_submitted += 1
        if flight is not None:
            self._door.commit(flight, future)
        self._c_submitted.inc()
        return self._track_request(future, tenant, tier)

    def _attempt(self, routed: _Routed, handle: ReplicaHandle,
                 hedge: bool = False, block: bool = False,
                 timeout: Optional[float] = None,
                 ) -> Optional[BaseException]:
        """One replica attempt: emit the route event, submit, wire the
        inner future into the router-level bookkeeping.  Returns None
        on success, the typed submit error on failure (the failure is
        also recorded against the replica's rotation breaker — a full
        or closed replica should shed load until its probe readmits
        it)."""
        obs.spans.request_event(
            self._reg, "route", routed.ctx, routed.uuid,
            replica=handle.rid, hedge=hedge)
        try:
            # tenant rides along only when named: the default ""
            # tenant keeps pre-tenant replica surfaces (external
            # routers' stubs) callable unchanged
            kw = {"tenant": routed.tenant} if routed.tenant else {}
            fut = handle.server.submit(
                routed.article, uuid=routed.uuid,
                reference=routed.reference, block=block, timeout=timeout,
                tier=routed.tier, trace=routed.ctx, **kw)
        except (ServeOverloadError, ServeClosedError) as e:
            handle.breaker.record_failure()
            return e
        routed.tried.add(handle.rid)
        routed.add_outstanding()
        fut.add_done_callback(
            lambda f: self._attempt_done(routed, handle, hedge, f))
        return None

    def _attempt_done(self, routed: _Routed, handle: ReplicaHandle,
                      hedge: bool, fut: ServeFuture) -> None:
        """A replica attempt reported back (any thread: a replica's
        dispatch thread, the kill path, a drain)."""
        err = fut.error
        if err is None:
            if routed.offer_result(fut.result()):
                if hedge:
                    self._c_hedge_wins.labels(
                        tenant=routed.tenant or "default").inc()
            return
        if isinstance(err, ReplicaKilledError) and self._requeue(
                routed, handle, err):
            return
        routed.offer_error(err)

    def _requeue(self, routed: _Routed, dead: ReplicaHandle,
                 err: BaseException) -> bool:
        """Re-enqueue a kill-orphaned request on a survivor (the
        failover path).  True when a new attempt is in flight; False
        falls through to normal error settlement."""
        if routed.future.done() or routed.requeues >= self._max_requeues:
            return False
        with self._lock:
            survivor = pick_replica(self._handle_list,
                                    exclude=routed.tried)
        if survivor is None:
            return False
        routed.requeues += 1
        self._c_requeued.inc()
        obs.spans.request_event(
            self._reg, "requeued", routed.ctx, routed.uuid,
            from_replica=dead.rid, to_replica=survivor.rid,
            cause=type(err).__name__)
        if self._attempt(routed, survivor) is not None:
            return False
        # the dead attempt is replaced, not reported: retire its
        # outstanding slot only AFTER the replacement registered, so
        # a concurrent twin's failure can never observe zero attempts
        routed.drop_outstanding()
        return True

    # -- fleet orchestration --
    def tick(self) -> None:
        """One router round: chaos -> rotation health refresh -> swap
        state machine -> hedge scan -> settled-request GC.  Driven by
        the router thread in production, or directly by deterministic
        harnesses (the fleet SLO gate) — same code either way."""
        self._maybe_chaos_kill()
        # burn-rate refresh once per router round: alert transitions
        # (and the slo_burn flight dump) fire on the router tick,
        # deterministically under the virtual-time gate
        slo_lib.evaluate(self._reg)
        for rid, what in refresh_rotation(self._handle_list):
            log.warning("replica %s %s rotation", rid,
                        "removed from" if what == "removed" else
                        "readmitted to")
        self._set_rotation_gauge()
        self._swap_step()
        self._hedge_scan(self._clock())
        with self._lock:
            n_inflight = len(self._inflight)
            swapping = self._swap is not None
            self._inflight = [r for r in self._inflight
                              if not r.future.done()]
        flightrec.record(
            self._reg, "fleet_tick",
            in_rotation=sum(h.in_rotation() for h in self._handle_list),
            inflight=n_inflight, swapping=swapping,
            hedges=self._n_hedges)

    def _set_rotation_gauge(self) -> None:
        self._g_rotation.set(
            sum(h.in_rotation() for h in self._handle_list))

    def _maybe_chaos_kill(self) -> None:
        """The ``serve.replica_kill`` injection point: when armed and
        firing, kill the most-loaded live replica (the one most likely
        to be mid-decode — that is the failover path worth testing),
        but never the last one standing."""
        if not self._faults.fire("serve.replica_kill"):
            return
        alive = [h for h in self._handle_list if not h.killed]
        if len(alive) <= 1:
            log.warning("serve.replica_kill fired with %d live replica(s);"
                        " refusing to kill the last one", len(alive))
            return
        victim = max(alive, key=lambda h: h.load())
        self.kill_replica(victim.rid)

    def kill_replica(self, rid: str,
                     error: Optional[BaseException] = None) -> int:
        """Kill one replica mid-decode (chaos, or surfacing a real
        death).  Its admitted requests reject typed through the
        server's fail paths and requeue on survivors via the router's
        attempt callbacks; returns the number the server rejected."""
        h = self._handles[rid]
        if h.killed:
            return 0
        h.killed = True
        self._c_kills.inc()
        # dump the ring BEFORE the rejection storm: the post-mortem
        # wants the fleet ticks strictly preceding the death
        flightrec.trigger(self._reg, "replica_kill", replica=rid,
                          load=h.server.load())
        err = error if error is not None else ReplicaKilledError(
            f"replica {rid!r} killed mid-decode")
        n = h.server.kill(err)
        self._set_rotation_gauge()
        log.warning("replica %s killed; %d request(s) rejected for "
                    "requeue on %d survivor(s)", rid, n,
                    sum(1 for x in self._handle_list if not x.killed))
        return n

    def start_rolling_swap(self) -> None:
        """Begin a replica-at-a-time checkpoint hot-swap: each tick
        advances drain -> swap -> readmit for one replica before moving
        to the next, so the fleet never has more than one replica out
        and every replica's (params, draft) pair swaps atomically
        behind its own lock."""
        with self._lock:
            if self._swap is not None:
                raise RuntimeError("rolling swap already in progress")
            order = [h.rid for h in self._handle_list if not h.killed]
            if not order:
                raise RuntimeError("no live replicas to swap")
            self._swap = _SwapState(order)

    def swap_active(self) -> bool:
        with self._lock:
            return self._swap is not None

    def rolling_swap(self, timeout: float = 120.0,
                     poll: float = 0.01) -> None:
        """Blocking convenience over ``start_rolling_swap``: returns
        when the whole fleet swapped.  Drives ticks itself when no
        router thread is running (replica dispatch threads still do
        the decoding)."""
        self.start_rolling_swap()
        end = time.monotonic() + timeout
        while self.swap_active():
            if self._thread is None:
                self.tick()
            if time.monotonic() > end:
                raise TimeoutError(
                    f"rolling swap did not finish in {timeout:.0f}s")
            time.sleep(poll)

    def _swap_step(self) -> None:
        """Advance the rolling-swap state machine one phase (tick-
        driven, no thread of its own): mark the cursor replica
        draining, wait for it to go idle, force its hot-swap, readmit,
        advance.  A swap FAILURE (e.g. an injected ckpt.load fault)
        leaves the replica serving its old snapshot and IN ROTATION —
        a bad checkpoint must degrade the upgrade, not the fleet."""
        with self._lock:
            sw = self._swap
        if sw is None:
            return
        handle: Optional[ReplicaHandle] = None
        while sw.idx < len(sw.order):
            h = self._handles[sw.order[sw.idx]]
            if h.killed:  # died while awaiting its turn: skip
                sw.idx += 1
                continue
            handle = h
            break
        if handle is None:
            with self._lock:
                self._swap = None
            log.info("rolling swap complete")
            return
        if not handle.draining:
            handle.draining = True  # routing skips it; backlog drains
            self._set_rotation_gauge()
            return
        if not handle.server.idle():
            return  # still draining; re-check next tick
        ok = handle.server.hot_swap()
        handle.draining = False
        self._set_rotation_gauge()
        self._c_swaps.inc()
        log.info("replica %s hot-swap %s; readmitted", handle.rid,
                 "succeeded" if ok else
                 "FAILED (serving on its previous snapshot)")
        sw.idx += 1

    def _hedge_scan(self, now: float) -> None:
        """Duplicate stragglers: any un-hedged, un-requeued in-flight
        request outstanding past ``serve_hedge_ms`` gets ONE twin on a
        different replica, budget permitting (the committed
        ``serve_hedge_max_ratio`` waste cap)."""
        if self._hedge_s <= 0.0:
            return
        with self._lock:
            due = [r for r in self._inflight
                   if not r.hedged and not r.requeues
                   and not r.future.done()
                   and now - r.submit_t >= self._hedge_s]
        for routed in due:
            with self._lock:
                allowed = (self._n_hedges + 1
                           <= self._hedge_ratio * self._n_submitted)
            if not allowed:
                self._c_hedge_suppressed.inc()
                continue
            with self._lock:
                twin = pick_replica(self._handle_list,
                                    exclude=routed.tried)
            if twin is None:
                continue  # nowhere to hedge to; the primary stands
            if self._attempt(routed, twin, hedge=True) is not None:
                continue  # twin refused the submit: the request keeps
                # its hedge eligibility for the next scan (marking it
                # hedged here would burn its only hedge on a failure)
            routed.hedged = True
            obs.spans.request_event(
                self._reg, "hedge", routed.ctx, routed.uuid,
                replica=twin.rid,
                waited_ms=round((now - routed.submit_t) * 1000.0, 3))
            with self._lock:
                self._n_hedges += 1
            # per-tenant hedge spend (ISSUE 15 cost accounting): waste
            # per tenant = hedges - hedge wins on these labeled children
            self._c_hedges.labels(tenant=routed.tenant or "default").inc()

    # -- introspection --
    def replicas(self) -> List[ReplicaHandle]:
        return list(self._handle_list)

    def handle(self, rid: str) -> ReplicaHandle:
        return self._handles[rid]

    def in_rotation(self) -> int:
        return sum(h.in_rotation() for h in self._handle_list)

    @property
    def registry(self) -> obs.Registry:
        return self._reg


__all__ = ["FleetRouter"]
