"""Hierarchical streaming summarizer — map-reduce long documents over
the serving fleet (ISSUE 19 tentpole; SERVING.md "Hierarchical
summarization").

The serving stack up to PR 17 answers one question per request: "give
me the summary of THIS article" — and every article is implicitly
bounded by ``T_enc``.  The Flink heritage promises streaming *document*
summarization (PAPER.md §0), where a document grows on a topic without
bound.  This module closes that gap with a two-level map-reduce over
the EXISTING submit surface:

  map:    split the document into overlap-aware word chunks, key each
          chunk by the front door's canonical ``article_key()``, and
          fan every chunk through ``submit()`` as its own sub-request
          (``ServingServer`` or ``FleetRouter`` — anything with the
          submit surface).  In continuous mode each chunk rides the
          PR-11 bucketed prefill, so a short tail chunk never pays the
          full-width encode.
  reduce: when the last chunk summary lands, concatenate the chunk
          summaries (decode/reduce.py budgets the words so every chunk
          keeps representation inside ``max_enc_steps``) and submit ONE
          more request on the reduce tier (beam by default) whose
          output is the document's summary.

The incremental lever — FastSeq's "never do redundant work" applied at
document granularity (PAPERS.md): chunk boundaries are a pure function
of word INDEX (stride = chunk - overlap), so appending to an open
``DocumentSession`` leaves every previously-complete chunk
byte-identical.  Resubmitted through the armed front door those chunks
cache-hit (or coalesce onto in-flight twins) and resolve synchronously
at submit — only the appended tail chunks and one reduce pass ever
decode.  Deduplication by construction, not by policy.

Tracing: ONE parent ``TraceContext`` is minted per document and a
``.child()`` of it threads through every chunk and the reduce
sub-request, so the whole fan-out tree reconstructs from events.jsonl
(``scripts/trace_summary.py --request <parent uuid>`` renders the
chunk children indented under the parent).  Two new lifecycle events:
``hier_chunk`` (per chunk, after submit — carries chunk index, key,
bucket, tier, cache_hit) and ``hier_reduce`` (the reduce submit).

Failure contract (tests/test_hiersum.py chaos case): a failed chunk
sub-request fails TYPED and alone; the parent future waits for every
outstanding chunk to resolve (no orphaned chunk futures), then rejects
exactly once with ``HierPartialFailureError`` naming the failed chunk
indices and their causes.  The reduce pass is never submitted over a
partial map.

Quality check (guided-attention lesson, PAPERS.md): the reduce output
is scored for n-gram containment against the chunk summaries it read
and against the source chunks (``serve/hier_copy_fidelity`` histogram)
— a reduce pass that hallucinates past its inputs shows up as a
low-fidelity bucket, not a silent quality cliff.

Import-light: no jax — chunking, fan-out bookkeeping, and fidelity are
pure Python over the submit surface (the same discipline as queue.py /
frontdoor.py).
"""

from __future__ import annotations

import logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu import config
from textsummarization_on_flink_tpu.decode.reduce import (
    assemble_reduce_input,
)
from textsummarization_on_flink_tpu.obs import locksan
from textsummarization_on_flink_tpu.serve.errors import (
    HierPartialFailureError,
)
from textsummarization_on_flink_tpu.serve.frontdoor import article_key
from textsummarization_on_flink_tpu.serve.queue import ServeFuture

log = logging.getLogger(__name__)

#: fidelity is a ratio in [0, 1]; latency-shaped exponential buckets
#: would dump every observation into one bin
FIDELITY_BUCKETS = (0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9, 0.95, 1.0)

#: fan-out width per document (chunks); documents past the last bucket
#: land in +Inf — the histogram is for shape, the exact count rides the
#: hier_reduce event's ``chunks`` attr
FANOUT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)


def chunk_document(article: str, chunk_words: int,
                   overlap_words: int = 0) -> List[str]:
    """Split `article` into overlap-aware word chunks.

    Chunk i covers words ``[i*stride, i*stride + chunk_words)`` with
    ``stride = chunk_words - overlap_words`` — boundaries are a pure
    function of word index, never of document length.  That property IS
    the append-path cache lever: growing the document leaves every
    chunk that was already complete (``start + chunk_words <= old_len``)
    byte-identical, so its ``article_key()`` — and therefore its front
    door cache entry — still matches.  Only a previously-TRUNCATED tail
    chunk (and chunks past the old end) change.

    The last chunk always reaches the document's end (it may be shorter
    than ``chunk_words``); an empty/whitespace article yields [].
    """
    if chunk_words < 1:
        raise ValueError(f"chunk_words must be >= 1, got {chunk_words}")
    if not 0 <= overlap_words < chunk_words:
        raise ValueError(
            f"overlap_words must be in [0, chunk_words={chunk_words}), "
            f"got {overlap_words}")
    words = article.split()
    if not words:
        return []
    stride = chunk_words - overlap_words
    chunks: List[str] = []
    start = 0
    while True:
        chunks.append(" ".join(words[start:start + chunk_words]))
        if start + chunk_words >= len(words):
            return chunks
        start += stride


def ngram_containment(words: Sequence[str],
                      sources: Sequence[Sequence[str]],
                      n: int = 2) -> float:
    """Fraction of `words`' n-grams present in the union of `sources`'
    n-grams — the copy-fidelity score of a reduce output against what
    it was allowed to read.  Falls back to unigrams for texts shorter
    than `n`; empty inputs score 1.0 (nothing was fabricated)."""
    if n < 1:
        raise ValueError(f"n must be >= 1, got {n}")

    def grams(ws: Sequence[str], k: int) -> List[Tuple[str, ...]]:
        return [tuple(ws[i:i + k]) for i in range(len(ws) - k + 1)]

    k = min(n, len(words)) or 1
    target = grams(list(words), k)
    if not target:
        return 1.0
    pool = set()
    for src in sources:
        pool.update(grams(list(src), k))
    return sum(1 for g in target if g in pool) / len(target)


class DocumentSession:
    """One open document stream: the text so far + the chunk keys of
    the last summarize, so a re-summarize after ``append()`` can report
    exactly how many chunks were reusable (the front door does the
    actual dedup — this is the bookkeeping that makes it observable and
    pinnable in tests).  Sessions are driver-side state: one per
    streaming doc id in the pipeline stage (estimator.py)."""

    __slots__ = ("doc_id", "text", "revision", "chunk_keys")

    def __init__(self, doc_id: str, text: str = ""):
        self.doc_id = doc_id
        self.text = text
        #: completed summarize passes over this stream (rides the
        #: parent uuid: ``<doc_id>@r<revision>``)
        self.revision = 0
        #: per-chunk ``article_key`` list as of the last summarize
        self.chunk_keys: List[str] = []

    def append(self, text: str) -> "DocumentSession":
        """Extend the stream (word-level concatenation — the framing
        layer has already joined parts with whitespace)."""
        text = text.strip()
        if text:
            self.text = f"{self.text} {text}".strip()
        return self


class HierResult:
    """The parent request's resolution payload: the reduce summary
    re-keyed to the DOCUMENT (uuid/article/reference of the caller's
    request, not the reduce sub-request's).  A fresh object per
    document — the reduce ``DecodedResult`` may be a shared front-door
    cache payload and must never be mutated."""

    __slots__ = ("uuid", "article", "reference", "decoded_words",
                 "chunk_count", "reused_chunks", "copy_fidelity",
                 "degraded")

    def __init__(self, uuid: str, article: str, reference: str,
                 decoded_words: List[str], chunk_count: int,
                 reused_chunks: int, copy_fidelity: float,
                 degraded: bool = False):
        self.uuid = uuid
        self.article = article
        self.reference = reference
        self.decoded_words = decoded_words
        self.chunk_count = chunk_count
        self.reused_chunks = reused_chunks
        #: n-gram containment of the summary vs the chunk summaries
        self.copy_fidelity = copy_fidelity
        self.degraded = degraded

    @property
    def summary(self) -> str:
        return " ".join(self.decoded_words)

    def as_row(self) -> Tuple[str, str, str, str]:
        """The pipeline output row (uuid, article, summary, reference) —
        same shape as DecodedResult.as_row()."""
        return (self.uuid, self.article, self.summary, self.reference)


class _FanOut:
    """Bookkeeping for one document's in-flight map-reduce: chunk
    results land by index under a lock; the LAST chunk's resolution
    (and only it) advances to the reduce submit or the typed partial
    rejection.  The parent future resolves exactly once because every
    path out of here funnels through it exactly once."""

    __slots__ = ("uuid", "article", "reference", "tenant", "parent",
                 "ctx", "chunks", "results", "errors", "remaining",
                 "reused", "lock")

    def __init__(self, uuid: str, article: str, reference: str,
                 tenant: str, parent: ServeFuture,
                 ctx: Optional[obs.TraceContext], chunks: List[str],
                 reused: int):
        self.uuid = uuid
        self.article = article
        self.reference = reference
        self.tenant = tenant
        self.parent = parent
        self.ctx = ctx
        self.chunks = chunks
        self.results: List[Optional[Any]] = [None] * len(chunks)
        self.errors: Dict[int, BaseException] = {}
        #: chunks not yet resolved — set to the FULL width before any
        #: submit, so a synchronously-resolving cache hit mid-loop can
        #: never see a premature zero
        self.remaining = len(chunks)
        self.reused = reused
        self.lock = locksan.make_lock("HierFanOut._lock")


class HierarchicalSummarizer:
    """Map-reduce document summarization over an existing submit
    surface (``ServingServer`` or ``FleetRouter``).

    ``summarize()`` returns a ``ServeFuture`` resolving to a
    ``HierResult`` — the caller blocks (or attaches callbacks) exactly
    as for a plain submit.  The summarizer owns no threads: chunk
    completions drive the reduce submit from the server's own resolve
    callbacks, so the tick-driven virtual-time gate
    (tests/test_serve_slo.py "hierarchical") replays it deterministically
    on a single thread.

    Tier policy: chunks decode on ``hps.hier_chunk_tier`` (greedy by
    default — cheap extractive passes), the reduce on
    ``hps.hier_reduce_tier`` (beam — the caller-visible quality).  A
    continuous-mode surface decodes beam-only by construction
    (server.py submit validation), so both collapse to beam there — the
    fan-out win comes from slot parallelism + bucketed prefill instead
    of tier pricing.
    """

    def __init__(self, server: Any, hps: "config.HParams",
                 registry: Optional[obs.Registry] = None):
        self._server = server
        self._hps = hps
        self._reg = registry if registry is not None \
            else obs.registry_for(hps)
        self._chunk_words = config.resolve_hier_chunk_words(hps)
        self._overlap = hps.hier_overlap_words
        mode = getattr(server, "serve_mode", "") \
            or getattr(hps, "serve_mode", "microbatch")
        self._chunk_tier = "beam" if mode == "continuous" \
            else (hps.hier_chunk_tier or "greedy")
        self._reduce_tier = "beam" if mode == "continuous" \
            else (hps.hier_reduce_tier or "beam")
        self._buckets = config.parse_bucket_spec(
            getattr(hps, "serve_buckets", ""), hps.max_enc_steps)
        # construction-time metric handles (the cached-sibling idiom of
        # every serve hot path: no registry lock on the per-chunk path)
        self._c_docs = self._reg.counter("serve/hier_documents_total")
        self._c_chunks = self._reg.counter("serve/hier_chunks_total")
        self._c_reused = self._reg.counter("serve/hier_chunks_reused_total")
        self._c_chunk_hits = self._reg.counter(
            "serve/hier_chunk_cache_hits_total")
        self._c_reduce = self._reg.counter("serve/hier_reduce_total")
        self._c_partial = self._reg.counter(
            "serve/hier_partial_failures_total")
        self._h_fanout = self._reg.histogram(
            "serve/hier_fanout_chunks", buckets=FANOUT_BUCKETS)
        self._h_fidelity = self._reg.histogram(
            "serve/hier_copy_fidelity", buckets=FIDELITY_BUCKETS)

    # -- public API --

    def summarize(self, article: str, uuid: str = "", reference: str = "",
                  session: Optional[DocumentSession] = None,
                  tenant: str = "", block: bool = False,
                  timeout: Optional[float] = None) -> ServeFuture:
        """Fan one document out chunk-by-chunk and return the parent
        future (resolves to a ``HierResult`` when the reduce lands, or
        rejects typed).

        With a ``session``, the DOCUMENT IS THE SESSION's accumulated
        text (`article` must be empty — append first, then summarize),
        the parent uuid defaults to ``<doc_id>@r<N>``, and the session's
        chunk keys from the previous pass pin how many chunks were
        reusable this pass (``serve/hier_chunks_reused_total``).

        ``block=True`` applies pipeline backpressure per chunk submit
        (the transform path); the default sheds typed on a full queue
        exactly like a plain submit."""
        if session is not None:
            if article:
                raise ValueError(
                    "summarize(session=...) reads the session's text; "
                    "append() new content instead of passing article=")
            article = session.text
            session.revision += 1
            if not uuid:
                uuid = f"{session.doc_id}@r{session.revision}"
        if not uuid:
            uuid = f"hier-{article_key(article, self._hps.max_enc_steps)}"
        chunks = chunk_document(article, self._chunk_words, self._overlap)
        if not chunks:
            raise ValueError(
                f"document {uuid!r} has no words to summarize")
        keys = [article_key(c, self._hps.max_enc_steps) for c in chunks]
        reused = 0
        if session is not None:
            reused = sum(1 for old, new in zip(session.chunk_keys, keys)
                         if old == new)
            session.chunk_keys = keys
        self._c_docs.inc()
        self._c_chunks.inc(len(chunks))
        if reused:
            self._c_reused.inc(reused)
        self._h_fanout.observe(float(len(chunks)))
        # ONE parent context per document; every chunk and the reduce
        # submit a .child() of it, so the whole fan-out shares one
        # trace_id with parent_id -> parent span linkage (the tree
        # trace_summary.py --request renders)
        ctx = obs.TraceContext.new() if self._reg.enabled else None
        parent = ServeFuture(uuid, registry=self._reg)
        parent.trace = ctx
        # scope-tag the parent's terminal resolve (the fleet idiom):
        # the chunk sub-requests resolve in the same trace, and the
        # timeline's total_ms must key on the DOCUMENT's resolution
        parent.scope = "hier"
        fo = _FanOut(uuid, article, reference, tenant, parent, ctx,
                     chunks, reused)
        self._fan_out(fo, keys, block=block, timeout=timeout)
        return parent

    # -- fan-out / reduce driver --

    def _fan_out(self, fo: _FanOut, keys: List[str], block: bool,
                 timeout: Optional[float]) -> None:
        """Submit every chunk as its own sub-request.  A submit that
        raises (overload, closed, tier validation) fails THAT chunk and
        every not-yet-submitted one with the same typed cause — the
        in-flight chunks still drain before the parent rejects, so no
        chunk future is ever orphaned."""
        n = len(fo.chunks)
        for i, chunk in enumerate(fo.chunks):
            child = fo.ctx.child() if fo.ctx is not None else None
            chunk_uuid = f"{fo.uuid}/c{i}"
            words = len(chunk.split())
            try:
                fut = self._server.submit(
                    chunk, uuid=chunk_uuid, reference="", block=block,
                    timeout=timeout, tier=self._chunk_tier, trace=child,
                    tenant=fo.tenant)
            except BaseException as e:  # tslint: disable=TS005 — not swallowed: the typed cause fails THIS and every unsubmitted chunk via _record_chunk and rejects the parent as HierPartialFailureError
                log.warning("hier chunk submit failed for %s (%d..%d "
                            "of %d): %s", fo.uuid, i, n - 1, n, e)
                for j in range(i, n):
                    self._record_chunk(fo, j, None, e)
                return
            # a future already resolved here came straight off the
            # front door cache (a coalesced follower resolves later,
            # with its leader) — the flag the append-path pins ride
            hit = fut.done() and fut.error is None
            if hit:
                self._c_chunk_hits.inc()
            obs.spans.request_event(
                self._reg, "hier_chunk", child, chunk_uuid,
                parent_uuid=fo.uuid, chunk=i, chunks=n, key=keys[i],
                words=words,
                bucket=config.bucket_for(
                    self._buckets, min(words, self._hps.max_enc_steps)),
                tier=self._chunk_tier, cache_hit=hit)
            fut.add_done_callback(
                lambda f, idx=i: self._chunk_done(fo, idx, f))

    def _chunk_done(self, fo: _FanOut, idx: int, fut: ServeFuture) -> None:
        """One chunk resolved (any thread).  Runs inside the server's
        resolve callback — must stay cheap and must not block."""
        if fut.error is not None:
            self._record_chunk(fo, idx, None, fut.error)
        else:
            self._record_chunk(fo, idx, fut.result(timeout=0), None)

    def _record_chunk(self, fo: _FanOut, idx: int, result: Any,
                      error: Optional[BaseException]) -> None:
        with fo.lock:
            if error is not None:
                fo.errors[idx] = error
            else:
                fo.results[idx] = result
            fo.remaining -= 1
            last = fo.remaining == 0
        if last:
            self._map_complete(fo)

    def _map_complete(self, fo: _FanOut) -> None:
        """Every chunk future has resolved (success or typed failure):
        either submit the reduce pass or reject the parent with the
        typed partial-failure verdict.  Exactly one of the two runs —
        the caller is the unique remaining==0 transition."""
        if fo.errors:
            self._c_partial.inc()
            fo.parent._reject(HierPartialFailureError(
                fo.uuid, dict(fo.errors), len(fo.chunks)))
            return
        summaries = [list(getattr(r, "decoded_words", []) or [])
                     for r in fo.results]
        reduce_input = assemble_reduce_input(
            summaries, self._hps.max_enc_steps)
        child = fo.ctx.child() if fo.ctx is not None else None
        reduce_uuid = f"{fo.uuid}/reduce"
        self._c_reduce.inc()
        try:
            fut = self._server.submit(
                reduce_input, uuid=reduce_uuid, reference=fo.reference,
                block=False, tier=self._reduce_tier, trace=child,
                tenant=fo.tenant)
        except BaseException as e:
            self._c_partial.inc()
            fo.parent._reject(HierPartialFailureError(
                fo.uuid, {"reduce": e}, len(fo.chunks)))
            return
        hit = fut.done() and fut.error is None
        obs.spans.request_event(
            self._reg, "hier_reduce", child, reduce_uuid,
            parent_uuid=fo.uuid, chunks=len(fo.chunks),
            words=len(reduce_input.split()), tier=self._reduce_tier,
            cache_hit=hit)
        fut.add_done_callback(lambda f: self._reduce_done(fo, f))

    def _reduce_done(self, fo: _FanOut, fut: ServeFuture) -> None:
        if fut.error is not None:
            self._c_partial.inc()
            fo.parent._reject(HierPartialFailureError(
                fo.uuid, {"reduce": fut.error}, len(fo.chunks)))
            return
        res = fut.result(timeout=0)
        words = list(getattr(res, "decoded_words", []) or [])
        summaries = [list(getattr(r, "decoded_words", []) or [])
                     for r in fo.results]
        # the guided-attention check in measurable form: how much of
        # the reduce output is grounded in what it was allowed to read —
        # the chunk summaries it decoded from AND the source chunks
        # (an extractive reduce that copies source spans verbatim is
        # faithful, not fabricated)
        pool = summaries + [c.split() for c in fo.chunks]
        fidelity = ngram_containment(words, pool)
        self._h_fidelity.observe(fidelity)
        fo.parent._resolve(HierResult(
            fo.uuid, fo.article, fo.reference, words,
            chunk_count=len(fo.chunks), reused_chunks=fo.reused,
            copy_fidelity=fidelity,
            degraded=bool(getattr(res, "degraded", False))))


__all__ = ["HierarchicalSummarizer", "DocumentSession", "HierResult",
           "chunk_document", "ngram_containment",
           "FIDELITY_BUCKETS", "FANOUT_BUCKETS"]
