"""Dynamic micro-batcher: coalesce queued requests into device batches.

The decode path dispatches ONE compiled program per batch
(decode/beam_search.py), so serving throughput is set by how full each
dispatch is and how few distinct shapes the jit cache must hold.  This
module owns both levers:

  * **Coalescing** — after the first request of a batch arrives, wait
    up to ``serve_max_wait_ms`` for neighbors, up to ``serve_max_batch``
    requests per dispatch (the FastSeq observation, PAPERS.md: most
    sequence-generation serving wins are batching/dispatch engineering
    around an unchanged model).
  * **Shape buckets** — pad the batch's encoder axis to the smallest
    ``serve_buckets`` entry covering its longest article (the
    ``Batch(..., enc_steps=bucket)`` hook from data/batching.py), so a
    short article never pays full ``max_enc_steps`` decode FLOPs and
    the jit cache stays bounded at len(buckets) shapes — hits/misses
    are visible in the existing ``decode/compile_cache_*_total``
    counters (decode/beam_search.py).

The device batch SHAPE is always ``hps.batch_size``: a short
micro-batch is padded with repeats of its last example tagged
``real_mask=False``, which the decoder already drops (the same
contract as data/batcher.py trickle padding).
"""

from __future__ import annotations

import time
from collections import deque
from typing import Any, Deque, List, Optional, Tuple

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.obs import flightrec
from textsummarization_on_flink_tpu.obs import profile as profile_lib
from textsummarization_on_flink_tpu.config import (
    HParams,
    parse_bucket_spec,
)
from textsummarization_on_flink_tpu.config import bucket_for as \
    config_bucket_for
from textsummarization_on_flink_tpu.data.batching import Batch
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.resilience.errors import (
    ArenaExhaustedError,
    DeadlineExceededError,
)
from textsummarization_on_flink_tpu.serve.queue import (
    RequestQueue,
    ServeRequest,
)


def resolve_buckets(hps: HParams) -> List[int]:
    """The ascending encoder-length bucket list for this job (the one
    parser lives in config.parse_bucket_spec; see its docstring)."""
    return parse_bucket_spec(hps.serve_buckets, hps.max_enc_steps)


class MicroBatcher:
    """Pull requests off a RequestQueue and pack them into Batches.

    ``next_group`` implements the time/size coalescing policy;
    ``build`` packs a group into a bucket-padded, static-shape Batch.
    Single consumer by design (the ServingServer dispatch thread);
    the queue itself is the thread-safe boundary.
    """

    def __init__(self, hps: HParams, vocab: Vocab, rqueue: RequestQueue,
                 registry: Optional[obs.Registry] = None):
        self._hps = hps
        self._vocab = vocab
        self._q = rqueue
        self.max_batch = min(hps.serve_max_batch or hps.batch_size,
                             hps.batch_size)
        self._window = max(hps.serve_max_wait_ms, 0.0) / 1000.0
        self.buckets = resolve_buckets(hps)
        #: requests popped off the queue into the group currently being
        #: coalesced or dispatched (ISSUE 13): from the moment
        #: next_group takes its first request until the server's
        #: dispatch loop calls end_group, these are ADMITTED work that
        #: the queue no longer shows — the fleet's idle()/load()
        #: surfaces must see them or a rolling swap could fire
        #: mid-coalesce.  Single writer (the dispatch thread); readers
        #: only need zero/non-zero.
        self.in_flight = 0
        reg = registry if registry is not None else obs.registry_for(hps)
        # fill is the headline batching metric: mean fill ~1 means the
        # window is too short (or traffic too thin) and every dispatch
        # pays full-batch device time for one article
        self._h_fill = reg.histogram(
            "serve/batch_fill",
            buckets=[float(i) for i in range(1, hps.batch_size + 1)])
        self._h_bucket = reg.histogram(
            "serve/batch_bucket_len", buckets=[float(b) for b in self.buckets])
        self._c_batches = reg.counter("serve/batches_total")
        self._c_pad_rows = reg.counter("serve/pad_rows_total")

    def bucket_for(self, enc_len: int) -> int:
        """Smallest bucket covering `enc_len` (SummaryExample.build has
        already truncated to max_enc_steps == buckets[-1]).  Routes
        through config.bucket_for — the continuous engine's prefill
        stage shares the same rule."""
        return config_bucket_for(self.buckets, enc_len)

    def next_group(self, poll: float = 0.05) -> Optional[List[ServeRequest]]:
        """The next micro-batch worth of requests, or None after an idle
        `poll` seconds (the caller's loop re-checks its stop flag).

        The window clock starts at the FIRST request of the group: a
        request never waits more than ``serve_max_wait_ms`` for
        neighbors on top of its own queue time."""
        first = self._q.get(timeout=poll)
        if first is None:
            return None
        group = [first]
        self.in_flight = 1
        window_ends = time.monotonic() + self._window
        while len(group) < self.max_batch:
            remaining = window_ends - time.monotonic()
            if remaining <= 0:
                # the window closed; grab whatever is ALREADY queued
                # (free fill — no extra waiting), then ship
                while len(group) < self.max_batch:
                    req = self._q.get_nowait()
                    if req is None:
                        break
                    group.append(req)
                    self.in_flight = len(group)
                break
            req = self._q.get(timeout=remaining)
            if req is None:
                break
            group.append(req)
            self.in_flight = len(group)
        return group

    def end_group(self) -> None:
        """The dispatch loop finished the current group (every member's
        future resolved or rejected): the in-flight window closes."""
        self.in_flight = 0

    def build(self, group: List[ServeRequest]) -> Batch:
        """Pack a group into one static-shape Batch: encoder axis padded
        to the group's bucket, batch axis padded to ``hps.batch_size``
        with real_mask=False repeats."""
        bucket = max(self.bucket_for(r.example.enc_len) for r in group)
        examples = [r.example for r in group]
        n_real = len(examples)
        pad = self._hps.batch_size - n_real
        if pad:
            examples = examples + [examples[-1]] * pad
            self._c_pad_rows.inc(pad)
        mask = [i < n_real for i in range(self._hps.batch_size)]
        self._h_fill.observe(n_real)
        self._h_bucket.observe(bucket)
        self._c_batches.inc()
        return Batch(examples, self._hps, self._vocab, enc_steps=bucket,
                     real_mask=mask)


class ContinuousBatcher:
    """Continuous batching: admit into free decode slots, step a chunk,
    harvest finished sequences — no dispatch-window barrier (ISSUE 6).

    Where the MicroBatcher waits for a GROUP and dispatches it
    all-or-nothing (one long article holds the whole batch hostage, new
    arrivals wait out the window), this scheduler keeps a persistent
    slotted decode loop running: every ``tick()``

      1. evicts residents whose Deadline expired (typed
         ``DeadlineExceededError``, ``serve/deadline_evictions_total``);
      2. PREFILLS queued requests through the engine's bucketed encoder
         stage (ISSUE 11) into a small ready queue — encoder cost paid
         at the article's bucket shape, ``serve_prefill_depth`` entries
         ahead of the free slots so a freed slot refills from an
         already-encoded article (``serve/prefill_*`` metrics; engines
         without a ``prefill`` surface — stubs, the SLO gate's
         uniform-baseline sim — keep the direct-pack path);
      3. refills free slots from the prefill queue (or straight off the
         RequestQueue on legacy engines) — a request admitted
         mid-decode starts at the NEXT chunk boundary, not the next
         batch;
      4. advances every resident slot one chunk through the engine;
      5. harvests finished slots — each future resolves the moment ITS
         sequence completes, independent of its neighbors.

    The engine (decode/decoder.SlotDecodeEngine, or a test stub) owns
    the device state; this class owns request bookkeeping and obs.  It
    is jax-free by design — scheduling is testable (and the SLO gate
    drivable) without a device.  Single consumer, like MicroBatcher:
    only the server's dispatch thread calls ``tick``.

    Exactly-once: every request this scheduler accepts from the queue is
    either resident (``fail_resident`` covers engine faults), harvested
    (resolved with its result), or evicted (rejected typed) — the
    server-level contract survives the mode switch.
    """

    def __init__(self, hps: HParams, rqueue: RequestQueue, engine: Any,
                 registry: Optional[obs.Registry] = None,
                 faults: Optional[Any] = None):
        self._hps = hps
        self._q = rqueue
        self._engine = engine
        self._faults = faults
        self.slots = int(engine.slots)
        self._resident: List[Optional[ServeRequest]] = [None] * self.slots
        self._chunks = [0] * self.slots  # chunks each resident has seen
        # the prefill queue (ISSUE 11): requests whose bucketed encoder
        # pass already ran, awaiting a free slot.  Engines without a
        # prefill surface (stub engines, the SLO gate's uniform
        # baseline) keep the legacy direct-pack refill.
        self._supports_prefill = hasattr(engine, "prefill")
        self._prefilled: Deque[Tuple[ServeRequest, Any]] = deque()
        self._prefill_depth = max(
            0, int(getattr(hps, "serve_prefill_depth", 0)))
        self._tick = 0  # scheduler rounds (the T of "refill at tick T")
        # per-tick activity, reset each tick for the flight-recorder
        # frame (obs/flightrec.py): post-mortems need the rounds BEFORE
        # a failure, not only the cumulative counters
        self._tick_evictions = 0
        self._tick_refills = 0
        reg = registry if registry is not None else obs.registry_for(hps)
        self._reg = reg
        # the phase ledger (obs/profile.py, ISSUE 16): every tick's
        # evict/prefill/pack/dispatch/harvest wall lands in labeled
        # phase histograms, bracketed by a per-tick wall so the
        # phases-sum-to-wall accounting check holds (dark registries
        # get the allocation-free null profiler)
        self._prof = profile_lib.profiler_for(reg)
        # the divergence sentinel's dispatch-shape key: the slot chunk
        # is the one compiled decode program this batcher drives
        self._dispatch_key = f"slot_chunk{getattr(engine, 'chunk', 0)}"
        self._g_active = reg.gauge("serve/slots_active")
        # the /healthz-scrapeable routing input (ISSUE 13): the
        # FleetRouter's least-loaded pick wants free capacity, and
        # slots - slots_active is not derivable from gauges alone (the
        # slot COUNT is construction state, not a metric)
        self._g_free = reg.gauge("serve/slots_free")
        self._g_free.set(self.slots)
        # occupancy is the headline continuous metric: fraction of slots
        # doing useful work at each chunk step (mean ~1 under load means
        # refill keeps up; the microbatch analogue is fill/batch_size)
        self._h_occupancy = reg.histogram(
            "serve/slot_occupancy",
            buckets=[i / self.slots for i in range(1, self.slots + 1)])
        self._h_resident = reg.histogram(
            "serve/request_resident_chunks",
            buckets=[1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0])
        self._c_refills = reg.counter("serve/slot_refills_total")
        self._c_evictions = reg.counter("serve/deadline_evictions_total")
        # prefill-stage telemetry (SERVING.md "Prefill/decode
        # disaggregation"): volume, failures, and WHICH bucket each
        # article's encoder pass ran at — the disaggregation evidence
        # (a bucket histogram pinned at max_enc_steps means the stage
        # is not routing short articles to short shapes)
        self._c_prefills = reg.counter("serve/prefill_total")
        self._c_prefill_errors = reg.counter("serve/prefill_errors_total")
        # bucketed on the serve buckets themselves (length-scaled):
        # the default time-scaled bounds would dump every token-length
        # observation into +inf and blind the percentiles
        self._h_prefill_bucket = reg.histogram(
            "serve/prefill_bucket_len",
            buckets=[float(b) for b in resolve_buckets(hps)])
        self._g_prefill_ready = reg.gauge("serve/prefill_ready")
        self._h_queue_time = reg.histogram("serve/time_in_queue_seconds")
        self._h_e2e = reg.histogram("serve/e2e_latency_seconds")
        self._c_done = reg.counter("serve/completed_total")
        self._c_errors = reg.counter("serve/errors_total")
        # per-tenant cost accounting (ISSUE 15): decoded tokens charged
        # to the tenant whose request occupied the slot
        self._c_tenant_tokens = reg.counter("serve/tenant_tokens_total")
        # paged-resident-state telemetry (ISSUE 20): arena occupancy per
        # tick plus the allocation-failure backpressure count.  Emitted
        # HERE rather than in the engine so the jax-free sim engines the
        # SLO gate drives light the same series the real engine does —
        # an engine without an arena surface simply never updates them.
        self._supports_arena = bool(getattr(engine, "paged", False))
        self._arena_blocked = False  # rising-edge state for the trigger
        self._g_arena_pages = reg.gauge("serve/arena_pages_in_use")
        self._c_arena_fail = reg.counter("serve/arena_alloc_failures_total")
        self._h_arena_fill = reg.histogram(
            "serve/arena_fill",
            buckets=[0.1, 0.25, 0.5, 0.75, 0.9, 0.95, 1.0])

    def busy(self) -> bool:
        return any(r is not None for r in self._resident)

    def active(self) -> int:
        """Resident (occupied) slot count right now — the FleetRouter's
        load input alongside the queue depth."""
        return sum(r is not None for r in self._resident)

    def prefilled(self) -> int:
        """Prefilled-but-unslotted request count (admitted work that is
        neither queued nor resident — the router's load math must not
        lose it)."""
        return len(self._prefilled)

    def pending(self) -> bool:
        """True while prefilled-but-unslotted requests await a slot —
        part of the drain condition: a tick can harvest EVERY resident
        after the prefill stage drained the queue's tail into the
        prefill queue, and those entries are admitted work the loop
        must keep ticking for (they pack on the next refill)."""
        return bool(self._prefilled)

    def _set_active_gauge(self) -> None:
        n = sum(r is not None for r in self._resident)
        self._g_active.set(n)
        self._g_free.set(self.slots - n)

    def _evict_expired(self) -> None:
        """Resident requests whose enqueue-measured Deadline ran out are
        evicted at the chunk boundary — the ISSUE-6 bugfix: a deadline
        is enforced while the request is RESIDENT, not only at admission
        (continuous mode has no dispatch to re-check it)."""
        evicted = 0
        for idx, req in enumerate(self._resident):
            if req is None or not req.deadline.expired():
                continue
            self._engine.release(idx)
            self._resident[idx] = None
            self._c_evictions.inc()
            evicted += 1
            obs.spans.request_event(
                self._reg, "evict", req.trace, req.uuid, where="resident",
                slot=idx, chunks=self._chunks[idx])
            req.future._reject(DeadlineExceededError(
                f"request {req.uuid!r} deadline expired after "
                f"{self._chunks[idx]} resident chunk(s)"))
        self._tick_evictions += evicted  # tslint: disable=TS009 — single-writer: only the dispatch thread ticks; the main root is the single-threaded virtual-time tests
        if evicted >= max(2, (self.slots + 1) // 2):
            # an eviction STORM (half the engine thrown away at one
            # boundary) is a latency incident, not routine aging: leave
            # the preceding ticks behind for the post-mortem.  The
            # 2-eviction noise floor means tiny engines (slots<=2)
            # trigger only on a FULL wipe, and a 1-slot engine never
            # does — losing its single resident is indistinguishable
            # from routine deadline aging (documented, OBSERVABILITY.md)
            flightrec.trigger(self._reg, "eviction_storm",
                              evicted=evicted, tick=self._tick)
        self._set_active_gauge()

    def _next_live(self, may_block: bool, poll: float,
                   ) -> Optional[ServeRequest]:
        """Pop the next LIVE request off the RequestQueue, resolving
        queue-expired ones typed on the way (the ISSUE-6 eviction site).
        Queue time is observed for EVERY dequeued request — including
        the expired ones, whose long waits are exactly the histogram
        tail that shows queue pressure — and the admit event fires only
        for live requests (a queue-expired request's timeline is
        enqueue -> evict -> resolve, never admit -> evict, so bench's
        admit-anchored resident split can't count eviction latency as
        decode time)."""
        while True:
            req = (self._q.get(timeout=poll) if may_block
                   else self._q.get_nowait())
            may_block = False
            if req is None:
                return None
            queue_s = time.monotonic() - req.enqueue_t
            self._h_queue_time.observe(queue_s)
            if req.deadline.expired():  # died waiting in the queue
                self._c_evictions.inc()
                self._tick_evictions += 1
                obs.spans.request_event(
                    self._reg, "evict", req.trace, req.uuid,
                    where="queue")
                req.future._reject(DeadlineExceededError(
                    f"request {req.uuid!r} deadline expired while "
                    f"queued"))
                continue
            # tenant rides the admit event when named (ISSUE 14): the
            # weighted-fair pickup's interleaving is reconstructable
            # per uuid from the same stream bench's queue split reads
            obs.spans.request_event(
                self._reg, "admit", req.trace, req.uuid,
                queue_ms=round(queue_s * 1e3, 3),
                **({"tenant": req.tenant} if req.tenant else {}))
            return req

    def _prefill_stage(self, poll: float) -> None:
        """Run the bucketed PREFILL stage (ISSUE 11): pop queued
        requests and push them through the engine's encoder pass at
        their bucket shape, up to free-slots + ``serve_prefill_depth``
        ready entries — the lookahead that overlaps next admissions'
        encoder work with resident decode.  Blocks at most once and
        only while the engine is fully idle.  A prefill failure rejects
        ITS request typed and re-raises so the server's tick handler
        applies the standard dispatch-failure blast radius
        (fail_resident) to the engine."""
        if not self._supports_prefill:
            return
        free = sum(r is None for r in self._resident)
        target = free + self._prefill_depth
        may_block = not self.busy() and not self._prefilled
        while len(self._prefilled) < target:
            req = self._next_live(may_block, poll)
            may_block = False
            if req is None:
                break
            t0 = self._prof.start()
            try:
                with obs.spans.span(self._reg, "serve/prefill"):
                    pre = self._engine.prefill(req.example)
            except Exception as e:
                # the request left the queue but never became resident:
                # resolve it HERE, then let the server's dispatch-
                # failure handling deal with the engine state
                self._c_prefill_errors.inc()
                self._c_errors.inc()
                req.future._reject(e)
                raise
            trace_id = req.trace.trace_id if req.trace is not None else None
            dt = self._prof.end("serve/prefill", t0, trace_id=trace_id)
            bucket = int(getattr(pre, "bucket", req.example.enc_len))
            self._prof.observe_dispatch("serve/prefill", bucket, dt,
                                        trace_id=trace_id)
            self._c_prefills.inc()
            self._h_prefill_bucket.observe(bucket)
            obs.spans.request_event(
                self._reg, "prefill", req.trace, req.uuid, bucket=bucket)
            self._prefilled.append((req, pre))
        self._g_prefill_ready.set(len(self._prefilled))

    def _refill(self, poll: float) -> None:
        """Admit requests into every free slot — from the prefill queue
        (disaggregated engines) or straight off the RequestQueue
        (legacy engines; blocks at most once, `poll` seconds, and only
        while the engine is idle — under load the queue is polled
        non-blocking so a refill never stalls resident decodes).
        Requests whose Deadline expired while awaiting a slot are
        resolved typed here instead of wasting one."""
        may_block = not self.busy()
        for idx in range(self.slots):
            if self._resident[idx] is not None:
                continue
            while True:
                if self._supports_prefill:
                    if not self._prefilled:
                        self._g_prefill_ready.set(0)
                        return
                    req, payload = self._prefilled.popleft()
                    if req.deadline.expired():  # aged out awaiting a slot
                        self._c_evictions.inc()
                        self._tick_evictions += 1
                        obs.spans.request_event(
                            self._reg, "evict", req.trace, req.uuid,
                            where="prefilled")
                        req.future._reject(DeadlineExceededError(
                            f"request {req.uuid!r} deadline expired "
                            f"awaiting a free slot (prefilled)"))
                        continue
                else:
                    req = self._next_live(may_block, poll)
                    may_block = False  # one blocking poll per tick
                    if req is None:
                        return
                    payload = req.example
                if self._supports_arena and self._supports_prefill:
                    # admit by FREE PAGES, not free slots (ISSUE 20):
                    # an admission that cannot get its pages goes BACK
                    # to the head of the prefill queue — requeued, never
                    # rejected — and this tick stops refilling (a later
                    # entry stealing the pages would starve the head)
                    need = self._engine.pages_needed(payload)
                    free_pages = self._engine.free_pages()
                    if need > free_pages:
                        self._prefilled.appendleft((req, payload))
                        self._arena_backpressure(need, free_pages)
                        return
                t0 = self._prof.start()
                try:
                    if (self._supports_arena and self._faults is not None
                            and self._faults.fire("serve.arena_full")):
                        raise ArenaExhaustedError(
                            "injected serve.arena_full fault",
                            needed=self._engine.pages_needed(payload),
                            free=0)
                    self._engine.pack(idx, payload)
                except ArenaExhaustedError as e:
                    # typed backpressure from the engine's own alloc
                    # (belt to the proactive check's suspenders, and the
                    # chaos sweep's injection path): same requeue-never-
                    # reject contract.  Only the prefill path holds a
                    # repackable payload; a legacy direct-pack engine
                    # with an arena would have to reject — the engine
                    # guarantees prefill support whenever paged.
                    if not self._supports_prefill:
                        self._c_errors.inc()
                        req.future._reject(e)
                        raise
                    self._prefilled.appendleft((req, payload))
                    self._arena_backpressure(e.needed, e.free)
                    return
                except Exception as e:
                    # the request left the queue but never became
                    # resident: resolve it HERE, then let the server's
                    # dispatch-failure handling deal with the engine
                    self._c_errors.inc()
                    req.future._reject(e)
                    raise
                if self._supports_arena and self._arena_blocked:
                    self._arena_blocked = False  # pages freed; edge re-arms
                self._prof.end("serve/pack", t0,
                               trace_id=req.trace.trace_id
                               if req.trace is not None else None)
                self._resident[idx] = req
                self._chunks[idx] = 0
                self._c_refills.inc()
                self._tick_refills += 1  # tslint: disable=TS009 — single-writer dispatch-thread invariant (see _tick_evictions)
                # the refill-into-slot lifecycle event: WHICH slot at
                # WHICH tick — the datum aggregate histograms cannot
                # answer ("why was uuid X slow?")
                obs.spans.request_event(
                    self._reg, "slot", req.trace, req.uuid, slot=idx,
                    tick=self._tick)
                break
        if self._supports_prefill:
            self._g_prefill_ready.set(len(self._prefilled))
        self._set_active_gauge()

    def _harvest(self, finished: List[int]) -> None:
        done_t = time.monotonic()
        for idx in finished:
            req = self._resident[idx]
            if req is None:  # pragma: no cover - defensive
                continue
            res = self._engine.unpack(idx, req.example)
            self._resident[idx] = None
            self._h_resident.observe(self._chunks[idx])
            # exemplar (ISSUE 15): the landing latency bucket remembers
            # THIS request's trace_id, so a fat p99 bucket on /metrics
            # names a concrete uuid to chase
            self._h_e2e.observe(
                done_t - req.enqueue_t,
                trace_id=req.trace.trace_id if req.trace is not None
                else None)
            self._c_tenant_tokens.labels(
                tenant=req.tenant or "default").inc(
                len(getattr(res, "decoded_words", ()) or ()))
            self._c_done.inc()
            obs.spans.request_event(
                self._reg, "finish", req.trace, req.uuid, slot=idx,
                chunks=self._chunks[idx])
            req.future._resolve(res)
        self._set_active_gauge()

    def _arena_backpressure(self, needed: int, free: int) -> None:
        """Account one admit-blocked-on-pages event: count it, and dump
        the flight ring on the RISING EDGE only (the first blocked tick
        of a full-arena episode is the post-mortem moment — dumping on
        every requeued retry would flood the ring dir with near-
        identical dumps of the same episode)."""
        self._c_arena_fail.inc()
        if not self._arena_blocked:
            self._arena_blocked = True  # tslint: disable=TS009 — single-writer dispatch-thread invariant (see _tick_evictions)
            flightrec.trigger(self._reg, "arena_exhausted",
                              needed=needed, free=free, tick=self._tick,
                              prefilled=len(self._prefilled))
        self._g_prefill_ready.set(len(self._prefilled))

    def _observe_arena(self) -> None:
        """Per-tick arena occupancy series (ISSUE 20): pages in use and
        the fill fraction — host counters off the engine's arena
        surface, no device sync."""
        if not self._supports_arena:
            return
        stats = self._engine.arena_stats()
        if not stats:
            return
        self._g_arena_pages.set(stats["in_use"])
        self._h_arena_fill.observe(stats["fill"])

    def _record_frame(self, occupancy: float) -> None:
        """One flight-recorder frame per scheduler round (the serve-tick
        analogue of the trainer's per-step frame): what the engine was
        doing on the rounds BEFORE a failure trigger fires."""
        extra = {}
        if self._supports_arena:
            extra["arena_free"] = self._engine.free_pages()
        flightrec.record(
            self._reg, "serve_tick", tick=self._tick,
            occupancy=round(occupancy, 4), queue_depth=self._q.qsize(),
            evictions=self._tick_evictions, refills=self._tick_refills,
            prefilled=len(self._prefilled), **extra)

    def tick(self, poll: float = 0.05) -> bool:
        """One scheduler round: evict -> refill -> step -> harvest.
        Returns False when the engine stayed idle (nothing resident and
        nothing arrived within `poll`) so the caller's loop can re-check
        its stop flag without spinning."""
        self._tick += 1  # tslint: disable=TS009 — single-writer dispatch-thread invariant (see _tick_evictions)
        self._tick_evictions = 0
        self._tick_refills = 0
        # the per-tick wall bracket (obs/profile.py, ISSUE 16) closes
        # only on busy ticks: an idle tick blocks up to `poll` seconds
        # inside the queue poll, and that wait is idleness, not an
        # attributable phase — counting it would sink the coverage
        # ratio without naming a phase to fix
        w0 = self._prof.start()
        t0 = self._prof.start()
        self._evict_expired()
        self._prof.end("serve/evict", t0)
        self._prefill_stage(poll)
        self._refill(poll)
        if not self.busy():
            return False
        # the frame lands BEFORE the chunk dispatch, so a failing tick
        # contributes its own pre-failure frame (refill/evict state) and
        # the dump holds everything strictly preceding the trigger
        n_active = sum(r is not None for r in self._resident)
        self._observe_arena()
        self._record_frame(n_active / self.slots)
        t0 = self._prof.start()
        with obs.spans.span(
                self._reg, "serve/dispatch",
                fill=n_active, tick=self._tick):
            if self._faults is not None and self._faults.fire(
                    "serve.dispatch"):
                raise RuntimeError("injected serve.dispatch fault")
            finished = self._engine.step()
        dt = self._prof.end("serve/dispatch", t0)
        # divergence sentinel: the slot-chunk program is the one
        # dispatch shape continuous mode executes — price once, then
        # compare every chunk's achieved bytes/s against it
        self._prof.observe_dispatch("serve/dispatch", self._dispatch_key, dt)
        self._h_occupancy.observe(n_active / self.slots)
        for idx, req in enumerate(self._resident):
            if req is not None:
                self._chunks[idx] += 1
        t0 = self._prof.start()
        self._harvest(finished)
        self._prof.end("serve/harvest", t0)
        self._prof.end_wall("serve/tick", w0)
        return True

    def fail_resident(self, error: BaseException) -> int:
        """Reject EVERY resident request with `error` and free its slot
        (the continuous analogue of the micro-batch 'a failed dispatch
        fails its batch only'); returns the count rejected.  The engine
        keeps its (masked-out) state; the next pack overwrites it.
        Prefilled-but-unslotted requests are NOT part of the failing
        dispatch and stay queued for the next tick."""
        n = 0
        for idx, req in enumerate(self._resident):
            if req is None:
                continue
            self._engine.release(idx)
            self._resident[idx] = None
            req.future._reject(error)
            n += 1
        self._c_errors.inc(n)
        self._set_active_gauge()
        return n

    def fail_pending(self, error: BaseException) -> int:
        """Reject every PREFILLED-but-unslotted request with `error` —
        the shutdown backstop: if the dispatch thread dies with entries
        still in the prefill queue, their futures must not hang (the
        exactly-once contract).  Normal drains never get here: refill
        empties the prefill queue into free slots before the loop can
        observe an idle engine."""
        n = 0
        while self._prefilled:
            req, _ = self._prefilled.popleft()
            req.future._reject(error)
            n += 1
        if n:
            self._c_errors.inc(n)
            self._g_prefill_ready.set(0)
        return n
