"""Dynamic micro-batcher: coalesce queued requests into device batches.

The decode path dispatches ONE compiled program per batch
(decode/beam_search.py), so serving throughput is set by how full each
dispatch is and how few distinct shapes the jit cache must hold.  This
module owns both levers:

  * **Coalescing** — after the first request of a batch arrives, wait
    up to ``serve_max_wait_ms`` for neighbors, up to ``serve_max_batch``
    requests per dispatch (the FastSeq observation, PAPERS.md: most
    sequence-generation serving wins are batching/dispatch engineering
    around an unchanged model).
  * **Shape buckets** — pad the batch's encoder axis to the smallest
    ``serve_buckets`` entry covering its longest article (the
    ``Batch(..., enc_steps=bucket)`` hook from data/batching.py), so a
    short article never pays full ``max_enc_steps`` decode FLOPs and
    the jit cache stays bounded at len(buckets) shapes — hits/misses
    are visible in the existing ``decode/compile_cache_*_total``
    counters (decode/beam_search.py).

The device batch SHAPE is always ``hps.batch_size``: a short
micro-batch is padded with repeats of its last example tagged
``real_mask=False``, which the decoder already drops (the same
contract as data/batcher.py trickle padding).
"""

from __future__ import annotations

import time
from typing import List, Optional

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.config import HParams, parse_bucket_spec
from textsummarization_on_flink_tpu.data.batching import Batch
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.serve.queue import (
    RequestQueue,
    ServeRequest,
)


def resolve_buckets(hps: HParams) -> List[int]:
    """The ascending encoder-length bucket list for this job (the one
    parser lives in config.parse_bucket_spec; see its docstring)."""
    return parse_bucket_spec(hps.serve_buckets, hps.max_enc_steps)


class MicroBatcher:
    """Pull requests off a RequestQueue and pack them into Batches.

    ``next_group`` implements the time/size coalescing policy;
    ``build`` packs a group into a bucket-padded, static-shape Batch.
    Single consumer by design (the ServingServer dispatch thread);
    the queue itself is the thread-safe boundary.
    """

    def __init__(self, hps: HParams, vocab: Vocab, rqueue: RequestQueue,
                 registry: Optional[obs.Registry] = None):
        self._hps = hps
        self._vocab = vocab
        self._q = rqueue
        self.max_batch = min(hps.serve_max_batch or hps.batch_size,
                             hps.batch_size)
        self._window = max(hps.serve_max_wait_ms, 0.0) / 1000.0
        self.buckets = resolve_buckets(hps)
        reg = registry if registry is not None else obs.registry_for(hps)
        # fill is the headline batching metric: mean fill ~1 means the
        # window is too short (or traffic too thin) and every dispatch
        # pays full-batch device time for one article
        self._h_fill = reg.histogram(
            "serve/batch_fill",
            buckets=[float(i) for i in range(1, hps.batch_size + 1)])
        self._h_bucket = reg.histogram(
            "serve/batch_bucket_len", buckets=[float(b) for b in self.buckets])
        self._c_batches = reg.counter("serve/batches_total")
        self._c_pad_rows = reg.counter("serve/pad_rows_total")

    def bucket_for(self, enc_len: int) -> int:
        """Smallest bucket covering `enc_len` (SummaryExample.build has
        already truncated to max_enc_steps == buckets[-1])."""
        for b in self.buckets:
            if enc_len <= b:
                return b
        return self.buckets[-1]

    def next_group(self, poll: float = 0.05) -> Optional[List[ServeRequest]]:
        """The next micro-batch worth of requests, or None after an idle
        `poll` seconds (the caller's loop re-checks its stop flag).

        The window clock starts at the FIRST request of the group: a
        request never waits more than ``serve_max_wait_ms`` for
        neighbors on top of its own queue time."""
        first = self._q.get(timeout=poll)
        if first is None:
            return None
        group = [first]
        window_ends = time.monotonic() + self._window
        while len(group) < self.max_batch:
            remaining = window_ends - time.monotonic()
            if remaining <= 0:
                # the window closed; grab whatever is ALREADY queued
                # (free fill — no extra waiting), then ship
                while len(group) < self.max_batch:
                    req = self._q.get_nowait()
                    if req is None:
                        break
                    group.append(req)
                break
            req = self._q.get(timeout=remaining)
            if req is None:
                break
            group.append(req)
        return group

    def build(self, group: List[ServeRequest]) -> Batch:
        """Pack a group into one static-shape Batch: encoder axis padded
        to the group's bucket, batch axis padded to ``hps.batch_size``
        with real_mask=False repeats."""
        bucket = max(self.bucket_for(r.example.enc_len) for r in group)
        examples = [r.example for r in group]
        n_real = len(examples)
        pad = self._hps.batch_size - n_real
        if pad:
            examples = examples + [examples[-1]] * pad
            self._c_pad_rows.inc(pad)
        mask = [i < n_real for i in range(self._hps.batch_size)]
        self._h_fill.observe(n_real)
        self._h_bucket.observe(bucket)
        self._c_batches.inc()
        return Batch(examples, self._hps, self._vocab, enc_steps=bucket,
                     real_mask=mask)
