"""Replica handles + routing policy for the elastic serving fleet
(ISSUE 13; SERVING.md "Elastic fleet").

The ``FleetRouter`` (serve/fleet.py) owns request lifecycles; this
module owns the PER-REPLICA view it routes over:

  * ``ReplicaHandle`` — one ServingServer replica plus its rotation
    state: a ``CircuitBreaker`` (``resilience/serve.replica.<id>/*``)
    that takes the replica OUT of rotation on a health failure or a
    typed submit failure and readmits it through the breaker's
    single-in-flight half-open probe (resilience/policy.py — the
    ISSUE-13 satellite hardened exactly the probe semantics this
    leans on);
  * ``healthy()`` — the routing health predicate, read off the SAME
    facts /healthz serves — heartbeat staleness straight from the
    replica registry's HeartbeatBoard (the exact board and STALE_FACTOR
    rule obs/http.health renders; reading it directly skips health()'s
    full-registry breaker-gauge sweep, which at the router's tick rate
    would be N registry scans per 5 ms) — plus the replica's LIVE
    admission-breaker state (the scraped ``breaker_state`` gauge only
    refreshes on allow(), so an external router would read /healthz's
    ``breakers`` map; in-process we can re-evaluate and never act on a
    stale OPEN);
  * ``pick_replica`` — least-loaded selection over the in-rotation
    handles (load = queued + mid-dispatch + resident + prefilled, the
    ``ServingServer.load()`` surface whose inputs are the
    queue-depth/slots-free gauges /healthz exposes).

Health policy, in rotation terms:

  * STALE HEARTBEAT (the /healthz "degraded" signal) or OPEN admission
    breaker -> ``record_failure`` on the rotation breaker (threshold 1:
    one observed failure removes the replica — the fleet has spares;
    readmission is cheap);
  * a typed submit failure (``ServeOverloadError``/``ServeClosedError``)
    -> the same, from the routing path itself;
  * readmission: after ``reset_secs`` the rotation breaker goes
    HALF_OPEN and the router's next health refresh takes the ONE probe
    (``breaker.allow()``); a healthy scrape records success (back in
    rotation), an unhealthy one re-opens.  No user request is ever
    spent as the probe.

Import-light: no jax; everything here is host-side bookkeeping.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Sequence, Tuple

from textsummarization_on_flink_tpu import obs
from textsummarization_on_flink_tpu.resilience.policy import CircuitBreaker


class ReplicaHandle:
    """One fleet replica: the server, its id, and its rotation state."""

    def __init__(self, rid: str, server, registry: Optional[obs.Registry],
                 clock: Callable[[], float] = time.monotonic,
                 reset_secs: float = 1.0):
        self.rid = rid
        self.server = server
        #: permanently dead (killed mid-decode); never rejoins rotation
        self.killed = False
        #: rolling hot-swap drain: temporarily receives no NEW requests
        self.draining = False
        # the rotation breaker: OPEN = out of rotation; its half-open
        # probe (capped to ONE in flight) is the readmission gate.
        # threshold=1 — with spare replicas, eagerly shifting load off
        # a sick one beats giving it the benefit of the doubt
        self.breaker = CircuitBreaker(
            threshold=1, reset_secs=reset_secs,
            name=f"serve.replica.{rid}", clock=clock, registry=registry)

    def healthy(self) -> bool:
        """The routing health predicate: fresh heartbeats (the /healthz
        staleness rule, read off the same HeartbeatBoard) and an
        admission breaker that is not OPEN (live-read, see module
        docstring)."""
        board = self.server.registry.heartbeats
        if board is not None and any(
                not c["ok"] for c in board.status().values()):
            return False  # a stale component == /healthz "degraded"
        return self.server.stats()["admission"] != CircuitBreaker.OPEN

    def load(self) -> int:
        return self.server.load()

    def in_rotation(self) -> bool:
        """Routable RIGHT NOW: alive, not draining, rotation breaker
        closed.  (HALF_OPEN replicas are readmitted by the router's
        health probe, not by routing user requests at them.)"""
        return (not self.killed and not self.draining
                and self.breaker.state == CircuitBreaker.CLOSED)


def pick_replica(handles: Sequence[ReplicaHandle],
                 exclude: Sequence[str] = (),
                 ) -> Optional[ReplicaHandle]:
    """The least-loaded in-rotation replica (stable on ties: earliest
    handle wins, so a single-threaded driver is fully deterministic);
    None when the rotation is empty.  `exclude` names replica ids that
    must not be picked (a hedge needs a DIFFERENT replica; a requeue
    must avoid the corpse it came from)."""
    best: Optional[ReplicaHandle] = None
    best_load = -1
    for h in handles:
        if h.rid in exclude or not h.in_rotation():
            continue
        hl = h.load()
        if best is None or hl < best_load:
            best, best_load = h, hl
    return best


def fleet_fingerprint(handles: Sequence[ReplicaHandle],
                      ) -> Optional[str]:
    """The fleet's COMMON active-params fingerprint (the FleetRouter's
    summary-cache lookup key, SERVING.md "Front door"): the one
    fingerprint every live replica reports, or None while they
    disagree — mid-rolling-swap, which snapshot serves the next decode
    depends on routing, so a mixed fleet must not answer cache lookups
    (inserts still file under the decode-time fingerprint each result
    carries, so no entry is ever mis-keyed)."""
    fps = set()
    for h in handles:
        if h.killed:
            continue
        fps.add(getattr(h.server, "params_fingerprint", "") or "")
        if len(fps) > 1:
            return None
    return next(iter(fps)) if fps else ""


def refresh_rotation(handles: Sequence[ReplicaHandle],
                     ) -> List[Tuple[str, str]]:
    """One health sweep over the fleet (the router tick's rotation
    step); returns [(rid, transition)] for replicas that changed state
    ("removed" — now out of rotation; "readmitted" — probe succeeded).

    CLOSED + unhealthy -> record_failure (threshold 1 opens: removed).
    HALF_OPEN -> take the single probe (breaker.allow()); a healthy
    scrape re-closes (readmitted), an unhealthy one re-opens.  OPEN
    inside its reset window -> untouched (still cooling off)."""
    events: List[Tuple[str, str]] = []
    for h in handles:
        if h.killed:
            continue
        state = h.breaker.state
        if state == CircuitBreaker.CLOSED:
            if not h.healthy():
                h.breaker.record_failure()
                events.append((h.rid, "removed"))
        elif state == CircuitBreaker.HALF_OPEN and h.breaker.allow():
            # the ONE half-open probe: scrape health, report the verdict
            if h.healthy():
                h.breaker.record_success()
                events.append((h.rid, "readmitted"))
            else:
                h.breaker.record_failure()
    return events


__all__ = ["ReplicaHandle", "fleet_fingerprint", "pick_replica",
           "refresh_rotation"]
