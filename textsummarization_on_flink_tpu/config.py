"""Typed hyperparameter configuration.

Replaces the reference's 23 `tf.app.flags` definitions
(/root/reference/src/main/python/pointer-generator/run_summarization.py:48-88)
and the stringly-typed `TF_Hyperparameter` argv hand-off
(TFEstimator.java:52 -> run_summarization.py:418-420) with one frozen
dataclass.  Field names and defaults match the reference flag surface so
every reference invocation has a 1:1 equivalent here; `HParams.from_argv`
still accepts the reference's ``--flag=value`` argv string form for
pipeline-level compatibility.

TPU-specific additions (not in the reference):
  * ``max_oov_buckets`` — static in-article-OOV budget.  The reference uses
    a dynamic per-batch ``max_art_oovs`` (model.py:45,162); XLA needs static
    shapes, so we pad the extended vocabulary to a fixed budget.
  * ``compute_dtype`` — bf16 compute on the MXU (params stay f32).
  * mesh axis sizes (``dp``/``tp``/``sp``) for pjit/shard_map sharding.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class HParams:
    # Where to find data (run_summarization.py:48-50)
    data_path: str = ""
    vocab_path: str = ""

    # Important settings (run_summarization.py:52-56)
    mode: str = "train"  # train / eval / decode
    num_steps: int = 0  # 0 = never stop
    single_pass: bool = False
    inference: bool = False  # decode from raw text files

    # Where to save output (run_summarization.py:58-60)
    log_root: str = ""
    exp_name: str = ""

    # Model hyperparameters (run_summarization.py:62-74)
    hidden_dim: int = 256
    emb_dim: int = 128
    batch_size: int = 16
    max_enc_steps: int = 400
    max_dec_steps: int = 100
    beam_size: int = 4
    min_dec_steps: int = 35
    vocab_size: int = 50000
    lr: float = 0.15
    adagrad_init_acc: float = 0.1
    rand_unif_init_mag: float = 0.02
    trunc_norm_init_std: float = 1e-4
    max_grad_norm: float = 2.0

    # Pointer-generator / coverage (run_summarization.py:76-81)
    pointer_gen: bool = True
    coverage: bool = False
    cov_loss_wt: float = 1.0

    # Checkpoint surgery flags (run_summarization.py:83-85)
    convert_to_coverage_model: bool = False
    restore_best_model: bool = False

    # Debugging (run_summarization.py:88)
    debug: bool = False

    # ---- TPU-native additions ----
    max_oov_buckets: int = 128  # static extended-vocab budget
    compute_dtype: str = "float32"  # or "bfloat16"
    seed: int = 111  # reference seeds tf at 111 (run_summarization.py:329)
    dp: int = 1  # data-parallel mesh axis size
    tp: int = 1  # tensor-parallel mesh axis size (output projection)
    sp: int = 1  # sequence/context-parallel mesh axis size
    model_family: str = "pointer_generator"  # or "transformer"
    # transformer-family shape (BART-class encoder-decoder; hidden_dim is
    # d_model, embeddings are tied, ffn_dim=0 means 4*hidden_dim)
    enc_layers: int = 6
    dec_layers: int = 6
    num_heads: int = 8
    ffn_dim: int = 0
    # metrics fetch cadence in steps (one blocking D2H sync per window);
    # 0 = auto: 1 under --debug, 10 otherwise
    metrics_every: int = 0
    # checkpoint cadence in STEPS; REQUIRED (>0) on multi-host runs with
    # a checkpointer (collective saves must fire at the same step on
    # every host — Trainer hard-errors otherwise); 0 on single-host
    # keeps the wall-clock save_model_secs cadence
    checkpoint_steps: int = 0
    # ---- byte diet (PERF.md "Byte diet"; ISSUE 5) ----
    # Streaming chunked vocab loss: > 0 computes the training loss in
    # lax.scan chunks of this many decoder steps, so only a
    # [chunk, B, V] scores block ever exists (forward AND backward — a
    # custom VJP recomputes each chunk's scores instead of holding the
    # [T_dec, B, V] residual, ~2x320 MB at reference scale).  0 keeps
    # the materialized hoisted-projection path.  Token-exact and
    # grad-parity-pinned vs chunk=0 for both model families.
    loss_chunk: int = 0
    # Adagrad accumulator storage dtype: "bfloat16" halves the optimizer
    # state's HBM footprint and read/write traffic; the update math
    # still runs in f32 (accumulate -> rsqrt -> apply) and params stay
    # f32 masters.  N-step drift vs f32 is pinned by test.
    opt_state_dtype: str = "float32"
    # dp-gradient all-reduce dtype: "bfloat16" halves the per-step
    # gradient collective bytes.  A registry-level wire annotation
    # (parallel/sharding.py): the unified step stacks per-dp-group
    # grads under a P("dp", ...) constraint in this dtype and XLA's
    # partitioner inserts the dp all-reduce at it; f32 everywhere
    # else.  Works on any dp x tp mesh; requires sp=1 and pointer_gen
    # losses (whose per-example normalization makes group-mean ==
    # global-mean exactly).
    grad_allreduce_dtype: str = "float32"
    # rematerialize transformer layers in backward (jax.checkpoint):
    # trades ~1/3 more FLOPs for O(layers) less activation HBM — for the
    # long-context configs (enc 800+) where activations dominate
    remat: bool = False
    # Train-loop steps per host->device dispatch (the TPU-idiomatic
    # steps_per_execution pattern): k>1 runs k optimizer steps as ONE
    # on-device lax.scan over k stacked batches, cutting host round
    # trips k-fold — decisive on RPC-proxied backends where every
    # dispatch pays a tunnel round trip.  Numerically identical to k=1
    # (same ops, same order).  Checkpoint/metrics cadences quantize to
    # dispatch boundaries; --debug forces k=1 (step-exact NaN watchdog).
    steps_per_dispatch: int = 1
    # lax.scan unroll factor for the LSTM encoder / decoder recurrences
    # (pointer-generator family).  The step is LATENCY-bound: ~500
    # sequential scan iterations of small matmuls dominate the 29 ms
    # measured step (BASELINE.md), so amortizing per-iteration loop
    # overhead across k unrolled bodies is the lever XLA can't pull
    # itself.  Numerically identical at any value; raises compile time
    # with k.  1 = no unrolling.
    scan_unroll: int = 8
    # runtime observability (obs/ registry + spans + exporters,
    # OBSERVABILITY.md): False runs this job dark — obs.registry_for(hps)
    # hands the component null metrics.  The process-wide kill switch is
    # TS_OBS=0 (read once, at default-registry creation).
    obs: bool = True
    # ---- live telemetry plane (OBSERVABILITY.md; ISSUE 9) ----
    # Exposition HTTP port for /metrics, /healthz, /snapshot, /spans
    # (obs/http.py; binds 127.0.0.1 only).  0 (default) = off; the
    # process-wide TS_OBS_HTTP=<port> env var enables it when this is
    # unset.  One server per process (first enabler wins).
    obs_http_port: int = 0
    # Flight-recorder ring capacity in frames (obs/flightrec.py): the
    # newest N per-step / per-tick frames kept in memory and dumped to
    # flight_<reason>.jsonl when a typed failure trigger fires (NaN
    # watchdog/rollback, serve dispatch failure, breaker open,
    # eviction storm).  0 disables frame recording and dumps.
    flight_frames: int = 64
    # SummaryWriter flush cadence in records: 1 flushes every write
    # (historical behavior), k>1 buffers k records per flush (the
    # reference flushes every 100 steps, run_summarization.py:242-244)
    summary_flush_every: int = 1
    # ---- performance attribution plane (obs/profile.py; ISSUE 16) ----
    # JAX/XLA profiler trace output dir for the trainer's steps-2..7
    # capture window (train/trainer.py).  "" = no capture; the legacy
    # TS_PROFILE_DIR env var is the fallback when unset, so existing
    # launch scripts keep working.  Each capture lands in the profiler
    # ledger as a `profiler_capture` note and a train/profiler_capture
    # span.
    profile_dir: str = ""
    # Analytic pricing for the divergence sentinel: True registers
    # __graft_entry__ cost-model providers (decode_step_cost /
    # prefill_cost / train_step_cost) per dispatch shape, priced ONCE
    # off the hot path, and publishes achieved bytes/s + FLOPs/s
    # gauges against them.  Off by default: pricing AOT-compiles the
    # costed program, which a short test job must not pay for.
    profile_analytic: bool = False
    # A dispatch counts as DIVERGED when its achieved bytes/s falls
    # more than this factor below the shape's calibrated baseline
    # (best of the first samples) — then the profiler dumps the flight
    # ring (flight_perf_divergence.jsonl) and surfaces the entry on
    # /alerts.  Must exceed 1; 5x tolerates normal jitter while still
    # catching silent recompiles and host-sync regressions.
    profile_divergence_factor: float = 5.0
    # ---- resilience (RESILIENCE.md; ISSUE 2) ----
    # fault-injection arming for THIS job: comma-separated
    # "point:prob:seed[:max]" specs (same syntax as the process-wide
    # TS_FAULTS env var; known points listed in resilience/faultinject.py).
    # "" (the default) leaves the job on the env plan — and with TS_FAULTS
    # also unset, every injection hook is a null-singleton no-op.
    faults: str = ""
    # Divergence recovery (train/trainer.py).  On a non-finite loss the
    # watchdog first discards the offending dispatch and SKIPS up to
    # nan_skip_steps consecutive batches (params revert to the pre-step
    # state), then ROLLS BACK to the last good checkpoint — cutting the
    # learning rate by nan_lr_cut per rollback — up to nan_max_rollbacks
    # times, and only then raises NanLossError.  Both 0 (the default)
    # keeps the reference's hard abort (train.py:107-108) and its exact
    # windowed-watchdog cost; arming either pins a per-dispatch metrics
    # sync and disables buffer donation (the pre-step state must survive
    # the dispatch), so recovery is an explicit opt-in for long
    # unattended runs.  Single-host, default-mesh only.
    nan_skip_steps: int = 0
    nan_max_rollbacks: int = 0
    # multiplicative LR cut applied at each divergence rollback (0.5 =
    # halve); must be in (0, 1]
    nan_lr_cut: float = 0.5
    # Per-request decode deadline in seconds (decode/decoder.py).  When
    # > 0 each decode_batch gets a Deadline; once a full-beam latency
    # estimate exists and the remaining budget cannot cover it, the
    # decoder degrades beam search to greedy (beam_size=1) and tags the
    # results degraded=True (counted in resilience/decode_degraded_total).
    # 0 (default) = no deadline, never degrade.
    decode_deadline_secs: float = 0.0
    # ---- concurrent serving (SERVING.md; ISSUE 4) ----
    # Requests coalesced per device dispatch by the serve/ micro-batcher
    # (0 = use batch_size; must be <= batch_size — the device batch
    # shape is always batch_size, short micro-batches are padded with
    # real_mask=False repeats).
    serve_max_batch: int = 0
    # Micro-batch coalescing window in milliseconds: after the first
    # request of a batch arrives, the batcher waits at most this long
    # for neighbors before dispatching a partial batch.  0 = dispatch
    # immediately (latency-first, fill suffers).
    serve_max_wait_ms: float = 20.0
    # Admission-controlled request queue depth: a non-blocking submit
    # against a full queue is rejected with the typed ServeOverloadError
    # (and counts against the admission circuit breaker — sustained
    # overload sheds immediately, BreakerSink semantics).
    serve_max_queue: int = 256
    # Encoder-length padding buckets for serving, as a comma-separated
    # ascending list of lengths (e.g. "100,200,400"); each micro-batch
    # pads to the smallest bucket covering its longest article, so the
    # beam-search jit cache stays bounded at len(buckets) entries per
    # beam width (hits/misses visible in decode/compile_cache_*_total).
    # "" = auto: {max_enc_steps//4, //2, max_enc_steps}, dropping
    # sub-64 buckets (except max_enc_steps itself).
    serve_buckets: str = ""
    # ---- continuous batching (SERVING.md "Continuous batching"; ISSUE 6) ----
    # Serving dispatch engine: "microbatch" (the ISSUE-4 baseline and
    # fallback — coalesce into fixed micro-batches, pay the
    # dispatch-window barrier) or "continuous" (persistent slotted
    # decode loop: finished sequences are masked out and their slots
    # refilled from the queue at chunk boundaries, so one long article
    # never holds neighbors hostage).
    serve_mode: str = "microbatch"
    # Resident decode slots for continuous mode (the [slots, beam, ...]
    # persistent state's leading axis).  0 = batch_size.  More slots
    # amortize the per-chunk dispatch over more articles but grow the
    # resident state linearly.
    serve_slots: int = 0
    # Decode steps per continuous-mode chunk: finished slots are
    # harvested and refilled every this-many steps.  Smaller = lower
    # refill latency, more host round trips.  0 = the TS_BEAM_CHUNK
    # default (beam_chunk_from_env, same source as the chunked beam
    # loop), clamped to max_dec_steps.
    serve_refill_chunk: int = 0
    # ---- decode byte diet (PERF.md "Decode byte diet"; ISSUE 7) ----
    # Transformer beam-search KV-cache storage dtype: "bfloat16" halves
    # the per-hypothesis [K, L, T, nh, hd] self-attention cache — the
    # dominant per-hypothesis resident tensor in continuous serving —
    # and its per-step gather/re-read traffic.  The attention logits and
    # softmax still run in f32 (the cache widens at the einsum), so only
    # the HBM representation narrows; N-step drift vs the f32 cache is
    # pinned by test.  The pointer-generator family has no KV cache and
    # ignores this flag.
    decode_cache_dtype: str = "float32"
    # ---- prefill/decode disaggregation (SERVING.md; ISSUE 11) ----
    # Encoder-key block length for the LENGTH-MASKED slot decode step:
    # cross-attention (and the pg attends) over a resident's encoder
    # state runs as a chain of this-many-position blocks, each gated by
    # a TRACED "block < ceil(max_active_valid_len / block)" predicate —
    # so per-chunk decode FLOPs/bytes scale with the longest ACTIVE
    # resident's true article length (block-granular) instead of the
    # uniform max_enc_steps padding, while the step kernel still
    # compiles exactly once.  Clamped to max_enc_steps; 64 keeps the
    # per-block matmuls MXU-shaped at reference scale (400 -> 7 blocks).
    decode_enc_block: int = 64
    # Continuous-mode prefill lookahead: how many requests beyond the
    # currently-free slots the ContinuousBatcher prefills per tick
    # (encoder + cross-attention cache at the article's bucket shape),
    # so a slot freed at the next chunk boundary refills from an
    # already-encoded article instead of paying prefill latency inline.
    # 0 = prefill exactly the free slots.
    serve_prefill_depth: int = 2
    # ---- paged resident state (PERF.md "Paged resident state"; ISSUE 20) ----
    # Arena page count for the block-granular slot arena: the continuous
    # engine's enc-axis resident leaves (encoder view / cross-attention
    # KV cache, extended-vocab ids, attention history) become pools of
    # decode_enc_block-row pages shared by all slots, and each admission
    # allocates only ceil(true_len / block) pages — short requests stop
    # reserving long-request memory, so the same HBM holds 2-4x the
    # residents at the bimodal mix.  0 = paging off (dense SlotState)
    # unless serve_arena_mb sets a byte budget.  Must be at least
    # ceil(max_enc_steps / decode_enc_block) (one full-length article
    # must fit) — enforced by resolve_arena_pages.
    serve_arena_pages: int = 0
    # Arena sizing by HBM byte budget instead of a page count: the page
    # count becomes floor(serve_arena_mb MiB / page_bytes), where
    # page_bytes spans one page across ALL pools
    # (beam_search.paged_page_bytes).  Ignored when serve_arena_pages is
    # set explicitly.  0 = no byte budget.
    serve_arena_mb: float = 0.0
    # ---- speculative decode tier (SERVING.md "Quality tiers"; ISSUE 10) ----
    # Draft tokens proposed per verify cycle: the draft model (AAN
    # family) proposes spec_k tokens greedily, the full model scores all
    # spec_k+1 positions in one batched step and accepts the longest
    # agreeing prefix plus its own correction token — output token-exact
    # with full-model greedy decode by construction.
    spec_k: int = 4
    # Draft-model source for the spec/draft tiers: "" = no draft
    # configured (spec/draft tier requests are rejected typed at
    # submit); "map" = bootstrap the AAN draft from the full model's own
    # checkpoint (transformer family only — models/avg_attention.
    # init_from_transformer; re-mapped on every checkpoint hot-swap);
    # "fresh" = random init (tests/smokes; exactness holds, acceptance
    # is near zero).  Separately trained drafts inject params directly
    # (BeamSearchDecoder(draft_params=...)).
    spec_draft: str = ""
    # Decoder layers the draft keeps (evenly strided over the full
    # model's; 0 = all of them).  Fewer layers = cheaper draft steps =
    # lower FLOPs/token ratio in the spec gate (BYTE_BUDGET.json
    # "spec"), at the price of acceptance rate.
    draft_dec_layers: int = 0
    # ---- distilled narrow draft (PERF.md "Distilled narrow draft";
    # ISSUE 12) ----
    # Draft decoder hidden width H_d (0 = hidden_dim, the legacy
    # equal-width draft).  H_d < hidden_dim engages the NARROW variant:
    # the draft still shares the full model's embedding/positions and
    # encoder output verbatim (copied leaves), with learned
    # down-projections at the boundaries — an [H, H_d] embedding
    # adapter and [H, H_d] cross-attention K/V maps — so only the
    # per-token decoder blocks shrink.  The narrow decoder has no
    # full-model counterpart, so it must be TRAINED
    # (train/distill.DistillTrainer); requires draft_vocab_rank > 0
    # (the tied [V, H] projection cannot consume H_d states).
    draft_hidden: int = 0
    # Low-rank factored draft vocab head: scores = (h @ [H_d, r]) @
    # [r, V] + out_bias, so the draft's projection term scales with
    # r*V instead of H*V — the lever that moves the spec tier's FLOPs
    # break-even from ~96% acceptance to ~50% at the committed
    # ref-scale recipe (BYTE_BUDGET.json "spec" expected_speedup).
    # 0 = the tied full projection (legacy).
    draft_vocab_rank: int = 0
    # ---- acceptance-adaptive spec_k (SERVING.md "Quality tiers";
    # ISSUE 12) ----
    # True: the spec tier adapts the draft length per request from the
    # measured accept histogram (decode/speculative.SpecKController) —
    # k starts at spec_k, moves within [spec_k_min, spec_k_max] via
    # the expected-progress-per-FLOP model, and the adaptation happens
    # on the HOST between draft-verify cycles, so the jitted cycle
    # kernel compiles once per distinct k in the warm set (bounded by
    # the range).  Output stays token-exact with full-model greedy for
    # ANY k sequence (the verifier is unchanged).
    spec_k_adaptive: bool = False
    spec_k_min: int = 1
    spec_k_max: int = 8
    # Quality tier a request gets when it names none (serve/server.py
    # submit(tier=...)): beam (full search) > greedy (beam_size=1,
    # token-exact with spec) > spec (draft-then-verify fast path) >
    # draft (AAN greedy, no verify — gist quality).
    serve_default_tier: str = "beam"
    # Deadline-pressure degradation target (the beam->greedy ladder
    # generalized): a beam request whose remaining budget cannot cover
    # the observed full-beam latency is re-tiered HERE instead (and a
    # spec request to "draft"), per REQUEST, not per batch.
    serve_degrade_tier: str = "greedy"
    # ---- elastic serving fleet (SERVING.md "Elastic fleet"; ISSUE 13) ----
    # In-process ServingServer replicas behind the FleetRouter
    # (serve/fleet.py): 1 (default) = the single-server path, no router.
    # More replicas buy drain/upgrade/failover independence — a replica
    # can be hot-swapped or lost without touching its neighbors' queues.
    serve_replicas: int = 1
    # Request-hedging latency budget in milliseconds: once a routed
    # request has been outstanding this long, the router duplicates it
    # to a second replica and the FIRST resolution wins (the loser's
    # result is discarded — the exactly-once future never resolves
    # twice).  0 (default) = hedging off.  A hedge is a PURCHASED
    # duplicate (FastSeq: never do redundant work), so every hedge is
    # counted (serve/hedges_total, serve/hedge_wins_total) and the
    # spend is capped by serve_hedge_max_ratio.
    serve_hedge_ms: float = 0.0
    # Hedge-rate ceiling: hedged requests may never exceed this
    # fraction of fleet admissions (over-budget hedge candidates are
    # counted in serve/hedge_suppressed_total and left to their
    # primary).  The committed gate value lives in SERVE_SLO.json.
    serve_hedge_max_ratio: float = 0.1
    # ---- serving front door (SERVING.md "Front door"; ISSUE 14) ----
    # Bounded LRU summary-cache capacity in ENTRIES, keyed on
    # (content_hash, tier, params_fingerprint) — the fingerprint key is
    # what makes checkpoint hot-swap invalidate correctly by
    # construction (a swapped decoder reports a new fingerprint, so the
    # old entries simply stop matching).  A hit resolves the future
    # synchronously at submit without touching the queue, byte-identical
    # to a fresh decode of the same (article, tier, fingerprint) —
    # the pointer-generator's deterministic tiers are what make the
    # reuse exact, not approximate.  0 (default) = cache off, today's
    # behavior.
    serve_cache_entries: int = 0
    # Approximate byte ceiling for the summary cache (cached
    # decoded-word payloads); evicts LRU-first once exceeded.  0 = no
    # byte bound (the entry bound above still applies).
    serve_cache_bytes: int = 0
    # In-flight request coalescing: True attaches every submit whose
    # (content_hash, tier) matches a resident computation to that ONE
    # decode — all attached futures resolve exactly once from the
    # leader's result (leader failure fails the attached futures typed;
    # never hangs, never double-decodes).  False (default) keeps
    # today's one-decode-per-submit behavior.
    serve_coalesce: bool = False
    # Per-tenant token-bucket admission rate in requests/second
    # (ServeRequest.tenant; the default "" tenant is a tenant like any
    # other).  A submit finding its tenant's bucket empty is shed with
    # the typed TenantThrottledError BEFORE the queue/breaker — one
    # tenant's burst spends its own bucket, not the fleet's queue.
    # 0 (default) = unlimited, today's behavior.
    serve_tenant_rate: float = 0.0
    # Token-bucket burst depth (tokens a quiet tenant may accumulate).
    # 0 = auto: max(1, ceil(serve_tenant_rate)) — about one second of
    # burst (config.resolve_tenant_burst is the one resolver).
    serve_tenant_burst: int = 0
    # Weighted-fair queue pickup weights, "tenant:weight" comma-
    # separated (e.g. "free:1,paid:4"); unlisted tenants weigh 1.0.
    # The RequestQueue's consumer side picks across per-tenant FIFOs by
    # smooth weighted round-robin, so one tenant's deep backlog cannot
    # starve another's pickup.  "" = every tenant weighs 1.0 (and a
    # single-tenant queue is exactly the historical FIFO).
    serve_fair_weights: str = ""
    # ---- multi-process fleet transport (SERVING.md "Process fleet";
    # ISSUE 17) ----
    # "inproc" (default): replicas are threads in this process — the
    # fast path and the test substrate.  "proc": each replica is a
    # supervised OS child process (serve/procfleet.py, spawned via
    # `cli.py serve-replica`) reached over loopback sockets, so a
    # segfault, OOM, or wedged XLA call costs ONE replica, not the
    # fleet.
    serve_fleet_transport: str = "inproc"
    # Hard deadline on every supervisor->child HTTP scrape and ingress
    # socket connect, in milliseconds: a wedged child costs the router
    # exactly one timeout (counted in
    # serve/replica_scrape_errors_total and treated as unhealthy),
    # never a frozen FleetRouter.tick().
    serve_scrape_timeout_ms: float = 250.0
    # Scrape-result cache window in milliseconds: the remote handle
    # serves healthy()/load() off its last /healthz scrape until it is
    # this old (the router tick runs every ~5 ms; it must not issue N
    # HTTP GETs per tick).  0 = scrape on every read.
    serve_scrape_interval_ms: float = 50.0
    # ---- hierarchical document summarization (SERVING.md
    # "Hierarchical summarization"; ISSUE 19) ----
    # Words per document chunk in the map pass (serve/hiersum.py).
    # 0 = max_enc_steps (chunk at the full encoder width); explicit
    # values must fit the encoder (<= max_enc_steps) — a chunk wider
    # than the horizon would be silently truncated at tokenization and
    # its article_key would no longer describe what was decoded.
    hier_chunk_words: int = 0
    # Words of overlap between adjacent chunks: context carried across
    # the cut so a sentence split by a boundary is seen whole by one of
    # its chunks.  Must stay below the chunk width (stride =
    # chunk - overlap must be >= 1 or chunking cannot advance).
    hier_overlap_words: int = 0
    # Quality tier of the per-chunk map decodes ("" = greedy: chunks
    # are intermediate material, cheap extractive passes suffice) and
    # of the reduce decode ("" = beam: the caller-visible summary).
    # On a continuous-mode surface both collapse to beam (the resident
    # slot state is fixed-beam, server.py submit validation).
    hier_chunk_tier: str = "greedy"
    hier_reduce_tier: str = "beam"
    # sequence-parallel transformer encoder self-attention over the sp
    # mesh axis: "" (off), "ring" (K/V blocks rotate via ppermute with an
    # online softmax — no device ever holds the full [T, T] score
    # matrix), or "ulysses" (all-to-all re-shard from sequence to heads,
    # full attention per head group, all-to-all back; needs
    # num_heads % sp == 0).  Engages wherever an sp>1 mesh is active —
    # sharded train/eval steps AND the sharded beam search; on a single
    # device it falls back to flash/einsum attention.  Incompatible with
    # tp>1 (validated).
    sp_attention: str = ""

    # -- derived --
    @property
    def extended_vsize(self) -> int:
        return self.vocab_size + self.max_oov_buckets

    @property
    def ffn_width(self) -> int:
        """Transformer FFN hidden width (ffn_dim, or 4*hidden_dim when 0)."""
        return self.ffn_dim or 4 * self.hidden_dim

    def replace(self, **kw: Any) -> "HParams":
        return dataclasses.replace(self, **kw)

    def for_decode(self) -> "HParams":
        """Decode mode forces batch_size=beam_size in the reference
        (run_summarization.py:312-313); on-device beam search keeps an
        independent batch axis, but we mirror the mode switch."""
        return self.replace(mode="decode")

    # -- (de)serialization --
    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, s: str) -> "HParams":
        d = json.loads(s)
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    @classmethod
    def from_argv(cls, argv: List[str]) -> "HParams":
        """Parse the reference's space-joined ``--flag value`` /
        ``--flag=value`` hyperparameter string (known flags only, like
        FLAGS(known_only=True) at run_summarization.py:420)."""
        fields = {f.name: f for f in dataclasses.fields(cls)}
        bool_literals = ("1", "0", "true", "false", "yes", "no")
        out: Dict[str, Any] = {}
        i = 0
        toks = [t for t in argv if t]
        while i < len(toks):
            tok = toks[i]
            if not tok.startswith("--"):
                i += 1
                continue
            body = tok[2:]
            is_bool = body.split("=", 1)[0] in fields and \
                fields[body.split("=", 1)[0]].type in ("bool", bool)
            if "=" in body:
                name, val = body.split("=", 1)
                i += 1
            elif (i + 1 < len(toks) and not toks[i + 1].startswith("--")
                  and not (is_bool and toks[i + 1].lower() not in bool_literals)):
                # separate-token value; for booleans only consume a literal,
                # so `--single_pass train_*.bin` reads as a bare True flag
                name, val = body, toks[i + 1]
                i += 2
            elif is_bool:  # bare boolean flag
                name, val = body, "True"
                i += 1
            else:  # non-bool flag with no value: skip it
                i += 1
                continue
            if name not in fields:
                continue
            ftype = fields[name].type
            if ftype in ("bool", bool):
                out[name] = str(val).lower() in ("1", "true", "yes")
            elif ftype in ("int", int):
                out[name] = int(val)
            elif ftype in ("float", float):
                out[name] = float(val)
            else:
                out[name] = val
        return cls(**out)

    def to_argv(self) -> str:
        """Render as the reference's hyperparameter string form.  Values
        with whitespace are shell-quoted; parse back with `from_string`."""
        import shlex

        parts = []
        for f in dataclasses.fields(self):
            v = str(getattr(self, f.name))
            quoted = shlex.quote(v) if v else ""  # empty stays `--flag=`
            parts.append(f"--{f.name}={quoted}")
        return " ".join(parts)

    @classmethod
    def from_string(cls, s: str) -> "HParams":
        """Parse a whole hyperparameter string (shlex-split, so quoted
        values containing spaces survive the round trip)."""
        import shlex

        return cls.from_argv(shlex.split(s))

    def validate(self) -> None:
        if self.mode not in ("train", "eval", "decode"):
            raise ValueError(f"mode must be train/eval/decode, got {self.mode!r}")
        if self.compute_dtype not in ("float32", "bfloat16"):
            raise ValueError(f"bad compute_dtype {self.compute_dtype!r}")
        if self.max_dec_steps < 1 or self.max_enc_steps < 1:
            raise ValueError("max_enc_steps/max_dec_steps must be >= 1")
        if self.min_dec_steps >= self.max_dec_steps:
            raise ValueError("min_dec_steps must be < max_dec_steps")
        from textsummarization_on_flink_tpu.models import FAMILIES

        if self.model_family not in FAMILIES:
            raise ValueError(f"unknown model_family {self.model_family!r}; "
                             f"expected one of {FAMILIES}")
        if self.model_family in ("transformer", "avg_attention"):
            if self.hidden_dim % self.num_heads != 0:
                raise ValueError(
                    f"num_heads={self.num_heads} must divide "
                    f"hidden_dim={self.hidden_dim}")
            if self.enc_layers < 1 or self.dec_layers < 1:
                raise ValueError("enc_layers/dec_layers must be >= 1")
        if self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {self.spec_k}")
        if self.spec_draft not in ("", "map", "fresh"):
            raise ValueError(
                f"spec_draft must be ''|'map'|'fresh', got "
                f"{self.spec_draft!r}")
        if not 0 <= self.draft_dec_layers <= self.dec_layers:
            raise ValueError(
                f"draft_dec_layers must be in [0, dec_layers="
                f"{self.dec_layers}], got {self.draft_dec_layers}")
        if not 0 <= self.draft_hidden <= self.hidden_dim:
            raise ValueError(
                f"draft_hidden must be in [0, hidden_dim="
                f"{self.hidden_dim}] (0 = equal width), got "
                f"{self.draft_hidden}")
        if self.draft_hidden and self.draft_hidden % self.num_heads != 0:
            raise ValueError(
                f"num_heads={self.num_heads} must divide "
                f"draft_hidden={self.draft_hidden}")
        if self.draft_vocab_rank < 0:
            raise ValueError(
                f"draft_vocab_rank must be >= 0 (0 = tied projection), "
                f"got {self.draft_vocab_rank}")
        if (0 < self.draft_hidden < self.hidden_dim
                and self.draft_vocab_rank == 0):
            raise ValueError(
                "a narrow draft (draft_hidden < hidden_dim) requires a "
                "factored vocab head (draft_vocab_rank > 0): the tied "
                "[V, H] projection cannot consume H_d-wide states")
        if self.spec_k_min < 1 or self.spec_k_max < self.spec_k_min:
            raise ValueError(
                f"need 1 <= spec_k_min <= spec_k_max, got "
                f"[{self.spec_k_min}, {self.spec_k_max}]")
        if self.spec_k_adaptive and not (
                self.spec_k_min <= self.spec_k <= self.spec_k_max):
            raise ValueError(
                f"spec_k_adaptive needs the starting spec_k={self.spec_k} "
                f"inside [spec_k_min={self.spec_k_min}, "
                f"spec_k_max={self.spec_k_max}]")
        if self.serve_default_tier not in SERVE_TIERS:
            raise ValueError(
                f"serve_default_tier must be one of {SERVE_TIERS}, got "
                f"{self.serve_default_tier!r}")
        if (self.serve_degrade_tier not in SERVE_TIERS
                or self.serve_degrade_tier == "beam"):
            raise ValueError(
                f"serve_degrade_tier must be a tier BELOW beam "
                f"({SERVE_TIERS[1:]}), got {self.serve_degrade_tier!r}")
        if self.sp_attention not in ("", "ring", "ulysses"):
            raise ValueError(
                f"sp_attention must be '', 'ring', or 'ulysses', got "
                f"{self.sp_attention!r}")
        if self.scan_unroll < 1:
            raise ValueError(
                f"scan_unroll must be >= 1, got {self.scan_unroll}")
        if self.loss_chunk < 0:
            raise ValueError(
                f"loss_chunk must be >= 0 (0 = materialized loss), got "
                f"{self.loss_chunk}")
        if self.decode_cache_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"bad decode_cache_dtype {self.decode_cache_dtype!r} "
                f"(float32/bfloat16)")
        if self.opt_state_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"bad opt_state_dtype {self.opt_state_dtype!r} "
                f"(float32/bfloat16)")
        if self.grad_allreduce_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"bad grad_allreduce_dtype {self.grad_allreduce_dtype!r} "
                f"(float32/bfloat16)")
        if self.grad_allreduce_dtype == "bfloat16":
            if self.sp > 1:
                raise ValueError(
                    "grad_allreduce_dtype=bfloat16 supports dp x tp "
                    "meshes (sp=1): the per-group gradient vmap does not "
                    "compose with sequence-parallel attention's shard_map")
            if not self.pointer_gen:
                raise ValueError(
                    "grad_allreduce_dtype=bfloat16 requires pointer_gen "
                    "losses: the baseline CE normalizes by the GLOBAL "
                    "token count, which the per-shard objective cannot "
                    "express (shard-mean != global mean)")
        if self.steps_per_dispatch < 1:
            raise ValueError(f"steps_per_dispatch must be >= 1, got "
                             f"{self.steps_per_dispatch}")
        if not 0 <= self.obs_http_port <= 65535:
            raise ValueError(f"obs_http_port must be in [0, 65535] "
                             f"(0 = off), got {self.obs_http_port}")
        if self.flight_frames < 0:
            raise ValueError(f"flight_frames must be >= 0 (0 = off), got "
                             f"{self.flight_frames}")
        if self.summary_flush_every < 1:
            raise ValueError(f"summary_flush_every must be >= 1, got "
                             f"{self.summary_flush_every}")
        if self.profile_divergence_factor <= 1.0:
            raise ValueError(
                f"profile_divergence_factor must be > 1 (a dispatch "
                f"cannot 'diverge' by running at or above baseline), "
                f"got {self.profile_divergence_factor}")
        if self.nan_skip_steps < 0 or self.nan_max_rollbacks < 0:
            raise ValueError("nan_skip_steps/nan_max_rollbacks must be >= 0")
        if not 0.0 < self.nan_lr_cut <= 1.0:
            raise ValueError(
                f"nan_lr_cut must be in (0, 1], got {self.nan_lr_cut}")
        if self.decode_deadline_secs < 0:
            raise ValueError(f"decode_deadline_secs must be >= 0, got "
                             f"{self.decode_deadline_secs}")
        if self.serve_max_batch < 0 or self.serve_max_batch > self.batch_size:
            raise ValueError(
                f"serve_max_batch must be in [0, batch_size={self.batch_size}]"
                f", got {self.serve_max_batch}")
        if self.serve_max_wait_ms < 0:
            raise ValueError(f"serve_max_wait_ms must be >= 0, got "
                             f"{self.serve_max_wait_ms}")
        if self.serve_max_queue < 1:
            raise ValueError(f"serve_max_queue must be >= 1, got "
                             f"{self.serve_max_queue}")
        # parse for validation only — bad bucket specs fail at config
        # time, not at the first micro-batch
        parse_bucket_spec(self.serve_buckets, self.max_enc_steps)
        if self.serve_mode not in ("microbatch", "continuous"):
            raise ValueError(
                f"serve_mode must be 'microbatch' or 'continuous', got "
                f"{self.serve_mode!r}")
        if self.serve_slots < 0:
            raise ValueError(f"serve_slots must be >= 0 (0 = batch_size), "
                             f"got {self.serve_slots}")
        if self.serve_refill_chunk < 0:
            raise ValueError(
                f"serve_refill_chunk must be >= 0 (0 = TS_BEAM_CHUNK "
                f"default), got {self.serve_refill_chunk}")
        if self.decode_enc_block < 1:
            raise ValueError(
                f"decode_enc_block must be >= 1, got {self.decode_enc_block}")
        if self.serve_prefill_depth < 0:
            raise ValueError(
                f"serve_prefill_depth must be >= 0, got "
                f"{self.serve_prefill_depth}")
        if self.serve_arena_pages < 0:
            raise ValueError(
                f"serve_arena_pages must be >= 0 (0 = paging off), got "
                f"{self.serve_arena_pages}")
        if self.serve_arena_mb < 0:
            raise ValueError(
                f"serve_arena_mb must be >= 0 (0 = no byte budget), got "
                f"{self.serve_arena_mb}")
        if self.serve_replicas < 1:
            raise ValueError(
                f"serve_replicas must be >= 1, got {self.serve_replicas}")
        if self.serve_cache_entries < 0:
            raise ValueError(
                f"serve_cache_entries must be >= 0 (0 = cache off), got "
                f"{self.serve_cache_entries}")
        if self.serve_cache_bytes < 0:
            raise ValueError(
                f"serve_cache_bytes must be >= 0 (0 = no byte bound), "
                f"got {self.serve_cache_bytes}")
        if self.serve_tenant_rate < 0:
            raise ValueError(
                f"serve_tenant_rate must be >= 0 (0 = unlimited), got "
                f"{self.serve_tenant_rate}")
        if self.serve_tenant_burst < 0:
            raise ValueError(
                f"serve_tenant_burst must be >= 0 (0 = auto), got "
                f"{self.serve_tenant_burst}")
        # parse for validation only — a bad weights spec fails at config
        # time, not at the first queue pickup
        parse_fair_weights(self.serve_fair_weights)
        if self.serve_hedge_ms < 0:
            raise ValueError(
                f"serve_hedge_ms must be >= 0 (0 = hedging off), got "
                f"{self.serve_hedge_ms}")
        if not 0.0 <= self.serve_hedge_max_ratio <= 1.0:
            raise ValueError(
                f"serve_hedge_max_ratio must be in [0, 1], got "
                f"{self.serve_hedge_max_ratio}")
        if self.serve_fleet_transport not in ("inproc", "proc"):
            raise ValueError(
                f"serve_fleet_transport must be 'inproc' or 'proc', got "
                f"{self.serve_fleet_transport!r}")
        if self.serve_scrape_timeout_ms <= 0:
            raise ValueError(
                f"serve_scrape_timeout_ms must be > 0 (every remote "
                f"scrape needs a hard deadline), got "
                f"{self.serve_scrape_timeout_ms}")
        if self.serve_scrape_interval_ms < 0:
            raise ValueError(
                f"serve_scrape_interval_ms must be >= 0 (0 = scrape "
                f"every read), got {self.serve_scrape_interval_ms}")
        if self.hier_chunk_words < 0:
            raise ValueError(
                f"hier_chunk_words must be >= 0 (0 = max_enc_steps), "
                f"got {self.hier_chunk_words}")
        if self.hier_chunk_words > self.max_enc_steps:
            raise ValueError(
                f"hier_chunk_words={self.hier_chunk_words} exceeds "
                f"max_enc_steps={self.max_enc_steps}: a chunk wider than "
                f"the encoder horizon is silently truncated at "
                f"tokenization and its cache key lies about its content")
        effective_chunk = self.hier_chunk_words or self.max_enc_steps
        if not 0 <= self.hier_overlap_words < effective_chunk:
            raise ValueError(
                f"hier_overlap_words must be in [0, chunk_words="
                f"{effective_chunk}) so the chunk stride stays >= 1, "
                f"got {self.hier_overlap_words}")
        for name in ("hier_chunk_tier", "hier_reduce_tier"):
            tier = getattr(self, name)
            if tier and tier not in SERVE_TIERS:
                raise ValueError(
                    f"{name} must be one of {SERVE_TIERS} (or '' for "
                    f"the default), got {tier!r}")
        if self.faults:
            # parse for validation only (unknown points / bad probs fail
            # here, at config time, not at the injection site)
            from textsummarization_on_flink_tpu.resilience import faultinject

            faultinject.parse(self.faults)


#: Per-request serving quality tiers, costliest first (SERVING.md
#: "Quality tiers"; ISSUE 10).  Dependency-light single source: the
#: serve layer validates request tiers against this and the decoder
#: dispatches on it.
SERVE_TIERS = ("beam", "greedy", "spec", "draft")


def derive_draft_hps(hps: "HParams") -> "HParams":
    """The draft model's HParams, derived from the full model's: the
    avg_attention family at the same hidden width (the checkpoint
    mapping requires it) with ``draft_dec_layers`` decoder layers
    (0 = the full model's count).  The ONE resolver — the decoder,
    the spec engine, the FLOPs gate, and bench all derive through
    here so no two components can disagree about the draft's shape."""
    return hps.replace(
        model_family="avg_attention",
        dec_layers=hps.draft_dec_layers or hps.dec_layers)


def resolve_draft_hidden(hps: "HParams") -> int:
    """Effective draft decoder width (draft_hidden, or hidden_dim when
    0) — the ONE resolver, shared by models/avg_attention.py's param
    shapes, __graft_entry__'s analytic FLOPs model, and bench's
    fingerprint so no two components can disagree about the draft's
    width."""
    return hps.draft_hidden or hps.hidden_dim


def resolve_spec_bounds(hps: "HParams") -> "Tuple[int, int, int]":
    """(k_min, k_start, k_max) for the speculative tier.  Non-adaptive
    jobs pin all three to spec_k; adaptive jobs get the committed
    [spec_k_min, spec_k_max] range.  The ONE resolver — the decoder's
    accept-histogram buckets, the SpecKController, and the adaptive
    engine's verify-cache width all derive through here, so a metric
    bucket can never be narrower than the k the controller may pick."""
    if not hps.spec_k_adaptive:
        return (hps.spec_k, hps.spec_k, hps.spec_k)
    return (hps.spec_k_min, hps.spec_k, hps.spec_k_max)


def parse_bucket_spec(spec: str, max_enc_steps: int) -> "List[int]":
    """Resolve ``serve_buckets`` to the ascending encoder-length bucket
    list the serve/ micro-batcher pads into (SERVING.md).

    The ONE parser: HParams.validate() and serve/batcher.py both resolve
    through this, so a spec that validates is exactly the spec that
    serves.  ``max_enc_steps`` is always the top bucket — an article is
    already truncated to it by SummaryExample.build, so every request
    fits some bucket.  Auto ("" spec): {max//4, max//2, max}, dropping
    sub-64 buckets (a tiny bucket saves little padding but costs a
    whole extra jit-cache entry); explicit specs keep every entry.
    Dependency-light (no jax/numpy) so config stays importable anywhere.
    """
    spec = (spec or "").strip()
    if not spec:
        buckets = sorted({max_enc_steps // 4, max_enc_steps // 2,
                          max_enc_steps})
        return [b for b in buckets
                if b == max_enc_steps or b >= 64]
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        try:
            b = int(tok)
        except ValueError:
            raise ValueError(
                f"serve_buckets entry {tok!r} is not an integer") from None
        if b < 1:
            raise ValueError(f"serve_buckets entries must be >= 1, got {b}")
        if b > max_enc_steps:
            raise ValueError(
                f"serve_buckets entry {b} exceeds max_enc_steps="
                f"{max_enc_steps} (padding past the model's static "
                f"encoder budget buys nothing)")
        out.append(b)
    buckets = sorted(set(out))
    if not buckets or buckets[-1] != max_enc_steps:
        # the top bucket must cover every admissible article
        buckets.append(max_enc_steps)
    return buckets


def parse_fair_weights(spec: str) -> "Dict[str, float]":
    """Resolve ``serve_fair_weights`` to a {tenant: weight} dict
    (SERVING.md "Front door").

    The ONE parser: HParams.validate() and serve/queue.py both resolve
    through this, so a spec that validates is exactly the spec the
    weighted-fair pickup runs.  Unlisted tenants weigh 1.0 (the
    RequestQueue applies that default at pickup, not here).
    Dependency-light (no jax/numpy) so config stays importable anywhere.
    """
    spec = (spec or "").strip()
    if not spec:
        return {}
    out: Dict[str, float] = {}
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        if ":" not in tok:
            raise ValueError(
                f"serve_fair_weights entry {tok!r} must be tenant:weight")
        tenant, _, w = tok.rpartition(":")
        tenant = tenant.strip()
        if not tenant:
            raise ValueError(
                f"serve_fair_weights entry {tok!r} names no tenant (the "
                f"default tenant's weight is always 1.0)")
        try:
            weight = float(w)
        except ValueError:
            raise ValueError(
                f"serve_fair_weights weight {w!r} is not a number"
            ) from None
        if weight <= 0:
            raise ValueError(
                f"serve_fair_weights weight for {tenant!r} must be > 0, "
                f"got {weight}")
        out[tenant] = weight
    return out


def resolve_tenant_burst(hps: "HParams") -> int:
    """Effective per-tenant token-bucket burst depth: the explicit
    serve_tenant_burst, or ~one second of the configured rate (min 1)
    when 0 — the ONE resolver, shared by serve/frontdoor.py and the
    SLO gate so a committed isolation number runs the burst it names."""
    if hps.serve_tenant_burst:
        return hps.serve_tenant_burst
    return max(1, int(hps.serve_tenant_rate + 0.999999))


def beam_chunk_from_env() -> int:
    """Effective TS_BEAM_CHUNK for the chunked beam-decode loop.

    The SINGLE source of the 25-step default: decode/beam_search.py
    resolves the jit cache key through this, and bench.py's config
    fingerprint (which must stay importable without jax) records it —
    a drift between the two would let a measurement under one chunk
    size stand in for another.
    """
    import os

    return int(os.environ.get("TS_BEAM_CHUNK", "25"))


def resolve_serve_slots(hps: "HParams") -> int:
    """Effective continuous-mode slot count (serve_slots, or batch_size
    when 0) — the ONE resolver, shared by serve/server.py and bench.py
    so a measurement's slot count is exactly the server's."""
    return hps.serve_slots or hps.batch_size


def resolve_enc_block(hps: "HParams") -> int:
    """Effective encoder-key block length for the length-masked slot
    decode step (prefill/decode disaggregation, SERVING.md): the
    decode_enc_block HParam clamped to [1, max_enc_steps] — the ONE
    resolver, shared by the model families' blocked attention paths and
    __graft_entry__.decode_step_cost, so the measured program's block
    structure is exactly the served one's."""
    return max(1, min(int(hps.decode_enc_block), hps.max_enc_steps))


def bucket_for(buckets: "List[int]", enc_len: int) -> int:
    """Smallest bucket covering ``enc_len`` (the serve/ micro-batcher's
    routing rule, now shared with the continuous engine's prefill stage
    — ONE rule, so the two serving modes bucket identically).  Articles
    are already truncated to buckets[-1] by SummaryExample.build."""
    for b in buckets:
        if enc_len <= b:
            return b
    return buckets[-1]


def resolve_refill_chunk(hps: "HParams") -> int:
    """Effective continuous-mode chunk length: serve_refill_chunk, or
    the TS_BEAM_CHUNK default (the chunked beam loop's single source),
    clamped to [1, max_dec_steps]."""
    chunk = hps.serve_refill_chunk or beam_chunk_from_env()
    return max(1, min(int(chunk), hps.max_dec_steps))


def resolve_arena_pages(hps: "HParams",
                        page_bytes: "Optional[int]" = None) -> int:
    """Effective page count of the paged-resident-state arena (ISSUE
    20): ``serve_arena_pages`` when set explicitly, else the page count
    a ``serve_arena_mb`` HBM byte budget buys (page_bytes — one page's
    span across all pools, beam_search.paged_page_bytes — is required
    for budget mode), else 0 = paging off.  The ONE resolver, shared by
    decode/decoder.SlotDecodeEngine, __graft_entry__'s cost model, and
    bench.py's fingerprint, so the measured arena is exactly the served
    one.  A non-zero result is validated to hold at least one
    full-length article (ceil(max_enc_steps / decode_enc_block) pages)
    — anything smaller would deadlock the first max-length admission
    rather than backpressure it."""
    b_max = -(-hps.max_enc_steps // resolve_enc_block(hps))
    if hps.serve_arena_pages > 0:
        pages = int(hps.serve_arena_pages)
    elif hps.serve_arena_mb > 0:
        if not page_bytes or page_bytes <= 0:
            raise ValueError(
                "serve_arena_mb sizing needs page_bytes "
                "(beam_search.paged_page_bytes(params, hps))")
        pages = int(hps.serve_arena_mb * (1 << 20) // page_bytes)
    else:
        return 0
    if pages < b_max:
        raise ValueError(
            f"arena of {pages} page(s) cannot hold one full-length "
            f"article ({b_max} pages of {resolve_enc_block(hps)} rows "
            f"at max_enc_steps={hps.max_enc_steps}); raise "
            f"serve_arena_pages/serve_arena_mb or decode_enc_block")
    return pages


def resolve_hier_chunk_words(hps: "HParams") -> int:
    """Effective map-pass chunk width for hierarchical summarization:
    ``hier_chunk_words``, or the full encoder horizon when 0.  The ONE
    resolver — serve/hiersum.py's chunker, the SLO gate's document
    construction, and bench's fingerprint all derive through here so no
    two components can disagree about where a chunk boundary falls
    (boundary drift would silently break the append-path cache pins)."""
    return hps.hier_chunk_words or hps.max_enc_steps


def flash_mode_from_env() -> str:
    """TS_FLASH resolved to 'on' / 'off' / 'auto' — the ONE token parser
    (models/transformer._use_flash routes on it; bench.py's fingerprint
    resolves it further to the actual kernel choice)."""
    import os

    v = os.environ.get("TS_FLASH", "auto").lower()
    if v in ("1", "on", "true", "yes"):
        return "on"
    if v in ("0", "off", "false", "no"):
        return "off"
    return "auto"
