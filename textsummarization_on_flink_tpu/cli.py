"""Command-line entry: the reference's run_summarization.py surface.

Dispatch parity with /root/reference/src/main/python/pointer-generator/
run_summarization.py `main` (:333-367):

  * checkpoint-surgery flags run-and-exit: --convert_to_coverage_model
    (:157-178), --restore_best_model (:132-154);
  * --inference=1: decode raw text files (RawTextBatcher path, :339-348);
  * --mode=train: Batcher over chunk files + training loop with 60s
    checkpointing (:351-356, Supervisor save_model_secs);
  * --mode=eval: reload-latest-checkpoint eval loop with running-average
    loss and best-model saving (:357-359 -> :247-292);
  * --mode=decode: beam-search decode, ROUGE when --single_pass (:360-365).

Flags are the reference's 23 names via HParams.from_argv (config.py); the
seed matches tf.set_random_seed(111) (:329).

Usage:
    python -m textsummarization_on_flink_tpu --mode=train \
        --data_path=.../train_* --vocab_path=.../vocab \
        --log_root=/tmp/log --exp_name=myexperiment
"""

from __future__ import annotations

import logging
import os
import sys
from typing import List, Optional

from textsummarization_on_flink_tpu.checkpoint import checkpointer as ckpt_lib
from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.data.batcher import Batcher
from textsummarization_on_flink_tpu.data.etl import raw_text_example_source
from textsummarization_on_flink_tpu.data.vocab import Vocab
from textsummarization_on_flink_tpu.decode.decoder import BeamSearchDecoder
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

log = logging.getLogger(__name__)


def _dirs(hps: HParams):
    root = os.path.join(hps.log_root or ".", hps.exp_name or "exp")
    return root, os.path.join(root, "train"), os.path.join(root, "eval")


def setup_training(hps: HParams, vocab: Vocab,
                   batcher: Optional[Batcher] = None) -> trainer_lib.TrainState:
    """run_summarization.py:181-209: restore-or-init, train with periodic
    checkpoints (save_model_secs=60 parity)."""
    from textsummarization_on_flink_tpu.parallel import distributed

    from textsummarization_on_flink_tpu.utils import local_batch_hps

    _, train_dir, _ = _dirs(hps)
    # multi-host: the batcher feeds THIS host's shard of the global batch
    batcher = batcher or Batcher(hps.data_path, vocab, local_batch_hps(hps),
                                 single_pass=hps.single_pass)
    # Checkpointer.save is collective-then-chief-writes, so every host
    # holds one (the reference's is_chief MonitoredTrainingSession role,
    # train.py:74-81, applies to the WRITE inside save); every host also
    # restores so a resumed run starts from the same step.
    checkpointer = ckpt_lib.Checkpointer(train_dir, hps=hps)
    if distributed.is_chief():
        # embedding-projector metadata (model.py:185-197, data.py:93-105)
        vocab.write_metadata(os.path.join(train_dir, "vocab_metadata.tsv"))
    state = checkpointer.restore()
    if state is not None:
        log.info("restored training from step %d", int(state.step))
    trainer = trainer_lib.Trainer(hps, vocab.size(), batcher, state=state,
                                  checkpointer=checkpointer,
                                  train_dir=train_dir)
    return trainer.train(num_steps=hps.num_steps)


def run_eval(hps: HParams, vocab: Vocab, max_iters: int = 0,
             batcher: Optional[Batcher] = None) -> float:
    """run_summarization.py:247-292: each iteration loads the newest train
    checkpoint, evaluates one batch, updates the smoothed loss, and saves
    `bestmodel` on improvement.  max_iters=0 runs forever (reference
    behavior); tests pass a bound."""
    from textsummarization_on_flink_tpu.utils import local_batch_hps

    eval_hps = hps.replace(mode="eval")
    _, train_dir, eval_dir = _dirs(hps)
    batcher = batcher or Batcher(hps.data_path, vocab,
                                 local_batch_hps(eval_hps),
                                 single_pass=False)
    evaluator = trainer_lib.Evaluator(
        eval_hps, vocab.size(), batcher, eval_dir=eval_dir,
        best_saver=ckpt_lib.BestModelSaver(eval_dir))
    iters = 0
    while True:
        path, flat = ckpt_lib.load_ckpt(train_dir)
        state = ckpt_lib.arrays_to_state(flat)
        log.info("evaluating checkpoint %s (step %d)", path, int(state.step))
        evaluator.run(state.params, int(state.step), max_batches=1)
        iters += 1
        if max_iters and iters >= max_iters:
            return evaluator.running_avg_loss


def run_decode(hps: HParams, vocab: Vocab,
               batcher: Optional[Batcher] = None):
    """run_summarization.py:360-365 (+ raw-text inference :339-348)."""
    decode_hps = hps.replace(mode="decode")
    if batcher is None:
        if hps.inference:
            # Deliberate divergence: the reference keeps the process alive
            # after a non-single_pass raw-text run drains its (finite) file
            # glob, blocked forever in next_batch (batcher.py:382-395 ends
            # the fill thread without marking completion).  We treat the
            # glob as one bounded pass and exit cleanly either way.
            batcher = Batcher("", vocab, decode_hps, single_pass=True,
                              example_source=raw_text_example_source(
                                  hps.data_path))
        else:
            # The reference repeats ONE article across the batch because
            # its beam occupies the batch axis (run_summarization.py:312,
            # batcher.py:344-347).  Our beam search carries its own beam
            # axis, so a decode batch holds batch_size DISTINCT articles —
            # same per-article results, batch_size x the throughput.
            batcher = Batcher(hps.data_path, vocab, decode_hps,
                              single_pass=hps.single_pass,
                              decode_batch_mode="distinct")
    _, train_dir, _ = _dirs(hps)
    decoder = BeamSearchDecoder(decode_hps, vocab, batcher,
                                train_dir=train_dir)
    return decoder.decode(
        with_rouge=hps.single_pass and not hps.inference)


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s")
    argv = argv if argv is not None else sys.argv[1:]
    if argv and argv[0] == "serve-replica":
        # the process-fleet child entry (ISSUE 17; SERVING.md "Process
        # fleet"): config arrives as TS_HPS_JSON, ports leave through
        # the portfile handshake — not a flags-surface mode, so it
        # dispatches before the reference's 23-flag parse
        from textsummarization_on_flink_tpu.serve import procfleet

        return procfleet.replica_child_main(argv[1:])
    hps = HParams.from_argv(argv)
    hps.validate()
    log.info("Starting summarization in %s mode...", hps.mode)
    from textsummarization_on_flink_tpu.utils import apply_debug_mode

    apply_debug_mode(hps)  # --debug -> jax_debug_nans (ref :216-218)

    # surgery flags run-and-exit (:341-349 equivalents)
    _, train_dir, eval_dir = _dirs(hps)
    if hps.convert_to_coverage_model:
        ckpt_lib.convert_to_coverage_model(train_dir, hps, seed=hps.seed)
        return 0
    if hps.restore_best_model:
        ckpt_lib.restore_best_model(eval_dir, train_dir, hps)
        return 0

    vocab = Vocab(hps.vocab_path, hps.vocab_size)
    if hps.inference:
        run_decode(hps, vocab)
    elif hps.mode == "train":
        setup_training(hps, vocab)
    elif hps.mode == "eval":
        run_eval(hps, vocab)
    elif hps.mode == "decode":
        run_decode(hps, vocab)
    else:
        raise ValueError(
            "The 'mode' flag must be one of train/eval/decode")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
