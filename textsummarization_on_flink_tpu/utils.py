"""Small host-side utilities (me/littlebo/SysUtils.java parity)."""

from __future__ import annotations

import os


def get_project_root_dir() -> str:
    """The process working directory (SysUtils.java:4-6 `user.dir`)."""
    return os.getcwd()
