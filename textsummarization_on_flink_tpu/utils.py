"""Small host-side utilities (me/littlebo/SysUtils.java parity)."""

from __future__ import annotations

import os


def get_project_root_dir() -> str:
    """The process working directory (SysUtils.java:4-6 `user.dir`)."""
    return os.getcwd()


def apply_debug_mode(hps) -> None:
    """Wire the --debug flag: the reference attaches tfdbg's
    has_inf_or_nan filter (run_summarization.py:88,216-218); the JAX
    equivalent is jax_debug_nans, which re-runs the offending op
    un-jitted and raises at the first non-finite intermediate.  (The
    Trainer additionally dumps the offending batch under --debug.)"""
    if getattr(hps, "debug", False):
        import jax

        jax.config.update("jax_debug_nans", True)


def local_batch_hps(hps):
    """Per-host view of a global config for BATCHER construction: on a
    multi-host run each host's input pipeline must yield its own
    batch_size/process_count rows (the mesh/step functions keep the
    GLOBAL hps.batch_size)."""
    import jax

    nproc = jax.process_count()
    if nproc <= 1:
        return hps
    if hps.batch_size % nproc != 0:
        raise ValueError(f"batch_size={hps.batch_size} must be divisible "
                         f"by process_count={nproc}")
    return hps.replace(batch_size=hps.batch_size // nproc)
