"""GSPMD sharding-spec registry: ONE mesh story for train, serve, and
checkpoint (ISSUE 8; PERF.md "One mesh").

Every tensor role in the system maps HERE — and only here — to a
`PartitionSpec` over the named ``(dp, tp, sp)`` mesh, plus (where a role
is reduced across the mesh) a *wire dtype* annotation:

  role            spec source                  wire dtype
  --------------  ---------------------------  -----------------------
  params          `param_spec` (per-leaf rule) —
  opt_state       same tree rule as params     —
  step counter    replicated                   —
  train batch     `batch_spec` (dp rows,       —
                  sp over T_enc)
  eval batch      same as train batch          —
  step metrics    replicated scalars           —
  grads           same tree rule as params     ``hps.grad_allreduce_dtype``
  beam output     dp over articles             —
  slot state      dp over resident slots       —
  prefill batch   dp over prefill rows at      —
                  bucket shapes (replicated
                  for 1-article serving
                  prefills)
  prefill state   same leading-axis rule       —

Consumers: the unified train/eval step builders (parallel/mesh.py), the
serving paths (`make_sharded_beam_search`, `decode/decoder.py`'s
`SlotDecodeEngine`), the checkpointer (`Checkpointer.restore_sharded`),
and bench/roofline byte accounting (`analytic_comms`).  No step builder
constructs its own PartitionSpecs — layout decisions live in this one
declarative place so batch/mesh size can grow to fill the hardware
without touching application code (the FastSeq restructuring applied to
the whole system; SNIPPETS.md [2]/[3]).

The wire-dtype annotation is how the bf16 gradient all-reduce lever
(PR 5's 86 -> 43 MB/step) rides ANY dp x tp mesh: the registry says
*what* is reduced over dp and *in what dtype*; the step builder groups
the batch ``[B] -> [dp, B/dp]``, computes per-group grads under `vmap`,
casts the stacked grads to the wire dtype under a sharding constraint
``P("dp", *param_spec)``, and sums over the group axis — XLA's
partitioner turns that sum into the dp all-reduce at the wire dtype.
(jax 0.4.x's `shard_map(auto=...)` hard-crashes XLA's partitioner on
this scan-heavy model, so the manual-collective route is closed; the
constraint+sum route keeps the whole step ONE pjit program.)

Note on CPU HLO: the CPU backend promotes sub-f32 all-reduces to f32
around a convert pair, so a faked-mesh compile shows an f32 wire with
bf16 *rounding semantics* (parity tests pin those); on TPU the wire is
genuinely bf16.  The comms gate therefore pins the reduced ELEMENT
count from HLO and prices bytes at the registry's wire dtype.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from textsummarization_on_flink_tpu.config import HParams

PyTree = Any

#: Canonical mesh axis order (parallel/mesh.py builds meshes in this
#: order; replica-group attribution in the comms gate depends on it).
MESH_AXES = ("dp", "tp", "sp")

#: The train/eval batch array names (the model-family input contract).
BATCH_NAMES = ("enc_batch", "enc_lens", "enc_padding_mask",
               "enc_batch_extend_vocab", "dec_batch", "target_batch",
               "dec_padding_mask")

#: Encoder-side names only (the beam-search / serving input contract).
ENC_BATCH_NAMES = ("enc_batch", "enc_lens", "enc_padding_mask",
                   "enc_batch_extend_vocab")

#: Every role the registry answers for (`ShardingRegistry.table()`
#: documents each; tests assert coverage).
ROLES = ("params", "opt_state", "step", "train_batch", "eval_batch",
         "metrics", "grads", "beam_output", "slot_state",
         "prefill_batch", "prefill_state", "arena_pool", "page_table")


# --------------------------------------------------------------------------
# Spec rules (pure: hps + tensor role -> PartitionSpec)
# --------------------------------------------------------------------------

def param_spec(path: Tuple[Any, ...], leaf: Any = None) -> P:
    """PartitionSpec for one model-family parameter leaf.

    Pointer-generator: vocab-dimension tensors shard over `tp`;
    everything else (LSTM kernels, attention, reduce — all small:
    ~[384,1024] at the default config) is replicated, which keeps their
    per-step all-reduce traffic at zero.

    Transformer: the tied [V, H] embedding and [V] out_bias shard over
    vocab; attention wq/wk/wv and ffn w1 column-shard (heads/ffn over
    tp), wo and ffn w2 row-shard — the Megatron layout, so each
    attention/FFN block needs exactly one all-reduce on its output.
    """
    keys = tuple(getattr(p, "key", getattr(p, "idx", None)) for p in path)
    if "embedding" in keys:
        return P("tp", None)  # [V, E|H] row-sharded over vocab
    if "output_projection" in keys:
        if keys[-1] == "w":
            return P(None, "tp")  # [H, V] column-sharded over vocab
        return P("tp")  # bias v: [V]
    if keys[-1] == "out_bias":
        return P("tp")  # transformer tied-projection bias [V]
    if keys[-1] in ("wq", "wk", "wv", "w1"):
        return P(None, "tp")  # heads / ffn hidden over tp
    if keys[-1] in ("wo", "w2"):
        return P("tp", None)  # row-parallel back to H
    if keys[-1] == "b1":
        return P("tp")  # ffn hidden bias [F]
    return P()


def param_specs(params: PyTree) -> PyTree:
    """PartitionSpec tree for a parameter pytree (grads and Adagrad
    accumulators share this tree rule — same structure, same layout)."""
    return jax.tree_util.tree_map_with_path(param_spec, params)


def batch_spec(name: str) -> P:
    """Batch arrays shard over dp on axis 0; encoder-sequence-major
    arrays additionally shard T_enc over sp (context parallelism)."""
    if name in ("enc_batch", "enc_padding_mask", "enc_batch_extend_vocab"):
        return P("dp", "sp")
    return P("dp")


def state_specs(state: Any) -> Any:
    """Specs for a full TrainState: params and the Adagrad accumulators
    share the param tree rule; the scalar step is replicated."""
    pspecs = param_specs(state.params)
    acc_specs = param_specs(state.opt_state.accumulators)
    return type(state)(
        params=pspecs,
        opt_state=type(state.opt_state)(accumulators=acc_specs),
        step=P(),
    )


def grouped_batch_spec(name: str) -> P:
    """Spec for a batch array regrouped ``[B, ...] -> [dp, B/dp, ...]``
    (the wire-dtype gradient path): the group axis carries dp, the row
    axis un-shards, trailing axes keep their batch rule."""
    return P("dp", None, *batch_spec(name)[1:])


def stacked_grad_spec(leaf_spec: P) -> P:
    """Spec for per-dp-group grads stacked on a leading axis: dp leads,
    the leaf keeps its param-rule layout — constraining the stacked
    tree to this in the wire dtype is what makes XLA lower the group
    sum to the dp all-reduce at that dtype."""
    return P("dp", *leaf_spec)


def wire_dtype(hps: HParams, role: str = "grads"):
    """The dtype a reduced role rides the mesh wire in, or None when the
    reduction stays in the tensor's own dtype (XLA's default psum)."""
    if role == "grads" \
            and getattr(hps, "grad_allreduce_dtype", "float32") == "bfloat16":
        import jax.numpy as jnp

        return jnp.bfloat16
    return None


# --------------------------------------------------------------------------
# Registry (mesh-bound: specs + NamedSharding materialization)
# --------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ShardingRegistry:
    """The mesh-bound registry: every consumer asks THIS object for
    specs/shardings; nothing else constructs PartitionSpecs."""

    mesh: Mesh
    hps: HParams

    # -- axis sizes --
    @property
    def dp(self) -> int:
        return self.mesh.shape["dp"]

    @property
    def tp(self) -> int:
        return self.mesh.shape["tp"]

    @property
    def sp(self) -> int:
        return self.mesh.shape["sp"]

    # -- spec trees per role --
    def param_specs(self, params: PyTree) -> PyTree:
        return param_specs(params)

    def state_specs(self, state: Any) -> Any:
        return state_specs(state)

    def batch_spec(self, name: str) -> P:
        return batch_spec(name)

    def grouped_batch_spec(self, name: str) -> P:
        return grouped_batch_spec(name)

    def stacked_grad_spec(self, leaf_spec: P) -> P:
        return stacked_grad_spec(leaf_spec)

    def batch_specs(self, names: Sequence[str] = BATCH_NAMES,
                    ) -> Dict[str, P]:
        return {k: batch_spec(k) for k in names}

    def metric_specs(self) -> Any:
        """Replicated scalars, as a StepMetrics tree."""
        from textsummarization_on_flink_tpu.train import trainer as trainer_lib

        return trainer_lib.StepMetrics(
            loss=P(), coverage_loss=P(), total_loss=P(), global_norm=P())

    def beam_output_specs(self) -> Any:
        """Serving decode output: articles shard over dp, beams stay
        chip-local (zero cross-chip traffic in the decode loop)."""
        from textsummarization_on_flink_tpu.decode import beam_search

        return beam_search.BeamSearchOutput(
            tokens=P("dp"), length=P("dp"), avg_log_prob=P("dp"),
            attn_dists=P("dp"), p_gens=P("dp"))

    def slot_state_specs(self, state: PyTree) -> PyTree:
        """Continuous-serving SlotState: every leaf leads with the
        [slots, ...] axis, sharded over dp (slots % dp == 0, validated
        by the engine); per-slot beams stay chip-local like the batch
        search.

        Paged resident state (ISSUE 20): a PagedSlotState splits into
        two placement classes.  Slot-leading leaves (beam, enc_rest,
        masks/lengths) keep the dp rule above.  The page POOLS and the
        scratch row lead with the [pages+1, ...] arena axis, which has
        no relation to dp — they replicate (role ``arena_pool``), and
        the page TABLE passed alongside as data replicates too (role
        ``page_table``); every chip addresses its slots' pages locally.
        Sharding the arena itself over dp (per-chip sub-arenas with a
        dp-local free list) is a deferred follow-on — it needs the host
        allocator split per chip, not just a spec change here.
        """
        from textsummarization_on_flink_tpu.decode import beam_search

        if isinstance(state, beam_search.PagedSlotState):
            dp = jax.tree_util.tree_map(lambda _: P("dp"), state)
            rep = jax.tree_util.tree_map(lambda _: self.arena_pool_spec(),
                                         state)
            return beam_search.PagedSlotState(
                beam=dp.beam, enc_rest=dp.enc_rest,
                enc_pages=rep.enc_pages, ext_pool=rep.ext_pool,
                attn_pool=rep.attn_pool, enc_mask=dp.enc_mask,
                enc_valid_len=dp.enc_valid_len)
        return jax.tree_util.tree_map(lambda _: P("dp"), state)

    def arena_pool_spec(self) -> P:
        """Page pools ([pages+1, block, ...] leaves of a
        PagedSlotState): replicated — the arena axis is allocator
        bookkeeping, not a device axis (see slot_state_specs)."""
        return P()

    def page_table_spec(self) -> P:
        """The per-slot page table ([slots, B_max] int32, traced DATA
        never shape): replicated, like the length/mask operands of the
        compile-once kernels — it is tiny and consulted by every chip's
        gather."""
        return P()

    def slot_batch_specs(self) -> Dict[str, P]:
        """Encoder arrays stacked over slots (the slot-init contract):
        the slots axis shards over dp; T_enc stays unsharded (continuous
        serving pads to ONE resident shape, no sp context parallelism
        in the slot loop)."""
        return {k: P("dp") for k in ENC_BATCH_NAMES}

    # -- prefill/decode disaggregation (ISSUE 11) --
    def prefill_batch_spec(self, rows: int = 1) -> P:
        """PREFILL-stage placement rule: bucket-shaped encoder arrays
        batch-shard over dp when the prefill batch divides the axis;
        the continuous engine's one-article prefill replicates (its
        [1, bucket] leaves cannot split, and dp's job in serving is
        sharding the RESIDENT slots — the two stages place separately
        from this one table)."""
        return P("dp") if rows >= self.dp and rows % self.dp == 0 else P()

    def prefill_batch_specs(self, rows: int = 1) -> Dict[str, P]:
        spec = self.prefill_batch_spec(rows)
        return {k: spec for k in ENC_BATCH_NAMES}

    def prefill_state_specs(self, pre: PyTree) -> PyTree:
        """Specs for a PrefillState (padded encoder view + valid
        length, leading axis = the prefill batch): same leading-axis
        rule as the input arrays, so a prefilled article lands where
        pack_slot_jit's scatter into the dp-sharded resident state
        expects it."""
        rows = jax.tree_util.tree_leaves(pre)[0].shape[0]
        spec = self.prefill_batch_spec(rows)
        return jax.tree_util.tree_map(lambda _: spec, pre)

    def wire_dtype(self, role: str = "grads"):
        return wire_dtype(self.hps, role)

    # -- NamedSharding materialization / placement --
    def named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)

    def shardings(self, spec_tree: PyTree) -> PyTree:
        return jax.tree_util.tree_map(
            self.named, spec_tree, is_leaf=lambda x: isinstance(x, P))

    def constrain(self, x: Any, spec: P) -> Any:
        """with_sharding_constraint against this registry's mesh — the
        one sanctioned way for traced code to pin a layout."""
        return jax.lax.with_sharding_constraint(x, self.named(spec))

    def shard_state(self, state: Any) -> Any:
        """Place a host-resident TrainState onto the mesh."""
        specs = self.state_specs(state)
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, self.named(s)), state, specs,
            is_leaf=lambda x: isinstance(x, P))

    def shard_batch(self, arrays: Dict[str, Any]) -> Dict[str, Any]:
        return {k: jax.device_put(v, self.named(batch_spec(k)))
                for k, v in arrays.items()}

    def shard_params(self, params: PyTree) -> PyTree:
        return jax.tree_util.tree_map(
            lambda x, s: jax.device_put(x, self.named(s)), params,
            param_specs(params), is_leaf=lambda x: isinstance(x, P))

    # -- documentation --
    def table(self) -> List[Dict[str, str]]:
        """The role -> spec -> wire-dtype table (PERF.md "One mesh";
        tests assert it covers ROLES)."""
        w = self.hps.grad_allreduce_dtype
        rows = [
            {"role": "params", "spec": "per-leaf rule (vocab/heads over "
                                       "tp, else replicated)", "wire": "-"},
            {"role": "opt_state", "spec": "same tree rule as params",
             "wire": "-"},
            {"role": "step", "spec": "P()", "wire": "-"},
            {"role": "train_batch", "spec": "P('dp'[, 'sp'])", "wire": "-"},
            {"role": "eval_batch", "spec": "P('dp'[, 'sp'])", "wire": "-"},
            {"role": "metrics", "spec": "P()", "wire": "-"},
            {"role": "grads", "spec": "same tree rule as params",
             "wire": w},
            {"role": "beam_output", "spec": "P('dp')", "wire": "-"},
            {"role": "slot_state", "spec": "P('dp')", "wire": "-"},
            {"role": "prefill_batch",
             "spec": "P('dp') at bucket shapes when the prefill batch "
                     "divides dp, else P()", "wire": "-"},
            {"role": "prefill_state", "spec": "same leading-axis rule "
                                              "as prefill_batch",
             "wire": "-"},
            {"role": "arena_pool",
             "spec": "P() — [pages+1, block, ...] pools replicate; the "
                     "arena axis is allocator bookkeeping, not a device "
                     "axis", "wire": "-"},
            {"role": "page_table",
             "spec": "P() — [slots, B_max] int32 traced data, "
                     "replicated like length/mask operands", "wire": "-"},
        ]
        return rows


@functools.lru_cache(maxsize=16)
def _registry_cached(mesh: Mesh, hps: HParams) -> ShardingRegistry:
    return ShardingRegistry(mesh=mesh, hps=hps)


def registry_for(plan: Any) -> ShardingRegistry:
    """The registry for a parallel/mesh.MeshPlan (cached: one registry
    per (mesh, hps) pair, so every consumer sees the same object)."""
    return _registry_cached(plan.mesh, plan.hps)


# --------------------------------------------------------------------------
# Analytic comms accounting (the CPU-verifiable wire-byte claims)
# --------------------------------------------------------------------------

def analytic_comms(hps: HParams, params: Optional[PyTree] = None) -> dict:
    """Per-step collective-byte prediction from the registry specs alone
    (no mesh, no compile — importable wherever HParams is).

    Returns::

      param_elements     total parameter scalars
      dp_grad_elements   per-device elements the dp gradient all-reduce
                         moves each step: tp-sharded leaves contribute
                         their SHARD (each tp group reduces its own
                         slice over dp); replicated leaves contribute
                         their full size (every tp replica reduces its
                         own copy)
      dp_wire_bytes      dp_grad_elements x wire-dtype size — 43.0 MB
                         at reference scale under the bf16 wire, the
                         retired lowp path's committed number
      wire_dtype         the registry's grad wire dtype name
      tp_scores_bytes    analytic ceiling anchor for the tp activation
                         collectives: the per-step [T_dec, B, V]
                         scores-shaped all-gather/reduce at compute
                         dtype (0 when tp == 1)

    The comms gate (tests/test_bytes_gate.py) pins the HLO-measured
    element counts against dp_grad_elements and prices bytes at the
    wire dtype, because the CPU backend promotes bf16 all-reduces to
    f32 around a convert pair (see module docstring).
    """
    from textsummarization_on_flink_tpu.train import trainer as trainer_lib

    if params is None:
        params = jax.eval_shape(
            lambda: trainer_lib.init_train_state(
                hps, hps.vocab_size, seed=0)).params
    tp = max(int(hps.tp), 1)
    total = 0
    dp_elems = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        elems = int(np.prod(leaf.shape)) if leaf.shape else 1
        total += elems
        spec = param_spec(path, leaf)
        dp_elems += elems // tp if "tp" in spec else elems
    wire = hps.grad_allreduce_dtype
    wire_size = 2 if wire == "bfloat16" else 4
    compute_size = 2 if hps.compute_dtype == "bfloat16" else 4
    scores = (hps.max_dec_steps * hps.batch_size * hps.extended_vsize
              * compute_size if tp > 1 else 0)
    return {
        "param_elements": total,
        "dp_grad_elements": dp_elems,
        "dp_wire_bytes": dp_elems * wire_size,
        "wire_dtype": wire,
        "tp_scores_bytes": scores,
    }
