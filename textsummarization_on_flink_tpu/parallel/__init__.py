from textsummarization_on_flink_tpu.parallel.mesh import (  # noqa: F401
    MeshPlan,
    batch_pspec,
    batch_sharding,
    make_mesh,
    make_sharded_eval_step,
    make_sharded_train_step,
    param_pspecs,
    shard_train_state,
    state_pspecs,
)
