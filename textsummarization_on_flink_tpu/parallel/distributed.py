"""Multi-host initialization + cross-host utilities.

Replaces the reference's cluster bring-up — ZooKeeper rendezvous
(TFEstimator.java:50-51, MLConstants.STORAGE_ZOOKEEPER) plus
`tf.train.Server`/`ClusterSpec` boilerplate (run_summarization.py:403-417)
— with `jax.distributed.initialize`: the JAX coordination service is the
rendezvous, and after initialization every host sees the global device
list, so the same MeshPlan code works single-host and multi-host (the mesh
just spans DCN).

The parameter-server role does not exist here: where the reference's ps
processes busy-sleep holding variables (run_summarization.py:412-415),
SPMD keeps parameters resident on the devices that use them.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger(__name__)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up the JAX coordination service (idempotent, single-host no-op).

    In a managed TPU environment all three args auto-detect; pass them
    explicitly for manual bring-up (the equivalent of the reference's
    zookeeper_connect_str + worker index, HasClusterConfig.java:15-29).
    """
    if num_processes is not None and num_processes <= 1:
        log.info("single-process run; skipping jax.distributed.initialize")
        return
    # No local jax calls before initialize: anything that touches the
    # backend (device_count, process_count, ...) would pin a single-process
    # view and make initialization fail.  With no args this auto-detects
    # the cluster environment (TPU metadata / cluster plugins) and is a
    # no-op on genuinely single-process runs.
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
    except (RuntimeError, ValueError) as e:
        if coordinator_address is None and num_processes is None:
            # Only degrade to single-process when nothing in the environment
            # suggests we are part of a cluster; a transient coordinator
            # failure on a real multi-host job must fail fast, or every
            # host would think it is chief and clobber shared checkpoints.
            cluster_markers = (
                "JAX_COORDINATOR_ADDRESS", "MEGASCALE_COORDINATOR_ADDRESS",
                "TPU_WORKER_HOSTNAMES", "CLOUD_TPU_TASK_ID",
            )
            if any(os.environ.get(k) for k in cluster_markers):
                raise
            log.warning("jax.distributed auto-detect found no cluster (%s); "
                        "continuing single-process", e)
            return
        raise
    log.info("jax.distributed up: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())


def is_chief() -> bool:
    """The process that writes checkpoints/summaries (the reference's
    `is_chief=True` MonitoredTrainingSession role, train.py:74-81)."""
    return jax.process_index() == 0


def barrier(name: str = "barrier") -> None:
    """Cross-host sync point (used around checkpoint save/restore)."""
    if jax.process_count() == 1:
        return
    # A tiny psum over all devices acts as a barrier.
    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices(name)
