"""Ring attention: sequence-parallel self-attention over the sp mesh axis.

The reference has NO long-context story — inputs are truncated to
``max_enc_steps`` (/root/reference/src/main/python/pointer-generator/
batcher.py:52-55).  This module is the rebuild's first-class sequence/
context parallelism (SURVEY §5.7): each sp shard holds its own block of
queries, keys, and values ([B, T/sp, ...]); K/V blocks rotate around the
ring via ``jax.lax.ppermute`` while a numerically-stable online softmax
accumulates the output — the full [T, T] score matrix never exists on any
one device, and per-step communication is the [B, T/sp, nh, hd] K/V
blocks riding ICI neighbor-to-neighbor (the ring pattern overlaps compute
with transfer on TPU).

Semantically identical to full masked softmax attention: the online
max/sum telescopes to the global softmax (flash-attention algebra), and
padding keys are masked with -1e30 before the max so a block of pure
padding contributes exp(-1e30 - m) = 0.

Used by the transformer family (models/transformer.py) when
``hps.sp_attention`` selects 'ring' (or 'ulysses', below) and the encoder
runs under an sp>1 mesh; exposed standalone for tests and reuse.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

Array = jax.Array

NEG = -1e30


def _block_attn(q: Array, k: Array, kmask: Array,
                sm_scale: float) -> Array:
    """Masked scores of local q against one K block.

    q: [B, Tq, nh, hd]; k: [B, Tk, nh, hd]; kmask: [B, Tk].
    Returns logits [B, nh, Tq, Tk] (f32, padding keys at -1e30).
    """
    logits = jnp.einsum("bqnd,bknd->bnqk", q, k).astype(jnp.float32)
    logits = logits * sm_scale
    logits = jnp.where(kmask[:, None, None, :] > 0, logits, NEG)
    return logits


def _axis_size(axis_name: str) -> int:
    """Static size of a mapped axis, across jax versions: lax.axis_size
    where it exists (>= 0.6), else the classic psum-of-1 idiom (a static
    python int under shard_map on 0.4.x)."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def compat_shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """shard_map across jax versions — the ONE dispatch site (used here
    and by parallel/mesh.py's low-precision all-reduce step): jax >= 0.6
    has first-class jax.shard_map (check_vma); 0.4.x has the
    experimental module (check_rep), where a scalar's spec must be a
    fully-replicated P() rather than None."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map

    if isinstance(in_specs, tuple):
        in_specs = tuple(P() if s is None else s for s in in_specs)
    return shard_map(fn, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def ring_self_attention(q: Array, k: Array, v: Array, kv_mask: Array,
                        axis_name: str, sm_scale: float) -> Array:
    """One shard's view: q/k/v [B, T_blk, nh, hd], kv_mask [B, T_blk].

    Must run inside shard_map (or any SPMD context) where `axis_name` is a
    ring of sp devices.  Returns the attention output [B, T_blk, nh, hd]
    for the local queries against the GLOBAL key/value sequence.
    """
    n = _axis_size(axis_name)
    B, Tb, nh, hd = q.shape
    perm = [(j, (j + 1) % n) for j in range(n)]

    def body(carry, rotate):
        m, l, o, k_cur, v_cur, mask_cur = carry
        logits = _block_attn(q, k_cur, mask_cur, sm_scale)
        m_blk = jnp.max(logits, axis=-1)  # [B, nh, Tq]
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(logits - m_new[..., None])  # [B, nh, Tq, Tk]
        p = p * (mask_cur[:, None, None, :] > 0)  # exact zeros on padding
        scale_old = jnp.exp(m - m_new)
        l = l * scale_old + jnp.sum(p, axis=-1)
        o = o * scale_old[..., None] + jnp.einsum(
            "bnqk,bknd->bnqd", p.astype(v_cur.dtype), v_cur
        ).astype(jnp.float32)
        if rotate:
            # rotate K/V/mask to the next device on the ring (neighbor
            # transfer over ICI; overlapped with the next block's compute)
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            mask_cur = jax.lax.ppermute(mask_cur, axis_name, perm)
        return m_new, l, o, k_cur, v_cur, mask_cur

    m0 = jnp.full((B, nh, Tb), NEG, jnp.float32)
    l0 = jnp.zeros((B, nh, Tb), jnp.float32)
    o0 = jnp.zeros((B, nh, Tb, hd), jnp.float32)
    carry = (m0, l0, o0, k, v, kv_mask)
    # python loop (n is small and static) keeps each ppermute a separate
    # XLA op that the scheduler can overlap with the matmuls; the last
    # block's rotation is skipped — its carry is never read
    for i in range(n):
        carry = body(carry, rotate=i < n - 1)
    _, l, o, _, _, _ = carry
    # fully-masked query rows (all-padding article): l=0 -> zero output
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(q.dtype)  # [B,Tq,nh,hd]


def make_ring_attention(mesh: Mesh, axis_name: str = "sp"):
    """shard_map-wrapped ring attention over `mesh`'s sp axis.

    Inputs are GLOBAL arrays (inside or outside jit): q/k/v
    [B, T, nh, hd] sharded (or shardable) as P(None, sp) on T; mask
    [B, T].  Output matches q's global shape.
    """
    return make_sp_attention(mesh, "ring", axis_name)


# --------------------------------------------------------------------------
# Ulysses (all-to-all) sequence parallelism — the other standard SP layout
# --------------------------------------------------------------------------

def ulysses_self_attention(q: Array, k: Array, v: Array, kv_mask: Array,
                           axis_name: str, sm_scale: float) -> Array:
    """DeepSpeed-Ulysses-style SP: all-to-all re-shards q/k/v from
    sequence-sharded [B, T/sp, nh, hd] to head-sharded [B, T, nh/sp, hd],
    runs ordinary full attention per local head group, and all-to-alls
    back.  Per call: three input all-to-alls (q/k/v) + one mask
    all-gather + one output all-to-all, vs the ring's (sp-1) K/V
    rotations — better when heads divide evenly and T is only moderately
    long; the ring wins when T is so long that even one device's full-T
    K/V working set is the constraint.  Requires nh % sp == 0."""
    # [B, Tb, nh, hd] -> [B, T, nh/sp, hd]: split heads, concat sequence
    qh = jax.lax.all_to_all(q, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    kh = jax.lax.all_to_all(k, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    vh = jax.lax.all_to_all(v, axis_name, split_axis=2, concat_axis=1,
                            tiled=True)
    mask_full = jax.lax.all_gather(kv_mask, axis_name, axis=1, tiled=True)
    logits = _block_attn(qh, kh, mask_full, sm_scale)
    probs = jax.nn.softmax(logits, axis=-1)
    probs = probs * (mask_full[:, None, None, :] > 0)  # all-padding row -> 0
    ctx = jnp.einsum("bnqk,bknd->bqnd", probs.astype(vh.dtype), vh)
    # [B, T, nh/sp, hd] -> [B, T/sp, nh, hd]
    return jax.lax.all_to_all(ctx, axis_name, split_axis=1, concat_axis=2,
                              tiled=True).astype(q.dtype)


def make_sp_attention(mesh: Mesh, mode: str, axis_name: str = "sp"):
    """shard_map-wrapped sequence-parallel attention over `mesh`'s sp
    axis.  mode: 'ring' or 'ulysses'.  Global-array calling convention is
    identical for both (q/k/v [B, T, nh, hd] T-sharded, mask [B, T])."""
    if mode == "ring":
        inner = ring_self_attention
    elif mode == "ulysses":
        inner = ulysses_self_attention
    else:
        raise ValueError(f"unknown sp_attention mode {mode!r}")

    def fn(q, k, v, mask, sm_scale):
        return inner(q, k, v, mask, axis_name, sm_scale)

    batch = "dp" if mesh.shape.get("dp", 1) > 1 else None
    spec4 = P(batch, axis_name, None, None)
    spec2 = P(batch, axis_name)
    return compat_shard_map(fn, mesh,
                            in_specs=(spec4, spec4, spec4, spec2, None),
                            out_specs=spec4)


# --------------------------------------------------------------------------
# Mesh context: lets model code reach the ambient mesh during pjit tracing
# --------------------------------------------------------------------------

_CURRENT_MESH: Optional[Mesh] = None


class mesh_context:
    """Set the ambient mesh while tracing a sharded step so model-level
    code (transformer ring attention) can build shard_map calls against
    it.  Trace-time only: the mesh is captured into the jaxpr."""

    def __init__(self, mesh: Optional[Mesh]):
        self._mesh = mesh
        self._prev: Optional[Mesh] = None

    def __enter__(self):
        global _CURRENT_MESH
        self._prev = _CURRENT_MESH
        _CURRENT_MESH = self._mesh
        return self._mesh

    def __exit__(self, *exc):
        global _CURRENT_MESH
        _CURRENT_MESH = self._prev
        return False


def current_mesh() -> Optional[Mesh]:
    return _CURRENT_MESH
