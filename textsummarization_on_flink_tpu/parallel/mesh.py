"""Device mesh + sharding layer: SPMD data/tensor parallelism via pjit.

This replaces the reference's entire distributed stack — the TF1
parameter-server/worker cluster (`ClusterSpec`/`tf.train.Server`/
`replica_device_setter`, /root/reference/src/main/python/pointer-generator/
run_summarization.py:406-417), ZooKeeper coordination
(TFEstimator.java:50-51), and gRPC variable traffic — with a single SPMD
program over a `jax.sharding.Mesh`:

  * **dp** axis: batch sharding.  Gradients are all-reduced by XLA-inserted
    `psum` over ICI, replacing the reference's (scaffolded, never-exercised)
    async PS-style data parallelism (`worker_num`, HasClusterConfig.java:20-24).
  * **tp** axis: tensor parallelism for the big vocab matmuls — the
    `[H, vocab]` output projection (model.py:228-238) and the `[vocab, E]`
    embedding table are sharded over the vocab axis; XLA inserts the
    all-gather / reduce-scatter.
  * **sp** axis: context parallelism over the encoder sequence axis for the
    long-context configs (BASELINE.json configs[3]) — encoder states,
    attention energies, and coverage shard over T_enc; the per-step context
    reduction becomes a psum.  (The LSTM time scan itself is sequential, so
    sp shards the *attention/feature* tensors, which dominate memory at
    long T_enc.)

There is no parameter server and no coordination store to configure: in a
multi-host deployment `jax.distributed.initialize()` (distributed.py) is
the rendezvous, and collectives ride ICI within a slice / DCN across
slices.

Everything here works identically on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``), which is how tests and the
driver's `dryrun_multichip` validate multi-chip behavior without hardware.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

PyTree = Any

log = logging.getLogger(__name__)

MESH_AXES = ("dp", "tp", "sp")


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A mesh plus the sharding rules derived from it."""

    mesh: Mesh
    hps: HParams

    @property
    def dp(self) -> int:
        return self.mesh.shape["dp"]

    @property
    def tp(self) -> int:
        return self.mesh.shape["tp"]

    @property
    def sp(self) -> int:
        return self.mesh.shape["sp"]

    def named(self, spec: P) -> NamedSharding:
        return NamedSharding(self.mesh, spec)


def make_mesh(hps: HParams, devices: Optional[Sequence[jax.Device]] = None,
              ) -> MeshPlan:
    """Build the (dp, tp, sp) mesh.

    Axis sizes come from hps; when dp*tp*sp is smaller than the available
    device count the mesh uses a prefix subset (and logs it — raise your
    axis sizes to use the whole machine).  With all axes 1 this degrades
    gracefully to single-device.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    want = hps.dp * hps.tp * hps.sp
    if want > len(devices):
        raise ValueError(
            f"mesh needs dp*tp*sp={want} devices, have {len(devices)}")
    if want < len(devices):
        log.info("mesh uses %d of %d available devices (dp=%d tp=%d sp=%d)",
                 want, len(devices), hps.dp, hps.tp, hps.sp)
    grid = np.asarray(devices[:want]).reshape(hps.dp, hps.tp, hps.sp)
    return MeshPlan(mesh=Mesh(grid, MESH_AXES), hps=hps)


# --------------------------------------------------------------------------
# Sharding rules
# --------------------------------------------------------------------------

def param_pspecs(params: PyTree) -> PyTree:
    """PartitionSpec tree for a model-family parameter pytree.

    Pointer-generator: vocab-dimension tensors shard over `tp`; everything
    else (LSTM kernels, attention, reduce — all small: ~[384,1024] at the
    default config) is replicated, which keeps their per-step all-reduce
    traffic at zero.

    Transformer: the tied [V, H] embedding and [V] out_bias shard over
    vocab; attention wq/wk/wv and ffn w1 column-shard (heads/ffn over tp),
    wo and ffn w2 row-shard — the Megatron layout, so each attention/FFN
    block needs exactly one all-reduce on its output.
    """

    def spec_for(path: Tuple[Any, ...], leaf: Any) -> P:
        keys = tuple(getattr(p, "key", getattr(p, "idx", None)) for p in path)
        if "embedding" in keys:
            return P("tp", None)  # [V, E|H] row-sharded over vocab
        if "output_projection" in keys:
            if keys[-1] == "w":
                return P(None, "tp")  # [H, V] column-sharded over vocab
            return P("tp")  # bias v: [V]
        if keys[-1] == "out_bias":
            return P("tp")  # transformer tied-projection bias [V]
        if keys[-1] in ("wq", "wk", "wv", "w1"):
            return P(None, "tp")  # heads / ffn hidden over tp
        if keys[-1] in ("wo", "w2"):
            return P("tp", None)  # row-parallel back to H
        if keys[-1] == "b1":
            return P("tp")  # ffn hidden bias [F]
        return P()

    return jax.tree_util.tree_map_with_path(spec_for, params)


def batch_pspec(name: str) -> P:
    """Batch arrays shard over dp on axis 0; encoder-sequence-major arrays
    additionally shard T_enc over sp (context parallelism)."""
    if name in ("enc_batch", "enc_padding_mask", "enc_batch_extend_vocab"):
        return P("dp", "sp")
    return P("dp")


def batch_sharding(plan: MeshPlan) -> Dict[str, NamedSharding]:
    names = ("enc_batch", "enc_lens", "enc_padding_mask",
             "enc_batch_extend_vocab", "dec_batch", "target_batch",
             "dec_padding_mask")
    return {k: plan.named(batch_pspec(k)) for k in names}


def state_pspecs(state: trainer_lib.TrainState) -> trainer_lib.TrainState:
    """PartitionSpecs for the full TrainState: params and the Adagrad
    accumulators (same tree structure -> same specs); scalar step is
    replicated."""
    pspecs = param_pspecs(state.params)
    acc_specs = param_pspecs(state.opt_state.accumulators)
    return trainer_lib.TrainState(
        params=pspecs,
        opt_state=type(state.opt_state)(accumulators=acc_specs),
        step=P(),
    )


def shard_train_state(plan: MeshPlan,
                      state: trainer_lib.TrainState) -> trainer_lib.TrainState:
    """Place a host-resident TrainState onto the mesh."""
    specs = state_pspecs(state)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, plan.named(s)), state, specs,
        is_leaf=lambda x: isinstance(x, P))


def shard_batch(plan: MeshPlan, arrays: Dict[str, Any]) -> Dict[str, Any]:
    return {k: jax.device_put(v, plan.named(batch_pspec(k)))
            for k, v in arrays.items()}


# --------------------------------------------------------------------------
# Sharded step functions
# --------------------------------------------------------------------------

def _with_mesh_context(plan: MeshPlan, fn):
    """Expose the plan's mesh to model code while the step traces, so
    mesh-aware ops (ring attention's shard_map) can bind to it."""
    from textsummarization_on_flink_tpu.parallel import ring_attention as ra

    def wrapped(*args):
        with ra.mesh_context(plan.mesh):
            return fn(*args)

    return wrapped

def param_shardings(plan: MeshPlan, params: Optional[PyTree] = None):
    """NamedSharding tree for a parameter pytree; pass `params` when its
    structure differs from a fresh init (e.g. TF1-imported trees)."""
    probe = params if params is not None else jax.eval_shape(
        lambda: trainer_lib.init_train_state(
            plan.hps, plan.hps.vocab_size, seed=0)).params
    return jax.tree_util.tree_map(
        lambda s: plan.named(s), param_pspecs(probe),
        is_leaf=lambda x: isinstance(x, P))


def make_sharded_train_step(plan: MeshPlan, donate: bool = True,
                            state: Optional[trainer_lib.TrainState] = None):
    """pjit the train step over the mesh.

    The step function is the same pure function as single-device
    (train/trainer.make_train_step); sharding is expressed entirely through
    in/out shardings, and XLA inserts the dp-axis gradient psum, the
    tp-axis collectives around the vocab matmuls, and the sp-axis context
    reductions.  This is the whole "distributed backend".

    ``--grad_allreduce_dtype=bfloat16`` switches to an explicit-collective
    variant (make_lowp_allreduce_train_step) where the dp gradient psum is
    issued by hand in bf16 — half the per-step collective bytes.

    Pass `state` when its pytree structure differs from a fresh init (e.g.
    a TF1-imported non-coverage checkpoint has no decoder/attention/w_c
    leaf); specs are derived from the given tree so pjit's in_shardings
    structure matches.
    """
    hps = plan.hps
    if getattr(hps, "grad_allreduce_dtype", "float32") == "bfloat16":
        return make_lowp_allreduce_train_step(plan, donate=donate,
                                              state=state)
    step_fn = _with_mesh_context(plan, trainer_lib.make_train_step(hps))
    probe = state if state is not None else jax.eval_shape(
        # structure only, nothing allocated
        lambda: trainer_lib.init_train_state(hps, hps.vocab_size, seed=0))
    state_sh = jax.tree_util.tree_map(
        lambda s: plan.named(s), state_pspecs(probe),
        is_leaf=lambda x: isinstance(x, P))
    del probe
    batch_sh = batch_sharding(plan)
    metric_sh = trainer_lib.StepMetrics(
        loss=plan.named(P()), coverage_loss=plan.named(P()),
        total_loss=plan.named(P()), global_norm=plan.named(P()))
    return jax.jit(
        step_fn,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, metric_sh),
        donate_argnums=(0,) if donate else (),
    )


def make_lowp_allreduce_train_step(
        plan: MeshPlan, donate: bool = True,
        state: Optional[trainer_lib.TrainState] = None):
    """Data-parallel train step with the dp gradient all-reduce issued
    EXPLICITLY in a low-precision dtype (--grad_allreduce_dtype=bfloat16).

    The pjit path's gradient psum is inserted by XLA's partitioner in the
    gradients' own dtype (f32) and cannot be narrowed from the outside,
    so this variant runs the whole step under shard_map over the dp axis:
    each shard computes grads on its local batch rows, the per-leaf psum
    is cast to bf16 for the wire and widened back to f32 immediately
    after (clipping/Adagrad/params all stay f32), and the optimizer
    update replays identically on every shard.  Per-step collective bytes
    halve — the roofline lever PERF.md's byte-diet section measures.

    Restrictions (validated here and in HParams.validate):
      * pure-dp mesh (tp=sp=1) — forward-internal tp/sp collectives stay
        on the pjit path;
      * pointer_gen losses — their per-example normalization makes the
        mean-of-shard-means exactly the global mean, so the bf16 cast is
        the ONLY difference from the pjit step (parity pinned by test).
    """
    import jax.numpy as jnp

    hps = plan.hps
    if plan.tp > 1 or plan.sp > 1:
        raise ValueError(
            "grad_allreduce_dtype=bfloat16 supports pure-dp meshes only "
            f"(tp=sp=1), got tp={plan.tp} sp={plan.sp}")
    if not hps.pointer_gen:
        raise ValueError(
            "grad_allreduce_dtype=bfloat16 requires pointer_gen losses "
            "(shard-mean == global-mean); the baseline CE normalizes by "
            "the global token count")
    from textsummarization_on_flink_tpu.train import optim

    loss_fn = trainer_lib.make_loss_fn(hps)
    inv_dp = 1.0 / plan.dp

    def per_shard(state, arrays):
        grads, out = jax.grad(
            lambda p: loss_fn(p, arrays), has_aux=True)(state.params)
        # THE lever: the dp all-reduce rides the wire in bf16 (half the
        # bytes); f32 is restored before any update math touches it
        grads = jax.tree_util.tree_map(
            lambda g: jax.lax.psum(g.astype(jnp.bfloat16), "dp")
            .astype(jnp.float32) * inv_dp, grads)
        grads, gnorm = optim.clip_by_global_norm(grads, hps.max_grad_norm)
        new_params, new_opt = optim.adagrad_update(
            grads, state.opt_state, state.params, hps.lr)
        new_state = trainer_lib.TrainState(
            params=new_params, opt_state=new_opt, step=state.step + 1)
        metrics = trainer_lib.StepMetrics(
            loss=jax.lax.pmean(out.loss, "dp"),
            coverage_loss=jax.lax.pmean(out.coverage_loss, "dp"),
            total_loss=jax.lax.pmean(out.total_loss, "dp"),
            global_norm=gnorm)
        return new_state, metrics

    probe = state if state is not None else jax.eval_shape(
        lambda: trainer_lib.init_train_state(hps, hps.vocab_size, seed=0))
    state_specs = state_pspecs(probe)
    batch_specs = {k: batch_pspec(k)
                   for k in batch_sharding(plan)}
    metric_specs = trainer_lib.StepMetrics(
        loss=P(), coverage_loss=P(), total_loss=P(), global_norm=P())
    from textsummarization_on_flink_tpu.parallel import ring_attention as ra

    fn = ra.compat_shard_map(per_shard, plan.mesh,
                             in_specs=(state_specs, batch_specs),
                             out_specs=(state_specs, metric_specs))
    return jax.jit(fn, donate_argnums=(0,) if donate else ())


def make_sharded_eval_step(plan: MeshPlan, params: Optional[PyTree] = None):
    """Pass `params` when the tree structure differs from a fresh init
    (e.g. a TF1-imported checkpoint) so in_shardings match, mirroring
    make_sharded_train_step's `state` parameter."""
    hps = plan.hps
    eval_fn = _with_mesh_context(plan, trainer_lib.make_eval_step(hps))
    param_sh = param_shardings(plan, params)
    batch_sh = batch_sharding(plan)
    metric_sh = trainer_lib.StepMetrics(
        loss=plan.named(P()), coverage_loss=plan.named(P()),
        total_loss=plan.named(P()), global_norm=plan.named(P()))
    return jax.jit(eval_fn, in_shardings=(param_sh, batch_sh),
                   out_shardings=metric_sh)


def validate_divisibility(hps: HParams, params: Optional[PyTree] = None,
                          ) -> None:
    """Fail fast with actionable errors instead of opaque device_put
    shape complaints (the vocab file may hold fewer words than
    --vocab_size, so the ACTUAL embedding rows are what tp must divide)."""
    if hps.dp > 1 and hps.batch_size % hps.dp != 0:
        raise ValueError(f"data-parallel axis dp={hps.dp} must divide "
                         f"batch_size={hps.batch_size}")
    if hps.tp > 1 and params is not None:
        vsize_actual = params["embedding"].shape[0]
        if vsize_actual % hps.tp != 0:
            raise ValueError(
                f"tensor-parallel axis tp={hps.tp} must divide the actual "
                f"vocabulary size {vsize_actual} (the vocab file may hold "
                f"fewer words than --vocab_size); pick a dividing tp or "
                f"trim the vocab")
    if hps.sp > 1 and hps.max_enc_steps % hps.sp != 0:
        raise ValueError(f"sequence-parallel axis sp={hps.sp} must divide "
                         f"max_enc_steps={hps.max_enc_steps}")
    if hps.sp > 1 and hps.sp_attention == "ulysses" \
            and hps.num_heads % hps.sp != 0:
        raise ValueError(
            f"sp_attention=ulysses re-shards heads over sp: sp={hps.sp} "
            f"must divide num_heads={hps.num_heads}")
    if hps.tp > 1 and hps.model_family == "transformer":
        if hps.num_heads % hps.tp != 0:
            raise ValueError(
                f"tensor-parallel axis tp={hps.tp} must divide "
                f"num_heads={hps.num_heads} (Megatron head sharding)")
        if hps.ffn_width % hps.tp != 0:
            raise ValueError(f"tensor-parallel axis tp={hps.tp} must divide "
                             f"ffn_dim={hps.ffn_width}")
        if hps.sp_attention:
            raise ValueError(
                "sp_attention with tp>1 is not supported: the SP "
                "shard_map replicates the head axis, which would silently "
                "all-gather the Megatron-sharded q/k/v every layer — use "
                "sp-only attention (tp=1) or tp without sp_attention")


def make_sharded_beam_search(plan: MeshPlan,
                             params: Optional[PyTree] = None):
    """Multi-chip serving: beam-search decode with the article batch
    sharded over dp (each chip searches its own articles; beams stay
    chip-local, so there is zero cross-chip traffic during the decode
    loop — the ideal layout for throughput serving).

    Returns a jitted fn(params, arrays) -> BeamSearchOutput.  Encoder
    inputs shard over (dp[, sp]); params replicate/tp-shard as in
    training.
    """
    from textsummarization_on_flink_tpu.decode import beam_search

    hps = plan.hps
    param_sh = param_shardings(plan, params)
    enc_names = ("enc_batch", "enc_lens", "enc_padding_mask",
                 "enc_batch_extend_vocab")
    batch_sh = {k: plan.named(batch_pspec(k)) for k in enc_names}
    out_sh = beam_search.BeamSearchOutput(
        tokens=plan.named(P("dp")), length=plan.named(P("dp")),
        avg_log_prob=plan.named(P("dp")), attn_dists=plan.named(P("dp")),
        p_gens=plan.named(P("dp")))

    def search(p, arrays):
        return beam_search._search_batch(p, hps, arrays)

    # mesh context so the encoder's sp attention engages in serving too
    # (a model trained with --sp_attention because [T,T] doesn't fit one
    # device must not fall back to full attention at decode time)
    search = _with_mesh_context(plan, search)
    return jax.jit(search, in_shardings=(param_sh, batch_sh),
                   out_shardings=out_sh)


def make_host_local_transfer(plan: MeshPlan, global_batch_size: int,
                             label: str = "train"):
    """Batch-transfer fn for one host of a multi-host run: validates this
    host's row count (batch_size/process_count) then assembles the global
    dp-sharded batch.  Shared by Trainer and Evaluator so the check and
    the error text cannot drift."""
    import jax

    nproc = jax.process_count()
    if global_batch_size % nproc != 0:
        raise ValueError(f"{label} batch_size={global_batch_size} must be "
                         f"divisible by process_count={nproc}")
    local_rows = global_batch_size // nproc

    def to_global(arrays: Dict[str, Any]) -> Dict[str, Any]:
        got = next(iter(arrays.values())).shape[0]
        if got != local_rows:
            raise ValueError(
                f"multi-host {label} batcher must yield {local_rows} "
                f"rows/host (global batch {global_batch_size} / {nproc} "
                f"hosts), got {got}")
        return global_batch_from_host_local(plan, arrays)

    return to_global


def global_batch_from_host_local(plan: MeshPlan,
                                 arrays: Dict[str, Any]) -> Dict[str, Any]:
    """Multi-host batch assembly: each process contributes ITS OWN rows
    (batch_size/process_count of them) and the result is the global
    dp-sharded batch — per-host batchers legitimately hold different data
    (that IS data parallelism), so a plain device_put of per-host copies
    would silently interleave unrelated rows."""
    from jax.experimental import multihost_utils

    pspecs = {k: batch_pspec(k) for k in arrays}
    return multihost_utils.host_local_array_to_global_array(
        arrays, plan.mesh, pspecs)
