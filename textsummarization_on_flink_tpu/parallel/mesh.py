"""Device mesh + sharded step builders: SPMD data/tensor parallelism.

This replaces the reference's entire distributed stack — the TF1
parameter-server/worker cluster (`ClusterSpec`/`tf.train.Server`/
`replica_device_setter`, /root/reference/src/main/python/pointer-generator/
run_summarization.py:406-417), ZooKeeper coordination
(TFEstimator.java:50-51), and gRPC variable traffic — with a single SPMD
program over a `jax.sharding.Mesh`:

  * **dp** axis: batch sharding.  Gradients are all-reduced by XLA-inserted
    `psum` over ICI, replacing the reference's (scaffolded, never-exercised)
    async PS-style data parallelism (`worker_num`, HasClusterConfig.java:20-24).
  * **tp** axis: tensor parallelism for the big vocab matmuls — the
    `[H, vocab]` output projection (model.py:228-238) and the `[vocab, E]`
    embedding table are sharded over the vocab axis; XLA inserts the
    all-gather / reduce-scatter.
  * **sp** axis: context parallelism over the encoder sequence axis for the
    long-context configs (BASELINE.json configs[3]) — encoder states,
    attention energies, and coverage shard over T_enc; the per-step context
    reduction becomes a psum.  (The LSTM time scan itself is sequential, so
    sp shards the *attention/feature* tensors, which dominate memory at
    long T_enc.)

Layout decisions do NOT live here: every PartitionSpec comes from the
sharding-spec registry (parallel/sharding.py, ISSUE 8) — one declarative
role -> spec (+ wire dtype) table consumed by the step builders below,
the serving paths, the checkpointer, and bench alike.  The step builders
in this module construct no specs of their own (pinned by test).

There is no parameter server and no coordination store to configure: in a
multi-host deployment `jax.distributed.initialize()` (distributed.py) is
the rendezvous, and collectives ride ICI within a slice / DCN across
slices.

Everything here works identically on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``), which is how tests and the
driver's `dryrun_multichip` validate multi-chip behavior without hardware.
"""

from __future__ import annotations

import dataclasses
import logging
import warnings
from typing import Any, Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: F401 — P re-exported for callers/tests

from textsummarization_on_flink_tpu.config import HParams
from textsummarization_on_flink_tpu.parallel import sharding as sharding_lib
from textsummarization_on_flink_tpu.train import trainer as trainer_lib

PyTree = Any

log = logging.getLogger(__name__)

MESH_AXES = sharding_lib.MESH_AXES


@dataclasses.dataclass(frozen=True)
class MeshPlan:
    """A mesh plus the hps its sharding registry derives from."""

    mesh: Mesh
    hps: HParams

    @property
    def dp(self) -> int:
        return self.mesh.shape["dp"]

    @property
    def tp(self) -> int:
        return self.mesh.shape["tp"]

    @property
    def sp(self) -> int:
        return self.mesh.shape["sp"]

    @property
    def registry(self) -> sharding_lib.ShardingRegistry:
        return sharding_lib.registry_for(self)

    def named(self, spec: P) -> NamedSharding:
        return self.registry.named(spec)


def make_mesh(hps: HParams, devices: Optional[Sequence[jax.Device]] = None,
              ) -> MeshPlan:
    """Build the (dp, tp, sp) mesh.

    Axis sizes come from hps; when dp*tp*sp is smaller than the available
    device count the mesh uses a prefix subset (and logs it — raise your
    axis sizes to use the whole machine).  With all axes 1 this degrades
    gracefully to single-device.
    """
    devices = list(devices) if devices is not None else list(jax.devices())
    want = hps.dp * hps.tp * hps.sp
    if want > len(devices):
        raise ValueError(
            f"mesh needs dp*tp*sp={want} devices, have {len(devices)}")
    if want < len(devices):
        log.info("mesh uses %d of %d available devices (dp=%d tp=%d sp=%d)",
                 want, len(devices), hps.dp, hps.tp, hps.sp)
    grid = np.asarray(devices[:want]).reshape(hps.dp, hps.tp, hps.sp)
    return MeshPlan(mesh=Mesh(grid, MESH_AXES), hps=hps)


# --------------------------------------------------------------------------
# Registry delegates (public API preserved; the specs live in sharding.py)
# --------------------------------------------------------------------------

def param_pspecs(params: PyTree) -> PyTree:
    """PartitionSpec tree for a model-family parameter pytree (the
    registry's per-leaf param rule; see sharding.param_spec)."""
    return sharding_lib.param_specs(params)


def batch_pspec(name: str) -> P:
    return sharding_lib.batch_spec(name)


def batch_sharding(plan: MeshPlan) -> Dict[str, NamedSharding]:
    reg = plan.registry
    return reg.shardings(reg.batch_specs())


def state_pspecs(state: trainer_lib.TrainState) -> trainer_lib.TrainState:
    """PartitionSpecs for the full TrainState (registry state rule)."""
    return sharding_lib.state_specs(state)


def shard_train_state(plan: MeshPlan,
                      state: trainer_lib.TrainState) -> trainer_lib.TrainState:
    """Place a host-resident TrainState onto the mesh."""
    return plan.registry.shard_state(state)


def shard_batch(plan: MeshPlan, arrays: Dict[str, Any]) -> Dict[str, Any]:
    return plan.registry.shard_batch(arrays)


def param_shardings(plan: MeshPlan, params: Optional[PyTree] = None):
    """NamedSharding tree for a parameter pytree; pass `params` when its
    structure differs from a fresh init (e.g. TF1-imported trees)."""
    probe = params if params is not None else jax.eval_shape(
        lambda: trainer_lib.init_train_state(
            plan.hps, plan.hps.vocab_size, seed=0)).params
    return plan.registry.shardings(sharding_lib.param_specs(probe))


# --------------------------------------------------------------------------
# The unified sharded step
# --------------------------------------------------------------------------

def _with_mesh_context(plan: MeshPlan, fn):
    """Expose the plan's mesh to model code while the step traces, so
    mesh-aware ops (ring attention's shard_map) can bind to it."""
    from textsummarization_on_flink_tpu.parallel import ring_attention as ra

    def wrapped(*args):
        with ra.mesh_context(plan.mesh):
            return fn(*args)

    return wrapped


def _make_wire_grad_fn(plan: MeshPlan, reg: sharding_lib.ShardingRegistry,
                       param_spec_tree: PyTree):
    """(params, arrays) -> (grads, scalar losses) with the dp gradient
    all-reduce riding the wire in the registry's annotated dtype.

    Mechanism (ISSUE 8; see sharding.py's module docstring for why the
    shard_map route is closed on this jax): the batch regroups
    ``[B] -> [dp, B/dp]`` under a `P("dp", ...)` constraint, per-group
    grads come from ONE vmap'd jax.grad (each dp shard computes exactly
    its local rows, as under shard_map), the stacked grads are cast to
    the wire dtype under a ``P("dp", *param_spec)`` constraint, and the
    group-axis sum is partitioned by XLA into the dp all-reduce at that
    dtype — spec-level wire annotation, collective inserted by the
    partitioner.  f32 is restored before clip/Adagrad; forward-internal
    tp collectives stay wherever GSPMD puts them, which is what makes
    this compose with dp x tp meshes (the retired shard_map step was
    pure-dp-only).

    Requirements (validated in HParams.validate and here): sp == 1, and
    pointer_gen losses — their per-example normalization makes the
    mean of per-group means exactly the global mean, so the wire cast
    is the ONLY difference from the f32 step (parity pinned by test).
    """
    import jax.numpy as jnp

    hps = plan.hps
    if plan.sp > 1:
        raise ValueError(
            "grad_allreduce_dtype=bfloat16 supports dp x tp meshes "
            f"(sp=1), got sp={plan.sp}")
    if not hps.pointer_gen:
        raise ValueError(
            "grad_allreduce_dtype=bfloat16 requires pointer_gen losses "
            "(group-mean == global-mean); the baseline CE normalizes by "
            "the global token count")
    loss_fn = trainer_lib.make_loss_fn(hps)
    wire = reg.wire_dtype("grads")
    dp = plan.dp

    def grad_fn(params, arrays):
        def regroup(name, v):
            v = v.reshape((dp, v.shape[0] // dp) + v.shape[1:])
            return reg.constrain(v, reg.grouped_batch_spec(name))

        grouped = {k: regroup(k, v) for k, v in arrays.items()}

        def one_group(group_arrays):
            grads, out = jax.grad(
                lambda p: loss_fn(p, group_arrays),
                has_aux=True)(params)
            return grads, (out.loss, out.coverage_loss, out.total_loss)

        grads, scal = jax.vmap(one_group)(grouped)
        # THE lever: stacked per-group grads pinned to the registry's
        # stacked-grad spec in the wire dtype, so the group-axis sum
        # lowers to the dp all-reduce at that dtype; f32 restored
        # before any update math
        grads = jax.tree_util.tree_map(
            lambda g, s: reg.constrain(g.astype(wire),
                                       reg.stacked_grad_spec(s)),
            grads, param_spec_tree, is_leaf=lambda x: isinstance(x, P))
        grads = jax.tree_util.tree_map(
            lambda g: g.sum(axis=0).astype(jnp.float32) / dp, grads)
        return grads, tuple(jnp.mean(s) for s in scal)

    return grad_fn


def make_sharded_train_step(plan: MeshPlan, donate: bool = True,
                            state: Optional[trainer_lib.TrainState] = None):
    """THE sharded train step: one jitted program whose in/out shardings
    come from the sharding registry and whose body is the single
    trainer_lib.make_train_step body.

    Sharding is expressed entirely through registry specs — XLA inserts
    the dp-axis gradient psum, the tp-axis collectives around the vocab
    matmuls, and the sp-axis context reductions.  When the registry
    annotates a grad wire dtype (``--grad_allreduce_dtype=bfloat16``)
    the gradient computation swaps to the wire variant above — same
    step body, half the per-step dp collective bytes, now on any
    dp x tp mesh (the separate pure-dp shard_map builder is retired;
    see make_lowp_allreduce_train_step's shim).

    Pass `state` when its pytree structure differs from a fresh init
    (e.g. a TF1-imported non-coverage checkpoint has no
    decoder/attention/w_c leaf); specs are derived from the given tree
    so the jit's in_shardings structure matches.
    """
    hps = plan.hps
    reg = plan.registry
    probe = state if state is not None else jax.eval_shape(
        # structure only, nothing allocated
        lambda: trainer_lib.init_train_state(hps, hps.vocab_size, seed=0))
    grad_fn = None
    if reg.wire_dtype("grads") is not None:
        grad_fn = _make_wire_grad_fn(plan, reg,
                                     sharding_lib.param_specs(probe.params))
    step_fn = _with_mesh_context(
        plan, trainer_lib.make_train_step(hps, grad_fn=grad_fn))
    state_sh = reg.shardings(reg.state_specs(probe))
    del probe
    batch_sh = reg.shardings(reg.batch_specs())
    metric_sh = reg.shardings(reg.metric_specs())
    return jax.jit(
        step_fn,
        in_shardings=(state_sh, batch_sh),
        out_shardings=(state_sh, metric_sh),
        donate_argnums=(0,) if donate else (),
    )


def make_lowp_allreduce_train_step(
        plan: MeshPlan, donate: bool = True,
        state: Optional[trainer_lib.TrainState] = None):
    """DEPRECATED shim (ISSUE 8 satellite): the explicit-collective
    shard_map step this built is retired — the unified builder folds the
    bf16 gradient wire in as a registry-level dtype annotation and works
    on dp x tp meshes the shard_map step rejected.  Kept so existing
    callers resolve; delegates to make_sharded_train_step with the wire
    dtype forced on."""
    warnings.warn(
        "make_lowp_allreduce_train_step is deprecated: the unified "
        "make_sharded_train_step reads the grad wire dtype from the "
        "sharding registry (hps.grad_allreduce_dtype) and supports "
        "dp x tp meshes; call it directly",
        DeprecationWarning, stacklevel=2)
    hps = plan.hps
    if getattr(hps, "grad_allreduce_dtype", "float32") != "bfloat16":
        plan = dataclasses.replace(
            plan, hps=hps.replace(grad_allreduce_dtype="bfloat16"))
    return make_sharded_train_step(plan, donate=donate, state=state)


def make_sharded_eval_step(plan: MeshPlan, params: Optional[PyTree] = None):
    """Pass `params` when the tree structure differs from a fresh init
    (e.g. a TF1-imported checkpoint) so in_shardings match, mirroring
    make_sharded_train_step's `state` parameter."""
    hps = plan.hps
    reg = plan.registry
    eval_fn = _with_mesh_context(plan, trainer_lib.make_eval_step(hps))
    param_sh = param_shardings(plan, params)
    batch_sh = reg.shardings(reg.batch_specs())
    metric_sh = reg.shardings(reg.metric_specs())
    return jax.jit(eval_fn, in_shardings=(param_sh, batch_sh),
                   out_shardings=metric_sh)


def validate_divisibility(hps: HParams, params: Optional[PyTree] = None,
                          ) -> None:
    """Fail fast with actionable errors instead of opaque device_put
    shape complaints (the vocab file may hold fewer words than
    --vocab_size, so the ACTUAL embedding rows are what tp must divide)."""
    if hps.dp > 1 and hps.batch_size % hps.dp != 0:
        raise ValueError(f"data-parallel axis dp={hps.dp} must divide "
                         f"batch_size={hps.batch_size}")
    if hps.tp > 1 and params is not None:
        vsize_actual = params["embedding"].shape[0]
        if vsize_actual % hps.tp != 0:
            raise ValueError(
                f"tensor-parallel axis tp={hps.tp} must divide the actual "
                f"vocabulary size {vsize_actual} (the vocab file may hold "
                f"fewer words than --vocab_size); pick a dividing tp or "
                f"trim the vocab")
    if hps.sp > 1 and hps.max_enc_steps % hps.sp != 0:
        raise ValueError(f"sequence-parallel axis sp={hps.sp} must divide "
                         f"max_enc_steps={hps.max_enc_steps}")
    if hps.sp > 1 and hps.sp_attention == "ulysses" \
            and hps.num_heads % hps.sp != 0:
        raise ValueError(
            f"sp_attention=ulysses re-shards heads over sp: sp={hps.sp} "
            f"must divide num_heads={hps.num_heads}")
    if hps.tp > 1 and hps.model_family == "transformer":
        if hps.num_heads % hps.tp != 0:
            raise ValueError(
                f"tensor-parallel axis tp={hps.tp} must divide "
                f"num_heads={hps.num_heads} (Megatron head sharding)")
        if hps.ffn_width % hps.tp != 0:
            raise ValueError(f"tensor-parallel axis tp={hps.tp} must divide "
                             f"ffn_dim={hps.ffn_width}")
        if hps.sp_attention:
            raise ValueError(
                "sp_attention with tp>1 is not supported: the SP "
                "shard_map replicates the head axis, which would silently "
                "all-gather the Megatron-sharded q/k/v every layer — use "
                "sp-only attention (tp=1) or tp without sp_attention")


def make_sharded_beam_search(plan: MeshPlan,
                             params: Optional[PyTree] = None):
    """Multi-chip serving: beam-search decode with the article batch
    sharded over dp (each chip searches its own articles; beams stay
    chip-local, so there is zero cross-chip traffic during the decode
    loop — the ideal layout for throughput serving).

    Returns a jitted fn(params, arrays) -> BeamSearchOutput.  All
    shardings come from the registry (enc batch, params, beam output).
    """
    from textsummarization_on_flink_tpu.decode import beam_search

    hps = plan.hps
    reg = plan.registry
    param_sh = param_shardings(plan, params)
    batch_sh = reg.shardings(
        reg.batch_specs(sharding_lib.ENC_BATCH_NAMES))
    out_sh = reg.shardings(reg.beam_output_specs())

    def search(p, arrays):
        return beam_search._search_batch(p, hps, arrays)

    # mesh context so the encoder's sp attention engages in serving too
    # (a model trained with --sp_attention because [T,T] doesn't fit one
    # device must not fall back to full attention at decode time)
    search = _with_mesh_context(plan, search)
    return jax.jit(search, in_shardings=(param_sh, batch_sh),
                   out_shardings=out_sh)


def make_host_local_transfer(plan: MeshPlan, global_batch_size: int,
                             label: str = "train"):
    """Batch-transfer fn for one host of a multi-host run: validates this
    host's row count (batch_size/process_count) then assembles the global
    dp-sharded batch.  Shared by Trainer and Evaluator so the check and
    the error text cannot drift."""
    import jax

    nproc = jax.process_count()
    if global_batch_size % nproc != 0:
        raise ValueError(f"{label} batch_size={global_batch_size} must be "
                         f"divisible by process_count={nproc}")
    local_rows = global_batch_size // nproc

    def to_global(arrays: Dict[str, Any]) -> Dict[str, Any]:
        got = next(iter(arrays.values())).shape[0]
        if got != local_rows:
            raise ValueError(
                f"multi-host {label} batcher must yield {local_rows} "
                f"rows/host (global batch {global_batch_size} / {nproc} "
                f"hosts), got {got}")
        return global_batch_from_host_local(plan, arrays)

    return to_global


def global_batch_from_host_local(plan: MeshPlan,
                                 arrays: Dict[str, Any]) -> Dict[str, Any]:
    """Multi-host batch assembly: each process contributes ITS OWN rows
    (batch_size/process_count of them) and the result is the global
    dp-sharded batch — per-host batchers legitimately hold different data
    (that IS data parallelism), so a plain device_put of per-host copies
    would silently interleave unrelated rows."""
    from jax.experimental import multihost_utils

    pspecs = plan.registry.batch_specs(tuple(arrays))
    return multihost_utils.host_local_array_to_global_array(
        arrays, plan.mesh, pspecs)
