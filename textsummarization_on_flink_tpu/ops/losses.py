"""Sequence losses: masked-average NLL over the pointer mixture + coverage.

Parity targets in the reference:
  * `_mask_and_avg` (model.py:446-460): per-example sum over steps of
    masked values, normalized by true decoder length, then batch mean.
  * pointer NLL (model.py:252-265): -log of the gold token's probability
    under the final (mixture) distribution.
  * `_coverage_loss` (model.py:463-480): sum_i min(a_i^t, c_i^t) per step,
    with coverage starting at zero and accumulating attention.

TPU-first difference: the reference materializes the full extended-vocab
final distribution per step ([B, ext_V], via scatter_nd, model.py:176) and
then gathers the gold entry.  We never build that tensor for training —
the gold probability of target w is

    p_gen * vocab_dist[w] * [w < V]  +  (1 - p_gen) * sum_{i: ext_ids_i = w} a_i

which needs only a [B, T_enc] comparison per step.  Mathematically
identical (scatter-add followed by gather-at-index == masked sum), and it
turns a [B, 50k+] scatter into an HBM-friendly reduction.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

Array = jax.Array


def mask_and_avg(values: Array, padding_mask: Array) -> Array:
    """values: [B, T]; padding_mask: [B, T] -> scalar (model.py:446-460)."""
    dec_lens = jnp.sum(padding_mask, axis=1)
    values_per_ex = jnp.sum(values * padding_mask, axis=1) / dec_lens
    return jnp.mean(values_per_ex)


def gold_mixture_prob(vocab_dist: Array, attn_dist: Array, p_gen: Array,
                      target: Array, enc_batch_extend_vocab: Array) -> Array:
    """Probability of the gold target under the pointer mixture, one step.

    vocab_dist: [B, V] softmax over the fixed vocab;
    attn_dist: [B, T_enc]; p_gen: [B]; target: [B] extended-vocab ids;
    enc_batch_extend_vocab: [B, T_enc] extended-vocab ids per source pos.

    Thin wrapper over gold_mixture_prob_from_scores (log-probabilities ARE
    scores whose logsumexp is 0), keeping one source of truth for the
    mixture math.
    """
    return gold_mixture_prob_from_scores(
        jnp.log(vocab_dist)[None], attn_dist[None], p_gen[None],
        target[None], enc_batch_extend_vocab)[0]


def gold_mixture_prob_from_scores(vocab_scores: Array, attn_dists: Array,
                                  p_gens: Array, targets: Array,
                                  enc_batch_extend_vocab: Array) -> Array:
    """Gold-target probability for ALL steps at once, from raw vocab
    scores.

    vocab_scores: [T, B, V]; attn_dists: [T, B, T_enc]; p_gens: [T, B];
    targets: [T, B] extended ids; enc_batch_extend_vocab: [B, T_enc].
    Returns [T, B].

    Same mixture as gold_mixture_prob with the vocab softmax written as
    exp(score_target - logsumexp(scores)), so callers can hoist the
    [H, V] projection out of their decoder scan into one
    [T*B, H] @ [H, V] matmul — a per-step M=B slice starves the MXU's
    128-row tiles; M=T*B fills them.
    """
    V = vocab_scores.shape[-1]
    lse = jax.scipy.special.logsumexp(vocab_scores, axis=-1)  # [T, B]
    in_vocab = targets < V
    safe_t = jnp.where(in_vocab, targets, 0)
    score_t = jnp.take_along_axis(
        vocab_scores, safe_t[..., None], axis=-1)[..., 0]
    gen_prob = jnp.where(in_vocab, jnp.exp(score_t - lse), 0.0)
    copy_prob = jnp.sum(
        attn_dists * (enc_batch_extend_vocab[None] == targets[..., None]),
        axis=-1)
    return p_gens * gen_prob + (1.0 - p_gens) * copy_prob


def pointer_nll(gold_probs: Array, dec_padding_mask: Array,
                eps: float = 0.0) -> Array:
    """-log(gold prob), masked-averaged.  gold_probs: [B, T].

    eps=0 matches the reference exactly (model.py:261 has no epsilon); a
    tiny eps guards against -inf on degenerate batches if callers want it.
    """
    losses = -jnp.log(gold_probs + eps)
    return mask_and_avg(losses, dec_padding_mask)


def coverage_loss(attn_dists: Array, dec_padding_mask: Array) -> Array:
    """attn_dists: [B, T_dec, T_enc] -> scalar (model.py:463-480).

    covloss_t = sum_i min(a_i^t, c_i^t), c_0 = 0, c_{t+1} = c_t + a_t.
    The cumulative coverage at step t is an exclusive prefix sum over the
    step axis — computed in closed form, no scan needed.
    """
    cum = jnp.cumsum(attn_dists, axis=1)
    coverage = cum - attn_dists  # exclusive prefix: coverage before step t
    covlosses = jnp.sum(jnp.minimum(attn_dists, coverage), axis=2)  # [B, T_dec]
    return mask_and_avg(covlosses, dec_padding_mask)


def softmax_cross_entropy_baseline(vocab_scores: Array, targets: Array,
                                   dec_padding_mask: Array) -> Array:
    """Baseline (non-pointer) loss: tf.contrib.seq2seq.sequence_loss parity
    (model.py:268) — with its defaults this is the global token-weighted
    mean: sum(nll * mask) / sum(mask), not the per-example normalization
    mask_and_avg applies in pointer mode."""
    log_probs = jax.nn.log_softmax(vocab_scores, axis=-1)
    nll = -jnp.take_along_axis(log_probs, targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * dec_padding_mask) / jnp.sum(dec_padding_mask)
