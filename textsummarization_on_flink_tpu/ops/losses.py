"""Sequence losses: masked-average NLL over the pointer mixture + coverage.

Parity targets in the reference:
  * `_mask_and_avg` (model.py:446-460): per-example sum over steps of
    masked values, normalized by true decoder length, then batch mean.
  * pointer NLL (model.py:252-265): -log of the gold token's probability
    under the final (mixture) distribution.
  * `_coverage_loss` (model.py:463-480): sum_i min(a_i^t, c_i^t) per step,
    with coverage starting at zero and accumulating attention.

TPU-first difference: the reference materializes the full extended-vocab
final distribution per step ([B, ext_V], via scatter_nd, model.py:176) and
then gathers the gold entry.  We never build that tensor for training —
the gold probability of target w is

    p_gen * vocab_dist[w] * [w < V]  +  (1 - p_gen) * sum_{i: ext_ids_i = w} a_i

which needs only a [B, T_enc] comparison per step.  Mathematically
identical (scatter-add followed by gather-at-index == masked sum), and it
turns a [B, 50k+] scatter into an HBM-friendly reduction.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def mask_and_avg(values: Array, padding_mask: Array) -> Array:
    """values: [B, T]; padding_mask: [B, T] -> scalar (model.py:446-460)."""
    dec_lens = jnp.sum(padding_mask, axis=1)
    values_per_ex = jnp.sum(values * padding_mask, axis=1) / dec_lens
    return jnp.mean(values_per_ex)


def gold_mixture_prob(vocab_dist: Array, attn_dist: Array, p_gen: Array,
                      target: Array, enc_batch_extend_vocab: Array) -> Array:
    """Probability of the gold target under the pointer mixture, one step.

    vocab_dist: [B, V] softmax over the fixed vocab;
    attn_dist: [B, T_enc]; p_gen: [B]; target: [B] extended-vocab ids;
    enc_batch_extend_vocab: [B, T_enc] extended-vocab ids per source pos.

    Thin wrapper over gold_mixture_prob_from_scores (log-probabilities ARE
    scores whose logsumexp is 0), keeping one source of truth for the
    mixture math.
    """
    return gold_mixture_prob_from_scores(
        jnp.log(vocab_dist)[None], attn_dist[None], p_gen[None],
        target[None], enc_batch_extend_vocab)[0]


def gold_mixture_prob_from_scores(vocab_scores: Array, attn_dists: Array,
                                  p_gens: Array, targets: Array,
                                  enc_batch_extend_vocab: Array) -> Array:
    """Gold-target probability for ALL steps at once, from raw vocab
    scores.

    vocab_scores: [T, B, V]; attn_dists: [T, B, T_enc]; p_gens: [T, B];
    targets: [T, B] extended ids; enc_batch_extend_vocab: [B, T_enc].
    Returns [T, B].

    Same mixture as gold_mixture_prob with the vocab softmax written as
    exp(score_target - logsumexp(scores)), so callers can hoist the
    [H, V] projection out of their decoder scan into one
    [T*B, H] @ [H, V] matmul — a per-step M=B slice starves the MXU's
    128-row tiles; M=T*B fills them.
    """
    V = vocab_scores.shape[-1]
    lse = jax.scipy.special.logsumexp(vocab_scores, axis=-1)  # [T, B]
    in_vocab = targets < V
    safe_t = jnp.where(in_vocab, targets, 0)
    score_t = jnp.take_along_axis(
        vocab_scores, safe_t[..., None], axis=-1)[..., 0]
    gen_prob = jnp.where(in_vocab, jnp.exp(score_t - lse), 0.0)
    copy_prob = jnp.sum(
        attn_dists * (enc_batch_extend_vocab[None] == targets[..., None]),
        axis=-1)
    return p_gens * gen_prob + (1.0 - p_gens) * copy_prob


def pointer_nll(gold_probs: Array, dec_padding_mask: Array,
                eps: float = 0.0) -> Array:
    """-log(gold prob), masked-averaged.  gold_probs: [B, T].

    eps=0 matches the reference exactly (model.py:261 has no epsilon); a
    tiny eps guards against -inf on degenerate batches if callers want it.
    """
    losses = -jnp.log(gold_probs + eps)
    return mask_and_avg(losses, dec_padding_mask)


def coverage_loss(attn_dists: Array, dec_padding_mask: Array) -> Array:
    """attn_dists: [B, T_dec, T_enc] -> scalar (model.py:463-480).

    covloss_t = sum_i min(a_i^t, c_i^t), c_0 = 0, c_{t+1} = c_t + a_t.
    The cumulative coverage at step t is an exclusive prefix sum over the
    step axis — computed in closed form, no scan needed.
    """
    cum = jnp.cumsum(attn_dists, axis=1)
    coverage = cum - attn_dists  # exclusive prefix: coverage before step t
    covlosses = jnp.sum(jnp.minimum(attn_dists, coverage), axis=2)  # [B, T_dec]
    return mask_and_avg(covlosses, dec_padding_mask)


def softmax_cross_entropy_baseline(vocab_scores: Array, targets: Array,
                                   dec_padding_mask: Array) -> Array:
    """Baseline (non-pointer) loss: tf.contrib.seq2seq.sequence_loss parity
    (model.py:268) — with its defaults this is the global token-weighted
    mean: sum(nll * mask) / sum(mask), not the per-example normalization
    mask_and_avg applies in pointer mode."""
    log_probs = jax.nn.log_softmax(vocab_scores, axis=-1)
    nll = -jnp.take_along_axis(log_probs, targets[..., None], axis=-1)[..., 0]
    return jnp.sum(nll * dec_padding_mask) / jnp.sum(dec_padding_mask)


# --------------------------------------------------------------------------
# Streaming chunked vocab loss (ISSUE 5 tentpole)
#
# The hoisted [T_dec, B, V] scores tensor is the train step's dominant
# byte sink: ~320 MB f32 at reference scale, held TWICE (value + autodiff
# residual — logsumexp/take_along_axis grads need it).  The streaming
# formulation below scans over T_dec chunks, projecting only a
# [chunk, B, V] block at a time, and its custom VJP RECOMPUTES each
# chunk's scores in backward instead of saving them — so the full scores
# tensor never materializes in either pass.  Token-exact: each step's
# math (projection row, logsumexp, gather) is identical to the
# materialized path; only the dw/dv accumulation order differs in
# backward (sequential chunk sums instead of one [T*B]-row contraction).
# --------------------------------------------------------------------------


def project_scores(x: Array, w: Array,
                   compute_dtype: str = "float32") -> Array:
    """x @ w with bf16 operands + f32 accumulation in bfloat16 mode — the
    [H, vocab] output projection is the FLOP-dominant matmul; casting it
    to the MXU's native bf16 roughly doubles its throughput while the f32
    accumulator keeps softmax-grade precision.  The ONE dtype-aware vocab
    matmul: models/pointer_generator._proj and the streaming chunk bodies
    below all project through this, so chunked and materialized paths can
    never drift."""
    if compute_dtype == "bfloat16":
        return jnp.dot(x.astype(jnp.bfloat16), w.astype(jnp.bfloat16),
                       preferred_element_type=jnp.float32)
    return x @ w


def _int_zero_cotangent(x: Array):
    """Symbolic-zero cotangent for integer primal inputs (custom_vjp
    requires float0 for non-inexact types)."""
    return np.zeros(x.shape, dtype=jax.dtypes.float0)


def _pack_chunks(chunk: int, *arrays: Array) -> Tuple[int, Tuple[Array, ...]]:
    """Pad leading axis T to a multiple of `chunk` and reshape each array
    to [n, chunk, ...].  Padded tail rows are zeros; callers slice them
    away (forward) or feed them zero cotangents (backward)."""
    T = arrays[0].shape[0]
    n = -(-T // chunk)
    pad = n * chunk - T
    out = []
    for a in arrays:
        if pad:
            a = jnp.pad(a, [(0, pad)] + [(0, 0)] * (a.ndim - 1))
        out.append(a.reshape((n, chunk) + a.shape[1:]))
    return n, tuple(out)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _streaming_gold(chunk: int, compute_dtype: str, outputs: Array,
                    attn_dists: Array, p_gens: Array, targets: Array,
                    enc_batch_extend_vocab: Array, w: Array,
                    v: Array) -> Array:
    """Gold mixture probability [T, B] from pre-projection decoder
    outputs [T, B, H], computed in T-chunks so only [chunk, B, V] scores
    exist at a time.  See streaming_gold_probs for the public wrapper."""

    def body(_, xs):
        o, a, p, t = xs
        scores = project_scores(o, w, compute_dtype) + v
        return (), gold_mixture_prob_from_scores(
            scores, a, p, t, enc_batch_extend_vocab)

    T, B = targets.shape
    n, xs = _pack_chunks(chunk, outputs, attn_dists, p_gens, targets)
    _, gold = jax.lax.scan(body, (), xs)
    return gold.reshape(n * chunk, B)[:T]


def _streaming_gold_fwd(chunk, compute_dtype, outputs, attn_dists, p_gens,
                        targets, enc_batch_extend_vocab, w, v):
    gold = _streaming_gold(chunk, compute_dtype, outputs, attn_dists,
                           p_gens, targets, enc_batch_extend_vocab, w, v)
    # residuals are the PRIMAL INPUTS only — never the chunk scores
    return gold, (outputs, attn_dists, p_gens, targets,
                  enc_batch_extend_vocab, w, v)


def _streaming_gold_bwd(chunk, compute_dtype, res, g):
    outputs, attn_dists, p_gens, targets, ext, w, v = res
    T = targets.shape[0]
    _, xs = _pack_chunks(chunk, outputs, attn_dists, p_gens, targets, g)

    def body(carry, xs_c):
        o, a, p, t, g_c = xs_c
        dw_acc, dv_acc = carry

        def chunk_gold(o_, a_, p_, w_, v_):
            # the chunk's [chunk, B, V] scores are REBUILT here, inside
            # the backward scan — the recompute the whole scheme buys
            scores = project_scores(o_, w_, compute_dtype) + v_
            return gold_mixture_prob_from_scores(scores, a_, p_, t, ext)

        _, vjp_fn = jax.vjp(chunk_gold, o, a, p, w, v)
        do, da, dp, dw_c, dv_c = vjp_fn(g_c)
        return (dw_acc + dw_c, dv_acc + dv_c), (do, da, dp)

    (dw, dv), (do, da, dp) = jax.lax.scan(
        body, (jnp.zeros_like(w), jnp.zeros_like(v)), xs)
    unpack = lambda x: x.reshape((-1,) + x.shape[2:])[:T]  # noqa: E731
    return (unpack(do), unpack(da), unpack(dp),
            _int_zero_cotangent(targets), _int_zero_cotangent(ext), dw, dv)


_streaming_gold.defvjp(_streaming_gold_fwd, _streaming_gold_bwd)


def streaming_gold_probs(outputs: Array, attn_dists: Array, p_gens: Array,
                         targets: Array, enc_batch_extend_vocab: Array,
                         w: Array, v: Array, *, chunk: int,
                         compute_dtype: str = "float32") -> Array:
    """Chunked-streaming gold_mixture_prob_from_scores, from PRE-projection
    decoder outputs.

    outputs: [T, B, H] (time-major); attn_dists: [T, B, T_enc];
    p_gens: [T, B]; targets: [T, B]; enc_batch_extend_vocab: [B, T_enc];
    w: [H, V]; v: [V].  Returns gold probabilities [T, B], token-exact vs
    `gold_mixture_prob_from_scores(project_scores(outputs, w) + v, ...)`
    but with peak scores memory of one [chunk, B, V] block in forward AND
    backward (the custom VJP recomputes each chunk's scores instead of
    holding the [T, B, V] residual)."""
    T = outputs.shape[0]
    return _streaming_gold(int(min(max(chunk, 1), T)), compute_dtype,
                           outputs, attn_dists, p_gens, targets,
                           enc_batch_extend_vocab, w, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _streaming_ce_nll(chunk: int, compute_dtype: str, outputs: Array,
                      targets: Array, w: Array, v: Array) -> Array:
    """Per-token NLL [T, B] of the plain vocab softmax, chunked over T
    (log-space, so it is token-exact vs softmax_cross_entropy_baseline's
    log_softmax + gather on the materialized scores)."""

    def body(_, xs):
        o, t = xs
        scores = project_scores(o, w, compute_dtype) + v
        log_probs = jax.nn.log_softmax(scores, axis=-1)
        return (), -jnp.take_along_axis(
            log_probs, t[..., None], axis=-1)[..., 0]

    T, B = targets.shape
    n, xs = _pack_chunks(chunk, outputs, targets)
    _, nll = jax.lax.scan(body, (), xs)
    return nll.reshape(n * chunk, B)[:T]


def _streaming_ce_fwd(chunk, compute_dtype, outputs, targets, w, v):
    nll = _streaming_ce_nll(chunk, compute_dtype, outputs, targets, w, v)
    return nll, (outputs, targets, w, v)


def _streaming_ce_bwd(chunk, compute_dtype, res, g):
    outputs, targets, w, v = res
    T = targets.shape[0]
    _, xs = _pack_chunks(chunk, outputs, targets, g)

    def body(carry, xs_c):
        o, t, g_c = xs_c
        dw_acc, dv_acc = carry

        def chunk_nll(o_, w_, v_):
            scores = project_scores(o_, w_, compute_dtype) + v_
            log_probs = jax.nn.log_softmax(scores, axis=-1)
            return -jnp.take_along_axis(
                log_probs, t[..., None], axis=-1)[..., 0]

        _, vjp_fn = jax.vjp(chunk_nll, o, w, v)
        do, dw_c, dv_c = vjp_fn(g_c)
        return (dw_acc + dw_c, dv_acc + dv_c), do

    (dw, dv), do = jax.lax.scan(
        body, (jnp.zeros_like(w), jnp.zeros_like(v)), xs)
    do = do.reshape((-1,) + do.shape[2:])[:T]
    return do, _int_zero_cotangent(targets), dw, dv


_streaming_ce_nll.defvjp(_streaming_ce_fwd, _streaming_ce_bwd)


def streaming_softmax_cross_entropy(outputs: Array, targets: Array,
                                    dec_padding_mask: Array, w: Array,
                                    v: Array, *, chunk: int,
                                    compute_dtype: str = "float32") -> Array:
    """Chunked-streaming softmax_cross_entropy_baseline from
    PRE-projection outputs.  All step-major: outputs [T, B, H], targets
    [T, B], dec_padding_mask [T, B].  Same global token-weighted mean as
    the materialized formula."""
    T = outputs.shape[0]
    nll = _streaming_ce_nll(int(min(max(chunk, 1), T)), compute_dtype,
                            outputs, targets, w, v)
    return jnp.sum(nll * dec_padding_mask) / jnp.sum(dec_padding_mask)
