"""Fused additive attention + coverage as a Pallas TPU kernel.

The hot op of the pointer-generator (SURVEY.md §7.2 step 7): per decoder
step the reference computes, over every encoder position i
(/root/reference/src/main/python/pointer-generator/attention_decoder.py:79-129),

    e_i  = v . tanh(W_h h_i + W_s s_t [+ w_c c_i] + b)
    a    = masked_softmax(e)
    ctx  = sum_i a_i h_i

The XLA path (ops/attention.py) materializes the [B, T, D] `feats` tensor
in HBM between the add and the tanh reduction.  This kernel fuses energy,
masked softmax, and the context matmul into ONE pass per batch row: the
encoder tensors stream HBM->VMEM once, the [T, D] intermediate never
leaves VMEM, the context reduction rides the MXU.

At reference scale (T=400->pad 512, D=512, f32) one row's working set is
~2 MB — comfortably inside the ~16 MB VMEM budget, so the grid is simply
(B,) with full-[T, D] blocks.  (A T-blocked flash-style variant is the
obvious extension for long-context configs; see sp-axis notes in
parallel/mesh.py.)

Masking parity: the reference softmaxes THEN masks THEN renormalizes
(attention_decoder.py:96-101); energy-level -inf masking is algebraically
identical and is what the kernel does.

Training support: `fused_attention` carries a custom VJP whose backward
recomputes the (cheap) reference formula under XLA autodiff — kernel
forward speed, reference-exact gradients, no handwritten backward to
maintain.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

NEG = -1e30
_LANE = 128


def _pad_to(x: Array, axis: int, mult: int, value: float = 0.0) -> Array:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _kernel(es_ref, ef_ref, mask_ref, df_ref, cov_ref, v_ref, wc_ref,
            ctx_ref, attn_ref, *, use_coverage: bool):
    """One batch row: es/ef [1, T, D], mask/cov [1, T], df/v/wc [1, D]."""
    ef = ef_ref[0]  # [T, D]
    df = df_ref[0]  # [D]
    feats = ef + df[None, :]
    if use_coverage:
        feats = feats + cov_ref[0][:, None] * wc_ref[0][None, :]
    e = jnp.sum(v_ref[0][None, :] * jnp.tanh(feats), axis=-1)  # [T]
    mask = mask_ref[0]
    e = jnp.where(mask > 0, e, NEG)
    m = jnp.max(e)
    p = jnp.exp(e - m) * (mask > 0)  # exp(NEG-m) could be denormal; zero it
    l = jnp.sum(p)
    a = p / l
    attn_ref[0, :] = a
    # context: [1, T] @ [T, D] on the MXU
    ctx_ref[0, :] = jnp.dot(a[None, :], es_ref[0],
                            preferred_element_type=jnp.float32)[0]


def _attention_xla(enc_states, enc_feats, enc_mask, dec_feats, coverage,
                   v, w_c, use_coverage):
    """Reference formula (ops/attention.py semantics) — backward path and
    non-TPU fallback."""
    feats = enc_feats + dec_feats[:, None, :]
    if use_coverage:
        feats = feats + coverage[:, :, None] * w_c[None, None, :]
    e = jnp.sum(v * jnp.tanh(feats), axis=-1)
    e = jnp.where(enc_mask > 0, e, NEG)
    e = e - jax.lax.stop_gradient(jnp.max(e, axis=-1, keepdims=True))
    p = jnp.exp(e) * (enc_mask > 0)
    attn = p / jnp.sum(p, axis=-1, keepdims=True)
    context = jnp.einsum("bt,btd->bd", attn, enc_states)
    return context, attn


def _attention_pallas(enc_states, enc_feats, enc_mask, dec_feats, coverage,
                      v, w_c, use_coverage, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, D = enc_states.shape
    es = _pad_to(_pad_to(enc_states, 1, _LANE), 2, _LANE)
    ef = _pad_to(_pad_to(enc_feats, 1, _LANE), 2, _LANE)
    mask = _pad_to(enc_mask, 1, _LANE)
    cov = _pad_to(coverage, 1, _LANE)
    df = _pad_to(dec_feats, 1, _LANE)
    vp = _pad_to(v[None, :], 1, _LANE)[0]
    wcp = _pad_to(w_c[None, :], 1, _LANE)[0]
    Tp, Dp = es.shape[1], es.shape[2]

    row = lambda b: (b, 0)
    row3 = lambda b: (b, 0, 0)
    rep = lambda b: (0, 0)
    ctx, attn = pl.pallas_call(
        functools.partial(_kernel, use_coverage=use_coverage),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Tp, Dp), row3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, Dp), row3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Dp), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Dp), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Dp), rep, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, Dp), row, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp), row, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Dp), jnp.float32),
            jax.ShapeDtypeStruct((B, Tp), jnp.float32),
        ],
        interpret=interpret,
    )(es.astype(jnp.float32), ef.astype(jnp.float32),
      mask.astype(jnp.float32), df.astype(jnp.float32),
      cov.astype(jnp.float32), vp[None].astype(jnp.float32),
      wcp[None].astype(jnp.float32))
    return ctx[:, :D], attn[:, :T]


def _use_pallas() -> bool:
    env = os.environ.get("TS_PALLAS", "auto").lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    try:
        return jax.default_backend() == "tpu"
    except Exception:  # pragma: no cover
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def fused_attention(enc_states: Array, enc_feats: Array, enc_mask: Array,
                    dec_feats: Array, coverage: Array, v: Array, w_c: Array,
                    use_coverage: bool) -> Tuple[Array, Array]:
    """(context [B, D], attn_dist [B, T]).  coverage is read only when
    use_coverage (pass zeros otherwise)."""
    if _use_pallas():
        return _attention_pallas(enc_states, enc_feats, enc_mask, dec_feats,
                                 coverage, v, w_c, use_coverage)
    return _attention_xla(enc_states, enc_feats, enc_mask, dec_feats,
                          coverage, v, w_c, use_coverage)


def _fwd(enc_states, enc_feats, enc_mask, dec_feats, coverage, v, w_c,
         use_coverage):
    out = fused_attention(enc_states, enc_feats, enc_mask, dec_feats,
                          coverage, v, w_c, use_coverage)
    return out, (enc_states, enc_feats, enc_mask, dec_feats, coverage, v, w_c)


def _bwd(use_coverage, saved, grads):
    """Backward = autodiff of the reference formula, recomputed (a
    rematerialization: forward-kernel speed, exact reference gradients)."""
    enc_states, enc_feats, enc_mask, dec_feats, coverage, v, w_c = saved
    _, vjp = jax.vjp(
        lambda es, ef, df, cov, vv, wc: _attention_xla(
            es, ef, enc_mask, df, cov, vv, wc, use_coverage),
        enc_states, enc_feats, dec_feats, coverage, v, w_c)
    d_es, d_ef, d_df, d_cov, d_v, d_wc = vjp(grads)
    return (d_es, d_ef, None, d_df, d_cov, d_v, d_wc)


fused_attention.defvjp(_fwd, _bwd)
