"""Fused additive attention + coverage as a Pallas TPU kernel.

The hot op of the pointer-generator (SURVEY.md §7.2 step 7): per decoder
step the reference computes, over every encoder position i
(/root/reference/src/main/python/pointer-generator/attention_decoder.py:79-129),

    e_i  = v . tanh(W_h h_i + W_s s_t [+ w_c c_i] + b)
    a    = masked_softmax(e)
    ctx  = sum_i a_i h_i

The XLA path (ops/attention.py) materializes the [B, T, D] `feats` tensor
in HBM between the add and the tanh reduction.  This kernel fuses energy,
masked softmax, and the context matmul into ONE pass per batch row: the
encoder tensors stream HBM->VMEM once, the [T, D] intermediate never
leaves VMEM, the context reduction rides the MXU.

At reference scale (T=400->pad 512, D=512, f32) one row's working set is
~2 MB — comfortably inside the ~16 MB VMEM budget, so the grid is simply
(B,) with full-[T, D] blocks.  (A T-blocked flash-style variant is the
obvious extension for long-context configs; see sp-axis notes in
parallel/mesh.py.)

Masking parity: the reference softmaxes THEN masks THEN renormalizes
(attention_decoder.py:96-101); energy-level -inf masking is algebraically
identical and is what the kernel does.

Training support: `fused_attention` carries a custom VJP whose backward
recomputes the (cheap) reference formula under XLA autodiff — kernel
forward speed, reference-exact gradients, no handwritten backward to
maintain.
"""

from __future__ import annotations

import functools
import os
from typing import Tuple

import jax
import jax.numpy as jnp

Array = jax.Array

NEG = -1e30
_LANE = 128


def _pad_to(x: Array, axis: int, mult: int, value: float = 0.0) -> Array:
    size = x.shape[axis]
    pad = (-size) % mult
    if pad == 0:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths, constant_values=value)


def _kernel(es_ref, ef_ref, mask_ref, df_ref, cov_ref, v_ref, wc_ref,
            ctx_ref, attn_ref, *, use_coverage: bool):
    """One batch row: es/ef [1, T, D]; mask/cov/attn [1, T, 1];
    df/ctx [1, 1, D]; v/wc [1, D].

    Shapes are chosen for the Mosaic TPU block-mapping rule: every block's
    trailing two dims are either (8, 128)-aligned or span the whole array
    dim, so per-row [T, 1] columns and [1, D] rows are legal while plain
    [1, T] per-row slices of a [B, T] array are not.
    """
    # es/ef arrive in their HBM dtype (bf16 under compute_dtype=bfloat16
    # — casting to f32 OUTSIDE the kernel would materialize full-width
    # copies in HBM and forfeit the bf16 bandwidth win); upcast here, in
    # VMEM, so the energy/softmax math is f32 regardless
    ef = ef_ref[0].astype(jnp.float32)   # [T, D]
    feats = ef + df_ref[0]               # + [1, D]
    if use_coverage:
        feats = feats + cov_ref[0] * wc_ref[...]   # [T, 1] * [1, D]
    e = jnp.sum(v_ref[...] * jnp.tanh(feats), axis=-1,
                keepdims=True)           # [T, 1]
    mask = mask_ref[0]                   # [T, 1]
    e = jnp.where(mask > 0, e, NEG)
    m = jnp.max(e)
    p = jnp.where(mask > 0, jnp.exp(e - m), 0.0)
    l = jnp.sum(p)
    # fully-masked row (empty streamed article): l=0 would give NaN via
    # 0/0 and poison p_gen/final_dist; clamp -> zero attention instead
    a = p / jnp.maximum(l, 1e-30)        # [T, 1]
    attn_ref[0] = a
    # context: aᵀ[1, T] @ es [T, D] on the MXU (contraction over dim 0);
    # HIGHEST precision keeps full f32 (the matvec is a sliver of the
    # kernel's work; default bf16 passes cost ~1e-2 absolute ctx error)
    ctx_ref[0] = jax.lax.dot_general(
        a, es_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)


def _attention_xla(enc_states, enc_feats, enc_mask, dec_feats, coverage,
                   v, w_c, use_coverage):
    """Reference formula (ops/attention.py semantics) — backward path and
    non-TPU fallback."""
    feats = enc_feats + dec_feats[:, None, :]
    if use_coverage:
        feats = feats + coverage[:, :, None] * w_c[None, None, :]
    e = jnp.sum(v * jnp.tanh(feats), axis=-1)
    e = jnp.where(enc_mask > 0, e, NEG)
    e = e - jax.lax.stop_gradient(jnp.max(e, axis=-1, keepdims=True))
    p = jnp.exp(e) * (enc_mask > 0)
    # fully-masked row: clamp the l=0 denominator (match the kernels)
    attn = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    context = jnp.einsum("bt,btd->bd", attn, enc_states)
    return context, attn


def _attention_xla_shared(enc_states, enc_feats, enc_mask, dec_feats,
                          coverage, v, w_c, use_coverage):
    """The reference formula with the per-article encoder tensors SHARED
    across the K query rows (decode byte diet, ISSUE 7): enc_states /
    enc_feats are [T, D] and enc_mask [T] — no query axis — so the beam's
    K hypotheses broadcast against ONE copy and the context reduction is
    a plain [K, T] @ [T, D] matmul that streams the encoder from HBM
    once per step instead of K times.  dec_feats: [K, D]; coverage:
    [K, T].  Same math as _attention_xla row for row."""
    feats = enc_feats[None, :, :] + dec_feats[:, None, :]
    if use_coverage:
        feats = feats + coverage[:, :, None] * w_c[None, None, :]
    e = jnp.sum(v * jnp.tanh(feats), axis=-1)  # [K, T]
    e = jnp.where(enc_mask[None, :] > 0, e, NEG)
    e = e - jax.lax.stop_gradient(jnp.max(e, axis=-1, keepdims=True))
    p = jnp.exp(e) * (enc_mask[None, :] > 0)
    # fully-masked row: clamp the l=0 denominator (match the kernels)
    attn = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    context = attn @ enc_states  # promotes bf16 enc to f32 like the einsum
    return context, attn


def fused_attention_shared(enc_states, enc_feats, enc_mask, dec_feats,
                           coverage, v, w_c, use_coverage):
    """fused_attention for the shared-encoder decode layout (enc leaves
    carry no query axis; see _attention_xla_shared).  Forward-only — the
    beam search never differentiates through it.  TS_PALLAS=on keeps its
    meaning by broadcasting the encoder back to [K, ...] for the kernel
    (the kernel's grid is per query row); the default XLA path never
    materializes that broadcast."""
    if _use_pallas():
        K = dec_feats.shape[0]
        bc = lambda x: jnp.broadcast_to(x[None], (K,) + x.shape)  # noqa: E731
        return fused_attention(bc(enc_states), bc(enc_feats), bc(enc_mask),
                               dec_feats, coverage, v, w_c, use_coverage)
    return _attention_xla_shared(enc_states, enc_feats, enc_mask, dec_feats,
                                 coverage, v, w_c, use_coverage)


def _attention_pallas(enc_states, enc_feats, enc_mask, dec_feats, coverage,
                      v, w_c, use_coverage, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, D = enc_states.shape
    es = _pad_to(_pad_to(enc_states, 1, _LANE), 2, _LANE)
    ef = _pad_to(_pad_to(enc_feats, 1, _LANE), 2, _LANE)
    mask = _pad_to(enc_mask, 1, _LANE)
    cov = _pad_to(coverage, 1, _LANE)
    df = _pad_to(dec_feats, 1, _LANE)
    vp = _pad_to(v[None, :], 1, _LANE)[0]
    wcp = _pad_to(w_c[None, :], 1, _LANE)[0]
    Tp, Dp = es.shape[1], es.shape[2]

    row3 = lambda b: (b, 0, 0)
    rep = lambda b: (0, 0)
    ctx, attn = pl.pallas_call(
        functools.partial(_kernel, use_coverage=use_coverage),
        grid=(B,),
        in_specs=[
            pl.BlockSpec((1, Tp, Dp), row3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, Dp), row3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, 1), row3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Dp), row3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, 1), row3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Dp), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Dp), rep, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, Dp), row3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Tp, 1), row3, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, 1, Dp), jnp.float32),
            jax.ShapeDtypeStruct((B, Tp, 1), jnp.float32),
        ],
        interpret=interpret,
        # es/ef keep their HBM dtype (bf16 mode streams half the bytes);
        # the kernel upcasts in VMEM
    )(es, ef,
      mask.astype(jnp.float32)[:, :, None], df.astype(jnp.float32)[:, None, :],
      cov.astype(jnp.float32)[:, :, None], vp[None].astype(jnp.float32),
      wcp[None].astype(jnp.float32))
    return ctx[:, 0, :D], attn[:, :T, 0]


def _blocked_kernel(es_ref, ef_ref, mask_ref, df_ref, cov_ref, v_ref, wc_ref,
                    ctx_ref, e_ref, m_scr, l_scr, ctx_scr,
                    *, use_coverage: bool):
    """Flash-style online-softmax block: grid (B, nT), T-blocks sequential.

    The context accumulates in VMEM scratch with the usual running-max
    rescaling and is normalized in-kernel at the last block.  The masked
    energies stream out per block ([Tb, 1] columns); the wrapper recovers
    the attention distribution from them with one cheap XLA softmax —
    that keeps every output block TPU-legal (no per-block scalar stores)
    while the [T, D] feats intermediate still never leaves VMEM.
    """
    import jax.experimental.pallas as pl

    j = pl.program_id(1)
    nT = pl.num_programs(1)

    @pl.when(j == 0)
    def _init():
        m_scr[0, 0] = NEG
        l_scr[0, 0] = 0.0
        ctx_scr[:, :] = jnp.zeros_like(ctx_scr)

    # upcast in VMEM (see _kernel): es/ef stream HBM->VMEM at their
    # native width, possibly bf16
    ef = ef_ref[0].astype(jnp.float32)   # [Tb, D]
    feats = ef + df_ref[0]               # + [1, D]
    if use_coverage:
        feats = feats + cov_ref[0] * wc_ref[...]   # [Tb, 1] * [1, D]
    e = jnp.sum(v_ref[...] * jnp.tanh(feats), axis=-1,
                keepdims=True)           # [Tb, 1]
    mask = mask_ref[0]                   # [Tb, 1]
    e = jnp.where(mask > 0, e, NEG)
    e_ref[0] = e

    m_old = m_scr[0, 0]
    m_new = jnp.maximum(m_old, jnp.max(e))
    scale = jnp.exp(m_old - m_new)
    p = jnp.where(mask > 0, jnp.exp(e - m_new), 0.0)   # [Tb, 1]
    l_scr[0, 0] = l_scr[0, 0] * scale + jnp.sum(p)
    ctx_scr[:, :] = ctx_scr[:, :] * scale + jax.lax.dot_general(
        p, es_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32)
    m_scr[0, 0] = m_new

    @pl.when(j == nT - 1)
    def _finish():
        # clamp like the simple kernel: fully-masked row has l=0
        ctx_ref[0] = ctx_scr[:, :] / jnp.maximum(l_scr[0, 0], 1e-30)


def _attention_pallas_blocked(enc_states, enc_feats, enc_mask, dec_feats,
                              coverage, v, w_c, use_coverage,
                              block_t: int = 512, interpret=False):
    """Long-context path: stream T in `block_t` slices (VMEM holds one
    [block_t, D] slice at a time), online softmax across blocks."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, T, D = enc_states.shape
    es = _pad_to(_pad_to(enc_states, 1, block_t), 2, _LANE)
    ef = _pad_to(_pad_to(enc_feats, 1, block_t), 2, _LANE)
    mask = _pad_to(enc_mask, 1, block_t)
    cov = _pad_to(coverage, 1, block_t)
    df = _pad_to(dec_feats, 1, _LANE)
    vp = _pad_to(v[None, :], 1, _LANE)
    wcp = _pad_to(w_c[None, :], 1, _LANE)
    Tp, Dp = es.shape[1], es.shape[2]
    nT = Tp // block_t

    brow3 = lambda b, j: (b, 0, 0)
    tb3 = lambda b, j: (b, j, 0)
    rep = lambda b, j: (0, 0)
    ctx, energies = pl.pallas_call(
        functools.partial(_blocked_kernel, use_coverage=use_coverage),
        grid=(B, nT),
        in_specs=[
            pl.BlockSpec((1, block_t, Dp), tb3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_t, Dp), tb3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_t, 1), tb3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, Dp), brow3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_t, 1), tb3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Dp), rep, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, Dp), rep, memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, Dp), brow3, memory_space=pltpu.VMEM),
            pl.BlockSpec((1, block_t, 1), tb3, memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, 1, Dp), jnp.float32),
            jax.ShapeDtypeStruct((B, Tp, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.SMEM((1, 1), jnp.float32),
            pltpu.SMEM((1, 1), jnp.float32),
            pltpu.VMEM((1, Dp), jnp.float32),
        ],
        interpret=interpret,
        # es/ef in their HBM dtype; in-kernel upcast (see _kernel)
    )(es, ef,
      mask.astype(jnp.float32)[:, :, None], df.astype(jnp.float32)[:, None, :],
      cov.astype(jnp.float32)[:, :, None], vp.astype(jnp.float32),
      wcp.astype(jnp.float32))
    # attention from the streamed energies: one cheap [B, Tp] softmax in
    # XLA (masked positions carry NEG so they exp to 0); the clamp keeps a
    # fully-masked row at zero attention instead of NaN
    e = energies[:, :, 0]
    m_fin = jnp.max(e, axis=-1, keepdims=True)
    p = jnp.where(mask > 0, jnp.exp(e - m_fin), 0.0)
    attn = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    return ctx[:, 0, :D], attn[:, :T]


# VMEM budget heuristic: two [T, D] f32 slices per row beyond this, stream
# T in blocks instead (simple kernel holds both enc tensors at once).
_SIMPLE_KERNEL_MAX_ELEMS = 1_000_000


def _use_pallas() -> bool:
    """auto (default) prefers the XLA formula: on-device A/B at both
    reference scale (B16 T400 D512) and long context (B4 T4096 D512)
    measured the Pallas kernels at 0.99x / 0.94x of XLA on TPU v5e
    (BASELINE.md round-2 attention_ab) — XLA's own fusion of this
    additive-attention chain is already near-roofline, so the kernels
    stay opt-in (TS_PALLAS=on) and serve the VMEM-constrained sp path
    (blocked variant) rather than the default train step."""
    env = os.environ.get("TS_PALLAS", "auto").lower()
    if env in ("0", "off", "false"):
        return False
    if env in ("1", "on", "true"):
        return True
    return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def fused_attention(enc_states: Array, enc_feats: Array, enc_mask: Array,
                    dec_feats: Array, coverage: Array, v: Array, w_c: Array,
                    use_coverage: bool) -> Tuple[Array, Array]:
    """(context [B, D], attn_dist [B, T]).  coverage is read only when
    use_coverage (pass zeros otherwise)."""
    if _use_pallas():
        T, D = enc_states.shape[1], enc_states.shape[2]
        if T * D > _SIMPLE_KERNEL_MAX_ELEMS:  # long-context: stream T
            return _attention_pallas_blocked(enc_states, enc_feats, enc_mask,
                                             dec_feats, coverage, v, w_c,
                                             use_coverage)
        return _attention_pallas(enc_states, enc_feats, enc_mask, dec_feats,
                                 coverage, v, w_c, use_coverage)
    return _attention_xla(enc_states, enc_feats, enc_mask, dec_feats,
                          coverage, v, w_c, use_coverage)


def _fwd(enc_states, enc_feats, enc_mask, dec_feats, coverage, v, w_c,
         use_coverage):
    out = fused_attention(enc_states, enc_feats, enc_mask, dec_feats,
                          coverage, v, w_c, use_coverage)
    return out, (enc_states, enc_feats, enc_mask, dec_feats, coverage, v, w_c)


def _bwd(use_coverage, saved, grads):
    """Backward = autodiff of the reference formula, recomputed (a
    rematerialization: forward-kernel speed, exact reference gradients)."""
    enc_states, enc_feats, enc_mask, dec_feats, coverage, v, w_c = saved
    _, vjp = jax.vjp(
        lambda es, ef, df, cov, vv, wc: _attention_xla(
            es, ef, enc_mask, df, cov, vv, wc, use_coverage),
        enc_states, enc_feats, dec_feats, coverage, v, w_c)
    d_es, d_ef, d_df, d_cov, d_v, d_wc = vjp(grads)
    return (d_es, d_ef, None, d_df, d_cov, d_v, d_wc)


fused_attention.defvjp(_fwd, _bwd)
