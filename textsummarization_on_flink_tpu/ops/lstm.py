"""LSTM cell + length-aware scans, TF1-compatible math.

The reference encoder/decoder cells are `tf.contrib.rnn.LSTMCell`
(model.py:90-92,138).  TF1's LSTMCell computes, with gate order
[i, j, f, o] on the fused kernel and forget_bias=1.0:

    z = [x, h] @ kernel + bias
    i, j, f, o = split(z, 4)
    c' = c * sigmoid(f + 1.0) + sigmoid(i) * tanh(j)
    h' = tanh(c') * sigmoid(o)

We keep that exact gate order and forget bias so a TF1 checkpoint's fused
kernel/bias can be loaded verbatim.  The bidirectional encoder matches
`tf.nn.bidirectional_dynamic_rnn` with sequence_length (model.py:92):
outputs beyond each sequence's length are zeros and the carried state
freezes at the last valid step; the backward direction runs over the
length-aware reversed sequence (reverse_sequence semantics).

Everything here is jit/scan-based — no Python-level step loops.  BOTH
encoder directions share ONE `lax.scan` (the backward one consumes the
reversed sequence), with the input half of each fused kernel hoisted out
of the scan as a whole-sequence matmul; only the recurrent `h @ k_h`
half is sequential.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array
LSTMState = Tuple[Array, Array]  # (c, h)


def _apply_gates(z: Array, c: Array, forget_bias: float,
                 ) -> Tuple[Array, Array]:
    """TF1 LSTMCell gate math on pre-activations z = [x, h] @ kernel + b
    (gate order [i, j, f, o], see module docstring).  Returns (c', h')."""
    i, j, f, o = jnp.split(z, 4, axis=-1)
    new_c = c * jax.nn.sigmoid(f + forget_bias) \
        + jax.nn.sigmoid(i) * jnp.tanh(j)
    new_h = jnp.tanh(new_c) * jax.nn.sigmoid(o)
    return new_c, new_h


def lstm_cell(params: Dict[str, Array], x: Array, state: LSTMState,
              forget_bias: float = 1.0) -> Tuple[Array, LSTMState]:
    """One LSTM step. x: [B, I]; state: ([B, H], [B, H])."""
    c, h = state
    # Compute in the activation dtype (bf16 on the MXU when the caller casts
    # inputs); master params stay f32 and are cast per-step, so the scan
    # carry keeps one consistent dtype.
    kernel = params["kernel"].astype(x.dtype)
    bias = params["bias"].astype(x.dtype)
    z = jnp.concatenate([x, h], axis=-1) @ kernel + bias
    new_c, new_h = _apply_gates(z, c, forget_bias)
    return new_h, (new_c, new_h)


def reverse_sequence(x: Array, lens: Array) -> Array:
    """tf.reverse_sequence along axis 1: reverse only the first `lens[b]`
    entries of each row; padding stays in place."""
    T = x.shape[1]
    t = jnp.arange(T)[None, :]  # [1, T]
    idx = jnp.where(t < lens[:, None], lens[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)


def bidirectional_encoder(fw_params: Dict[str, Array], bw_params: Dict[str, Array],
                          inputs: Array, lens: Array, mask: Array,
                          forget_bias: float = 1.0, unroll: int = 1,
                          ) -> Tuple[Array, LSTMState, LSTMState]:
    """bidirectional_dynamic_rnn parity (model.py:76-94).

    Returns (outputs [B, T, 2H] fw||bw concat, fw_state, bw_state).

    Both directions run in ONE scan: the backward direction consumes the
    length-aware reversed sequence, so stacking (fw, bw) on a leading
    direction axis makes each step a [2, B, H] x [2, H, 4H] batched
    matmul.  That halves the sequential depth versus two consecutive
    scans — at LSTM sizes the scan is latency-bound, so depth is the
    cost that matters — while the per-direction kernels stay separate
    (and TF1-checkpoint-loadable) via the batched einsum.
    """
    B = inputs.shape[0]
    H = fw_params["kernel"].shape[1] // 4
    I = inputs.shape[-1]
    rev_inputs = reverse_sequence(inputs, lens)
    inputs2 = jnp.stack([inputs, rev_inputs])  # [2, B, T, I]
    kernel2 = jnp.stack([fw_params["kernel"], bw_params["kernel"]]
                        ).astype(inputs.dtype)  # [2, I+H, 4H]
    bias2 = jnp.stack([fw_params["bias"], bw_params["bias"]]
                      ).astype(inputs.dtype)  # [2, 4H]
    k_x2, k_h2 = kernel2[:, :I], kernel2[:, I:]
    # input half hoisted out of the scan, both directions in one matmul
    x_proj2 = jnp.einsum("dbti,dif->dbtf", inputs2, k_x2) \
        + bias2[:, None, None, :]  # [2, B, T, 4H]

    def step(state, xm):
        xp, m = xm  # [2, B, 4H], [B]
        m = m[None, :, None]
        c, h = state  # each [2, B, H]
        z = xp + jnp.einsum("dbh,dhf->dbf", h, k_h2)
        new_c, new_h = _apply_gates(z, c, forget_bias)
        c = jnp.where(m > 0, new_c, c)
        h = jnp.where(m > 0, new_h, h)
        # multiply in the activation dtype: an f32 mask would silently
        # promote the whole output stream (and everything downstream
        # that re-reads it) back to f32
        return (c, h), new_h * m.astype(new_h.dtype)

    zero2 = (jnp.zeros((2, B, H), inputs.dtype),
             jnp.zeros((2, B, H), inputs.dtype))
    xs = (jnp.moveaxis(x_proj2, 2, 0), jnp.swapaxes(mask, 0, 1))
    # unroll amortizes per-iteration loop overhead — the scan is
    # latency-bound, not FLOP-bound (hps.scan_unroll; numerically
    # identical at any factor)
    (final_c, final_h), outs = jax.lax.scan(step, zero2, xs,
                                            unroll=max(unroll, 1))
    outs = jnp.moveaxis(outs, 0, 2)  # [2, B, T, H]
    fw_out = outs[0]
    bw_out = reverse_sequence(outs[1], lens)
    fw_state = (final_c[0], final_h[0])
    bw_state = (final_c[1], final_h[1])
    return jnp.concatenate([fw_out, bw_out], axis=-1), fw_state, bw_state
