"""LSTM cell + length-aware scans, TF1-compatible math.

The reference encoder/decoder cells are `tf.contrib.rnn.LSTMCell`
(model.py:90-92,138).  TF1's LSTMCell computes, with gate order
[i, j, f, o] on the fused kernel and forget_bias=1.0:

    z = [x, h] @ kernel + bias
    i, j, f, o = split(z, 4)
    c' = c * sigmoid(f + 1.0) + sigmoid(i) * tanh(j)
    h' = tanh(c') * sigmoid(o)

We keep that exact gate order and forget bias so a TF1 checkpoint's fused
kernel/bias can be loaded verbatim.  The bidirectional encoder matches
`tf.nn.bidirectional_dynamic_rnn` with sequence_length (model.py:92):
outputs beyond each sequence's length are zeros and the carried state
freezes at the last valid step; the backward direction runs over the
length-aware reversed sequence (reverse_sequence semantics).

Everything here is jit/scan-based: one `lax.scan` per direction, batched
matmuls on the MXU, no Python-level step loops.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

Array = jax.Array
LSTMState = Tuple[Array, Array]  # (c, h)


def lstm_cell(params: Dict[str, Array], x: Array, state: LSTMState,
              forget_bias: float = 1.0) -> Tuple[Array, LSTMState]:
    """One LSTM step. x: [B, I]; state: ([B, H], [B, H])."""
    c, h = state
    # Compute in the activation dtype (bf16 on the MXU when the caller casts
    # inputs); master params stay f32 and are cast per-step, so the scan
    # carry keeps one consistent dtype.
    kernel = params["kernel"].astype(x.dtype)
    bias = params["bias"].astype(x.dtype)
    z = jnp.concatenate([x, h], axis=-1) @ kernel + bias
    i, j, f, o = jnp.split(z, 4, axis=-1)
    new_c = c * jax.nn.sigmoid(f + forget_bias) + jax.nn.sigmoid(i) * jnp.tanh(j)
    new_h = jnp.tanh(new_c) * jax.nn.sigmoid(o)
    return new_h, (new_c, new_h)


def unidirectional_scan(params: Dict[str, Array], inputs: Array, mask: Array,
                        init_state: LSTMState,
                        forget_bias: float = 1.0) -> Tuple[Array, LSTMState]:
    """Run an LSTM over time with dynamic_rnn length semantics.

    inputs: [B, T, I]; mask: [B, T] (1.0 for valid steps).
    Returns outputs [B, T, H] (zeroed past each length) and the final state
    (frozen at each sequence's last valid step).

    MXU layout: the input half of the fused TF1 kernel is applied to the
    WHOLE sequence as one [B, T, I] @ [I, 4H] matmul before the scan (a
    single large tile instead of T skinny ones); only the recurrent
    h @ k_h half stays inside the scan.  Same math as lstm_cell — the
    fused z = [x, h] @ kernel splits exactly into x @ k_x + h @ k_h.
    """
    I = inputs.shape[-1]
    kernel = params["kernel"].astype(inputs.dtype)
    bias = params["bias"].astype(inputs.dtype)
    k_x, k_h = kernel[:I], kernel[I:]
    x_proj = inputs @ k_x + bias  # [B, T, 4H], hoisted out of the scan

    def step(state, xm):
        xp, m = xm
        m = m[:, None]
        c, h = state
        z = xp + h @ k_h
        i, j, f, o = jnp.split(z, 4, axis=-1)
        new_c = c * jax.nn.sigmoid(f + forget_bias) \
            + jax.nn.sigmoid(i) * jnp.tanh(j)
        new_h = jnp.tanh(new_c) * jax.nn.sigmoid(o)
        c = jnp.where(m > 0, new_c, c)
        h = jnp.where(m > 0, new_h, h)
        return (c, h), new_h * m

    xs = (jnp.swapaxes(x_proj, 0, 1), jnp.swapaxes(mask, 0, 1))
    final_state, outs = jax.lax.scan(step, init_state, xs)
    return jnp.swapaxes(outs, 0, 1), final_state


def reverse_sequence(x: Array, lens: Array) -> Array:
    """tf.reverse_sequence along axis 1: reverse only the first `lens[b]`
    entries of each row; padding stays in place."""
    T = x.shape[1]
    t = jnp.arange(T)[None, :]  # [1, T]
    idx = jnp.where(t < lens[:, None], lens[:, None] - 1 - t, t)
    return jnp.take_along_axis(
        x, idx.reshape(idx.shape + (1,) * (x.ndim - 2)), axis=1)


def bidirectional_encoder(fw_params: Dict[str, Array], bw_params: Dict[str, Array],
                          inputs: Array, lens: Array, mask: Array,
                          ) -> Tuple[Array, LSTMState, LSTMState]:
    """bidirectional_dynamic_rnn parity (model.py:76-94).

    Returns (outputs [B, T, 2H] fw||bw concat, fw_state, bw_state).
    """
    B = inputs.shape[0]
    H = fw_params["kernel"].shape[1] // 4
    zero = (jnp.zeros((B, H), inputs.dtype), jnp.zeros((B, H), inputs.dtype))
    fw_out, fw_state = unidirectional_scan(fw_params, inputs, mask, zero)
    rev_inputs = reverse_sequence(inputs, lens)
    bw_out_rev, bw_state = unidirectional_scan(bw_params, rev_inputs, mask, zero)
    bw_out = reverse_sequence(bw_out_rev, lens)
    return jnp.concatenate([fw_out, bw_out], axis=-1), fw_state, bw_state
