"""Core numeric ops: LSTM scans, additive attention + coverage, pointer
mixing, losses.  Plain XLA implementations here; Pallas TPU kernels live in
``pallas_*`` modules with these as their correctness baseline."""
