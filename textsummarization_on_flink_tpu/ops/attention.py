"""Bahdanau additive attention with coverage and padding-mask renorm.

Numeric parity with the reference attention
(/root/reference/src/main/python/pointer-generator/attention_decoder.py:79-129):

    e_i   = v . tanh(W_h h_i + W_s s_t [+ w_c c_i] + b_attn)
    a     = renorm(softmax(e) * enc_mask)            # masked_attention :96-101
    ctx   = sum_i a_i h_i

The reference computes W_h via a 1x1 conv2d (:66-67) and w_c via a
(1,1,1,D) conv2d (:105) — both are plain matmul / outer-product here, which
XLA maps straight onto the MXU.  ``encoder_features`` (W_h h_i) is
precomputed once per sequence outside the decoder loop, exactly like the
reference hoists it out of its step loop.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from textsummarization_on_flink_tpu.ops import pallas_attention

Array = jax.Array


def masked_softmax(e: Array, enc_mask: Array) -> Array:
    """softmax -> mask -> renormalize (attention_decoder.py:96-101).

    Subtracting the rowwise max first keeps exp() finite; the result is
    mathematically identical to the reference's plain softmax pipeline.
    """
    e = e - jax.lax.stop_gradient(jnp.max(e, axis=-1, keepdims=True))
    attn = jax.nn.softmax(e, axis=-1)
    attn = attn * enc_mask
    # fully-masked row (empty article): clamp the 0 denominator so the
    # result is zero attention, not NaN
    denom = jnp.maximum(jnp.sum(attn, axis=-1, keepdims=True), 1e-30)
    return attn / denom


def encoder_features(attn_params: Dict[str, Array], enc_states: Array) -> Array:
    """W_h h_i for every encoder position. enc_states: [B, T, D] -> [B, T, D].

    Computed in the encoder-stream dtype (bf16 under compute_dtype=
    bfloat16): the result is re-read from HBM every decoder step, so its
    width — not this matmul's precision — is what matters; the attention
    op promotes to f32 before the softmax either way."""
    return enc_states @ attn_params["W_h"].astype(enc_states.dtype)


def attend(attn_params: Dict[str, Array], enc_states: Array, enc_feats: Array,
           enc_mask: Array, dec_state: Tuple[Array, Array],
           coverage: Optional[Array], use_coverage: bool,
           ) -> Tuple[Array, Array, Optional[Array]]:
    """One attention query.

    Args:
      enc_states: [B, T, D]; enc_feats: precomputed W_h h_i [B, T, D];
      enc_mask: [B, T]; dec_state: (c, h) each [B, H];
      coverage: [B, T] accumulated attention, or None.

    Returns (context [B, D], attn_dist [B, T], new_coverage [B, T] or None).
    New coverage = coverage + attn_dist (the caller decides whether to keep
    it; decode mode sometimes discards the update, attention_decoder.py:156-158).
    """
    c, h = dec_state
    dec_in = jnp.concatenate([c, h], axis=-1)
    dec_feats = dec_in @ attn_params["linear_kernel"] + attn_params["linear_bias"]
    # energy + masked softmax + context in one call (XLA formula by
    # default — measured fastest; Pallas kernels opt-in via TS_PALLAS=on,
    # see pallas_attention._use_pallas).  Energy-level masking is
    # algebraically identical to the reference's softmax->mask->renorm
    # pipeline.
    apply_cov = bool(use_coverage and coverage is not None)
    cov_in = coverage if apply_cov else jnp.zeros_like(enc_mask)
    context, attn_dist = pallas_attention.fused_attention(
        enc_states, enc_feats, enc_mask, dec_feats.astype(jnp.float32),
        cov_in, attn_params["v"], attn_params["w_c"], apply_cov)
    new_coverage = None
    if use_coverage:
        new_coverage = (coverage if coverage is not None else 0.0) + attn_dist
    return context, attn_dist, new_coverage


def attend_shared(attn_params: Dict[str, Array], enc_states: Array,
                  enc_feats: Array, enc_mask: Array,
                  dec_state: Tuple[Array, Array],
                  coverage: Optional[Array], use_coverage: bool,
                  nb: Optional[Array] = None, block: int = 0,
                  ) -> Tuple[Array, Array, Optional[Array]]:
    """attend() with the encoder tensors shared across the K query rows
    (decode byte diet, ISSUE 7): enc_states/enc_feats [T, D] and
    enc_mask [T] carry no query axis, dec_state leaves are [K, H],
    coverage [K, T].  The beam search's per-hypothesis queries broadcast
    against ONE per-article encoder copy — same numerics as attend() on
    a K-fold broadcast, without the K-fold HBM stream.

    Length-masked slot decode (prefill/decode disaggregation, ISSUE 11):
    an explicit ``nb`` (traced scalar int32 — the number of active
    `block`-position encoder key blocks, ceil(valid_len / block)) routes
    through the BLOCKED formula: each block's energies/context matmul is
    gated by a real XLA conditional on ``b < nb``, so the work executed
    (and the bytes streamed) scales with the longest active resident's
    TRUE article length instead of the padded T.  Positions in inactive
    blocks stay at the masked energy floor, exactly where enc_mask=0
    positions sit in the dense path — so the result is numerically the
    dense attend's (context differs only by block-wise partial-sum
    association).  nb=None keeps the dense fused path."""
    c, h = dec_state
    dec_in = jnp.concatenate([c, h], axis=-1)
    dec_feats = dec_in @ attn_params["linear_kernel"] + attn_params["linear_bias"]
    apply_cov = bool(use_coverage and coverage is not None)
    cov_in = (coverage if apply_cov
              else jnp.zeros((dec_in.shape[0], enc_mask.shape[0]),
                             jnp.float32))
    if nb is None:
        context, attn_dist = pallas_attention.fused_attention_shared(
            enc_states, enc_feats, enc_mask, dec_feats.astype(jnp.float32),
            cov_in, attn_params["v"], attn_params["w_c"], apply_cov)
    else:
        context, attn_dist = _attend_shared_blocked(
            enc_states, enc_feats, enc_mask, dec_feats.astype(jnp.float32),
            cov_in, attn_params["v"], attn_params["w_c"], apply_cov,
            nb, block)
    new_coverage = None
    if use_coverage:
        new_coverage = (coverage if coverage is not None else 0.0) + attn_dist
    return context, attn_dist, new_coverage


NEG = -1e30  # masked-energy floor (matches pallas_attention.NEG)


def _attend_shared_blocked(enc_states: Array, enc_feats: Array,
                           enc_mask: Array, dec_feats: Array, coverage: Array,
                           v: Array, w_c: Array, use_coverage: bool,
                           nb: Array, block: int,
                           ) -> Tuple[Array, Array]:
    """The shared-encoder reference formula over a conditional chain of
    `block`-position encoder key blocks (length-masked slot decode).

    The chain is STATICALLY unrolled (python loop over ceil(T/block)
    blocks, each a `lax.cond` on the traced, query-uniform ``b < nb``),
    so the compiled step is ONE executable whose runtime FLOPs/bytes
    scale with nb — XLA conditionals with an unbatched predicate survive
    the slot vmap as real branches, and HloCostAnalysis prices each
    block once, which is what makes decode_step_cost's length axis
    faithful.  Energies land in a NEG-initialized [K, T] buffer:
    uncovered blocks sit at the same floor the dense path's enc_mask=0
    positions do, so softmax weights there are exactly 0 and the
    skipped context blocks contribute exactly nothing.  Forward-only,
    XLA-only (the masked slot path never routes to Pallas)."""
    K = dec_feats.shape[0]
    T = enc_mask.shape[0]
    block = max(1, min(int(block) or T, T))
    nblocks = -(-T // block)
    e = jnp.full((K, T), NEG, jnp.float32)
    for b in range(nblocks):
        lo, hi = b * block, min((b + 1) * block, T)

        def write_block(e, lo=lo, hi=hi):
            feats = enc_feats[lo:hi].astype(jnp.float32)[None, :, :] \
                + dec_feats[:, None, :]
            if use_coverage:
                feats = feats + coverage[:, lo:hi, None] * w_c[None, None, :]
            eb = jnp.sum(v * jnp.tanh(feats), axis=-1)  # [K, hi-lo]
            eb = jnp.where(enc_mask[lo:hi][None, :] > 0, eb, NEG)
            return e.at[:, lo:hi].set(eb)

        e = jax.lax.cond(b < nb, write_block, lambda e: e, e)
    e = e - jax.lax.stop_gradient(jnp.max(e, axis=-1, keepdims=True))
    p = jnp.exp(e) * (enc_mask[None, :] > 0)
    attn = p / jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    context = jnp.zeros((K, enc_states.shape[-1]), jnp.float32)
    for b in range(nblocks):
        lo, hi = b * block, min((b + 1) * block, T)

        def add_block(ctx, lo=lo, hi=hi):
            return ctx + attn[:, lo:hi] @ enc_states[lo:hi].astype(
                jnp.float32)

        context = jax.lax.cond(b < nb, add_block, lambda ctx: ctx, context)
    return context, attn
