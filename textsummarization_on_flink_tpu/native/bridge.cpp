// Host-side streaming bridge: bounded byte-record queue (C ABI).
//
// TPU-native counterpart of the reference's native data plane — the
// Flink-AI-Extended Java<->Python record queues (MLMapFunction read/write
// queues, doc/Flink-AI-Extended Integration Report.md:887-941).  One
// instance carries serialized tf.Example records one way between the
// pipeline driver and a worker.
//
// Semantics:
//   * bounded: put blocks (with optional timeout) while full;
//   * immediate flush: every put signals the consumer before returning —
//     the design fix for the reference's Issue-6 (a result only reached
//     the sink when the NEXT record arrived, report:879-897);
//   * end-of-stream: close() wakes everyone; drained gets return -1.
//
// Exposed through ctypes (pipeline/bridge.py NativeRecordQueue); the
// PyRecordQueue fallback implements identical behavior.

#include <sys/types.h>

#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <new>
#include <vector>

namespace {

struct Queue {
  std::mutex mu;
  std::condition_variable not_empty;
  std::condition_variable not_full;
  std::deque<std::vector<unsigned char>> items;
  size_t capacity;
  bool closed = false;

  explicit Queue(size_t cap) : capacity(cap == 0 ? 1 : cap) {}
};

bool wait_on(std::condition_variable& cv, std::unique_lock<std::mutex>& lk,
             double timeout_s, bool (*done)(Queue*), Queue* q) {
  if (timeout_s < 0) {
    cv.wait(lk, [&] { return done(q); });
    return true;
  }
  return cv.wait_for(lk, std::chrono::duration<double>(timeout_s),
                     [&] { return done(q); });
}

}  // namespace

extern "C" {

void* tsb_queue_new(size_t capacity) {
  return new (std::nothrow) Queue(capacity);
}

void tsb_queue_free(void* handle) {
  delete static_cast<Queue*>(handle);
}

// 0 on success; -1 on timeout or closed queue.
int tsb_queue_put(void* handle, const char* data, size_t len,
                  double timeout_s) {
  Queue* q = static_cast<Queue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  bool ok = wait_on(
      q->not_full, lk, timeout_s,
      [](Queue* qq) { return qq->closed || qq->items.size() < qq->capacity; },
      q);
  if (!ok || q->closed) return -1;
  q->items.emplace_back(reinterpret_cast<const unsigned char*>(data),
                        reinterpret_cast<const unsigned char*>(data) + len);
  lk.unlock();
  q->not_empty.notify_one();  // immediate flush: consumer wakes now
  return 0;
}

// Returns record length (>= 0) with *out set to a malloc'd copy the caller
// frees via tsb_record_free; -1 on closed-and-drained or timeout.
ssize_t tsb_queue_get(void* handle, void** out, double timeout_s) {
  Queue* q = static_cast<Queue*>(handle);
  std::unique_lock<std::mutex> lk(q->mu);
  bool ok = wait_on(
      q->not_empty, lk, timeout_s,
      [](Queue* qq) { return qq->closed || !qq->items.empty(); }, q);
  if (!ok || q->items.empty()) {
    *out = nullptr;
    return -1;  // timeout, or closed and drained
  }
  // Allocate the out-buffer BEFORE popping: on allocation failure the
  // record stays queued instead of vanishing from the stream.
  const std::vector<unsigned char>& front = q->items.front();
  void* buf = nullptr;
  if (!front.empty()) {
    buf = std::malloc(front.size());
    if (buf == nullptr) {
      *out = nullptr;
      return -1;
    }
    std::memcpy(buf, front.data(), front.size());
  }
  ssize_t n = static_cast<ssize_t>(front.size());
  q->items.pop_front();
  lk.unlock();
  q->not_full.notify_one();
  *out = buf;
  return n;
}

void tsb_record_free(void* p) { std::free(p); }

void tsb_queue_close(void* handle) {
  Queue* q = static_cast<Queue*>(handle);
  {
    std::lock_guard<std::mutex> lk(q->mu);
    q->closed = true;
  }
  q->not_empty.notify_all();
  q->not_full.notify_all();
}

int tsb_queue_closed(void* handle) {
  Queue* q = static_cast<Queue*>(handle);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->closed ? 1 : 0;
}

size_t tsb_queue_size(void* handle) {
  Queue* q = static_cast<Queue*>(handle);
  std::lock_guard<std::mutex> lk(q->mu);
  return q->items.size();
}

}  // extern "C"
