"""Build the native bridge shared library.

Usage: python -m textsummarization_on_flink_tpu.native.build
Produces libtsbridge.so next to bridge.cpp; pipeline/bridge.py picks it up
automatically (NativeRecordQueue).  Pure-Python fallback exists, so the
build is optional everywhere except performance-sensitive deployments.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRCS = [os.path.join(HERE, "bridge.cpp"), os.path.join(HERE, "chunkio.cpp")]
OUT = os.path.join(HERE, "libtsbridge.so")


def build(force: bool = False) -> str:
    if not force and os.path.exists(OUT) and \
            os.path.getmtime(OUT) >= max(os.path.getmtime(s) for s in SRCS):
        return OUT
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found (need g++ or c++)")
    # compile to a process-unique temp and rename into place: concurrent
    # builders (parallel pytest runs on one checkout) must never let a
    # loader see a half-written .so
    tmp = f"{OUT}.{os.getpid()}.tmp"
    cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           *SRCS, "-o", tmp]
    try:
        subprocess.run(cmd, check=True)
        os.replace(tmp, OUT)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return OUT


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    print(path)
