"""Build the native bridge shared library.

Usage: python -m textsummarization_on_flink_tpu.native.build
Produces libtsbridge.so next to bridge.cpp; pipeline/bridge.py picks it up
automatically (NativeRecordQueue).  Pure-Python fallback exists, so the
build is optional everywhere except performance-sensitive deployments.
"""

from __future__ import annotations

import os
import shutil
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "bridge.cpp")
OUT = os.path.join(HERE, "libtsbridge.so")


def build(force: bool = False) -> str:
    if not force and os.path.exists(OUT) and \
            os.path.getmtime(OUT) >= os.path.getmtime(SRC):
        return OUT
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        raise RuntimeError("no C++ compiler found (need g++ or c++)")
    cmd = [cxx, "-O2", "-std=c++17", "-shared", "-fPIC", "-pthread",
           SRC, "-o", OUT]
    subprocess.run(cmd, check=True)
    return OUT


if __name__ == "__main__":
    path = build(force="--force" in sys.argv)
    print(path)
