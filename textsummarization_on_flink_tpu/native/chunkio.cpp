// Native chunk-file reader: the data plane's hot file-IO path.
//
// The on-disk format is the reference's length-prefixed tf.Example framing
// (/root/reference/src/main/python/pointer-generator/data.py:108-141):
// <8-byte little-endian signed length><payload> repeated.  The reference
// parsed these inside TensorFlow's C++ runtime; the rebuild's equivalent
// reads and validates the framing natively in ONE pass — a single file
// slurp plus an offsets table — and hands Python a contiguous payload
// buffer to slice, replacing 2 read() calls + a struct.unpack per record.
//
// C ABI (ctypes-friendly, no C++ types across the boundary):
//   ts_chunk_read_file(path, &buf, &offs, &n) -> 0 ok / negative error
//     buf:  malloc'd concatenation of all payloads
//     offs: malloc'd array of n+1 offsets (record i = buf[offs[i]:offs[i+1]])
//   ts_chunk_free(buf, offs)
//
// Errors: -1 open failure, -2 truncated length prefix, -3 truncated
// record, -4 negative/absurd record length (framing corruption),
// -5 read failure, -6 allocation failure.  No C++ exception ever crosses
// the C ABI (the body is wrapped; bad_alloc maps to -6).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

extern "C" {

static int read_file_impl(const char* path, char** out_buf,
                          long long** out_offs, long long* out_n) {
  FILE* f = std::fopen(path, "rb");
  if (f == nullptr) return -1;
  std::fseek(f, 0, SEEK_END);
  long file_size = std::ftell(f);
  if (file_size < 0) {
    std::fclose(f);
    return -5;
  }
  std::fseek(f, 0, SEEK_SET);
  std::vector<char> raw(static_cast<size_t>(file_size));
  if (file_size > 0 &&
      std::fread(raw.data(), 1, raw.size(), f) != raw.size()) {
    std::fclose(f);
    return -5;
  }
  std::fclose(f);

  // first pass: validate framing, collect offsets
  std::vector<long long> offs;
  offs.push_back(0);
  long long payload_total = 0;
  size_t pos = 0;
  while (pos < raw.size()) {
    if (raw.size() - pos < 8) return -2;  // truncated length prefix
    int64_t len;
    std::memcpy(&len, raw.data() + pos, 8);  // little-endian hosts only
    pos += 8;
    if (len < 0 || static_cast<uint64_t>(len) > raw.size()) return -4;
    if (raw.size() - pos < static_cast<size_t>(len)) return -3;
    pos += static_cast<size_t>(len);
    payload_total += len;
    offs.push_back(payload_total);
  }
  long long n = static_cast<long long>(offs.size()) - 1;

  char* buf = static_cast<char*>(std::malloc(
      payload_total > 0 ? static_cast<size_t>(payload_total) : 1));
  long long* offs_out = static_cast<long long*>(
      std::malloc(sizeof(long long) * offs.size()));
  if (buf == nullptr || offs_out == nullptr) {
    std::free(buf);
    std::free(offs_out);
    return -6;
  }
  // second pass: copy payloads contiguously
  pos = 0;
  long long cursor = 0;
  while (pos < raw.size()) {
    int64_t len;
    std::memcpy(&len, raw.data() + pos, 8);
    pos += 8;
    std::memcpy(buf + cursor, raw.data() + pos, static_cast<size_t>(len));
    pos += static_cast<size_t>(len);
    cursor += len;
  }
  std::memcpy(offs_out, offs.data(), sizeof(long long) * offs.size());
  *out_buf = buf;
  *out_offs = offs_out;
  *out_n = n;
  return 0;
}

int ts_chunk_read_file(const char* path, char** out_buf,
                       long long** out_offs, long long* out_n) {
  *out_buf = nullptr;
  *out_offs = nullptr;
  *out_n = 0;
  try {
    return read_file_impl(path, out_buf, out_offs, out_n);
  } catch (...) {  // bad_alloc on huge files etc. must not cross the ABI
    return -6;
  }
}

void ts_chunk_free(char* buf, long long* offs) {
  std::free(buf);
  std::free(offs);
}

}  // extern "C"
