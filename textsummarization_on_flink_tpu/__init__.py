"""textsummarization_on_flink_tpu — a TPU-native text-summarization framework.

A ground-up JAX/XLA/Pallas rebuild of the capabilities of the reference
`yangzichuang/TextSummarization-On-Flink` project (pointer-generator
abstractive summarization served through an Estimator/Model streaming
pipeline).  The compute path is JAX (jit/pjit/shard_map over a TPU mesh);
the control plane is a typed Params + Estimator/Model/Pipeline API; the
data plane is a host-side feed/fetch bridge with pluggable stream
sources/sinks.

Layer map (mirrors SURVEY.md §1, rebuilt TPU-first):
  pipeline/   Estimator/Model/Pipeline API, params, sources/sinks, app
  train/      jitted/pjitted training loop, optimizer, eval + early stop
  decode/     on-device batched beam search, ROUGE, decode drivers
  models/     pointer-generator (LSTM) and transformer model families
  ops/        attention/coverage/final-dist/loss ops (+ Pallas kernels)
  parallel/   mesh, sharding rules, collectives, context parallelism
  data/       vocab, tf.Example codec, chunk IO, batching
  checkpoint/ save/restore, retention, surgery, inspection
  runtime/    native (C++) host bridge
"""

__version__ = "0.1.0"
