"""Failure flight recorder: a bounded ring of structured frames dumped
to disk when a typed failure trigger fires (ISSUE 9 tentpole, piece 3).

The recovery paths in ``resilience/`` (NaN rollback, serve dispatch
failure, breaker trips, deadline-eviction storms) each leave a COUNTER
behind today; post-mortem questions — "what did the last 50 steps look
like before the rollback?" — need the frames themselves.  Hot loops
call ``record(kind, **fields)`` with a tiny dict per train step / serve
tick; the ring (``collections.deque(maxlen=capacity)``) keeps only the
newest ``capacity`` frames, so a week-long run costs the same memory as
a minute-long one.  When a trigger fires, ``trigger(registry, reason)``
dumps the ring to ``flight_<reason>.jsonl`` in the recorder's directory
— the N frames *strictly preceding* the trigger, plus one header record
naming the reason.

Wiring (first install wins per registry, like the EventSink):

    rec = flightrec.install_flight_recorder(registry, train_dir,
                                            capacity=hps.flight_frames)
    flightrec.record(registry, "train_step", step=i, loss=..., ...)
    flightrec.trigger(registry, "train_nan")   # -> flight_train_nan.jsonl

Frame producers: train/trainer.py (per-step loss, grad-norm, step time,
prefetch depth), serve/batcher.py + serve/server.py (per-tick occupancy,
queue depth, evictions, refills / per-dispatch fill).  Trigger sites:
the trainer NaN watchdog + divergence recovery, both serve dispatch
failure paths, CircuitBreaker open transitions (resilience/policy.py),
and continuous-mode eviction storms.  All CPU-verifiable: the chaos
tests drive each trigger through the existing TS_FAULTS points.

Storm-proof by design: at most ``max_dumps_per_reason`` files per
reason (later triggers counted in ``obs/flight_dumps_dropped_total``,
never written), and a dump failure increments
``obs/flight_dump_errors_total`` instead of raising into the recovery
path that triggered it.  Import-light: no jax/numpy.
"""

from __future__ import annotations

import collections
import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional

from textsummarization_on_flink_tpu.obs.registry import Registry

DEFAULT_CAPACITY = 64
DEFAULT_MAX_DUMPS_PER_REASON = 5


def _safe_reason(reason: str) -> str:
    """`reason` as a filename fragment ([A-Za-z0-9._-] survives)."""
    return "".join(ch if (ch.isalnum() or ch in "._-") else "_"
                   for ch in reason) or "unknown"


def _json_safe(obj: Any) -> Any:
    """`obj` with non-finite floats stringified ("nan"/"inf"/"-inf").

    The train_nan dump's whole point is the non-finite loss frame, and
    Python's default ``json.dumps`` would write it as a bare ``NaN``
    token — which json.loads tolerates but jq / JSON.parse / strict
    JSONL tooling reject.  Strings keep the fact visible AND the file
    parseable everywhere."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else repr(obj)  # "nan"/"inf"
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


def _dumps(rec: Dict[str, Any]) -> str:
    return json.dumps(_json_safe(rec), allow_nan=False, default=str)


class FlightRecorder:
    """Bounded ring of structured frames + triggered JSONL dumps.

    ``replica_id`` (ISSUE 15 satellite) tags every frame and dump
    filename when set — fleet replicas sharing one log directory write
    ``flight_<reason>.<replica>.jsonl``, so replica 2's serve-dispatch
    dump can never clobber or shadow replica 0's.  Threaded from
    FleetRouter replica construction via ``set_replica_id``."""

    def __init__(self, directory: str, capacity: int = DEFAULT_CAPACITY,
                 registry: Optional[Registry] = None,
                 max_dumps_per_reason: int = DEFAULT_MAX_DUMPS_PER_REASON,
                 replica_id: str = ""):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.directory = directory
        self.capacity = capacity
        self.replica_id = _safe_reason(replica_id) if replica_id else ""
        self._frames: "collections.deque[dict]" = collections.deque(
            maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0
        # per reason: attempts drive file NAMING (monotonic, so a retry
        # after a failed write can never overwrite an earlier success);
        # successes drive the BUDGET (a transiently unwritable disk must
        # not burn the post-mortem allowance without leaving a file)
        self._dump_attempts: Dict[str, int] = {}
        self._dumps: Dict[str, int] = {}  # reason -> dumps WRITTEN
        self._max_dumps = max(max_dumps_per_reason, 1)
        reg = registry if registry is not None else Registry(enabled=True)
        self._c_dumps = reg.counter("obs/flight_dumps_total")
        self._c_dropped = reg.counter("obs/flight_dumps_dropped_total")
        self._c_errors = reg.counter("obs/flight_dump_errors_total")

    def record(self, kind: str, **fields: Any) -> None:
        """Append one frame (hot path: one small dict + deque append
        under a lock; the ring evicts the oldest frame itself)."""
        with self._lock:
            self._seq += 1
            frame = {"seq": self._seq, "kind": kind,
                     # serialized epoch timestamp, same dialect as span
                     # ts_us (the sanctioned time.time() use, spans.py)
                     "ts_us": int(time.time() * 1e6)}
            if self.replica_id:
                frame["replica"] = self.replica_id
            frame.update(fields)
            self._frames.append(frame)

    def frames(self) -> List[dict]:
        with self._lock:
            return list(self._frames)

    def dump(self, reason: str, **context: Any) -> Optional[str]:
        """Write the ring to ``flight_<reason>.jsonl`` (``-2``, ``-3``
        suffixes on repeat triggers); returns the path, or None when
        the per-reason dump budget is spent / the write failed.  The
        recovery path that triggered the dump NEVER sees an exception
        from here."""
        reason = _safe_reason(reason)
        with self._lock:
            frames = list(self._frames)
            if self._dumps.get(reason, 0) >= self._max_dumps:
                self._c_dropped.inc()
                return None
            n = self._dump_attempts.get(reason, 0) + 1
            self._dump_attempts[reason] = n
        # the replica tag keeps fleet replicas sharing one directory
        # from clobbering/shadowing each other's dumps (ISSUE 15)
        stem = (f"flight_{reason}.{self.replica_id}" if self.replica_id
                else f"flight_{reason}")
        name = f"{stem}.jsonl" if n == 1 else f"{stem}-{n}.jsonl"
        path = os.path.join(self.directory, name)
        header: Dict[str, Any] = {
            "kind": "flight", "reason": reason, "dump": n,
            "ts_us": int(time.time() * 1e6), "frames": len(frames),
            "capacity": self.capacity,
        }
        if self.replica_id:
            header["replica"] = self.replica_id
        if context:
            header["context"] = context
        try:
            os.makedirs(self.directory, exist_ok=True)
            with open(path, "w", encoding="utf-8") as f:
                f.write(_dumps(header) + "\n")
                for frame in frames:
                    f.write(_dumps(frame) + "\n")
        except (OSError, ValueError, TypeError):
            self._c_errors.inc()
            return None
        with self._lock:
            self._dumps[reason] = self._dumps.get(reason, 0) + 1
        self._c_dumps.inc()
        return path


_install_lock = threading.Lock()


def install_flight_recorder(registry: Registry, directory: str,
                            capacity: int = DEFAULT_CAPACITY,
                            ) -> Optional[FlightRecorder]:
    """Attach a FlightRecorder to `registry` (first install wins — a
    trainer and a server sharing one registry share one ring; the
    double-checked lock mirrors spans.tracer_for so two components
    constructed concurrently can never race two rings into existence).
    No-op (None) on a disabled registry."""
    if not registry.enabled:
        return None
    if registry.flight is None:
        with _install_lock:
            if registry.flight is None:
                registry.flight = FlightRecorder(
                    directory, capacity=capacity, registry=registry,
                    replica_id=getattr(registry, "replica_id", ""))
    return registry.flight


def set_replica_id(registry: Registry, replica_id: str) -> None:
    """Stamp `registry` (and any already-installed recorder) with the
    fleet replica id its frames/dumps — and request events — should
    carry (threaded from FleetRouter replica construction)."""
    registry.replica_id = replica_id
    rec = registry.flight
    if rec is not None:
        rec.replica_id = _safe_reason(replica_id) if replica_id else ""


def record(registry: Registry, kind: str, **fields: Any) -> None:
    """Append a frame to `registry`'s recorder; no-op when none is
    installed (the unarmed fast path is one attribute test)."""
    rec = registry.flight
    if rec is not None:
        rec.record(kind, **fields)


def trigger(registry: Registry, reason: str, **context: Any,
            ) -> Optional[str]:
    """Dump `registry`'s ring for `reason`; returns the dump path (None
    when no recorder is installed, budget spent, or the write failed)."""
    rec = registry.flight
    if rec is None:
        return None
    return rec.dump(reason, **context)
