"""Process-wide metrics registry: counters, gauges, histograms.

The reference system had no runtime telemetry beyond per-step loss
prints (run_summarization.py:223-226); this registry is the substrate
every layer of the rebuild reports through (OBSERVABILITY.md).

Design constraints (ISSUE 1 tentpole):
  * thread-safe — producer threads (batcher, prefetcher, bridge feeders)
    and the train loop all hit the same metrics;
  * near-zero-cost when disabled — a disabled registry hands out shared
    null singletons whose mutators are empty methods, so instrumented
    hot paths pay one attribute call and nothing else;
  * histograms are fixed-bucket and percentile-queryable (numpy-checked
    in tests/test_obs.py) — no unbounded sample retention;
  * text exposition (`render_text`) is Prometheus-style so a scrape
    endpoint can be bolted on without touching call sites.

Metric names follow ``<layer>/<name>`` (train/step_time_seconds,
decode/request_latency_seconds, ...); rendering flattens ``/`` and
``-`` to ``_`` for exposition compatibility.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


def exponential_buckets(start: float, factor: float, count: int,
                        ) -> Tuple[float, ...]:
    """`count` ascending bucket upper bounds: start * factor**i."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


# 1e-5 s .. ~168 s in x2 steps — covers a microsecond span probe through
# a multi-minute checkpoint save with <=2x relative bucket error
DEFAULT_TIME_BUCKETS = exponential_buckets(1e-5, 2.0, 24)


class Counter:
    """Monotonic float counter."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> Dict:
        return {"type": "counter", "value": self._value}


class Gauge:
    """Last-write-wins instantaneous value.

    Tracks whether it was ever written: a sampled gauge sitting at 0.0
    (e.g. a starved queue-depth) is a real observation and must survive
    a compact snapshot, unlike a gauge nothing ever touched.
    """

    __slots__ = ("name", "_value", "_lock", "touched")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()
        self.touched = False

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            self.touched = True

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n
            self.touched = True

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n
            self.touched = True

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> Dict:
        return {"type": "gauge", "value": self._value}


class Histogram:
    """Fixed-bucket histogram with percentile queries.

    `buckets` are ascending upper bounds; an implicit +inf bucket
    catches the overflow.  `percentile(q)` linearly interpolates within
    the winning bucket (the overflow bucket reports the observed max),
    which tests pin against numpy within bucket resolution.
    """

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count",
                 "_min", "_max", "_lock")

    def __init__(self, name: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        bs = tuple(buckets if buckets is not None else DEFAULT_TIME_BUCKETS)
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(
                f"histogram {name} needs ascending non-empty buckets")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # +1 overflow
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()

    def _bucket_index(self, v: float) -> int:
        # bisect over a tuple of <=~30 bounds; branchless enough
        lo, hi = 0, len(self.buckets)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.buckets[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def observe(self, v: float, n: int = 1) -> None:
        """Record `v`, optionally `n` times in one lock acquisition —
        for call sites that already hold aggregated per-value counts
        (e.g. the spec tier's device-side accept-length histogram);
        identical to n separate observes."""
        v = float(v)
        if n < 1:
            return
        i = self._bucket_index(v)
        with self._lock:
            self._counts[i] += n
            self._sum += v * n
            self._count += n
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def percentile(self, q: float) -> float:
        """Value at quantile q in [0, 100], interpolated within the
        bucket; 0.0 when empty."""
        if not 0 <= q <= 100:
            raise ValueError(f"q must be in [0, 100], got {q}")
        with self._lock:
            counts = list(self._counts)
            total = self._count
            vmin, vmax = self._min, self._max
        if total == 0:
            return 0.0
        rank = q / 100.0 * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            prev_cum = cum
            cum += c
            if cum >= rank:
                lo = self.buckets[i - 1] if i > 0 else min(vmin, 0.0)
                hi = self.buckets[i] if i < len(self.buckets) else vmax
                # clamp the bucket edges to what was actually observed
                lo = max(lo, vmin) if vmin != math.inf else lo
                hi = min(hi, vmax) if vmax != -math.inf else hi
                if hi <= lo or c == 0:
                    return hi
                frac = (rank - prev_cum) / c
                return lo + frac * (hi - lo)
        return vmax  # q == 100 falls through on float fuzz

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "type": "histogram",
                "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
                "buckets": list(self.buckets),
                "counts": list(self._counts),
                "p50": None, "p99": None,  # filled below, outside the lock
            }

    def snapshot_with_percentiles(self) -> Dict:
        s = self.snapshot()
        s["p50"] = self.percentile(50)
        s["p99"] = self.percentile(99)
        return s


# --------------------------------------------------------------------------
# Null objects — the disabled fast path
# --------------------------------------------------------------------------

class _NullCounter:
    __slots__ = ()
    name = "<null>"
    value = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def snapshot(self) -> Dict:
        return {"type": "counter", "value": 0.0}


class _NullGauge:
    __slots__ = ()
    name = "<null>"
    value = 0.0

    def set(self, v: float) -> None:
        pass

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def snapshot(self) -> Dict:
        return {"type": "gauge", "value": 0.0}


class _NullHistogram:
    __slots__ = ()
    name = "<null>"
    count = 0
    sum = 0.0
    mean = 0.0
    buckets = ()

    def observe(self, v: float, n: int = 1) -> None:
        pass

    def percentile(self, q: float) -> float:
        return 0.0

    def snapshot(self) -> Dict:
        return {"type": "histogram", "count": 0, "sum": 0.0}

    snapshot_with_percentiles = snapshot


NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()


# --------------------------------------------------------------------------
# Registry
# --------------------------------------------------------------------------

def _expo_name(name: str) -> str:
    """`train/step_time_seconds` -> `train_step_time_seconds` (Prometheus
    text exposition allows [a-zA-Z0-9_:] only)."""
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch in "_:") else "_")
    return "".join(out)


class Registry:
    """Get-or-create metric namespace.  One instance is the process-wide
    default (obs.registry()); tests construct their own for isolation."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()
        # span machinery lives here so swapping registries isolates it
        # (wired by obs.spans.Tracer at first span())
        self.tracer = None  # type: ignore[assignment]
        self.event_sink = None  # obs.export.EventSink, when installed
        # the live telemetry plane's per-registry state (ISSUE 9):
        # component heartbeats (obs.http.board_for) and the failure
        # flight recorder (obs.flightrec.install_flight_recorder)
        self.heartbeats = None  # obs.http.HeartbeatBoard
        self.flight = None  # obs.flightrec.FlightRecorder
        # non-numeric health facts a component wants on /healthz (e.g.
        # the serving layer's effective serve_mode — ISSUE 13: the
        # router's routing inputs must be scrapeable); set through
        # obs.http.set_health_info, read by obs.http.health
        self.health_info = None  # Optional[Dict[str, Any]]

    def _get_or_create(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        if not self.enabled:
            return NULL_COUNTER  # type: ignore[return-value]
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        if not self.enabled:
            return NULL_GAUGE  # type: ignore[return-value]
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        if not self.enabled:
            return NULL_HISTOGRAM  # type: ignore[return-value]
        return self._get_or_create(name, Histogram, buckets)

    def get(self, name: str):
        """The metric registered under `name`, or None."""
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self, compact: bool = False) -> Dict[str, Dict]:
        """{name: metric snapshot}.  compact=True drops metrics that were
        never touched (zero counters, empty histograms, never-written
        gauges) — the form BENCH rows embed (bench.py --obs-snapshot)."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Dict] = {}
        for name, m in sorted(items):
            if isinstance(m, Histogram):
                s = m.snapshot_with_percentiles()
                # bucket arrays are exposition detail, not snapshot payload
                s.pop("buckets", None)
                s.pop("counts", None)
            else:
                s = m.snapshot()
            if compact:
                if s["type"] == "histogram" and not s.get("count"):
                    continue
                if s["type"] == "counter" and not s.get("value"):
                    continue
                # a gauge legitimately at 0.0 (starved queue depth) is an
                # observation, not an untouched metric — keep it
                if s["type"] == "gauge" and not m.touched:
                    continue
            out[name] = s
        return out

    def render_text(self) -> str:
        """Prometheus-style text exposition of every metric."""
        with self._lock:
            items = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in items:
            ename = _expo_name(name)
            if isinstance(m, Counter):
                lines.append(f"# TYPE {ename} counter")
                lines.append(f"{ename} {m.value:g}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {ename} gauge")
                lines.append(f"{ename} {m.value:g}")
            elif isinstance(m, Histogram):
                snap = m.snapshot()
                lines.append(f"# TYPE {ename} histogram")
                cum = 0
                for bound, c in zip(snap["buckets"], snap["counts"]):
                    cum += c
                    lines.append(f'{ename}_bucket{{le="{bound:g}"}} {cum}')
                cum += snap["counts"][-1]
                lines.append(f'{ename}_bucket{{le="+Inf"}} {cum}')
                lines.append(f"{ename}_sum {snap['sum']:g}")
                lines.append(f"{ename}_count {snap['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every metric (tests)."""
        with self._lock:
            self._metrics.clear()


NULL_REGISTRY = Registry(enabled=False)
