"""Process-wide metrics registry: counters, gauges, histograms.

The reference system had no runtime telemetry beyond per-step loss
prints (run_summarization.py:223-226); this registry is the substrate
every layer of the rebuild reports through (OBSERVABILITY.md).

Design constraints (ISSUE 1 tentpole):
  * thread-safe — producer threads (batcher, prefetcher, bridge feeders)
    and the train loop all hit the same metrics;
  * near-zero-cost when disabled — a disabled registry hands out shared
    null singletons whose mutators are empty methods, so instrumented
    hot paths pay one attribute call and nothing else;
  * histograms are fixed-bucket and percentile-queryable (numpy-checked
    in tests/test_obs.py) — no unbounded sample retention;
  * text exposition (`render_text`) is Prometheus-style so a scrape
    endpoint can be bolted on without touching call sites.

Labeled metrics (ISSUE 15 tentpole): every metric can hand out labeled
CHILD series via ``metric.labels(tenant=..., tier=...)`` — same class,
same API, rendered ``name{tenant="...",tier="..."}`` by
``render_text``.  The label surface is BOUNDED: each parent keeps at
most ``max_label_sets`` children in an LRU (a hostile tenant name
cannot grow the registry without end); evictions are counted in
``obs/label_evictions_total``.  Counter and histogram children ROLL UP
into their parent (the unlabeled series stays the total, so an evicted
child loses only its per-label split, never aggregate truth); gauges
are last-write-wins per series and do not roll up.

Trace exemplars (ISSUE 15): ``Histogram.observe(v, trace_id=...)``
stamps the landing bucket's last-seen trace id, so a fat p99 bucket
carries a concrete request to chase — ``scripts/trace_summary.py
--request <trace_id>`` reconstructs its full timeline.  Exemplars ride
``render_text`` in OpenMetrics ``# {trace_id="..."} v`` syntax and the
``/exemplars`` JSON endpoint (obs/http.py).

Fleet aggregation (ISSUE 15): ``Registry.series()`` flattens a registry
into (name, labels, kind, payload) rows, and ``merge_fleet_series`` /
``render_fleet_text`` / ``merge_fleet_snapshot`` combine N replica
registries into one view — counters summed, gauges labeled
``{replica="..."}``, histograms bucket-merged (a bucket-layout mismatch
falls back to per-replica labeled series rather than a wrong sum).

Metric names follow ``<layer>/<name>`` (train/step_time_seconds,
decode/request_latency_seconds, ...); rendering flattens ``/`` and
``-`` to ``_`` for exposition compatibility.
"""

from __future__ import annotations

import math
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterable, List, Optional, Sequence, Tuple


def exponential_buckets(start: float, factor: float, count: int,
                        ) -> Tuple[float, ...]:
    """`count` ascending bucket upper bounds: start * factor**i."""
    if start <= 0 or factor <= 1 or count < 1:
        raise ValueError("need start > 0, factor > 1, count >= 1")
    return tuple(start * factor ** i for i in range(count))


# 1e-5 s .. ~168 s in x2 steps — covers a microsecond span probe through
# a multi-minute checkpoint save with <=2x relative bucket error
DEFAULT_TIME_BUCKETS = exponential_buckets(1e-5, 2.0, 24)

#: per-parent bound on labeled child series (LRU-evicted past this;
#: evictions counted in obs/label_evictions_total).  128 covers every
#: sane tenant/tier population while keeping a hostile tenant-name
#: stream from growing the registry — and render_text — without bound.
DEFAULT_MAX_LABEL_SETS = 128

#: labels-dict type: tuple of sorted (key, value) string pairs — the
#: canonical child identity (dict-order-insensitive, hashable)
LabelsKV = Tuple[Tuple[str, str], ...]


def _label_key(kv: Dict[str, Any]) -> LabelsKV:
    return tuple(sorted((str(k), str(v)) for k, v in kv.items()))


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_suffix(kv: LabelsKV, extra: str = "") -> str:
    """``{k="v",...}`` exposition suffix ("" for the unlabeled series
    unless `extra` — e.g. a histogram's ``le="..."`` — needs braces)."""
    parts = [f'{k}="{_escape_label(v)}"' for k, v in kv]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def _get_label_child(parent, make, kv: Dict[str, Any]):
    """Get-or-create `parent`'s labeled child for `kv` (LRU-bounded;
    evictions fire the parent's eviction callback).  Shared by all three
    metric classes."""
    if not kv:
        return parent
    if parent._parent is not None:
        raise ValueError(
            f"labels() on the already-labeled series {parent.name!r}"
            f"{_label_suffix(parent.labels_kv)}")
    key = _label_key(kv)
    evicted = 0
    with parent._lock:
        children = parent._children
        if children is None:
            children = parent._children = OrderedDict()
        child = children.get(key)
        if child is None:
            child = make()
            child._parent = parent
            child.labels_kv = key
            children[key] = child
            while len(children) > parent._max_label_sets:
                children.popitem(last=False)
                evicted += 1
        else:
            children.move_to_end(key)
    if evicted and parent._evict_cb is not None:
        parent._evict_cb(evicted)
    return child


class _LabeledMixin:
    """The label-family surface every metric class shares (children map,
    bound, eviction callback).  Slots live on the concrete classes —
    the empty declaration here keeps the mixin from silently handing
    every metric (and every LRU-bounded labeled child) a __dict__."""

    __slots__ = ()

    def _init_labels(self) -> None:
        self.labels_kv: LabelsKV = ()
        self._parent = None
        self._children: Optional["OrderedDict"] = None
        self._max_label_sets = DEFAULT_MAX_LABEL_SETS
        self._evict_cb: Optional[Callable[[int], None]] = None

    def label_children(self) -> Tuple:
        """The live labeled children (snapshot; LRU order)."""
        if self._children is None:
            return ()
        with self._lock:
            return tuple(self._children.values())

    def remove_labels(self, **kv: Any) -> bool:
        """Drop the labeled child for `kv` from the family (True when
        one existed).  For series whose OWNER retires them — e.g. the
        SLO engine evicting a (objective, key) series must also retire
        its alert-state gauge child, or a stale ``page`` would render
        on every scrape forever.  Counter children are normally left in
        place instead (a stale monotonic total is honest; a stale gauge
        lies)."""
        if self._children is None:
            return False
        with self._lock:
            return self._children.pop(_label_key(kv), None) is not None


class Counter(_LabeledMixin):
    """Monotonic float counter.  Labeled children (``labels(...)``)
    ROLL UP: a child inc also incs the parent, so the unlabeled series
    is always the total across labels (eviction-proof aggregate)."""

    __slots__ = ("name", "_value", "_lock", "labels_kv", "_parent",
                 "_children", "_max_label_sets", "_evict_cb")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()
        self._init_labels()

    def labels(self, **kv: Any) -> "Counter":
        return _get_label_child(self, lambda: Counter(self.name), kv)

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        with self._lock:
            self._value += n
        p = self._parent
        if p is not None:
            with p._lock:
                p._value += n

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> Dict:
        return {"type": "counter", "value": self._value}


class Gauge(_LabeledMixin):
    """Last-write-wins instantaneous value.

    Tracks whether it was ever written: a sampled gauge sitting at 0.0
    (e.g. a starved queue-depth) is a real observation and must survive
    a compact snapshot, unlike a gauge nothing ever touched.  Labeled
    children are independent series (no roll-up: summing last-write
    gauges would be meaningless)."""

    __slots__ = ("name", "_value", "_lock", "touched", "labels_kv",
                 "_parent", "_children", "_max_label_sets", "_evict_cb")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()
        self.touched = False
        self._init_labels()

    def labels(self, **kv: Any) -> "Gauge":
        return _get_label_child(self, lambda: Gauge(self.name), kv)

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)
            self.touched = True

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n
            self.touched = True

    def dec(self, n: float = 1.0) -> None:
        with self._lock:
            self._value -= n
            self.touched = True

    @property
    def value(self) -> float:
        return self._value

    def snapshot(self) -> Dict:
        return {"type": "gauge", "value": self._value}


class Histogram(_LabeledMixin):
    """Fixed-bucket histogram with percentile queries.

    `buckets` are ascending upper bounds; an implicit +inf bucket
    catches the overflow.  `percentile(q)` linearly interpolates within
    the winning bucket (the overflow bucket reports the observed max),
    which tests pin against numpy within bucket resolution.

    Trace exemplars (ISSUE 15): ``observe(v, trace_id=...)`` stamps the
    landing bucket's last exemplar — (trace_id, value) — so a scrape of
    a fat latency bucket names a concrete request to chase.  Labeled
    children share the parent's bucket layout and ROLL UP observations
    (value and exemplar) into it."""

    __slots__ = ("name", "buckets", "_counts", "_sum", "_count",
                 "_min", "_max", "_lock", "_exemplars", "labels_kv",
                 "_parent", "_children", "_max_label_sets", "_evict_cb")

    def __init__(self, name: str,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        bs = tuple(buckets if buckets is not None else DEFAULT_TIME_BUCKETS)
        if not bs or any(b2 <= b1 for b1, b2 in zip(bs, bs[1:])):
            raise ValueError(
                f"histogram {name} needs ascending non-empty buckets")
        self.buckets = bs
        self._counts = [0] * (len(bs) + 1)  # +1 overflow
        self._sum = 0.0
        self._count = 0
        self._min = math.inf
        self._max = -math.inf
        self._lock = threading.Lock()
        #: per-bucket last exemplar: (trace_id, value) or None
        self._exemplars: List[Optional[Tuple[str, float]]] = \
            [None] * (len(bs) + 1)
        self._init_labels()

    def labels(self, **kv: Any) -> "Histogram":
        return _get_label_child(
            self, lambda: Histogram(self.name, self.buckets), kv)

    def _bucket_index(self, v: float) -> int:
        # bisect over a tuple of <=~30 bounds; branchless enough
        lo, hi = 0, len(self.buckets)
        while lo < hi:
            mid = (lo + hi) // 2
            if v <= self.buckets[mid]:
                hi = mid
            else:
                lo = mid + 1
        return lo

    def observe(self, v: float, n: int = 1,
                trace_id: Optional[str] = None) -> None:
        """Record `v`, optionally `n` times in one lock acquisition —
        for call sites that already hold aggregated per-value counts
        (e.g. the spec tier's device-side accept-length histogram);
        identical to n separate observes.  `trace_id` stamps the
        landing bucket's exemplar (the active request's TraceContext
        id — OBSERVABILITY.md "Labeled metrics & exemplars")."""
        v = float(v)
        if n < 1:
            return
        i = self._bucket_index(v)
        with self._lock:
            self._counts[i] += n
            self._sum += v * n
            self._count += n
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            if trace_id is not None:
                self._exemplars[i] = (str(trace_id), v)
        p = self._parent
        if p is not None:
            p.observe(v, n, trace_id)

    def exemplars(self) -> List[Dict[str, Any]]:
        """The stamped bucket exemplars: [{"le", "trace_id", "value"}]
        (only buckets that ever saw a traced observation)."""
        with self._lock:
            exs = list(self._exemplars)
        out: List[Dict[str, Any]] = []
        for i, e in enumerate(exs):
            if e is None:
                continue
            le = (f"{self.buckets[i]:g}" if i < len(self.buckets)
                  else "+Inf")
            out.append({"le": le, "trace_id": e[0], "value": e[1]})
        return out

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def mean(self) -> float:
        return self._sum / self._count if self._count else 0.0

    def percentile(self, q: float) -> float:
        """Value at quantile q in [0, 100], interpolated within the
        bucket; 0.0 when empty."""
        if not 0 <= q <= 100:
            raise ValueError(f"q must be in [0, 100], got {q}")
        with self._lock:
            counts = list(self._counts)
            total = self._count
            vmin, vmax = self._min, self._max
        if total == 0:
            return 0.0
        rank = q / 100.0 * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            prev_cum = cum
            cum += c
            if cum >= rank:
                lo = self.buckets[i - 1] if i > 0 else min(vmin, 0.0)
                hi = self.buckets[i] if i < len(self.buckets) else vmax
                # clamp the bucket edges to what was actually observed
                lo = max(lo, vmin) if vmin != math.inf else lo
                hi = min(hi, vmax) if vmax != -math.inf else hi
                if hi <= lo or c == 0:
                    return hi
                frac = (rank - prev_cum) / c
                return lo + frac * (hi - lo)
        return vmax  # q == 100 falls through on float fuzz

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "type": "histogram",
                "count": self._count,
                "sum": self._sum,
                "min": self._min if self._count else None,
                "max": self._max if self._count else None,
                "buckets": list(self.buckets),
                "counts": list(self._counts),
                "p50": None, "p99": None,  # filled below, outside the lock
            }

    def snapshot_with_percentiles(self) -> Dict:
        s = self.snapshot()
        s["p50"] = self.percentile(50)
        s["p99"] = self.percentile(99)
        return s


# --------------------------------------------------------------------------
# Null objects — the disabled fast path
# --------------------------------------------------------------------------

class _NullCounter:
    __slots__ = ()
    name = "<null>"
    value = 0.0
    labels_kv = ()

    def labels(self, **kv: Any) -> "_NullCounter":
        return self

    def label_children(self) -> Tuple:
        return ()

    def remove_labels(self, **kv: Any) -> bool:
        return False

    def inc(self, n: float = 1.0) -> None:
        pass

    def snapshot(self) -> Dict:
        return {"type": "counter", "value": 0.0}


class _NullGauge:
    __slots__ = ()
    name = "<null>"
    value = 0.0
    labels_kv = ()

    def labels(self, **kv: Any) -> "_NullGauge":
        return self

    def label_children(self) -> Tuple:
        return ()

    def remove_labels(self, **kv: Any) -> bool:
        return False

    def set(self, v: float) -> None:
        pass

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def snapshot(self) -> Dict:
        return {"type": "gauge", "value": 0.0}


class _NullHistogram:
    __slots__ = ()
    name = "<null>"
    count = 0
    sum = 0.0
    mean = 0.0
    buckets = ()
    labels_kv = ()

    def labels(self, **kv: Any) -> "_NullHistogram":
        return self

    def label_children(self) -> Tuple:
        return ()

    def remove_labels(self, **kv: Any) -> bool:
        return False

    def observe(self, v: float, n: int = 1,
                trace_id: Optional[str] = None) -> None:
        pass

    def exemplars(self) -> List[Dict[str, Any]]:
        return []

    def percentile(self, q: float) -> float:
        return 0.0

    def snapshot(self) -> Dict:
        return {"type": "histogram", "count": 0, "sum": 0.0}

    snapshot_with_percentiles = snapshot


NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()


# --------------------------------------------------------------------------
# Registry
# --------------------------------------------------------------------------

def _expo_name(name: str) -> str:
    """`train/step_time_seconds` -> `train_step_time_seconds` (Prometheus
    text exposition allows [a-zA-Z0-9_:] only)."""
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() or ch in "_:") else "_")
    return "".join(out)


def _series_key(name: str, kv: LabelsKV) -> str:
    """The JSON-snapshot key of one labeled series: the raw metric name
    plus the exposition label suffix (``serve/queue_depth{replica="r0"}``)."""
    return name + _label_suffix(kv)


class Registry:
    """Get-or-create metric namespace.  One instance is the process-wide
    default (obs.registry()); tests construct their own for isolation."""

    def __init__(self, enabled: bool = True,
                 max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        self.enabled = enabled
        self.max_label_sets = max_label_sets
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()
        # span machinery lives here so swapping registries isolates it
        # (wired by obs.spans.Tracer at first span())
        self.tracer = None  # type: ignore[assignment]
        self.event_sink = None  # obs.export.EventSink, when installed
        # the live telemetry plane's per-registry state (ISSUE 9):
        # component heartbeats (obs.http.board_for) and the failure
        # flight recorder (obs.flightrec.install_flight_recorder)
        self.heartbeats = None  # obs.http.HeartbeatBoard
        self.flight = None  # obs.flightrec.FlightRecorder
        # non-numeric health facts a component wants on /healthz (e.g.
        # the serving layer's effective serve_mode — ISSUE 13: the
        # router's routing inputs must be scrapeable); set through
        # obs.http.set_health_info, read by obs.http.health AND /snapshot
        self.health_info = None  # Optional[Dict[str, Any]]
        # fleet identity + aggregation plane (ISSUE 15): replica_id tags
        # this registry's request events and flight dumps; fleet_sources
        # (set by the FleetRouter) is a zero-arg callable returning the
        # ordered {replica_id: Registry} map /fleet/* merges over
        self.replica_id = ""
        self.fleet_sources = None  # Optional[Callable[[], Dict[str, Registry]]]
        # the SLO burn-rate engine (obs/slo.py), when installed
        self.slo = None
        # the performance attribution plane (obs/profile.py, ISSUE 16):
        # phase ledger + compile ledger + divergence sentinel, attached
        # first-install-wins by profile.install_profiler
        self.profile = None

    def _note_label_evictions(self, n: int) -> None:
        self.counter("obs/label_evictions_total").inc(n)

    def _get_or_create(self, name: str, cls, *args):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, *args)
                # wire the label-cardinality bound + eviction counter
                # (harmless on the eviction counter itself: it never
                # hands out labeled children)
                m._max_label_sets = self.max_label_sets
                m._evict_cb = self._note_label_evictions
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        if not self.enabled:
            return NULL_COUNTER  # type: ignore[return-value]
        return self._get_or_create(name, Counter)

    def gauge(self, name: str) -> Gauge:
        if not self.enabled:
            return NULL_GAUGE  # type: ignore[return-value]
        return self._get_or_create(name, Gauge)

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        if not self.enabled:
            return NULL_HISTOGRAM  # type: ignore[return-value]
        return self._get_or_create(name, Histogram, buckets)

    def get(self, name: str):
        """The metric registered under `name`, or None."""
        with self._lock:
            return self._metrics.get(name)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._metrics)

    def snapshot(self, compact: bool = False) -> Dict[str, Dict]:
        """{name: metric snapshot}.  compact=True drops metrics that were
        never touched (zero counters, empty histograms, never-written
        gauges) — the form BENCH rows embed (bench.py --obs-snapshot).
        Labeled children ride along keyed ``name{k="v",...}`` (same
        compaction rule per series)."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict[str, Dict] = {}
        for name, m in sorted(items):
            for metric in (m, *m.label_children()):
                if isinstance(metric, Histogram):
                    s = metric.snapshot_with_percentiles()
                    # bucket arrays are exposition detail, not snapshot
                    # payload
                    s.pop("buckets", None)
                    s.pop("counts", None)
                else:
                    s = metric.snapshot()
                if compact:
                    if s["type"] == "histogram" and not s.get("count"):
                        continue
                    if s["type"] == "counter" and not s.get("value"):
                        continue
                    # a gauge legitimately at 0.0 (starved queue depth)
                    # is an observation, not an untouched metric — keep
                    if s["type"] == "gauge" and not metric.touched:
                        continue
                out[_series_key(name, metric.labels_kv)] = s
        return out

    def series(self) -> List[Tuple[str, LabelsKV, str, Any]]:
        """Flat per-series rows: (name, labels, kind, payload) for every
        parent metric and labeled child — the fleet aggregation plane's
        input (``merge_fleet_series``).  Payloads: counter -> value;
        gauge -> (value, touched); histogram -> its ``snapshot()`` dict
        plus an ``"exemplars"`` list."""
        with self._lock:
            items = sorted(self._metrics.items())
        out: List[Tuple[str, LabelsKV, str, Any]] = []
        for name, m in items:
            for metric in (m, *m.label_children()):
                if isinstance(m, Counter):
                    out.append((name, metric.labels_kv, "counter",
                                metric.value))
                elif isinstance(m, Gauge):
                    out.append((name, metric.labels_kv, "gauge",
                                (metric.value, metric.touched)))
                elif isinstance(m, Histogram):
                    snap = metric.snapshot()
                    snap["exemplars"] = metric.exemplars()
                    out.append((name, metric.labels_kv, "histogram", snap))
        return out

    def _render_histogram_series(self, lines: List[str], ename: str,
                                 h: Histogram, kv: LabelsKV,
                                 exemplars: bool = True) -> None:
        snap = h.snapshot()
        ex_by_le = {e["le"]: e for e in h.exemplars()} if exemplars \
            else {}
        cum = 0
        for bound, c in zip(snap["buckets"], snap["counts"]):
            cum += c
            le = f"{bound:g}"
            suffix = _label_suffix(kv, 'le="%s"' % le)
            line = f"{ename}_bucket{suffix} {cum}"
            ex = ex_by_le.get(le)
            if ex is not None:
                # OpenMetrics exemplar syntax: the bucket's last traced
                # observation (OBSERVABILITY.md "Labeled metrics &
                # exemplars")
                line += (' # {trace_id="%s"} %g'
                         % (_escape_label(ex["trace_id"]), ex["value"]))
            lines.append(line)
        cum += snap["counts"][-1]
        suffix = _label_suffix(kv, 'le="+Inf"')
        line = f"{ename}_bucket{suffix} {cum}"
        ex = ex_by_le.get("+Inf")
        if ex is not None:
            line += (' # {trace_id="%s"} %g'
                     % (_escape_label(ex["trace_id"]), ex["value"]))
        lines.append(line)
        lines.append(f"{ename}_sum{_label_suffix(kv)} {snap['sum']:g}")
        lines.append(f"{ename}_count{_label_suffix(kv)} {snap['count']}")

    def render_text(self, exemplars: Optional[bool] = None,
                    openmetrics: bool = False) -> str:
        """Prometheus-style text exposition of every metric series —
        unlabeled parents and labeled children alike; histogram buckets
        carry their OpenMetrics trace exemplars when stamped.

        ``exemplars`` defaults to `openmetrics`: the ``# {trace_id=...}``
        annotation is OpenMetrics syntax and a Prometheus-0.0.4 parser
        rejects it as a trailing timestamp token, so the default render
        is always a VALID exposition in whichever format was asked for
        — strict 0.0.4 without negotiation, annotated OpenMetrics with.
        ``exemplars=True`` forces the annotations into a 0.0.4 body for
        callers that want the hybrid (debug dumps).

        ``openmetrics=True`` makes the body a VALID OpenMetrics 1.0
        exposition, not just exemplar-annotated text: counter families
        are typed under their ``_total``-stripped name with samples
        keeping the ``_total`` suffix (the OpenMetrics sample-suffix
        rule), and the mandatory ``# EOF`` terminator is appended — a
        negotiating Prometheus server rejects the whole scrape without
        either ('data does not end with # EOF')."""
        if exemplars is None:
            exemplars = openmetrics
        with self._lock:
            items = sorted(self._metrics.items())
        lines: List[str] = []
        for name, m in items:
            ename = _expo_name(name)
            if isinstance(m, Counter):
                if openmetrics:
                    fam = ename[:-len("_total")] \
                        if ename.endswith("_total") else ename
                    sample = fam + "_total"
                else:
                    fam = sample = ename
                lines.append(f"# TYPE {fam} counter")
                lines.append(f"{sample} {m.value:g}")
                for child in m.label_children():
                    lines.append(f"{sample}{_label_suffix(child.labels_kv)}"
                                 f" {child.value:g}")
            elif isinstance(m, Gauge):
                lines.append(f"# TYPE {ename} gauge")
                lines.append(f"{ename} {m.value:g}")
                for child in m.label_children():
                    lines.append(f"{ename}{_label_suffix(child.labels_kv)}"
                                 f" {child.value:g}")
            elif isinstance(m, Histogram):
                lines.append(f"# TYPE {ename} histogram")
                self._render_histogram_series(lines, ename, m, (),
                                              exemplars=exemplars)
                for child in m.label_children():
                    self._render_histogram_series(lines, ename, child,
                                                  child.labels_kv,
                                                  exemplars=exemplars)
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every metric (tests)."""
        with self._lock:
            self._metrics.clear()


NULL_REGISTRY = Registry(enabled=False)


# --------------------------------------------------------------------------
# Fleet aggregation (ISSUE 15 tentpole, piece 3)
# --------------------------------------------------------------------------

def _merged_histogram(name: str, buckets: Sequence[float],
                      snaps: Iterable[Dict]) -> Dict:
    """Bucket-wise merge of same-layout histogram snapshots: counts sum
    per bucket, sum/count sum, min/max fold — the merged exposition is
    exactly what one registry observing every replica's stream would
    render (pinned by tests/test_obs_labels.py)."""
    counts = [0] * (len(buckets) + 1)
    total, vsum = 0, 0.0
    vmin, vmax = math.inf, -math.inf
    for s in snaps:
        for i, c in enumerate(s["counts"]):
            counts[i] += c
        total += s["count"]
        vsum += s["sum"]
        if s["count"]:
            vmin = min(vmin, s["min"])
            vmax = max(vmax, s["max"])
    return {"type": "histogram", "count": total, "sum": vsum,
            "min": vmin if total else None, "max": vmax if total else None,
            "buckets": list(buckets), "counts": counts}


def merge_fleet_series(named: Dict[str, "Registry"],
                       ) -> List[Tuple[str, LabelsKV, str, Any]]:
    """Merge N registries' series into one fleet view (ISSUE 15):

      * counters — summed per (name, labels) across registries;
      * gauges — one series per replica, labels extended with
        ``replica=<id>`` (summing last-write-wins values would lie);
      * histograms — bucket-wise merged when every replica agrees on
        the bucket layout; a layout mismatch falls back to per-replica
        ``replica=``-labeled series (never a wrong sum).

    Returns the same row shape as ``Registry.series()`` (histogram
    payloads carry ``counts``/``buckets`` for exposition).  Declared a
    TS002 hot function: /fleet/metrics is scraped on a cadence and a
    stray device sync here would stall every replica's scrape at once.
    """
    counters: "OrderedDict[Tuple[str, LabelsKV], float]" = OrderedDict()
    gauges: List[Tuple[str, LabelsKV, float]] = []
    hists: "OrderedDict[Tuple[str, LabelsKV], List[Tuple[str, Dict]]]" = \
        OrderedDict()
    for rid, reg in named.items():
        for name, kv, kind, payload in reg.series():
            if kind == "counter":
                key = (name, kv)
                counters[key] = counters.get(key, 0.0) + payload
            elif kind == "gauge":
                value, touched = payload
                if touched or value:
                    tag = () if any(k == "replica" for k, _ in kv) \
                        else (("replica", rid),)
                    gauges.append((name, kv + tag, value))
            elif kind == "histogram":
                hists.setdefault((name, kv), []).append((rid, payload))
    out: List[Tuple[str, LabelsKV, str, Any]] = []
    for (name, kv), value in counters.items():
        out.append((name, kv, "counter", value))
    for name, kv, value in gauges:
        out.append((name, kv, "gauge", value))
    for (name, kv), snaps in hists.items():
        layouts = {tuple(s["buckets"]) for _, s in snaps}
        if len(layouts) == 1:
            out.append((name, kv, "histogram", _merged_histogram(
                name, next(iter(layouts)), (s for _, s in snaps))))
        else:  # layout mismatch: honest per-replica series, never a
            # cross-layout "sum"
            for rid, s in snaps:
                out.append((name, kv + (("replica", rid),),
                            "histogram", s))
    out.sort(key=lambda row: (row[0], row[1]))
    return out


def render_fleet_text(named: Dict[str, "Registry"]) -> str:
    """The merged fleet exposition (/fleet/metrics): Prometheus text
    over ``merge_fleet_series`` rows."""
    rows = merge_fleet_series(named)
    lines: List[str] = []
    last_typed = None
    for name, kv, kind, payload in rows:
        ename = _expo_name(name)
        if (ename, kind) != last_typed:
            lines.append(f"# TYPE {ename} "
                         f"{'histogram' if kind == 'histogram' else kind}")
            last_typed = (ename, kind)
        if kind in ("counter", "gauge"):
            lines.append(f"{ename}{_label_suffix(kv)} {payload:g}")
            continue
        cum = 0
        for bound, c in zip(payload["buckets"], payload["counts"]):
            cum += c
            suffix = _label_suffix(kv, 'le="%g"' % bound)
            lines.append(f"{ename}_bucket{suffix} {cum}")
        cum += payload["counts"][-1]
        suffix = _label_suffix(kv, 'le="+Inf"')
        lines.append(f"{ename}_bucket{suffix} {cum}")
        lines.append(f"{ename}_sum{_label_suffix(kv)} {payload['sum']:g}")
        lines.append(f"{ename}_count{_label_suffix(kv)} {payload['count']}")
    return "\n".join(lines) + ("\n" if lines else "")


def merge_fleet_snapshot(named: Dict[str, "Registry"]) -> Dict[str, Any]:
    """The merged fleet snapshot (/fleet/snapshot): JSON-shaped
    ``{"replicas": [...], "metrics": {series-key: snapshot}, "health":
    {replica: health_info}}``.  Histogram entries carry merged
    count/sum/min/max plus p50/p99 recomputed over the merged buckets.
    """
    metrics: Dict[str, Dict] = {}
    for name, kv, kind, payload in merge_fleet_series(named):
        key = _series_key(name, kv)
        if kind == "counter":
            metrics[key] = {"type": "counter", "value": payload}
        elif kind == "gauge":
            metrics[key] = {"type": "gauge", "value": payload}
        else:
            h = Histogram(name, payload["buckets"])
            h._counts = list(payload["counts"])
            h._count = payload["count"]
            h._sum = payload["sum"]
            h._min = payload["min"] if payload["min"] is not None \
                else math.inf
            h._max = payload["max"] if payload["max"] is not None \
                else -math.inf
            metrics[key] = {
                "type": "histogram", "count": payload["count"],
                "sum": payload["sum"], "min": payload["min"],
                "max": payload["max"], "p50": h.percentile(50),
                "p99": h.percentile(99),
            }
    health = {rid: reg.health_info for rid, reg in named.items()
              if getattr(reg, "health_info", None)}
    return {"replicas": list(named), "metrics": metrics, "health": health}
