"""Lightweight span tracing: ``with obs.span("decode/beam_step"): ...``.

Spans nest (a thread-local stack tracks depth/parent), record both
wall-clock start (epoch, for cross-process alignment) and monotonic
duration (perf_counter, for arithmetic), and land in a bounded
per-registry ring buffer — a long-running server never grows without
bound; overflow is counted in ``obs/spans_dropped_total``.

Request-scoped distributed tracing (ISSUE 9 tentpole): the thread-local
stack alone can never link the serve path's submit thread, dispatch
thread, and slot engine — so spans also carry explicit trace ids.  A
``TraceContext`` (trace_id / span_id / parent_id) is minted where a
request (or a train run) is born and handed across threads; any span
opened with ``parent=ctx`` joins that trace, and nested spans inherit
the enclosing span's trace through the stack.  Per-request lifecycle
events (``request_event``: enqueue, admit, slot, finish, evict,
resolve) stream through the registry's EventSink carrying the same ids,
so one uuid's full timeline is reconstructable from ``events.jsonl``
(scripts/trace_summary.py --request).

Two export shapes:
  * Chrome-trace events (`chrome_trace_events`) — 'ph': 'X' complete
    events in the exact dialect scripts/trace_summary.py summarizes
    (same tool as the jax.profiler captures);
  * unified JSONL records (`{"kind": "span", ...}`) pushed to the
    registry's EventSink when one is installed (obs/export.py), sharing
    the `<log_root>/<exp>/<job>/events.jsonl` file with SummaryWriter
    scalars.
"""

from __future__ import annotations

import collections
import itertools
import os
import threading
import time
from typing import Any, Dict, List, Optional

from textsummarization_on_flink_tpu.obs.registry import Registry

DEFAULT_MAX_SPANS = 10_000

# process-unique span-id mint: pid disambiguates across processes
# sharing one events.jsonl, the counter across threads (next() on an
# itertools.count is GIL-atomic — no lock on this hot-ish path)
_ids = itertools.count(1)


def _next_id() -> str:
    return f"{os.getpid():x}-{next(_ids):x}"


class TraceContext:
    """One node of a request-scoped trace: ids only, no timing.

    Minted at a request's birth (``ServingServer.submit``) or a train
    run's start and CARRIED across threads on the request object —
    unlike the thread-local span stack, a TraceContext links spans and
    lifecycle events no matter which thread touches the request next.
    """

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id

    @classmethod
    def new(cls, trace_id: Optional[str] = None) -> "TraceContext":
        """A fresh root context (random 64-bit trace id unless given)."""
        return cls(trace_id if trace_id is not None
                   else os.urandom(8).hex(), _next_id())

    def child(self) -> "TraceContext":
        """A child node in the same trace (parent = this node)."""
        return TraceContext(self.trace_id, _next_id(), self.span_id)

    def as_dict(self) -> Dict[str, str]:
        d = {"trace_id": self.trace_id, "span_id": self.span_id}
        if self.parent_id:
            d["parent_id"] = self.parent_id
        return d

    def __repr__(self) -> str:  # pragma: no cover - debugging sugar
        return (f"TraceContext(trace_id={self.trace_id!r}, "
                f"span_id={self.span_id!r}, parent_id={self.parent_id!r})")


class SpanRecord:
    __slots__ = ("name", "wall_start", "duration", "depth", "parent",
                 "thread_id", "thread_name", "attrs", "trace_id",
                 "span_id", "parent_id")

    def __init__(self, name: str, wall_start: float, duration: float,
                 depth: int, parent: Optional[str], thread_id: int,
                 thread_name: str, attrs: Optional[Dict[str, Any]],
                 trace_id: Optional[str] = None,
                 span_id: Optional[str] = None,
                 parent_id: Optional[str] = None):
        self.name = name
        self.wall_start = wall_start  # epoch seconds
        self.duration = duration  # monotonic seconds
        self.depth = depth
        self.parent = parent
        self.thread_id = thread_id
        self.thread_name = thread_name
        self.attrs = attrs
        self.trace_id = trace_id  # request-scoped linkage (None = untraced)
        self.span_id = span_id
        self.parent_id = parent_id

    def as_event(self) -> Dict[str, Any]:
        """The unified events.jsonl record shape."""
        rec: Dict[str, Any] = {
            "kind": "span",
            "name": self.name,
            "ts_us": int(self.wall_start * 1e6),
            "dur_us": int(self.duration * 1e6),
            "depth": self.depth,
            "pid": os.getpid(),
            "tid": self.thread_id,
        }
        if self.span_id:
            rec["span_id"] = self.span_id
        if self.trace_id:
            rec["trace_id"] = self.trace_id
        if self.parent_id:
            rec["parent_id"] = self.parent_id
        if self.parent:
            rec["parent"] = self.parent
        if self.attrs:
            rec["attrs"] = self.attrs
        return rec

    def as_chrome_event(self) -> Dict[str, Any]:
        """A Chrome-trace complete event ('ph': 'X', microsecond units)."""
        ev: Dict[str, Any] = {
            "ph": "X",
            "name": self.name,
            "ts": self.wall_start * 1e6,
            "dur": self.duration * 1e6,
            "pid": os.getpid(),
            "tid": self.thread_id,
        }
        args = dict(self.attrs or {})
        if self.parent:
            args["parent"] = self.parent
        if self.trace_id:
            args["trace_id"] = self.trace_id
            args["span_id"] = self.span_id
            if self.parent_id:
                args["parent_id"] = self.parent_id
        if args:
            ev["args"] = args
        return ev


class _SpanContext:
    """The live context-manager handed out by Tracer.span().

    Exposes ``ctx`` (its TraceContext) once entered, so a caller can
    hand the span's identity to work it fans out to other threads.
    """

    __slots__ = ("_tracer", "name", "attrs", "_t0", "_wall0", "_parent",
                 "ctx")

    def __init__(self, tracer: "Tracer", name: str,
                 attrs: Optional[Dict[str, Any]],
                 parent: Optional[TraceContext] = None):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self._parent = parent
        self.ctx: Optional[TraceContext] = None
        self._t0 = 0.0
        self._wall0 = 0.0

    def __enter__(self) -> "_SpanContext":
        stack = self._tracer._stack()
        # trace linkage: an EXPLICIT parent (a TraceContext carried
        # across threads) wins; otherwise inherit the enclosing span's
        # trace through the thread-local stack; otherwise untraced.
        if self._parent is not None:
            self.ctx = self._parent.child()
        elif stack and stack[-1][2] is not None:
            _, pspan, ptrace = stack[-1]
            self.ctx = TraceContext(ptrace, _next_id(), pspan)
        stack.append((self.name,
                      self.ctx.span_id if self.ctx else None,
                      self.ctx.trace_id if self.ctx else None))
        # wall_start is SERIALIZED (the ts_us event timestamp, aligned
        # across processes) — the one legitimate time.time() use (TS003
        # exemption, ANALYSIS.md); durations NEVER derive from it: they
        # come from the monotonic perf_counter below.
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = time.perf_counter() - self._t0
        stack = self._tracer._stack()
        if stack and stack[-1][0] == self.name:
            stack.pop()
        parent = stack[-1][0] if stack else None
        t = threading.current_thread()
        ctx = self.ctx
        self._tracer._record(SpanRecord(
            self.name, self._wall0, dur, depth=len(stack), parent=parent,
            thread_id=t.ident or 0, thread_name=t.name, attrs=self.attrs,
            trace_id=ctx.trace_id if ctx else None,
            span_id=ctx.span_id if ctx else None,
            parent_id=ctx.parent_id if ctx else None))


class _NullSpan:
    """Disabled-mode span: enter/exit do nothing.  Shared singleton —
    the hot-path cost of a disabled span is two empty method calls."""

    __slots__ = ()

    ctx = None  # matches _SpanContext's post-enter surface

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


NULL_SPAN = _NullSpan()


class Tracer:
    """Per-registry span collector (bounded ring buffer)."""

    def __init__(self, registry: Registry, max_spans: int = DEFAULT_MAX_SPANS):
        self._registry = registry
        self._spans: "collections.deque[SpanRecord]" = collections.deque(
            maxlen=max_spans)
        self._local = threading.local()
        self._lock = threading.Lock()
        self._dropped = registry.counter("obs/spans_dropped_total")

    def _stack(self) -> List[tuple]:
        """Per-thread stack of (name, span_id, trace_id) for the spans
        currently open on this thread."""
        s = getattr(self._local, "stack", None)
        if s is None:
            s = []
            self._local.stack = s
        return s

    def _record(self, rec: SpanRecord) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped.inc()
            self._spans.append(rec)
        sink = self._registry.event_sink
        if sink is not None:
            sink.emit(rec.as_event())

    def span(self, name: str, parent: Optional[TraceContext] = None,
             **attrs: Any) -> _SpanContext:
        return _SpanContext(self, name, attrs or None, parent=parent)

    def finished(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_trace_events(self) -> List[Dict[str, Any]]:
        """All buffered spans as Chrome-trace events plus process/thread
        metadata rows — directly loadable by scripts/trace_summary.py."""
        spans = self.finished()
        events: List[Dict[str, Any]] = [{
            "ph": "M", "name": "process_name", "pid": os.getpid(),
            "args": {"name": "obs"},
        }]
        seen_tids = {}
        for s in spans:
            if s.thread_id not in seen_tids:
                seen_tids[s.thread_id] = s.thread_name
        for tid, tname in seen_tids.items():
            events.append({"ph": "M", "name": "thread_name",
                           "pid": os.getpid(), "tid": tid,
                           "args": {"name": tname}})
        events.extend(s.as_chrome_event() for s in spans)
        return events


_tracer_init_lock = threading.Lock()


def tracer_for(registry: Registry) -> Tracer:
    """The registry's tracer, created on first use (double-checked under
    a module lock so concurrent first spans share one buffer)."""
    t = registry.tracer
    if t is None:
        with _tracer_init_lock:
            t = registry.tracer
            if t is None:
                t = Tracer(registry)
                registry.tracer = t
    return t


def span(registry: Registry, name: str,
         parent: Optional[TraceContext] = None, **attrs: Any):
    """Context manager recording one span into `registry` (the module
    facade obs.span() routes here with the default registry).  An
    explicit ``parent=`` TraceContext links the span into a
    request-scoped trace regardless of which thread opens it."""
    if not registry.enabled:
        return NULL_SPAN
    return tracer_for(registry).span(name, parent=parent, **attrs)


def request_event(registry: Registry, event: str,
                  ctx: Optional[TraceContext], uuid: str,
                  **attrs: Any) -> bool:
    """Emit one per-request lifecycle record to the registry's EventSink.

    Record shape (the ``{"kind": "request"}`` events.jsonl family,
    OBSERVABILITY.md "Request-scoped tracing"):

        {"kind": "request", "event": "enqueue" | "admit" | "slot" |
         "finish" | "evict" | "resolve" | "shed" | "route" | "hedge" |
         "requeued", "uuid": ...,
         "ts_us": ..., "trace_id": ..., "span_id": ..., "pid": ...,
         "attrs": {...}}

    All events of one request carry its TraceContext's ids, so the
    timeline reconstructs by uuid OR trace_id.  No-op (False) when the
    registry is disabled or has no sink — lifecycle events exist only
    in the unified events.jsonl, never in memory."""
    if not registry.enabled:
        return False
    sink = registry.event_sink
    if sink is None:
        return False
    rec: Dict[str, Any] = {
        "kind": "request",
        "event": event,
        "uuid": uuid,
        # serialized epoch timestamp, same dialect as span ts_us (the
        # sanctioned time.time() use — see _SpanContext.__enter__)
        "ts_us": int(time.time() * 1e6),
        "pid": os.getpid(),
    }
    # fleet identity (ISSUE 15): a replica-tagged registry stamps its
    # id on every lifecycle event, so one events.jsonl shared by N
    # replicas reads as a self-describing cross-replica timeline
    rid = getattr(registry, "replica_id", "")
    if rid:
        rec["replica"] = rid
    if ctx is not None:
        rec.update(ctx.as_dict())
    if attrs:
        rec["attrs"] = attrs
    return sink.emit(rec)
