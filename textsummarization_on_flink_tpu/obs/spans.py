"""Lightweight span tracing: ``with obs.span("decode/beam_step"): ...``.

Spans nest (a thread-local stack tracks depth/parent), record both
wall-clock start (epoch, for cross-process alignment) and monotonic
duration (perf_counter, for arithmetic), and land in a bounded
per-registry ring buffer — a long-running server never grows without
bound; overflow is counted in ``obs/spans_dropped_total``.

Two export shapes:
  * Chrome-trace events (`chrome_trace_events`) — 'ph': 'X' complete
    events in the exact dialect scripts/trace_summary.py summarizes
    (same tool as the jax.profiler captures);
  * unified JSONL records (`{"kind": "span", ...}`) pushed to the
    registry's EventSink when one is installed (obs/export.py), sharing
    the `<log_root>/<exp>/<job>/events.jsonl` file with SummaryWriter
    scalars.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Dict, List, Optional

from textsummarization_on_flink_tpu.obs.registry import Registry

DEFAULT_MAX_SPANS = 10_000


class SpanRecord:
    __slots__ = ("name", "wall_start", "duration", "depth", "parent",
                 "thread_id", "thread_name", "attrs")

    def __init__(self, name: str, wall_start: float, duration: float,
                 depth: int, parent: Optional[str], thread_id: int,
                 thread_name: str, attrs: Optional[Dict[str, Any]]):
        self.name = name
        self.wall_start = wall_start  # epoch seconds
        self.duration = duration  # monotonic seconds
        self.depth = depth
        self.parent = parent
        self.thread_id = thread_id
        self.thread_name = thread_name
        self.attrs = attrs

    def as_event(self) -> Dict[str, Any]:
        """The unified events.jsonl record shape."""
        rec: Dict[str, Any] = {
            "kind": "span",
            "name": self.name,
            "ts_us": int(self.wall_start * 1e6),
            "dur_us": int(self.duration * 1e6),
            "depth": self.depth,
            "pid": os.getpid(),
            "tid": self.thread_id,
        }
        if self.parent:
            rec["parent"] = self.parent
        if self.attrs:
            rec["attrs"] = self.attrs
        return rec

    def as_chrome_event(self) -> Dict[str, Any]:
        """A Chrome-trace complete event ('ph': 'X', microsecond units)."""
        ev: Dict[str, Any] = {
            "ph": "X",
            "name": self.name,
            "ts": self.wall_start * 1e6,
            "dur": self.duration * 1e6,
            "pid": os.getpid(),
            "tid": self.thread_id,
        }
        args = dict(self.attrs or {})
        if self.parent:
            args["parent"] = self.parent
        if args:
            ev["args"] = args
        return ev


class _SpanContext:
    """The live context-manager handed out by Tracer.span()."""

    __slots__ = ("_tracer", "name", "attrs", "_t0", "_wall0")

    def __init__(self, tracer: "Tracer", name: str,
                 attrs: Optional[Dict[str, Any]]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0
        self._wall0 = 0.0

    def __enter__(self) -> "_SpanContext":
        stack = self._tracer._stack()
        stack.append(self.name)
        # wall_start is SERIALIZED (the ts_us event timestamp, aligned
        # across processes) — the one legitimate time.time() use (TS003
        # exemption, ANALYSIS.md); durations NEVER derive from it: they
        # come from the monotonic perf_counter below.
        self._wall0 = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur = time.perf_counter() - self._t0
        stack = self._tracer._stack()
        if stack and stack[-1] == self.name:
            stack.pop()
        parent = stack[-1] if stack else None
        t = threading.current_thread()
        self._tracer._record(SpanRecord(
            self.name, self._wall0, dur, depth=len(stack), parent=parent,
            thread_id=t.ident or 0, thread_name=t.name, attrs=self.attrs))


class _NullSpan:
    """Disabled-mode span: enter/exit do nothing.  Shared singleton —
    the hot-path cost of a disabled span is two empty method calls."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        pass


NULL_SPAN = _NullSpan()


class Tracer:
    """Per-registry span collector (bounded ring buffer)."""

    def __init__(self, registry: Registry, max_spans: int = DEFAULT_MAX_SPANS):
        self._registry = registry
        self._spans: "collections.deque[SpanRecord]" = collections.deque(
            maxlen=max_spans)
        self._local = threading.local()
        self._lock = threading.Lock()
        self._dropped = registry.counter("obs/spans_dropped_total")

    def _stack(self) -> List[str]:
        s = getattr(self._local, "stack", None)
        if s is None:
            s = []
            self._local.stack = s
        return s

    def _record(self, rec: SpanRecord) -> None:
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self._dropped.inc()
            self._spans.append(rec)
        sink = self._registry.event_sink
        if sink is not None:
            sink.emit(rec.as_event())

    def span(self, name: str, **attrs: Any) -> _SpanContext:
        return _SpanContext(self, name, attrs or None)

    def finished(self) -> List[SpanRecord]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_trace_events(self) -> List[Dict[str, Any]]:
        """All buffered spans as Chrome-trace events plus process/thread
        metadata rows — directly loadable by scripts/trace_summary.py."""
        spans = self.finished()
        events: List[Dict[str, Any]] = [{
            "ph": "M", "name": "process_name", "pid": os.getpid(),
            "args": {"name": "obs"},
        }]
        seen_tids = {}
        for s in spans:
            if s.thread_id not in seen_tids:
                seen_tids[s.thread_id] = s.thread_name
        for tid, tname in seen_tids.items():
            events.append({"ph": "M", "name": "thread_name",
                           "pid": os.getpid(), "tid": tid,
                           "args": {"name": tname}})
        events.extend(s.as_chrome_event() for s in spans)
        return events


_tracer_init_lock = threading.Lock()


def tracer_for(registry: Registry) -> Tracer:
    """The registry's tracer, created on first use (double-checked under
    a module lock so concurrent first spans share one buffer)."""
    t = registry.tracer
    if t is None:
        with _tracer_init_lock:
            t = registry.tracer
            if t is None:
                t = Tracer(registry)
                registry.tracer = t
    return t


def span(registry: Registry, name: str, **attrs: Any):
    """Context manager recording one span into `registry` (the module
    facade obs.span() routes here with the default registry)."""
    if not registry.enabled:
        return NULL_SPAN
    return tracer_for(registry).span(name, **attrs)
